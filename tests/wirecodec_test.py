"""Tests for the lossless host->device wire codec (ops/wirecodec.py).

The codec's contract is bit-exactness: decode(encode(columns)) must return
the original (pid, pk, value) multiset, and the native C++ encoder must be
byte-identical to the numpy reference.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pipelinedp_tpu.ops import streaming, wirecodec


def _random_columns(n, n_users, n_parts, value_kind, seed=0):
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n_users, n, dtype=np.int32)
    pk = rng.integers(0, n_parts, n, dtype=np.int32)
    if value_kind == "ratings":
        value = rng.integers(1, 6, n).astype(np.float32)
    elif value_kind == "halfstar":
        value = (rng.integers(1, 11, n) * 0.5).astype(np.float32)
    elif value_kind == "uniform":
        value = rng.uniform(0.0, 5.0, n).astype(np.float32)
    elif value_kind == "none":
        value = None
    else:
        raise ValueError(value_kind)
    return pid, pk, value


def _decode_all(slab, n_rows, n_uniq, fmt):
    """Host-visible decode of every bucket -> concatenated valid rows."""
    pids, pks, vals = [], [], []
    for c in range(slab.shape[0]):
        pid, pk, value, valid = wirecodec.decode_bucket(
            jnp.asarray(slab[c]), int(n_rows[c]), int(n_uniq[c]), fmt)
        m = int(n_rows[c])
        pids.append(np.asarray(pid)[:m])
        pks.append(np.asarray(pk)[:m])
        if value is not None:
            vals.append(np.asarray(value)[:m])
        assert int(np.asarray(valid).sum()) == m
    return (np.concatenate(pids) if pids else np.zeros(0),
            np.concatenate(pks) if pks else np.zeros(0),
            np.concatenate(vals) if vals else None)


class TestValuePlan:
    def test_integer_ratings_get_planes(self):
        v = np.array([1, 5, 3, 2, 2, 4], dtype=np.float32)
        plan = wirecodec.plan_value_encoding(v)
        assert plan.mode == wirecodec.VALUE_PLANES
        assert plan.scale == 1.0
        assert plan.bits == 3  # max idx = 4

    def test_halfstar_ratings_get_planes(self):
        v = np.array([0.5, 5.0, 2.5, 3.0], dtype=np.float32)
        plan = wirecodec.plan_value_encoding(v)
        assert plan.mode == wirecodec.VALUE_PLANES
        assert plan.scale == 0.5

    def test_uniform_floats_fall_back_to_raw(self):
        rng = np.random.default_rng(0)
        v = rng.uniform(0, 5, 10_000).astype(np.float32)
        plan = wirecodec.plan_value_encoding(v)
        assert plan.mode == wirecodec.VALUE_F32

    def test_nan_falls_back_to_raw(self):
        v = np.array([1.0, np.nan, 3.0], dtype=np.float32)
        assert wirecodec.plan_value_encoding(v).mode == wirecodec.VALUE_F32

    def test_none_and_f16(self):
        assert (wirecodec.plan_value_encoding(None).mode
                == wirecodec.VALUE_NONE)
        v = np.array([1.25], dtype=np.float32)
        assert (wirecodec.plan_value_encoding(v, value_f16=True).mode
                == wirecodec.VALUE_F16)

    def test_planes_reconstruction_is_bit_exact_by_construction(self):
        # Decimal scale 0.1 is NOT exactly representable; the plan is only
        # chosen when the f32 round-trip is verified exact.
        v = (np.arange(100, dtype=np.float64) * 0.1).astype(np.float32)
        plan = wirecodec.plan_value_encoding(v)
        if plan.mode == wirecodec.VALUE_PLANES:
            idx = np.rint((v.astype(np.float64) - plan.lo)
                          / plan.scale)
            rec = (np.float32(plan.lo)
                   + idx.astype(np.float32) * np.float32(plan.scale))
            np.testing.assert_array_equal(rec, v)


@pytest.mark.parametrize("value_kind",
                         ["ratings", "halfstar", "uniform", "none"])
def test_roundtrip_exact(value_kind):
    n, n_users, n_parts, k = 20_000, 700, 300, 5
    pid, pk, value = _random_columns(n, n_users, n_parts, value_kind)
    plan = wirecodec.plan_value_encoding(value)
    slab, n_rows, n_uniq, fmt = wirecodec.encode_buckets_numpy(
        pid, pk, value, pid_lo=0, k=k, bytes_pid=2,
        bits_pk=max(1, (n_parts - 1).bit_length()), plan=plan)
    dpid, dpk, dval = _decode_all(slab, n_rows, n_uniq, fmt)
    assert int(n_rows.sum()) == n

    # Same multiset of rows: sort both sides by (pid, pk, value).
    def canon(p, q, v):
        v = np.zeros_like(p, dtype=np.float64) if v is None else v
        order = np.lexsort((v, q, p))
        return p[order], q[order], v[order]

    a = canon(pid.astype(np.int64), pk.astype(np.int64),
              None if value is None else value.astype(np.float64))
    b = canon(dpid.astype(np.int64), dpk.astype(np.int64),
              None if dval is None else dval.astype(np.float64))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_rows_arrive_pid_sorted_within_bucket():
    pid, pk, value = _random_columns(5_000, 50, 64, "ratings", seed=3)
    plan = wirecodec.plan_value_encoding(value)
    slab, n_rows, n_uniq, fmt = wirecodec.encode_buckets_numpy(
        pid, pk, value, pid_lo=0, k=3, bytes_pid=1, bits_pk=6, plan=plan)
    for c in range(3):
        dpid, _, _, _ = wirecodec.decode_bucket(
            jnp.asarray(slab[c]), int(n_rows[c]), int(n_uniq[c]), fmt)
        got = np.asarray(dpid)[:int(n_rows[c])]
        assert np.all(np.diff(got) >= 0)


def test_run_split_long_runs():
    # One pid with 200k rows: runs must split at 65535 and decode exactly.
    n = 200_000
    pid = np.zeros(n, dtype=np.int32)
    pk = np.arange(n, dtype=np.int32) % 7
    plan = wirecodec.plan_value_encoding(None)
    slab, n_rows, n_uniq, fmt = wirecodec.encode_buckets_numpy(
        pid, pk, None, pid_lo=0, k=2, bytes_pid=1, bits_pk=3, plan=plan)
    dpid, dpk, _ = _decode_all(slab, n_rows, n_uniq, fmt)
    assert len(dpid) == n
    assert np.all(dpid == 0)
    np.testing.assert_array_equal(np.bincount(dpk, minlength=7),
                                  np.bincount(pk, minlength=7))


@pytest.mark.parametrize("value_kind", ["ratings", "uniform", "none"])
def test_native_matches_numpy_bit_identically(value_kind):
    from pipelinedp_tpu.native import loader
    lib = loader.load_row_packer()
    if lib is None or not hasattr(lib, "pdp_rle_prep"):
        pytest.skip("native row packer unavailable")
    n, n_users, n_parts, k = 30_000, 900, 500, 6
    pid, pk, value = _random_columns(n, n_users, n_parts, value_kind,
                                     seed=7)
    plan = wirecodec.plan_value_encoding(value)
    kw = dict(pid_lo=0, k=k, bytes_pid=2,
              bits_pk=max(1, (n_parts - 1).bit_length()), plan=plan)
    nat = wirecodec.encode_buckets_native(pid, pk, value, **kw)
    assert nat is not None
    ref = wirecodec.encode_buckets_numpy(pid, pk, value, **kw)
    slab_n, rows_n, uniq_n, fmt_n = nat
    slab_r, rows_r, uniq_r, fmt_r = ref
    np.testing.assert_array_equal(rows_n, rows_r)
    np.testing.assert_array_equal(uniq_n, uniq_r)
    assert fmt_n.ucap == fmt_r.ucap and fmt_n.cap >= fmt_r.cap
    if fmt_n.cap == fmt_r.cap:
        np.testing.assert_array_equal(slab_n, slab_r)
    else:
        # Different row capacity (native pads by a heuristic): compare the
        # decoded rows instead.
        a = _decode_all(slab_n, rows_n, uniq_n, fmt_n)
        b = _decode_all(slab_r, rows_r, uniq_r, fmt_r)
        for x, y in zip(a, b):
            if x is None:
                assert y is None
            else:
                np.testing.assert_array_equal(x, y)


def test_f16_mode_matches_legacy_lossy_cast():
    pid, pk, value = _random_columns(4_000, 80, 32, "uniform", seed=9)
    plan = wirecodec.plan_value_encoding(value, value_f16=True)
    assert plan.mode == wirecodec.VALUE_F16
    slab, n_rows, n_uniq, fmt = wirecodec.encode_buckets_numpy(
        pid, pk, value, pid_lo=0, k=2, bytes_pid=1, bits_pk=5, plan=plan)
    _, _, dval = _decode_all(slab, n_rows, n_uniq, fmt)
    np.testing.assert_array_equal(np.sort(dval),
                                  np.sort(value.astype(np.float16)
                                          .astype(np.float32)))


class TestStreamingEncodings:
    """The streamed kernel must produce identical results under the codec
    and the legacy byte packing when contribution bounding does not bind
    (no sampling randomness -> deterministic accumulators)."""

    @pytest.mark.parametrize("value_kind", ["ratings", "uniform"])
    def test_rle_equals_bytes_when_caps_do_not_bind(self, value_kind):
        import jax
        n, n_users, n_parts = 30_000, 3_000, 40
        pid, pk, value = _random_columns(n, n_users, n_parts, value_kind,
                                         seed=11)
        key = jax.random.PRNGKey(0)
        kw = dict(num_partitions=n_parts, linf_cap=10**9, l0_cap=n_parts,
                  row_clip_lo=0.0, row_clip_hi=10.0, middle=5.0,
                  group_clip_lo=-np.inf, group_clip_hi=np.inf,
                  n_chunks=4, has_group_clip=False)
        a = streaming.stream_bound_and_aggregate(
            key, pid, pk, value, transfer_encoding="rle", **kw)
        b = streaming.stream_bound_and_aggregate(
            key, pid, pk, value, transfer_encoding="bytes", **kw)
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-6, atol=1e-4)

    def test_rle_count_exact_vs_bytes(self):
        # COUNT-style (value None): integer accumulators, exact equality.
        import jax
        n = 25_000
        rng = np.random.default_rng(5)
        pid = rng.integers(0, 2_000, n, dtype=np.int32)
        pk = rng.integers(0, 30, n, dtype=np.int32)
        key = jax.random.PRNGKey(1)
        kw = dict(num_partitions=30, linf_cap=10**9, l0_cap=30,
                  row_clip_lo=0.0, row_clip_hi=1.0, middle=0.5,
                  group_clip_lo=-np.inf, group_clip_hi=np.inf,
                  n_chunks=3, has_group_clip=False,
                  need_flags=(True, False, False, False))
        a = streaming.stream_bound_and_aggregate(
            key, pid, pk, None, transfer_encoding="rle", **kw)
        b = streaming.stream_bound_and_aggregate(
            key, pid, pk, None, transfer_encoding="bytes", **kw)
        np.testing.assert_array_equal(np.asarray(a.count),
                                      np.asarray(b.count))
        np.testing.assert_array_equal(np.asarray(a.pid_count),
                                      np.asarray(b.pid_count))

    def test_rle_bounded_sampling_statistics(self):
        # With binding caps the two encodings differ only by the sampling
        # permutation; totals must respect the caps and match closely.
        import jax
        n, n_users, n_parts = 40_000, 400, 50
        pid, pk, value = _random_columns(n, n_users, n_parts, "ratings",
                                         seed=13)
        key = jax.random.PRNGKey(2)
        kw = dict(num_partitions=n_parts, linf_cap=3, l0_cap=5,
                  row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                  group_clip_lo=-np.inf, group_clip_hi=np.inf,
                  n_chunks=4, has_group_clip=False)
        a = streaming.stream_bound_and_aggregate(
            key, pid, pk, value, transfer_encoding="rle", **kw)
        total = float(np.asarray(a.count).sum())
        assert total <= n_users * 3 * 5
        assert total > 0
        b = streaming.stream_bound_and_aggregate(
            key, pid, pk, value, transfer_encoding="bytes", **kw)
        total_b = float(np.asarray(b.count).sum())
        assert abs(total - total_b) / total_b < 0.02


class TestInlineVerification:
    """make_encoder verifies the affine value plan inside the native prep
    pass; a sample that looks integral but a full array that is not must
    fall back losslessly."""

    def test_sample_integral_full_not(self):
        n = 200_000
        rng = np.random.default_rng(2)
        pid = rng.integers(0, 5_000, n, dtype=np.int32)
        pk = rng.integers(0, 64, n, dtype=np.int32)
        value = rng.integers(1, 6, n).astype(np.float32)
        value[150_000:] = rng.uniform(0, 5, 50_000).astype(np.float32)
        enc, info = wirecodec.make_encoder(
            pid, pk, value, num_partitions=64, k=4)
        plan, bytes_pid, bits_pk = info.plan, info.bytes_pid, info.bits_pk
        # The 64k sample is integral, the tail is not: the plan must end
        # raw (either via inline-verify failure or host verification).
        assert plan.mode == wirecodec.VALUE_F32
        if enc is None:
            pytest.skip("native encoder unavailable")
        with enc:
            nu = enc.sort_range(0, 4)
            fmt = wirecodec.WireFormat(
                bytes_pid=bytes_pid, bits_pk=bits_pk,
                cap=wirecodec._round8(int(enc.counts.max())),
                ucap=wirecodec.round_ucap(int(nu.max())), value=plan)
            slab = enc.emit_range(0, 4, fmt)
        # Decode must reproduce the values bit-exactly despite the mixed
        # content.
        vals = []
        for c in range(4):
            _, _, v, _ = wirecodec.decode_bucket(
                jnp.asarray(slab[c]), int(enc.counts[c]), int(nu[c]), fmt)
            vals.append(np.asarray(v)[:int(enc.counts[c])])
        got = np.sort(np.concatenate(vals))
        np.testing.assert_array_equal(got, np.sort(value))

    def test_inline_bits_match_full_range(self):
        # Sample max is 5 but the full array reaches 900: the inline path
        # must size the planes from the TRUE max index.
        n = 100_000
        rng = np.random.default_rng(3)
        pid = rng.integers(0, 2_000, n, dtype=np.int32)
        pk = rng.integers(0, 32, n, dtype=np.int32)
        value = rng.integers(1, 6, n).astype(np.float32)
        value[90_000:] = rng.integers(100, 901, 10_000).astype(np.float32)
        enc, info = wirecodec.make_encoder(
            pid, pk, value, num_partitions=32, k=4)
        plan, bytes_pid, bits_pk = info.plan, info.bytes_pid, info.bits_pk
        if enc is None:
            pytest.skip("native encoder unavailable")
        assert plan.mode == wirecodec.VALUE_PLANES
        assert plan.bits >= 10  # max idx 899 -> 10 bits
        with enc:
            nu = enc.sort_range(0, 4)
            fmt = wirecodec.WireFormat(
                bytes_pid=bytes_pid, bits_pk=bits_pk,
                cap=wirecodec._round8(int(enc.counts.max())),
                ucap=wirecodec.round_ucap(int(nu.max())), value=plan)
            slab = enc.emit_range(0, 4, fmt)
        vals = []
        for c in range(4):
            _, _, v, _ = wirecodec.decode_bucket(
                jnp.asarray(slab[c]), int(enc.counts[c]), int(nu[c]), fmt)
            vals.append(np.asarray(v)[:int(enc.counts[c])])
        np.testing.assert_array_equal(np.sort(np.concatenate(vals)),
                                      np.sort(value))


class TestCodecWidthEdges:
    """Byte/bit-width edges: wide privacy-id spans (4-byte ids), negative
    affine values (lo < 0), and >20-bit partition vocabularies."""

    def test_wide_pid_span_roundtrip(self):
        n = 30_000
        rng = np.random.default_rng(21)
        pid = rng.integers(0, 1 << 25, n, dtype=np.int64)  # 4-byte span
        pk = rng.integers(0, 100, n, dtype=np.int32)
        value = rng.integers(-3, 4, n).astype(np.float32)  # lo = -3
        import jax
        kw = dict(num_partitions=100, linf_cap=10**9, l0_cap=100,
                  row_clip_lo=-5.0, row_clip_hi=5.0, middle=0.0,
                  group_clip_lo=-np.inf, group_clip_hi=np.inf,
                  n_chunks=3, has_group_clip=False)
        a = streaming.stream_bound_and_aggregate(
            jax.random.PRNGKey(0), pid, pk, value,
            transfer_encoding="rle", **kw)
        truth_cnt = np.bincount(pk, minlength=100)
        truth_sum = np.zeros(100)
        np.add.at(truth_sum, pk, value)
        np.testing.assert_array_equal(np.asarray(a.count), truth_cnt)
        np.testing.assert_allclose(np.asarray(a.sum), truth_sum, atol=1e-3)

    def test_negative_affine_values_get_planes(self):
        v = np.array([-3, -1, 0, 2, 3], dtype=np.float32)
        plan = wirecodec.plan_value_encoding(v)
        assert plan.mode == wirecodec.VALUE_PLANES
        assert plan.lo == -3.0

    def test_wide_partition_vocabulary(self):
        # 21-bit pk ids through the full encode/decode.
        n = 20_000
        rng = np.random.default_rng(5)
        pid = rng.integers(0, 1_000, n, dtype=np.int32)
        pk = rng.integers(0, 1 << 21, n, dtype=np.int32)
        plan = wirecodec.plan_value_encoding(None)
        slab, n_rows, n_uniq, fmt = wirecodec.encode_buckets_numpy(
            pid, pk, None, pid_lo=0, k=3, bytes_pid=2, bits_pk=21,
            plan=plan)
        dpid, dpk, _ = _decode_all(slab, n_rows, n_uniq, fmt)
        np.testing.assert_array_equal(np.sort(dpk), np.sort(pk))

    def test_make_encoder_wide_span_native_matches_numpy(self):
        from pipelinedp_tpu.native import loader
        if loader.load_row_packer() is None:
            pytest.skip("native unavailable")
        n = 25_000
        rng = np.random.default_rng(8)
        pid = (rng.integers(0, 1 << 25, n, dtype=np.int64)
               + (1 << 27))  # nonzero pid_lo, 4-byte span
        pk = rng.integers(0, 500, n, dtype=np.int32)
        value = (rng.integers(-6, 7, n) * 0.5).astype(np.float32)
        enc, info = wirecodec.make_encoder(
            pid, pk, value, num_partitions=500, k=4)
        plan, pid_lo = info.plan, info.pid_lo
        bytes_pid, bits_pk = info.bytes_pid, info.bits_pk
        assert enc is not None and plan.mode == wirecodec.VALUE_PLANES
        with enc:
            nu = enc.sort_range(0, 4)
            fmt = wirecodec.WireFormat(
                bytes_pid=bytes_pid, bits_pk=bits_pk,
                cap=wirecodec._round8(int(enc.counts.max())),
                ucap=wirecodec._round8(int(nu.max())), value=plan)
            slab_n = enc.emit_range(0, 4, fmt)
        full_plan, full_vidx = wirecodec.plan_and_index(value)
        slab_r, rows_r, uniq_r, fmt_r = wirecodec.encode_buckets_numpy(
            pid, pk, value, pid_lo=pid_lo, k=4, bytes_pid=bytes_pid,
            bits_pk=bits_pk, plan=full_plan)
        np.testing.assert_array_equal(nu, uniq_r)
        np.testing.assert_array_equal(enc.counts, rows_r)
        assert fmt.cap == fmt_r.cap and plan == full_plan
        assert fmt.ucap == fmt_r.ucap  # _round8 of equal maxima
        np.testing.assert_array_equal(slab_n, slab_r)


class TestPidPlanesMode:
    """The unsorted pid bit-plane wire mode: chosen automatically when
    near-unique privacy ids make RLE a net loss, skips the host radix sort
    entirely, and must stay exact (the device kernel sorts anyway)."""

    def test_unique_pids_choose_planes(self):
        n = 50_000
        rng = np.random.default_rng(0)
        pid = rng.permutation(n).astype(np.int32)
        pk = rng.integers(0, 100, n).astype(np.int32)
        enc, info = wirecodec.make_encoder(pid, pk, None,
                                           num_partitions=100, k=4)
        assert info.pid_mode == wirecodec.PID_PLANES
        if enc is not None:
            enc.close()

    def test_repetitive_pids_choose_rle(self):
        n = 50_000
        rng = np.random.default_rng(0)
        pid = rng.integers(0, n // 20, n).astype(np.int32)  # ~20 rows/user
        pk = rng.integers(0, 100, n).astype(np.int32)
        enc, info = wirecodec.make_encoder(pid, pk, None,
                                           num_partitions=100, k=4)
        assert info.pid_mode == wirecodec.PID_RLE
        if enc is not None:
            enc.close()

    def test_planes_native_matches_numpy_bit_identically(self):
        from pipelinedp_tpu.native import loader
        if loader.load_row_packer() is None:
            pytest.skip("native unavailable")
        n = 40_000
        rng = np.random.default_rng(3)
        pid = rng.permutation(n).astype(np.int32) + 5
        pk = rng.integers(0, 700, n).astype(np.int32)
        value = rng.uniform(-2, 2, n).astype(np.float32)
        enc, info = wirecodec.make_encoder(pid, pk, value,
                                           num_partitions=700, k=4)
        assert enc is not None and info.pid_mode == wirecodec.PID_PLANES
        with enc:
            fmt = wirecodec.WireFormat(
                bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                cap=wirecodec._round8(int(enc.counts.max())), ucap=8,
                value=info.plan, pid_mode=wirecodec.PID_PLANES,
                bits_pid=info.bits_pid)
            slab = enc.emit_range(0, 4, fmt)  # no sort_range call at all
            counts = enc.counts
        ref_slab, ref_counts, _, ref_fmt = wirecodec.encode_buckets_numpy(
            pid, pk, value, pid_lo=info.pid_lo, k=4,
            bytes_pid=info.bytes_pid, bits_pk=info.bits_pk, plan=info.plan,
            pid_mode=wirecodec.PID_PLANES, bits_pid=info.bits_pid)
        assert ref_fmt == fmt
        np.testing.assert_array_equal(ref_counts, counts)
        np.testing.assert_array_equal(ref_slab, slab)

    def test_planes_streamed_matches_groupby(self):
        import jax
        n = 60_000
        rng = np.random.default_rng(5)
        pid = rng.permutation(n).astype(np.int64)  # unique -> planes
        pk = rng.integers(0, 150, n).astype(np.int32)
        value = rng.uniform(0, 5, n).astype(np.float32)
        accs = streaming.stream_bound_and_aggregate(
            jax.random.PRNGKey(0), pid, pk, value, num_partitions=150,
            linf_cap=n, l0_cap=150, row_clip_lo=-np.inf,
            row_clip_hi=np.inf, middle=0.0, group_clip_lo=-np.inf,
            group_clip_hi=np.inf, n_chunks=3, has_group_clip=False)
        np.testing.assert_allclose(np.asarray(accs.count),
                                   np.bincount(pk, minlength=150))
        truth = np.zeros(150)
        np.add.at(truth, pk, value)
        np.testing.assert_allclose(np.asarray(accs.sum), truth, rtol=1e-4)


class TestSortednessInvariant:
    """The pid-sorted wire order is load-bearing end to end: decode must
    produce nondecreasing pids (including the padding suffix), and the
    prep-time analytic RLE entry counts must equal the post-sort truth —
    the invariant that lets the radix sort join the transfer pipeline."""

    def test_decoded_rows_nondecreasing_with_padding(self):
        n = 30_000
        rng = np.random.default_rng(2)
        pid = rng.integers(50, 2_000, n).astype(np.int32)
        pk = rng.integers(0, 64, n).astype(np.int32)
        plan = wirecodec.plan_value_encoding(None)
        slab, n_rows, n_uniq, fmt = wirecodec.encode_buckets_numpy(
            pid, pk, None, pid_lo=50, k=4, bytes_pid=2, bits_pk=6,
            plan=plan)
        for c in range(4):
            p, _, _, valid = wirecodec.decode_bucket(
                jnp.asarray(slab[c]), int(n_rows[c]), int(n_uniq[c]), fmt)
            p = np.asarray(p)
            # Nondecreasing over the FULL padded row range, not just the
            # valid prefix — the presorted kernel sorts padding via its
            # all-ones keys but the decode contract is stronger.
            assert np.all(np.diff(p) >= 0)
            assert np.asarray(valid).sum() == n_rows[c]

    def test_entry_counts_numpy_matches_sorted_truth(self):
        n = 25_000
        rng = np.random.default_rng(4)
        pid = rng.integers(0, 3_000, n).astype(np.int64)
        span = int(pid.max() - pid.min())
        entries = wirecodec.rle_entry_counts_numpy(pid, int(pid.min()), 8,
                                                   span)
        assert entries is not None
        _, _, n_uniq, _ = wirecodec.encode_buckets_numpy(
            pid, np.zeros(n, np.int32), None, pid_lo=int(pid.min()), k=8,
            bytes_pid=2, bits_pk=1, plan=wirecodec.plan_value_encoding(None))
        np.testing.assert_array_equal(entries, n_uniq)

    def test_entry_counts_account_for_run_splits(self):
        # 70k rows of ONE pid: RLE must split at 65535 -> 2 entries.
        pid = np.zeros(70_000, dtype=np.int64)
        entries = wirecodec.rle_entry_counts_numpy(pid, 0, 2, 0)
        assert entries is not None and int(entries.sum()) == 2

    def test_native_entry_counts_match_sort(self):
        from pipelinedp_tpu.native import loader
        if loader.load_row_packer() is None:
            pytest.skip("native unavailable")
        n = 80_000
        rng = np.random.default_rng(6)
        pid = rng.integers(10, 4_000, n).astype(np.int32)
        pk = rng.integers(0, 32, n).astype(np.int32)
        enc, info = wirecodec.make_encoder(pid, pk, None,
                                           num_partitions=32, k=6)
        assert enc is not None and enc.entry_counts is not None
        with enc:
            np.testing.assert_array_equal(enc.sort_range(0, 6),
                                          enc.entry_counts)

    def test_huge_span_disables_entry_counts(self):
        pid = np.array([0, 1 << 30], dtype=np.int64)
        assert wirecodec.rle_entry_counts_numpy(pid, 0, 2, 1 << 30) is None


class TestAdversarialStreamedInputs:
    """Hostile inputs through the full streamed path."""

    def _stream(self, pid, pk, value, P, **kw):
        import jax
        args = dict(num_partitions=P, linf_cap=len(pid), l0_cap=P,
                    row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
                    group_clip_lo=-np.inf, group_clip_hi=np.inf,
                    n_chunks=3, has_group_clip=False)
        args.update(kw)
        return streaming.stream_bound_and_aggregate(
            jax.random.PRNGKey(0), pid, pk, value, **args)

    def test_nan_inf_values_roundtrip(self):
        n = 10_000
        rng = np.random.default_rng(0)
        pid = rng.integers(0, 500, n).astype(np.int32)
        pk = rng.integers(1, 8, n).astype(np.int32)  # partition 0 clean
        value = rng.uniform(0, 1, n).astype(np.float32)
        value[::7] = np.nan
        value[1::7] = np.inf
        value[2::7] = -np.inf
        pk[:100] = 0
        value[:100] = 1.0  # partition 0 gets only finite values
        accs = self._stream(pid, pk, value, 8)
        # Counts never touch the value column: exact despite NaN/Inf.
        np.testing.assert_allclose(np.asarray(accs.count),
                                   np.bincount(pk, minlength=8))
        # The clean partition's sum is exact; poisoned partitions
        # propagate their NaN/Inf honestly instead of corrupting others.
        assert float(np.asarray(accs.sum)[0]) == 100.0

    def test_empty_and_singleton_partitions(self):
        # Public partitions 0..9; data only in partitions {3} (many rows)
        # and {7} (exactly one row). Streamed == single-shot == truth.
        import pipelinedp_tpu as pdp
        pid = np.concatenate([np.arange(200), [999]]).astype(np.int64)
        pk = np.concatenate([np.full(200, 3), [7]]).astype(np.int32)
        value = np.concatenate([np.ones(200), [2.5]]).astype(np.float32)

        def run(chunks):
            accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
            engine = pdp.JaxDPEngine(accountant, seed=5,
                                     stream_chunks=chunks,
                                     secure_host_noise=False)
            params = pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                max_partitions_contributed=10,
                max_contributions_per_partition=10,
                min_value=0.0, max_value=5.0)
            result = engine.aggregate(
                pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
                public_partitions=list(range(10)))
            accountant.compute_budgets()
            return result.to_columns()

        single, streamed = run(1), run(3)
        np.testing.assert_allclose(streamed["count"],
                                   np.bincount(pk, minlength=10), atol=0.01)
        np.testing.assert_allclose(single["count"], streamed["count"],
                                   atol=0.01)
        assert streamed["sum"][7] == pytest.approx(2.5, abs=0.01)
        assert streamed["count"][0] == pytest.approx(0.0, abs=0.01)

    def test_duplicate_public_partition_keys_collapse(self):
        # A public partition list with duplicate keys must not double the
        # output vocabulary (vocab collision hygiene).
        import pipelinedp_tpu as pdp
        pid = np.arange(50, dtype=np.int64)
        pk = np.zeros(50, dtype=np.int32)
        value = np.ones(50, dtype=np.float32)
        accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=1,
                                 secure_host_noise=False)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=1.0)
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=[0, 1, 1, 0, 2])
        accountant.compute_budgets()
        cols = result.to_columns()
        assert len(cols["partition_id"]) == 3
        assert cols["count"][0] == pytest.approx(50.0, abs=0.01)

    def test_all_rows_one_pid_rle_run_split_streamed(self):
        # One privacy id with 70k rows forces uint16 run splitting inside
        # a single bucket; exactness must survive.
        n = 70_000
        pid = np.full(n, 42, dtype=np.int64)
        pk = (np.arange(n) % 5).astype(np.int32)
        value = np.ones(n, dtype=np.float32)
        accs = self._stream(pid, pk, value, 5, n_chunks=2)
        np.testing.assert_allclose(np.asarray(accs.count),
                                   np.bincount(pk, minlength=5))
