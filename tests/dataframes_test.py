"""QueryBuilder / Query tests.

Mirrors the intent of the reference's dataframes tests (query validation +
end-to-end runs with effectively-no-noise budgets), on pandas frames and
dict-of-column frames instead of Spark DataFrames.
"""

import numpy as np
import pandas as pd
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import dataframes


def _visits_df():
    # 30 users; each visits day 1 and day 2 once, spending 10 + user%3.
    rows = []
    for user in range(30):
        for day in (1, 2):
            rows.append((user, day, 10.0 + user % 3))
    return pd.DataFrame(rows, columns=["user_id", "day", "spent"])


HUGE = dataframes.Budget(epsilon=1e8, delta=1 - 1e-12)


class TestQueryBuilderValidation:

    def test_unknown_privacy_column(self):
        with pytest.raises(ValueError, match="not present"):
            pdp.QueryBuilder(_visits_df(), "nope")

    def test_unknown_groupby_column(self):
        with pytest.raises(ValueError, match="not present"):
            pdp.QueryBuilder(_visits_df(), "user_id").groupby(
                "nope", max_groups_contributed=1,
                max_contributions_per_group=1)

    def test_groupby_twice(self):
        builder = pdp.QueryBuilder(_visits_df(), "user_id").groupby(
            "day", max_groups_contributed=1, max_contributions_per_group=1)
        with pytest.raises(ValueError, match="only once"):
            builder.groupby("day", max_groups_contributed=1,
                            max_contributions_per_group=1)

    def test_aggregation_before_groupby(self):
        with pytest.raises(NotImplementedError, match="groupby"):
            pdp.QueryBuilder(_visits_df(), "user_id").count()

    def test_no_aggregations(self):
        with pytest.raises(ValueError, match="No aggregations"):
            pdp.QueryBuilder(_visits_df(), "user_id").groupby(
                "day", max_groups_contributed=1,
                max_contributions_per_group=1).build_query()

    def test_duplicate_aggregation(self):
        with pytest.raises(ValueError, match="only once"):
            (pdp.QueryBuilder(_visits_df(), "user_id").groupby(
                "day", max_groups_contributed=1,
                max_contributions_per_group=1).count().count().build_query())

    def test_missing_caps(self):
        with pytest.raises(ValueError, match="min_value and max_value"):
            (pdp.QueryBuilder(_visits_df(), "user_id").groupby(
                "day", max_groups_contributed=1,
                max_contributions_per_group=1).sum("spent").build_query())

    def test_conflicting_caps(self):
        with pytest.raises(ValueError, match="must be the same"):
            (pdp.QueryBuilder(_visits_df(), "user_id").groupby(
                "day", max_groups_contributed=1,
                max_contributions_per_group=1).sum(
                    "spent", min_value=0,
                    max_value=20).mean("spent", min_value=0,
                                       max_value=30).build_query())

    def test_two_value_columns(self):
        df = _visits_df()
        df["other"] = 1.0
        with pytest.raises(NotImplementedError, match="one column"):
            (pdp.QueryBuilder(df, "user_id").groupby(
                "day", max_groups_contributed=1,
                max_contributions_per_group=1).sum(
                    "spent", min_value=0,
                    max_value=20).mean("other").build_query())


class TestRunQuery:

    @pytest.mark.parametrize("engine", ["jax", "local"])
    def test_count_sum_mean_public_keys(self, engine):
        df = _visits_df()
        query = (pdp.QueryBuilder(df, "user_id").groupby(
            "day",
            max_groups_contributed=2,
            max_contributions_per_group=1,
            public_keys=[1, 2, 3]).count().sum(
                "spent", min_value=0.0,
                max_value=20.0).mean("spent").build_query())
        out = query.run_query(HUGE, engine=engine)
        assert sorted(out["day"].tolist()) == [1, 2, 3]
        by_day = {d: i for i, d in enumerate(out["day"].tolist())}
        # 30 visits each real day, none on day 3 (noise-only).
        assert out["count"][by_day[1]] == pytest.approx(30, abs=0.5)
        assert out["count"][by_day[2]] == pytest.approx(30, abs=0.5)
        assert out["count"][by_day[3]] == pytest.approx(0, abs=0.5)
        expected_sum = sum(10.0 + u % 3 for u in range(30))
        assert out["sum"][by_day[1]] == pytest.approx(expected_sum, rel=0.01)
        assert out["mean"][by_day[1]] == pytest.approx(expected_sum / 30,
                                                       rel=0.01)

    def test_private_selection_keeps_dense_days(self):
        df = _visits_df()
        query = (pdp.QueryBuilder(df, "user_id").groupby(
            "day", max_groups_contributed=2,
            max_contributions_per_group=1).count().build_query())
        out = query.run_query(dataframes.Budget(epsilon=50, delta=1e-4))
        assert set(out["day"].tolist()) == {1, 2}

    def test_output_column_names(self):
        df = _visits_df()
        query = (pdp.QueryBuilder(df, "user_id").groupby(
            "day",
            max_groups_contributed=2,
            max_contributions_per_group=1,
            public_keys=[1, 2]).count(name="n_visits").privacy_id_count(
                name="n_users").build_query())
        out = query.run_query(HUGE)
        assert set(out.columns) == {"day", "n_visits", "n_users"}
        assert out["n_users"].max() == pytest.approx(30, abs=0.5)

    def test_multi_column_groupby(self):
        rows = []
        for user in range(25):
            rows.append((user, "a", 1, 5.0))
            rows.append((user, "b", 1, 7.0))
        df = pd.DataFrame(rows, columns=["user_id", "site", "day", "spent"])
        query = (pdp.QueryBuilder(df, "user_id").groupby(
            ["site", "day"],
            max_groups_contributed=2,
            max_contributions_per_group=1,
            public_keys=[("a", 1), ("b", 1), ("c", 2)]).count().build_query())
        out = query.run_query(HUGE)
        assert set(out.columns) == {"site", "day", "count"}
        assert sorted(zip(out["site"], out["day"])) == [("a", 1), ("b", 1),
                                                        ("c", 2)]
        lookup = {(s, d): c
                  for s, d, c in zip(out["site"], out["day"], out["count"])}
        assert lookup[("a", 1)] == pytest.approx(25, abs=0.5)
        assert lookup[("c", 2)] == pytest.approx(0, abs=0.5)

    def test_dict_frame(self):
        data = {
            "user": np.arange(40) % 20,
            "shop": np.arange(40) % 2,
            "spent": np.full(40, 3.0),
        }
        # User u owns rows u and u+20, both in shop u%2: 10 users per shop,
        # 2 contributions each.
        query = (pdp.QueryBuilder(data, "user").groupby(
            "shop",
            max_groups_contributed=2,
            max_contributions_per_group=2,
            public_keys=[0, 1]).sum("spent", min_value=0,
                                    max_value=5).build_query())
        out = query.run_query(HUGE)
        assert isinstance(out, dict)
        assert out["sum"].shape == (2,)
        np.testing.assert_allclose(out["sum"], [60.0, 60.0], atol=1.0)

    def test_percentile_and_variance(self):
        rng = np.random.default_rng(0)
        df = pd.DataFrame({
            "user": np.arange(400),
            "g": np.zeros(400, dtype=int),
            "v": rng.uniform(0, 10, 400),
        })
        query = (pdp.QueryBuilder(df, "user").groupby(
            "g",
            max_groups_contributed=1,
            max_contributions_per_group=1,
            public_keys=[0]).variance("v", min_value=0.0,
                                      max_value=10.0).build_query())
        out = query.run_query(HUGE)
        assert out["variance"][0] == pytest.approx(np.var(df["v"]), abs=1.5)
