"""Profiler hooks: traces capture the engine's named stages."""

import glob
import os

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler


class TestProfiler:

    def test_stage_is_noop_without_trace(self):
        with profiler.stage("anything"):
            x = 1 + 1
        assert x == 2

    def test_profile_captures_engine_trace(self, tmp_path):
        logdir = str(tmp_path / "trace")
        rng = np.random.default_rng(0)
        data = pdp.ColumnarData(pid=rng.integers(0, 100, 2000),
                                pk=rng.integers(0, 5, 2000),
                                value=rng.uniform(0, 1, 2000))
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=5,
                                     max_contributions_per_partition=50)
        with profiler.profile(logdir):
            accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
            engine = pdp.JaxDPEngine(accountant)
            result = engine.aggregate(data, params,
                                      public_partitions=list(range(5)))
            accountant.compute_budgets()
            result.to_columns()
        traces = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                           recursive=True)
        assert traces, f"no trace files under {logdir}"
