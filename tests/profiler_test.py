"""Profiler hooks: traces capture the engine's named stages."""

import glob
import os

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler


class TestProfiler:

    def test_stage_is_noop_without_trace(self):
        with profiler.stage("anything"):
            x = 1 + 1
        assert x == 2

    def test_profile_captures_engine_trace(self, tmp_path):
        logdir = str(tmp_path / "trace")
        rng = np.random.default_rng(0)
        data = pdp.ColumnarData(pid=rng.integers(0, 100, 2000),
                                pk=rng.integers(0, 5, 2000),
                                value=rng.uniform(0, 1, 2000))
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=5,
                                     max_contributions_per_partition=50)
        with profiler.profile(logdir):
            accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
            engine = pdp.JaxDPEngine(accountant)
            result = engine.aggregate(data, params,
                                      public_partitions=list(range(5)))
            accountant.compute_budgets()
            result.to_columns()
        traces = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                           recursive=True)
        assert traces, f"no trace files under {logdir}"


class TestThreadSafety:
    """Stage timers and event counters under concurrent recording — the
    encode/prefetch worker pools record from pool threads into the main
    thread's collectors (ISSUE 5 satellite: lock + thread-local sinks)."""

    def test_stage_time_hammer_no_lost_updates(self):
        import threading

        n_threads, n_iters = 8, 5_000
        with profiler.collect_stage_times() as sink:
            sinks = profiler.current_sinks()

            def worker():
                for _ in range(n_iters):
                    profiler._add_stage_time(sinks, "hammer", 1.0)

            threads = [threading.Thread(target=worker)
                       for _ in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        # Exactly one update per call: lost updates would undercount.
        assert sink["hammer"] == float(n_threads * n_iters)

    def test_event_count_hammer(self):
        import threading

        profiler.reset_events("test/")
        n_threads, n_iters = 8, 5_000

        def worker():
            for _ in range(n_iters):
                profiler.count_event("test/hammer")

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert profiler.event_count("test/hammer") == n_threads * n_iters
        profiler.reset_events("test/")

    def test_adopt_sinks_merges_worker_stages(self):
        import threading

        with profiler.collect_stage_times() as sink:
            parent_sinks = profiler.current_sinks()

            def worker():
                with profiler.adopt_sinks(parent_sinks):
                    with profiler.stage("worker_stage"):
                        pass

            t = threading.Thread(target=worker)
            t.start()
            t.join()
            # The worker thread's stage landed in the parent's sink; the
            # worker's thread-local state was restored on exit.
            assert "worker_stage" in sink
            assert sink["worker_stage"] >= 0.0

    def test_adopt_sinks_restores_previous(self):
        with profiler.collect_stage_times() as outer:
            with profiler.adopt_sinks([{}]):
                pass
            with profiler.stage("after_adopt"):
                pass
        assert "after_adopt" in outer


class TestDebugLocks:
    """PIPELINEDP_TPU_DEBUG_LOCKS=1 asserts the sink lock around every
    sink mutation (validated through native.loader.env_int)."""

    def test_debug_locks_assertion_passes_on_locked_path(self, monkeypatch):
        monkeypatch.setenv(profiler.DEBUG_LOCKS_ENV, "1")
        with profiler.collect_stage_times() as sink:
            with profiler.stage("debug_locks_stage"):
                pass
            profiler._add_stage_time(profiler.current_sinks(),
                                     "direct", 0.5)
        assert "debug_locks_stage" in sink
        assert sink["direct"] == 0.5

    def test_debug_locks_off_by_default(self, monkeypatch):
        monkeypatch.delenv(profiler.DEBUG_LOCKS_ENV, raising=False)
        assert profiler._debug_locks() is False

    def test_debug_locks_env_is_validated(self, monkeypatch):
        monkeypatch.setenv(profiler.DEBUG_LOCKS_ENV, "banana")
        with pytest.raises(ValueError, match="DEBUG_LOCKS"):
            profiler._debug_locks()
        monkeypatch.setenv(profiler.DEBUG_LOCKS_ENV, "7")
        with pytest.raises(ValueError, match=r"\[0, 1\]"):
            profiler._debug_locks()
