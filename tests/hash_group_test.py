"""Round-10 sortless hash-binned group stage: parity matrix, adversarial
distributions, overflow demotion, and sampler-identity fingerprints.

The contract pinned here (ISSUE 12 tentpole): ``segment_sort="hash"``
replaces the group stage's sort with one-pass hash binning + keyed-
priority selection. The sampled row multiset is IDENTICAL to the sorted
paths' for the same PRNG key (same salt / truncated-rand draws), and
under the order-exactness gate (``columnar.hash_exact_gate``) released
values are BIT-identical to ``segment_sort=True``/``False`` regardless
of reduction order — across {group-clip, no-clip} x {single-device,
mesh8} x {compact merge on/off}, cold, warm replay and crash-resume.
Outside the gate counts stay exact and sums are ULP-close.

Satellites pinned alongside: the bound cache keys on the RESOLVED
sampler (not the knob string), checkpoints refuse resumes produced
under a different sampler, and the overflow-demotion backstop engages
without changing a bit.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler
from pipelinedp_tpu import runtime
from pipelinedp_tpu.ops import columnar, streaming, wirecodec
from pipelinedp_tpu.parallel import sharded


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


@pytest.fixture(autouse=True)
def _reset_counters():
    profiler.reset_events("ops/")
    yield


def _rle_data(n=60_000, n_parts=300, seed=0, integer_values=True):
    """Repetitive pids (~20 rows/user) -> PID_RLE wire with small
    max_run; integer values -> VALUE_PLANES -> the exactness gate can
    hold."""
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n // 20, n).astype(np.int64)
    pk = rng.integers(0, n_parts, n).astype(np.int32)
    if integer_values:
        value = rng.integers(0, 6, n).astype(np.float32)
    else:
        value = rng.uniform(0, 5, n).astype(np.float32)
    return pid, pk, value


def _stream(pid, pk, value, *, mesh=None, n_parts=300, has_group_clip=True,
            need_flags=(True, True, False, False), **kw):
    clips = (dict(row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
                  group_clip_lo=-30.0, group_clip_hi=30.0)
             if has_group_clip else
             dict(row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                  group_clip_lo=-np.inf, group_clip_hi=np.inf))
    args = (jax.random.PRNGKey(7), pid, pk, value)
    common = dict(num_partitions=n_parts, linf_cap=6, l0_cap=8,
                  has_group_clip=has_group_clip,
                  n_chunks=kw.pop("n_chunks", 8),
                  need_flags=need_flags, **clips, **kw)
    if mesh is not None:
        accs = sharded.stream_bound_and_aggregate(mesh, *args, **common)
    else:
        accs = streaming.stream_bound_and_aggregate(*args, **common)
    return jax.device_get(accs)


def _assert_bitwise(a, b):
    for name, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=name)


class TestHashParityMatrix:
    """segment_sort="hash" vs the round-8 oracle, bitwise, under the
    exactness gate (integer values, COUNT/SUM/PID_COUNT columns)."""

    @pytest.mark.parametrize("has_group_clip", [True, False])
    @pytest.mark.parametrize("compact", [True, False])
    def test_rle_single_device(self, has_group_clip, compact):
        pid, pk, value = _rle_data()
        legacy = _stream(pid, pk, value, has_group_clip=has_group_clip,
                         compact_merge=compact, segment_sort=False)
        profiler.reset_events("ops/")
        hashed = _stream(pid, pk, value, has_group_clip=has_group_clip,
                         compact_merge=compact, segment_sort="hash")
        # Non-vacuous: every chunk ran the sortless stage, whose group
        # stage moves ZERO sort operand bytes.
        assert profiler.event_count(columnar.EVENT_HASH_PASSES) == 8
        assert profiler.event_count(columnar.EVENT_HASH_DEMOTIONS) == 0
        assert profiler.event_count(columnar.EVENT_SORT_BYTES) == 0
        assert profiler.event_count(columnar.EVENT_HASH_OCCUPANCY) > 0
        _assert_bitwise(legacy, hashed)

    @pytest.mark.parametrize("has_group_clip", [True, False])
    @pytest.mark.parametrize("compact", [True, False])
    def test_rle_mesh8(self, mesh, has_group_clip, compact):
        pid, pk, value = _rle_data(n=40_000)
        legacy = _stream(pid, pk, value, mesh=mesh,
                         has_group_clip=has_group_clip,
                         compact_merge=compact, segment_sort=False)
        profiler.reset_events("ops/")
        hashed = _stream(pid, pk, value, mesh=mesh,
                         has_group_clip=has_group_clip,
                         compact_merge=compact, segment_sort="hash")
        assert profiler.event_count(columnar.EVENT_HASH_PASSES) > 0
        assert profiler.event_count(columnar.EVENT_SORT_BYTES) == 0
        _assert_bitwise(legacy, hashed)

    def test_hash_matches_tiled_and_packed(self):
        pid, pk, value = _rle_data(seed=3)
        tiled = _stream(pid, pk, value, segment_sort=True)
        hashed = _stream(pid, pk, value, segment_sort="hash")
        _assert_bitwise(tiled, hashed)

    def test_auto_resolves_to_hash_under_gate(self):
        # COUNT+SUM (no norm columns) over an integer grid: auto must
        # pick the sortless stage and match the forced knob bitwise.
        pid, pk, value = _rle_data(seed=4)
        profiler.reset_events("ops/")
        auto = _stream(pid, pk, value, segment_sort="auto")
        assert profiler.event_count(columnar.EVENT_HASH_PASSES) == 8
        forced = _stream(pid, pk, value, segment_sort="hash")
        _assert_bitwise(auto, forced)

    def test_auto_declines_outside_gate(self):
        # Norm columns (MEAN/VARIANCE) are non-integer: auto must fall
        # back to the sorted dispatch even though the values are integer.
        pid, pk, value = _rle_data(seed=5)
        profiler.reset_events("ops/")
        _stream(pid, pk, value, segment_sort="auto",
                need_flags=(True, True, True, True))
        assert profiler.event_count(columnar.EVENT_HASH_PASSES) == 0
        # Continuous values defeat the integer grid: no gate, no hash.
        pid, pk, value = _rle_data(seed=6, integer_values=False)
        profiler.reset_events("ops/")
        _stream(pid, pk, value, segment_sort="auto")
        assert profiler.event_count(columnar.EVENT_HASH_PASSES) == 0

    def test_continuous_values_forced_hash_ulp_contract(self):
        # Forced outside the gate: counts/pid-counts exact, sums
        # ULP-close (different reduction order), never wrong.
        pid, pk, value = _rle_data(seed=7, integer_values=False)
        legacy = _stream(pid, pk, value, has_group_clip=False,
                         segment_sort=False)
        profiler.reset_events("ops/")
        hashed = _stream(pid, pk, value, has_group_clip=False,
                         segment_sort="hash")
        assert profiler.event_count(columnar.EVENT_HASH_PASSES) == 8
        np.testing.assert_array_equal(np.asarray(legacy.count),
                                      np.asarray(hashed.count))
        np.testing.assert_array_equal(np.asarray(legacy.pid_count),
                                      np.asarray(hashed.pid_count))
        np.testing.assert_allclose(np.asarray(legacy.sum),
                                   np.asarray(hashed.sum),
                                   rtol=1e-5, atol=1e-4)


class TestHashAdversarial:
    """Adversarial distributions of ISSUE 12 satellite 2."""

    def test_one_pid_owns_an_entire_bucket(self):
        # One privacy id holds every row of its bucket: its segment IS
        # the bucket, so the bin width must stretch to the whole run.
        rng = np.random.default_rng(1)
        n_heavy, n_rest = 96, 400
        pid = np.concatenate([np.zeros(n_heavy, np.int64),
                              rng.integers(1, 50, n_rest)])
        pk = rng.integers(0, 40, n_heavy + n_rest).astype(np.int32)
        value = rng.integers(0, 6, n_heavy + n_rest).astype(np.float32)
        legacy = _stream(pid, pk, value, n_parts=40, segment_sort=False,
                         n_chunks=2)
        hashed = _stream(pid, pk, value, n_parts=40, segment_sort="hash",
                         n_chunks=2)
        _assert_bitwise(legacy, hashed)

    def test_all_unique_pids_planes_mode(self):
        # Near-unique pids choose the PID_PLANES wire (arrival order, no
        # pid-sorted invariant): the hash stage cannot engage and parity
        # must hold trivially through the general sampler.
        rng = np.random.default_rng(2)
        n = 20_000
        pid = rng.permutation(n).astype(np.int64)
        pk = rng.integers(0, 300, n).astype(np.int32)
        value = rng.integers(0, 6, n).astype(np.float32)
        legacy = _stream(pid, pk, value, segment_sort=False)
        profiler.reset_events("ops/")
        hashed = _stream(pid, pk, value, segment_sort="hash")
        assert profiler.event_count(columnar.EVENT_HASH_PASSES) == 0
        _assert_bitwise(legacy, hashed)

    def test_adversarial_group_hash_collisions(self, monkeypatch):
        # Force EVERY (pid, pk) group onto one hash value: group order
        # degenerates to pk order in both paths, and the pairwise
        # selection must fall back exactly like the packed sort's key
        # comparison does. Distinct shape so the jit cache cannot serve
        # a pre-patch compilation.
        monkeypatch.setattr(
            columnar, "_group_hash",
            lambda pid, pk, salt: jnp.zeros(pid.shape, jnp.uint32))
        pid, pk, value = _rle_data(n=7_777, n_parts=123, seed=8)
        legacy = _stream(pid, pk, value, n_parts=123, segment_sort=False)
        hashed = _stream(pid, pk, value, n_parts=123, segment_sort="hash")
        _assert_bitwise(legacy, hashed)

    def test_empty_and_singleton_partitions(self):
        # A huge partition vocabulary where almost every partition is
        # empty and the occupied ones hold single rows.
        rng = np.random.default_rng(3)
        n = 5_000
        pid = np.sort(rng.integers(0, n, n)).astype(np.int64)
        pk = rng.choice([0, 1, 777, 4_095], n).astype(np.int32)
        value = rng.integers(0, 6, n).astype(np.float32)
        legacy = _stream(pid, pk, value, n_parts=4_096,
                         segment_sort=False)
        hashed = _stream(pid, pk, value, n_parts=4_096,
                         segment_sort="hash")
        _assert_bitwise(legacy, hashed)

    def test_overflow_demotion_engages_without_changing_bits(self):
        # Crafted skew: bucket 0 holds ONE pid with a long run (stretches
        # the bin width, shrinking the grid's bin budget), bucket 1 holds
        # thousands of distinct pids — more segments than the budgeted
        # bins, so that chunk MUST demote to the sorted kernel while the
        # other chunk stays on the hash stage. Bits never change.
        k = 2
        cand = np.arange(0, 60_000, dtype=np.int64)
        b = ((cand.astype(np.uint32) * np.uint32(2654435761))
             >> np.uint32(16)) % np.uint32(k)
        bucket_of_zero = int(b[0])
        heavy = 0  # pid 0 keeps pid_lo == 0, so the hash is unshifted
        others = cand[(b != bucket_of_zero) & (cand != heavy)][:3_000]
        # ~8 rows per light pid: repetitive enough that the codec keeps
        # the PID_RLE (pid-sorted) wire the hash stage needs.
        pid = np.concatenate([np.full(600, heavy, np.int64),
                              np.repeat(others, 8)])
        rng = np.random.default_rng(4)
        pk = rng.integers(0, 64, len(pid)).astype(np.int32)
        value = rng.integers(0, 6, len(pid)).astype(np.float32)

        legacy = _stream(pid, pk, value, n_parts=64, segment_sort=False,
                         n_chunks=k)
        profiler.reset_events("ops/")
        hashed = _stream(pid, pk, value, n_parts=64, segment_sort="hash",
                         n_chunks=k)
        assert profiler.event_count(columnar.EVENT_HASH_DEMOTIONS) == 1
        assert profiler.event_count(columnar.EVENT_HASH_PASSES) == 1
        _assert_bitwise(legacy, hashed)

    def test_overflow_demotion_mesh8(self, mesh):
        # Mesh twin of the demotion backstop: a chunk demotes when ANY
        # of its n_dev buckets overflows the planned bins; the demoted
        # chunk runs the sorted kernel, bits unchanged.
        k = 16  # 2 chunks x 8 devices
        cand = np.arange(0, 120_000, dtype=np.int64)
        b = ((cand.astype(np.uint32) * np.uint32(2654435761))
             >> np.uint32(16)) % np.uint32(k)
        heavy = 0
        others = cand[(b != int(b[0])) & (cand != heavy)][:3_000]
        pid = np.concatenate([np.full(600, heavy, np.int64),
                              np.repeat(others, 8)])
        rng = np.random.default_rng(4)
        pk = rng.integers(0, 64, len(pid)).astype(np.int32)
        value = rng.integers(0, 6, len(pid)).astype(np.float32)
        legacy = _stream(pid, pk, value, mesh=mesh, n_parts=64,
                         segment_sort=False, n_chunks=2)
        profiler.reset_events("ops/")
        hashed = _stream(pid, pk, value, mesh=mesh, n_parts=64,
                         segment_sort="hash", n_chunks=2)
        assert profiler.event_count(columnar.EVENT_HASH_DEMOTIONS) > 0
        _assert_bitwise(legacy, hashed)

    def test_bin_overflow_backstop_empties_not_corrupts(self):
        # Direct kernel call with lying geometry (more segments than
        # bins / a run longer than the bin width — corrupt wire
        # metadata): the backstop must yield EMPTY accumulators, never a
        # silently re-sampled release.
        n = 1_024
        rng = np.random.default_rng(5)
        pid = np.sort(rng.integers(0, 100, n)).astype(np.int32)
        pk = rng.integers(0, 64, n).astype(np.int32)
        value = np.ones(n, dtype=np.float32)
        valid = np.ones(n, dtype=bool)
        out = jax.device_get(columnar.bound_and_aggregate(
            jax.random.PRNGKey(11), pid, pk, value, valid,
            num_partitions=64, linf_cap=3, l0_cap=4,
            row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
            group_clip_lo=-np.inf, group_clip_hi=np.inf,
            pid_sorted=True, max_segments=1 << 10,
            hash_bins=8, hash_bin_rows=8))
        assert float(np.asarray(out.count).sum()) == 0.0
        assert float(np.asarray(out.pid_count).sum()) == 0.0


class TestHashKernelUnit:
    """Direct columnar-level parity of the hash-binned stage."""

    def _sorted_rows(self, n=8_192, n_parts=64, seed=2, runs=12):
        rng = np.random.default_rng(seed)
        pid = np.sort(rng.integers(0, n // runs, n)).astype(np.int32)
        pk = rng.integers(0, n_parts, n).astype(np.int32)
        value = rng.integers(0, 6, n).astype(np.float32)
        valid = np.arange(n) < (n - 100)  # padded tail
        return pid, pk, value, valid

    def _geometry(self, pid, valid):
        per = np.bincount(pid[valid])
        w = max(8, (int(per.max()) + 7) & ~7)
        bins = max(8, (int((per > 0).sum()) + 7) & ~7)
        return bins, w

    def _kernel(self, pid, pk, value, valid, n_parts, **kw):
        return jax.device_get(columnar.bound_and_aggregate(
            jax.random.PRNGKey(11), pid, pk, value, valid,
            num_partitions=n_parts, linf_cap=3, l0_cap=4,
            row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
            group_clip_lo=-np.inf, group_clip_hi=np.inf,
            need_norm=False, need_norm_sq=False,
            pid_sorted=True, max_segments=1 << 11, **kw))

    def test_hash_bitwise_equals_packed_and_tiled(self):
        pid, pk, value, valid = self._sorted_rows()
        bins, w = self._geometry(pid, valid)
        max_run = int(np.bincount(pid[valid]).max())
        base = self._kernel(pid, pk, value, valid, 64)
        tiled = self._kernel(pid, pk, value, valid, 64,
                             tile_rows=1024, tile_slack=max_run)
        hashed = self._kernel(pid, pk, value, valid, 64,
                              hash_bins=bins, hash_bin_rows=w)
        _assert_bitwise(base, hashed)
        _assert_bitwise(tiled, hashed)

    def test_row_mask_replays_hash(self):
        # The row-mask kernel with the same hash statics must make
        # exactly the sorted samplers' decisions (quantile replay
        # contract).
        pid, pk, value, valid = self._sorted_rows()
        bins, w = self._geometry(pid, valid)
        key = jax.random.PRNGKey(11)
        base = columnar.bound_row_mask(
            key, pid, pk, valid, 3, 4, pid_sorted=True,
            max_segments=1 << 11, num_partitions=64)
        hashed = columnar.bound_row_mask(
            key, pid, pk, valid, 3, 4, pid_sorted=True,
            max_segments=1 << 11, num_partitions=64,
            hash_bins=bins, hash_bin_rows=w)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(hashed))

    def test_compact_bitwise_under_gate(self):
        # Compact emission reuses PR 5's merge shapes: folding the hash
        # path's CompactGroups must release the same bits as the sorted
        # compact path (exact-integer columns).
        pid, pk, value, valid = self._sorted_rows(seed=9)
        bins, w = self._geometry(pid, valid)
        kw = dict(num_partitions=64, max_groups=512, linf_cap=3, l0_cap=4,
                  row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                  group_clip_lo=-20.0, group_clip_hi=20.0,
                  need_norm=False, need_norm_sq=False,
                  pid_sorted=True, max_segments=1 << 11)
        key = jax.random.PRNGKey(4)
        base = columnar.bound_and_aggregate_compact(
            key, pid, pk, value, valid, **kw)
        hashed = columnar.bound_and_aggregate_compact(
            key, pid, pk, value, valid, hash_bins=bins, hash_bin_rows=w,
            **kw)
        zero = columnar.PartitionAccumulators(
            *(jnp.zeros((64,), jnp.float32) for _ in range(5)))

        def fold(cg):
            stacked = [jnp.stack([cg[i]]) for i in range(6)]
            return jax.device_get(columnar.merge_compact_chunks(
                zero, *stacked, num_partitions=64,
                need_flags=(True, True, False, False)))

        assert int(jax.device_get(base.n_kept)) == int(
            jax.device_get(hashed.n_kept))
        _assert_bitwise(fold(base), fold(hashed))


class TestHashGateAndPlanning:
    def test_hash_exact_gate(self):
        ok = columnar.hash_exact_gate(0.0, 1.0, 3, 0.0, 5.0, 6,
                                      -np.inf, np.inf, 1 << 15)
        assert ok
        # Integer finite group clips pass; fractional ones fail.
        assert columnar.hash_exact_gate(0.0, 1.0, 3, 0.0, 5.0, 6,
                                        -30.0, 30.0, 1 << 15)
        assert not columnar.hash_exact_gate(0.0, 1.0, 3, 0.0, 5.0, 6,
                                            -30.5, 30.0, 1 << 15)
        # NaN group clip fails.
        assert not columnar.hash_exact_gate(0.0, 1.0, 3, 0.0, 5.0, 6,
                                            np.nan, 30.0, 1 << 15)
        # Partition-fold exactness: cap * max bound must stay < 2^24.
        assert not columnar.hash_exact_gate(0.0, 1.0, 3, 0.0, 5.0, 6,
                                            -np.inf, np.inf, 1 << 24)
        assert not columnar.hash_exact_gate(0.0, 1.0, 3, 0.0, 5.0, 6,
                                            -np.inf, np.inf,
                                            (1 << 24) // 5 + 1)
        # A huge finite clip can RAISE the partition bound past 2^24.
        assert not columnar.hash_exact_gate(0.0, 1.0, 3, 0.0, 5.0, 6,
                                            0.0, float(1 << 23), 1 << 15)
        # The int plan itself failing (fractional grid) fails the gate.
        assert not columnar.hash_exact_gate(0.0, 0.5, 3, 0.0, 5.0, 6,
                                            -np.inf, np.inf, 1 << 15)
        # Traced / non-concrete cap fails closed.
        assert not columnar.hash_exact_gate(0.0, 1.0, 3, 0.0, 5.0, 6,
                                            -np.inf, np.inf, None)

    def _fmt(self, cap=1 << 15, ucap=1 << 12,
             pid_mode=wirecodec.PID_RLE):
        return wirecodec.WireFormat(
            bytes_pid=3, bits_pk=10, cap=cap, ucap=ucap,
            value=wirecodec.ValuePlan(wirecodec.VALUE_PLANES, 0.0, 1.0, 3),
            pid_mode=pid_mode)

    def test_plan_group_binning_forced_and_auto(self):
        fmt = self._fmt()
        forced = wirecodec.plan_group_binning(fmt, "hash", 16)
        assert forced.hash_bins >= fmt.ucap and forced.hash_bin_rows == 16
        # auto requires the exactness gate...
        assert wirecodec.plan_group_binning(fmt, "auto", 16).hash_bins == 0
        auto = wirecodec.plan_group_binning(fmt, "auto", 16, exact=True)
        assert auto.hash_bins == forced.hash_bins
        # ...and True (tiling) never plans bins.
        assert wirecodec.plan_group_binning(fmt, True, 16).hash_bins == 0

    def test_plan_group_binning_declines(self):
        fmt = self._fmt()
        # No/unknown max_run, disabled knob, planes wire.
        assert wirecodec.plan_group_binning(fmt, "hash", -1).hash_bins == 0
        assert wirecodec.plan_group_binning(fmt, "hash", 0).hash_bins == 0
        assert wirecodec.plan_group_binning(fmt, False, 16).hash_bins == 0
        planes = self._fmt(pid_mode=wirecodec.PID_PLANES)
        assert wirecodec.plan_group_binning(planes, "hash",
                                            16).hash_bins == 0
        # Bin width ceilings: auto declines above HASH_MAX_BIN_ROWS,
        # forced above the forced ceiling.
        wide = wirecodec.plan_group_binning(fmt, "auto", 200, exact=True)
        assert wide.hash_bins == 0
        assert wirecodec.plan_group_binning(fmt, "hash", 200).hash_bins > 0
        assert wirecodec.plan_group_binning(fmt, "hash",
                                            2_000).hash_bins == 0
        # auto never plans a grid some chunks would overflow (ucap above
        # the grid budget); forced accepts the budgeted bins.
        crowded = self._fmt(cap=1 << 12, ucap=1 << 12)
        assert wirecodec.plan_group_binning(crowded, "auto", 64,
                                            exact=True).hash_bins == 0
        f = wirecodec.plan_group_binning(crowded, "hash", 64)
        assert 0 < f.hash_bins < crowded.ucap

    def test_sort_cost_hash_kind_zero_bytes(self):
        c = columnar.sort_cost(100_000, num_partitions=1 << 10,
                               pid_sorted=True, max_segments=4096,
                               hash_bins=4096, hash_bin_rows=32)
        assert c["kind"] == "hash"
        assert c["operand_bytes"] == 0
        assert c["rows"] == 4096 * 32 and c["tiles"] == 4096

    def test_resolved_sampler_desc(self):
        fmt = self._fmt()
        kw = dict(num_partitions=1 << 10, row_clip_lo=0.0, row_clip_hi=5.0,
                  linf_cap=6, l1_mode=False, group_clip_lo=-np.inf,
                  group_clip_hi=np.inf,
                  need_flags=(True, True, False, False))
        auto = streaming.resolved_sampler_desc(fmt, "auto", 16, **kw)
        forced = streaming.resolved_sampler_desc(fmt, "hash", 16, **kw)
        legacy = streaming.resolved_sampler_desc(fmt, False, 16, **kw)
        tiled = streaming.resolved_sampler_desc(fmt, True, 16, **kw)
        # Same resolved kernel -> same identity; different kernels ->
        # different identities (the satellite-1 contract).
        assert auto == forced and auto.startswith("hash:")
        assert legacy != forced and tiled != forced
        # auto outside the gate (norm columns) resolves to a sorted kind.
        norm = streaming.resolved_sampler_desc(
            fmt, "auto", 16, **{**kw,
                                "need_flags": (True, True, True, True)})
        assert not norm.startswith("hash:")


class TestSamplerFingerprints:
    """Satellite 1: flipping segment_sort can never alias a cached
    accumulator or resume a checkpoint from a different sampler."""

    def _session(self, **kw):
        rng = np.random.default_rng(6)
        n = 30_000
        data = pdp.ColumnarData(
            pid=rng.integers(0, n // 20, n).astype(np.int64),
            pk=rng.integers(0, 64, n).astype(np.int32),
            value=rng.integers(0, 6, n).astype(np.float32))
        from pipelinedp_tpu import serving
        return serving.DatasetSession(
            data, public_partitions=list(range(64)), **kw)

    def _engine_query(self, session, segment_sort, seed=3):
        accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=seed,
                                 secure_host_noise=False,
                                 stream_chunks=session.n_chunks,
                                 segment_sort=segment_sort)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=8,
            max_contributions_per_partition=6,
            min_value=0.0, max_value=5.0)
        result = engine.aggregate(session, params,
                                  public_partitions=list(range(64)))
        accountant.compute_budgets()
        return result.to_columns()

    def test_bound_cache_keys_on_resolved_sampler(self):
        from pipelinedp_tpu.serving import session as session_mod
        session = self._session()
        try:
            h0 = profiler.event_count(session_mod.EVENT_BOUND_HITS)
            m0 = profiler.event_count(session_mod.EVENT_BOUND_MISSES)
            a = self._engine_query(session, "auto")
            # Same seed, different knob STRING, same resolved sampler
            # (auto resolves to hash for COUNT+SUM on this wire): HIT.
            b = self._engine_query(session, "hash")
            assert profiler.event_count(
                session_mod.EVENT_BOUND_HITS) == h0 + 1
            for name in a:
                np.testing.assert_array_equal(a[name], b[name],
                                              err_msg=name)
            # Different resolved sampler (the round-8 oracle): MISS —
            # a hash-produced accumulator is never aliased across
            # samplers, even though the released bits agree under the
            # gate.
            self._engine_query(session, False)
            assert profiler.event_count(
                session_mod.EVENT_BOUND_MISSES) == m0 + 2
        finally:
            session.close()

    def test_checkpoint_refuses_other_sampler_resume(self):
        pid, pk, value = _rle_data()
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="hashfp",
                                          delete_on_success=False)
        full = _stream(pid, pk, value, segment_sort="hash")
        _stream(pid, pk, value, segment_sort="hash",
                resilience=runtime.StreamResilience(
                    checkpoint_policy=policy))
        checkpoint = store.load("hashfp")
        assert 0 < checkpoint.next_chunk < checkpoint.n_chunks
        # A checkpoint produced under the hash sampler must refuse a
        # resume under any other resolved sampler...
        with pytest.raises(runtime.CheckpointMismatchError):
            _stream(pid, pk, value, segment_sort=False,
                    resume_from=checkpoint)
        with pytest.raises(runtime.CheckpointMismatchError):
            _stream(pid, pk, value, segment_sort=True,
                    resume_from=checkpoint)
        # ...and resume bit-identically under its own.
        resumed = _stream(pid, pk, value, segment_sort="hash",
                          resume_from=checkpoint)
        _assert_bitwise(full, resumed)


class TestHashWarmAndResumeParity:
    """Cold / warm-replay / crash-resume all pinned bitwise (the
    acceptance matrix of ISSUE 12)."""

    def test_warm_replay_matches_cold_single_device(self):
        pid, pk, value = _rle_data(seed=10)
        cold = _stream(pid, pk, value, segment_sort="hash")
        wire = streaming.ingest_resident_wire(pid, pk, value,
                                              num_partitions=300,
                                              n_chunks=8)
        warm = jax.device_get(streaming.replay_resident_wire(
            jax.random.PRNGKey(7), wire, linf_cap=6, l0_cap=8,
            row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
            group_clip_lo=-30.0, group_clip_hi=30.0,
            need_flags=(True, True, False, False),
            segment_sort="hash"))
        _assert_bitwise(cold, warm)

    def test_warm_replay_matches_cold_mesh8(self, mesh):
        pid, pk, value = _rle_data(n=40_000, seed=11)
        cold = _stream(pid, pk, value, mesh=mesh, segment_sort="hash")
        wire = streaming.ingest_resident_wire(
            pid, pk, value, num_partitions=300,
            n_chunks=8, n_dev=mesh.devices.size)
        warm = jax.device_get(sharded.replay_resident_wire(
            mesh, jax.random.PRNGKey(7), wire, linf_cap=6, l0_cap=8,
            row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
            group_clip_lo=-30.0, group_clip_hi=30.0,
            need_flags=(True, True, False, False),
            segment_sort="hash"))
        _assert_bitwise(cold, warm)

    def test_crash_resume_through_engine(self):
        pid, pk, value = _rle_data(seed=12)
        n_parts = 300

        def run(**engine_kw):
            accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
            engine = pdp.JaxDPEngine(accountant, seed=3, stream_chunks=8,
                                     secure_host_noise=False,
                                     segment_sort="hash", **engine_kw)
            params = pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                max_partitions_contributed=8,
                max_contributions_per_partition=6,
                min_value=0.0, max_value=5.0)
            result = engine.aggregate(
                pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
                public_partitions=list(range(n_parts)))
            accountant.compute_budgets()
            return result.to_columns()

        clean = run()
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="hashkill")
        with pytest.raises(runtime.HostCrash):
            run(checkpoint_policy=policy,
                fault_injector=runtime.FaultInjector(
                    [runtime.FaultSpec("host_crash", at_slab=1)]))
        assert store.load("hashkill").next_chunk > 0
        resumed = run(checkpoint_policy=policy)
        for name in clean:
            np.testing.assert_array_equal(clean[name], resumed[name],
                                          err_msg=name)

    def test_session_warm_query_matches_cold_engine(self):
        rng = np.random.default_rng(13)
        n = 30_000
        data = pdp.ColumnarData(
            pid=rng.integers(0, n // 20, n).astype(np.int64),
            pk=rng.integers(0, 64, n).astype(np.int32),
            value=rng.integers(0, 6, n).astype(np.float32))
        from pipelinedp_tpu import serving
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=8,
            max_contributions_per_partition=6,
            min_value=0.0, max_value=5.0)
        session = serving.DatasetSession(
            data, public_partitions=list(range(64)),
            segment_sort="hash", secure_host_noise=False)
        try:
            warm = session.query(params, epsilon=1e9, delta=1 - 1e-9,
                                 seed=5).to_columns()
            accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
            engine = pdp.JaxDPEngine(accountant, seed=5,
                                     secure_host_noise=False,
                                     stream_chunks=session.n_chunks,
                                     segment_sort="hash")
            result = engine.aggregate(data, params,
                                      public_partitions=list(range(64)))
            accountant.compute_budgets()
            cold = result.to_columns()
            for name in cold:
                np.testing.assert_array_equal(cold[name], warm[name],
                                              err_msg=name)
        finally:
            session.close()


class TestQuantileHashReplay:
    """PERCENTILE rides the streamed kernels: the row mask must replay
    the SAME hash-binned sampling as the aggregation kernel, keeping
    released quantiles bitwise invariant to the knob."""

    def _run(self, segment_sort):
        rng = np.random.default_rng(9)
        n = 60_000
        pid = rng.integers(0, n // 20, n)
        pk = rng.integers(0, 40, n).astype(np.int32)
        value = rng.integers(0, 101, n).astype(np.float32)
        accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=4, stream_chunks=8,
                                 secure_host_noise=False,
                                 segment_sort=segment_sort)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=8,
            max_contributions_per_partition=6,
            min_value=0.0, max_value=100.0)
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=list(range(40)))
        accountant.compute_budgets()
        return result.to_columns()

    def test_percentiles_bitwise_invariant(self):
        legacy = self._run(False)
        hashed = self._run("hash")
        for name in legacy:
            np.testing.assert_array_equal(legacy[name], hashed[name],
                                          err_msg=name)
