"""Live-session tests (pipelinedp_tpu/serving/live.py, SERVING.md
"Live sessions").

Contracts:
  * Window algebra — tumbling and sliding window edges are exact
    (half-open ``[a, b)``, sealed iff ``b <= watermark - lateness``),
    and late arrivals follow the configured policy: typed
    ``LateArrivalError`` or dead-letter persistence, each with its
    counter — never a silent fold into a sealed window.
  * Bit-identity — a sealed window's query (and the full-union query)
    is BIT-identical to the same query over the same rows ingested
    cold with the session's pinned chunk count, including after
    save/open_live. All parity legs pin ``secure_host_noise=False``:
    the secure path draws OS entropy by design.
  * Exactly-once releases — a ReleaseSchedule re-created with its
    schedule_id after reopen owes exactly the unrecorded sealed
    windows; a deliberate replay is refused (``DoubleReleaseError``);
    empty windows release (noise-only) or suppress per policy,
    deterministically.
  * Backpressure — appends beyond the pending gate shed with a typed
    ``IngestOverloadedError`` before any durable or budget effect.
  * Per-window budget — ``register_tenant(window_epsilon=...)`` caps
    each window tag independently of the total ledger.

The true-SIGKILL legs (crash at either side of the WAL commit point,
mid-schedule kills) live in tests/process_kill_test.py — they need
real process death.
"""

import os

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler, runtime, serving
from pipelinedp_tpu.budget_accounting import BudgetExhaustedError
from pipelinedp_tpu.runtime.journal import DoubleReleaseError

M = pdp.Metrics

N_PARTS = 20
N_CHUNKS = 4
EPOCH_ROWS = 600


def epoch_batch(e, n=EPOCH_ROWS, with_value=True):
    rng = np.random.default_rng(200 + e)
    pid = rng.integers(0, 300, n).astype(np.int64)
    pk = rng.integers(0, N_PARTS, n).astype(np.int32)
    value = rng.uniform(0, 5, n).astype(np.float32) if with_value else None
    return pid, pk, value


def count_sum_params():
    return pdp.AggregateParams(
        metrics=[M.COUNT, M.SUM],
        max_partitions_contributed=N_PARTS,
        max_contributions_per_partition=100,
        min_value=0.0,
        max_value=5.0)


def make_live(tmp_path, sub="live", window=None, name="live-ds",
              tenant=True, **kwargs):
    store = serving.SessionStore(str(tmp_path / sub))
    session = serving.LiveDatasetSession.create(
        store=store, name=name,
        public_partitions=list(range(N_PARTS)), n_chunks=N_CHUNKS,
        window=window or serving.WindowSpec(size=1),
        secure_host_noise=False, **kwargs)
    if tenant:
        session.register_tenant("acme", total_epsilon=1e6,
                                total_delta=1 - 1e-9)
    return store, session


def cold_columns(pid, pk, value, *, epsilon, delta, seed):
    cold = serving.DatasetSession(
        pdp.ColumnarData(pid=pid, pk=pk, value=value),
        public_partitions=list(range(N_PARTS)), n_chunks=N_CHUNKS,
        name="cold-ref")
    return cold.query(count_sum_params(), epsilon=epsilon, delta=delta,
                      seed=seed, secure_host_noise=False).to_columns()


def assert_identical(a: dict, b: dict):
    assert list(a) == list(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


class TestWindowSpec:

    def test_tumbling_edges(self):
        spec = serving.WindowSpec(size=2)
        assert spec.stride == 2
        assert spec.windows_sealed_by(0) == []
        assert spec.windows_sealed_by(1) == []
        assert spec.windows_sealed_by(2) == [(0, 2)]
        assert spec.windows_sealed_by(3) == [(0, 2)]
        assert spec.windows_sealed_by(6) == [(0, 2), (2, 4), (4, 6)]

    def test_sliding_edges_overlap(self):
        spec = serving.WindowSpec(size=3, slide=1)
        assert spec.windows_sealed_by(3) == [(0, 3)]
        assert spec.windows_sealed_by(5) == [(0, 3), (1, 4), (2, 5)]

    def test_sliding_with_gaps(self):
        # slide > size: disjoint windows with unwindowed gaps between.
        spec = serving.WindowSpec(size=1, slide=3)
        assert spec.windows_sealed_by(7) == [(0, 1), (3, 4), (6, 7)]

    @pytest.mark.parametrize("kwargs", [
        dict(size=0), dict(size=-1), dict(size=2, slide=0),
        dict(size=1, allowed_lateness=-1),
        dict(size=1, late_policy="drop")])
    def test_invalid_specs_refused(self, kwargs):
        with pytest.raises(ValueError):
            serving.WindowSpec(**kwargs)

    def test_meta_roundtrip(self):
        spec = serving.WindowSpec(size=3, slide=2, allowed_lateness=1,
                                  late_policy="dead_letter")
        assert serving.WindowSpec.from_meta(spec.to_meta()) == spec


class TestAppendBasics:

    def test_epoch_watermark_progression(self, tmp_path):
        _, s = make_live(tmp_path)
        assert (s.epoch, s.watermark, s.sealed_windows()) == (0, 0, [])
        s.append(*epoch_batch(0))
        assert (s.epoch, s.watermark) == (1, 1)
        s.append(*epoch_batch(1))
        assert s.sealed_windows() == [(0, 1)]
        assert s.is_sealed(0, 1) and not s.is_sealed(1, 2)

    def test_empty_append_refused(self, tmp_path):
        _, s = make_live(tmp_path)
        with pytest.raises(ValueError, match="empty append"):
            s.append(np.zeros(0, np.int64), np.zeros(0, np.int32),
                     np.zeros(0, np.float32))

    def test_duplicate_is_idempotent_noop(self, tmp_path):
        _, s = make_live(tmp_path)
        first = s.append(*epoch_batch(0))
        assert first.committed and not first.duplicate
        before = profiler.event_count(serving.EVENT_APPEND_DUPLICATES)
        dup = s.append(*epoch_batch(0))
        assert dup.duplicate and not dup.committed
        assert dup.epoch == first.epoch
        assert s.epoch == 1
        assert profiler.event_count(
            serving.EVENT_APPEND_DUPLICATES) == before + 1

    def test_non_numeric_columns_refused(self, tmp_path):
        _, s = make_live(tmp_path)
        pid, pk, _ = epoch_batch(0)
        with pytest.raises(ValueError, match="numeric columns only"):
            s.append(pid, pk, np.array(["a"] * len(pid), dtype=object))

    def test_value_presence_must_stay_consistent(self, tmp_path):
        _, s = make_live(tmp_path)
        s.append(*epoch_batch(0))
        pid, pk, _ = epoch_batch(1)
        with pytest.raises(ValueError, match="consistent"):
            s.append(pid, pk, None)

    def test_mismatched_column_lengths_refused(self, tmp_path):
        _, s = make_live(tmp_path)
        pid, pk, value = epoch_batch(0)
        with pytest.raises(ValueError, match="lengths disagree"):
            s.append(pid, pk[:-1], value)

    def test_stats_and_status_report_live_state(self, tmp_path):
        _, s = make_live(tmp_path)
        s.append(*epoch_batch(0))
        live = s.stats()["live"]
        assert live == s.live_status()
        assert live["epoch"] == 1
        assert live["watermark"] == 1

    def test_batch_open_refuses_live_session(self, tmp_path):
        store, s = make_live(tmp_path)
        s.append(*epoch_batch(0))
        s.save()
        with pytest.raises(serving.SessionStoreError, match="open_live"):
            store.open("live-ds")

    def test_advance_watermark_is_monotone_and_durable(self, tmp_path):
        store, s = make_live(tmp_path)
        s.append(*epoch_batch(0))
        s.advance_watermark(3)
        assert s.watermark == 4
        s.advance_watermark(1)  # backwards: no-op
        assert s.watermark == 4
        assert s.sealed_windows() == [(0, 1), (1, 2), (2, 3)]
        reopened = store.open_live("live-ds")
        assert reopened.watermark == 4
        assert reopened.sealed_windows() == s.sealed_windows()


class TestLateArrivals:

    def test_reject_policy_raises_typed_error(self, tmp_path):
        _, s = make_live(tmp_path)
        for e in range(3):
            s.append(*epoch_batch(e))
        before = profiler.event_count(serving.EVENT_LATE_REJECTED)
        with pytest.raises(serving.LateArrivalError) as exc:
            s.append(*epoch_batch(9), event_epoch=0)
        assert exc.value.event_epoch == 0
        assert exc.value.horizon == 2
        assert s.epoch == 3  # nothing folded
        assert profiler.event_count(
            serving.EVENT_LATE_REJECTED) == before + 1

    def test_allowed_lateness_admits_stragglers(self, tmp_path):
        _, s = make_live(
            tmp_path, window=serving.WindowSpec(size=1,
                                                allowed_lateness=2))
        for e in range(3):
            s.append(*epoch_batch(e))
        # horizon = max_event - lateness = 0: event 0 is still open.
        res = s.append(*epoch_batch(9), event_epoch=0)
        assert res.committed
        # Lateness delays sealing by the same margin.
        assert s.sealed_windows() == []

    def test_dead_letter_policy_persists_and_counts(self, tmp_path):
        store, s = make_live(
            tmp_path, window=serving.WindowSpec(
                size=1, late_policy="dead_letter"))
        for e in range(3):
            s.append(*epoch_batch(e))
        before = profiler.event_count(serving.EVENT_LATE_DEADLETTERED)
        res = s.append(*epoch_batch(9), event_epoch=0)
        assert res.dead_lettered and not res.committed
        assert s.epoch == 3
        assert profiler.event_count(
            serving.EVENT_LATE_DEADLETTERED) == before + 1
        assert list(store.deadletter_digests("live-ds")) == [res.digest]
        # Re-submitting the dead-lettered batch is an idempotent no-op.
        again = s.append(*epoch_batch(9), event_epoch=0)
        assert again.duplicate and again.dead_lettered
        # The dead letter survives reopen — still refused, not folded.
        reopened = store.open_live("live-ds")
        again2 = reopened.append(*epoch_batch(9), event_epoch=0)
        assert again2.duplicate and again2.dead_lettered
        assert reopened.epoch == 3


class TestBitIdentity:

    def test_window_and_union_match_cold_batch(self, tmp_path):
        _, s = make_live(tmp_path)
        batches = [epoch_batch(e) for e in range(3)]
        for b in batches:
            s.append(*b)
        for a in range(2):
            live = s.window_query(
                a, a + 1, count_sum_params(), epsilon=0.5, delta=1e-7,
                seed=serving.window_seed(5, a, a + 1),
                tenant="acme").to_columns()
            cold = cold_columns(
                *batches[a], epsilon=0.5, delta=1e-7,
                seed=serving.window_seed(5, a, a + 1))
            assert_identical(live, cold)
        live_full = s.query(count_sum_params(), epsilon=1.0, delta=1e-6,
                            seed=3, tenant="acme").to_columns()
        cold_full = cold_columns(
            np.concatenate([b[0] for b in batches]),
            np.concatenate([b[1] for b in batches]),
            np.concatenate([b[2] for b in batches]),
            epsilon=1.0, delta=1e-6, seed=3)
        assert_identical(live_full, cold_full)

    def test_reopen_is_bit_deterministic(self, tmp_path):
        store, s = make_live(tmp_path)
        for e in range(3):
            s.append(*epoch_batch(e))
        fp = s.fingerprint
        # Tenantless queries: the SAME (seed, window) query re-issued
        # through a tenant would be refused by the at-most-once release
        # journal — which is its own contract, tested elsewhere.
        live = s.window_query(0, 1, count_sum_params(), epsilon=0.5,
                              delta=1e-7, seed=17).to_columns()
        reopened = store.open_live("live-ds")
        assert reopened.epoch == 3
        assert reopened.fingerprint == fp
        again = reopened.window_query(
            0, 1, count_sum_params(), epsilon=0.5, delta=1e-7,
            seed=17).to_columns()
        assert_identical(live, again)

    def test_unsealed_window_query_refused(self, tmp_path):
        _, s = make_live(tmp_path)
        s.append(*epoch_batch(0))
        with pytest.raises(ValueError, match="sealed"):
            s.window_query(0, 1, count_sum_params(), epsilon=0.5,
                           delta=1e-7, seed=1, tenant="acme")


class TestBackpressure:

    def test_zero_gate_sheds_before_any_effect(self, tmp_path):
        _, s = make_live(tmp_path, max_pending_appends=0)
        before = profiler.event_count(serving.EVENT_APPENDS_SHED)
        with pytest.raises(serving.IngestOverloadedError) as exc:
            s.append(*epoch_batch(0))
        assert exc.value.max_pending == 0
        assert profiler.event_count(
            serving.EVENT_APPENDS_SHED) == before + 1
        # Shed strictly before any durable or budget effect.
        assert s.epoch == 0
        assert s.tenant("acme").ledger.spent_epsilon == 0.0
        assert s.stats()["live"]["pending_appends"] == 0

    def test_env_default_gate(self, monkeypatch):
        monkeypatch.delenv(serving.MAX_PENDING_ENV, raising=False)
        assert serving.max_pending_appends_default() == 64
        monkeypatch.setenv(serving.MAX_PENDING_ENV, "3")
        assert serving.max_pending_appends_default() == 3


class TestGroupCommit:
    """Concurrent appends coalesce into group-commits (ISSUE 17): one
    fsync may cover many batches, while each batch keeps its own
    digest/epoch identity, duplicate no-op behavior, and the committed
    session stays bit-identical to the same batches appended serially."""

    def test_concurrent_appends_commit_dense_epochs(self, tmp_path,
                                                    monkeypatch):
        import threading
        monkeypatch.setenv(serving.APPEND_COMMIT_WINDOW_ENV, "10")
        _, s = make_live(tmp_path)
        n_batches = 6
        results = [None] * n_batches
        errors = []
        barrier = threading.Barrier(n_batches)

        def worker(i):
            try:
                barrier.wait()
                results[i] = s.append(*epoch_batch(i))
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_batches)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert all(r.committed and not r.duplicate for r in results)
        assert s.epoch == n_batches
        # Epoch numbering is dense regardless of interleaving.
        assert sorted(r.epoch for r in results) == list(range(n_batches))
        # Bit-identity: a serial session appending the same batches in
        # the committed epoch order answers queries identically.
        _, serial = make_live(tmp_path, sub="serial", name="serial-ds")
        for r in sorted(results, key=lambda r: r.epoch):
            batch_index = next(i for i in range(n_batches)
                               if results[i] is r)
            serial.append(*epoch_batch(batch_index))
        q = lambda sess: sess.query(  # noqa: E731
            count_sum_params(), epsilon=1.0, delta=1e-6, seed=3,
            secure_host_noise=False).to_columns()
        assert_identical(q(serial), q(s))

    def test_concurrent_duplicate_submissions_commit_once(self,
                                                          tmp_path):
        import threading
        _, s = make_live(tmp_path)
        n_threads = 6
        results = [None] * n_threads
        errors = []
        barrier = threading.Barrier(n_threads)

        def worker(i):
            try:
                barrier.wait()
                results[i] = s.append(*epoch_batch(0))
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        committed = [r for r in results if r.committed]
        duplicates = [r for r in results if r.duplicate]
        assert len(committed) == 1
        assert len(duplicates) == n_threads - 1
        assert all(r.epoch == 0 for r in results)
        assert s.epoch == 1

    def test_commit_window_env(self, monkeypatch):
        from pipelinedp_tpu.serving import live as live_mod
        monkeypatch.delenv(serving.APPEND_COMMIT_WINDOW_ENV,
                           raising=False)
        assert live_mod.append_commit_window_s() == 0.0
        monkeypatch.setenv(serving.APPEND_COMMIT_WINDOW_ENV, "25")
        assert live_mod.append_commit_window_s() == 0.025
        assert serving.append_commit_window_s() == 0.025


class TestReleaseSchedule:

    def _schedule(self, session, sid="sched", base_seed=5, **kwargs):
        return session.release_schedule(
            sid, count_sum_params(), epsilon=0.5, delta=1e-7,
            tenant="acme", base_seed=base_seed, **kwargs)

    def test_tick_releases_each_sealed_window_once(self, tmp_path):
        _, s = make_live(tmp_path)
        for e in range(3):
            s.append(*epoch_batch(e))
        sched = self._schedule(s)
        records = sched.tick()
        assert [r["window"] for r in records] == [(0, 1), (1, 2)]
        assert all(r["outcome"] == "released" for r in records)
        assert sched.tick() == []  # nothing due twice

    def test_catchup_owes_exactly_the_unrecorded_windows(self, tmp_path):
        store, s = make_live(tmp_path)
        for e in range(3):
            s.append(*epoch_batch(e))
        sched = self._schedule(s)
        sched.tick()
        sched.close()
        reopened = store.open_live("live-ds")
        reopened.append(*epoch_batch(3))
        again = self._schedule(reopened)
        # Recorded windows stay recorded across the reopen; only the
        # newly sealed window is due.
        assert again.due_windows() == [(2, 3)]
        records = again.tick()
        assert [r["window"] for r in records] == [(2, 3)]
        assert records[0]["outcome"] == "released"

    def test_deliberate_replay_refused_and_refunded(self, tmp_path):
        _, s = make_live(tmp_path)
        for e in range(2):
            s.append(*epoch_batch(e))
        sched = self._schedule(s)
        sched.tick()
        spent = s.tenant("acme").ledger.spent_epsilon
        with pytest.raises(DoubleReleaseError):
            sched.replay(0, 1)
        # The refused replay's charge was exactly refunded.
        assert s.tenant("acme").ledger.spent_epsilon == spent

    def test_replay_of_unrecorded_window_is_an_error(self, tmp_path):
        _, s = make_live(tmp_path)
        s.append(*epoch_batch(0))
        sched = self._schedule(s)
        with pytest.raises(ValueError, match="no recorded outcome"):
            sched.replay(0, 1)

    def test_schedule_requires_tenant(self, tmp_path):
        _, s = make_live(tmp_path)
        with pytest.raises(ValueError, match="tenant"):
            s.release_schedule("sched", count_sum_params(),
                               epsilon=0.5, tenant=None)

    def test_empty_window_releases_noise_only_by_default(self, tmp_path):
        _, s = make_live(tmp_path)
        s.append(*epoch_batch(0), event_epoch=0)
        s.advance_watermark(2)  # event 1 never arrives; [1,2) is empty
        sched = self._schedule(s)
        records = sched.tick()
        by_window = {r["window"]: r for r in records}
        assert by_window[(1, 2)]["outcome"] == "released"
        # Noise-only, but a real release: every public partition kept.
        cols = by_window[(1, 2)]["result"]
        assert len(np.asarray(cols["count"])) == N_PARTS

    def test_empty_window_release_is_deterministic(self, tmp_path):
        results = []
        for sub in ("a", "b"):
            _, s = make_live(tmp_path, sub=sub)
            s.append(*epoch_batch(0), event_epoch=0)
            s.advance_watermark(2)
            records = self._schedule(s).tick()
            results.append({r["window"]: r["result"] for r in records})
        for w in results[0]:
            assert_identical(results[0][w], results[1][w])

    def test_empty_window_suppress_policy(self, tmp_path):
        _, s = make_live(tmp_path)
        s.append(*epoch_batch(0), event_epoch=0)
        s.advance_watermark(2)
        before = profiler.event_count(serving.EVENT_RELEASES_SUPPRESSED)
        records = self._schedule(s, empty_policy="suppress").tick()
        by_window = {r["window"]: r for r in records}
        assert by_window[(1, 2)]["outcome"] == "suppressed"
        assert by_window[(1, 2)]["result"] is None
        assert by_window[(0, 1)]["outcome"] == "released"
        assert profiler.event_count(
            serving.EVENT_RELEASES_SUPPRESSED) == before + 1

    def test_invalid_empty_policy_refused(self, tmp_path):
        _, s = make_live(tmp_path)
        with pytest.raises(ValueError, match="empty_policy"):
            self._schedule(s, empty_policy="drop")


class TestWindowBudgetCaps:

    def test_per_window_cap_independent_of_total(self, tmp_path):
        _, s = make_live(tmp_path, tenant=False)
        s.register_tenant("acme", total_epsilon=1e6,
                          total_delta=1 - 1e-9, window_epsilon=1.0)
        for e in range(2):
            s.append(*epoch_batch(e))
        params = count_sum_params()
        s.window_query(0, 1, params, epsilon=0.6, delta=1e-7, seed=1,
                       tenant="acme")
        ledger = s.tenant("acme").ledger
        assert ledger.window_spent("w[0,1)").epsilon == \
            pytest.approx(0.6)
        # Second query on the SAME window busts its cap ...
        with pytest.raises(BudgetExhaustedError):
            s.window_query(0, 1, params, epsilon=0.6, delta=1e-7,
                           seed=2, tenant="acme")
        # ... while the total ledger is nowhere near exhausted and a
        # different window still has full headroom.
        s.append(*epoch_batch(2))
        s.window_query(1, 2, params, epsilon=0.6, delta=1e-7, seed=3,
                       tenant="acme")

    def test_window_caps_survive_reopen(self, tmp_path):
        store, s = make_live(tmp_path, tenant=False)
        s.register_tenant("acme", total_epsilon=1e6,
                          total_delta=1 - 1e-9, window_epsilon=1.0)
        for e in range(2):
            s.append(*epoch_batch(e))
        s.window_query(0, 1, count_sum_params(), epsilon=0.6,
                       delta=1e-7, seed=1, tenant="acme")
        reopened = store.open_live("live-ds")
        ledger = reopened.tenant("acme").ledger
        assert ledger.window_spent("w[0,1)").epsilon == \
            pytest.approx(0.6)
        with pytest.raises(BudgetExhaustedError):
            reopened.window_query(0, 1, count_sum_params(), epsilon=0.6,
                                  delta=1e-7, seed=2, tenant="acme")


class TestLiveStatusz:

    def test_statusz_surfaces_live_plane(self, tmp_path):
        from pipelinedp_tpu.obs import ops_plane
        _, s = make_live(tmp_path)
        s.append(*epoch_batch(0))
        s.append(*epoch_batch(1))
        payload = ops_plane.statusz_payload(s)
        live = payload["sessions"]["live-ds"]["live"]
        assert live["epoch"] == 2
        assert live["watermark"] == 2
        assert live["sealed_windows"] == 1
        assert live["window"] == serving.WindowSpec(size=1).to_meta()
        # Batch sessions keep their statusz shape: no live key.
        cold = serving.DatasetSession(
            pdp.ColumnarData(*epoch_batch(0)),
            public_partitions=list(range(N_PARTS)), n_chunks=N_CHUNKS,
            name="cold-ref")
        assert "live" not in ops_plane.statusz_payload(
            cold)["sessions"]["cold-ref"]


class TestLiveChaos:
    """CI's live-chaos job sweeps PIPELINEDP_TPU_CHAOS_SEED: scripted
    oom/transfer/kernel/host-crash faults (and hangs) injected into
    every scheduled window release must be absorbed by retries with a
    release stream bit-identical to the fault-free schedule. (The
    SIGKILL-during-append legs live in process_kill_test.py — those
    need real process death.)"""

    def _seeds(self):
        env = os.environ.get("PIPELINEDP_TPU_CHAOS_SEED")
        return [int(env)] if env is not None else [0, 1, 2]

    def _released(self, tmp_path, sub, **query_kwargs):
        _, s = make_live(tmp_path, sub=sub)
        for e in range(3):
            s.append(*epoch_batch(e))
        records = s.release_schedule(
            "sched", count_sum_params(), epsilon=0.5, delta=1e-7,
            tenant="acme", base_seed=5, **query_kwargs).tick()
        assert [r["outcome"] for r in records] == ["released"] * 2
        return {r["window"]: r["result"] for r in records}

    def test_chaotic_release_stream_matches_clean(self, tmp_path):
        clean = self._released(tmp_path, "clean")
        for seed in self._seeds():
            chaotic = self._released(
                tmp_path, f"chaos{seed}",
                fault_injector=runtime.FaultInjector.chaos(
                    seed=seed, n_slabs=N_CHUNKS, fire_percent=50),
                retry_policy=runtime.RetryPolicy(
                    max_retries=20, sleep=lambda s: None))
            for w in clean:
                assert_identical(clean[w], chaotic[w])

    def test_chaotic_releases_with_hangs_under_watchdog(self, tmp_path):
        clean = self._released(tmp_path, "clean_h")
        for seed in self._seeds():
            chaotic = self._released(
                tmp_path, f"chaos_h{seed}",
                fault_injector=runtime.FaultInjector.chaos(
                    seed=seed, n_slabs=N_CHUNKS, fire_percent=50,
                    include_hang=True, hang_s=2.0),
                watchdog_timeout_s=0.5,
                retry_policy=runtime.RetryPolicy(
                    max_retries=20, sleep=lambda s: None))
            for w in clean:
                assert_identical(clean[w], chaotic[w])
