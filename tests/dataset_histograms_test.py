"""Tests for dataset histograms (mirrors tests/dataset_histograms/ in the
reference)."""

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.dataset_histograms import computing_histograms as ch
from pipelinedp_tpu.dataset_histograms import histograms as hist


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def compute(data):
    backend = pdp.LocalBackend()
    result = list(ch.compute_dataset_histograms(data, extractors(), backend))
    assert len(result) == 1
    return result[0]


class TestLogBinning:

    @pytest.mark.parametrize("value,lower,upper", [
        (1, 1, 2),
        (999, 999, 1000),
        (1000, 1000, 1010),
        (1001, 1000, 1010),
        (1234, 1230, 1240),
        (12345, 12300, 12400),
        (1000000, 1000000, 1010000),
    ])
    def test_bin_bounds(self, value, lower, upper):
        assert ch._to_bin_lower_upper_logarithmic(value) == (lower, upper)

    def test_bin_lower_index(self):
        lowers = [0.0, 1.0, 2.0, 3.0]
        assert ch._bin_lower_index(lowers, 0.0) == 0
        assert ch._bin_lower_index(lowers, 1.5) == 1
        assert ch._bin_lower_index(lowers, 3.0) == 2  # last value -> last bin


class TestDatasetHistograms:

    def test_small_dataset(self):
        # user 0: 3 contributions to 'a' (sum 6), 1 to 'b'.
        # user 1: 1 contribution to 'a'.
        data = [(0, "a", 1.0), (0, "a", 2.0), (0, "a", 3.0), (0, "b", 4.0),
                (1, "a", 5.0)]
        h = compute(data)

        # L0: user0 -> 2 partitions, user1 -> 1 partition.
        l0 = {b.lower: b.count for b in h.l0_contributions_histogram.bins}
        assert l0 == {1: 1, 2: 1}
        # L1: user0 -> 4 contributions, user1 -> 1.
        l1 = {b.lower: b.count for b in h.l1_contributions_histogram.bins}
        assert l1 == {1: 1, 4: 1}
        # Linf: pairs (0,a)->3, (0,b)->1, (1,a)->1.
        linf = {b.lower: b.count for b in h.linf_contributions_histogram.bins}
        assert linf == {1: 2, 3: 1}
        # Count per partition: a->4, b->1.
        cpp = {b.lower: b.count for b in h.count_per_partition_histogram.bins}
        assert cpp == {1: 1, 4: 1}
        # Privacy ids per partition: a->2, b->1.
        pidpp = {b.lower: b.count
                 for b in h.count_privacy_id_per_partition.bins}
        assert pidpp == {1: 1, 2: 1}
        # Sum histograms exist and account for all mass.
        assert h.linf_sum_contributions_histogram.total_count() == 3
        assert h.linf_sum_contributions_histogram.total_sum() == pytest.approx(
            15.0)
        assert h.sum_per_partition_histogram.total_count() == 2
        assert h.sum_per_partition_histogram.total_sum() == pytest.approx(15.0)

    def test_large_values_binned_logarithmically(self):
        # One user contributes 12345 times to one partition.
        data = [(0, "a", 1.0)] * 12345
        h = compute(data)
        linf_bins = h.linf_contributions_histogram.bins
        assert len(linf_bins) == 1
        assert linf_bins[0].lower == 12300
        assert linf_bins[0].max == 12345

    def test_preaggregated_matches_raw(self):
        data = [(0, "a", 1.0), (0, "a", 2.0), (0, "b", 4.0), (1, "a", 5.0)]
        raw = compute(data)
        # Pre-aggregate by hand: (pk, (count, sum, n_partitions, n_contrib)).
        preagg = [
            ("a", (2, 3.0, 2, 3)),  # user0 in 'a'
            ("b", (1, 4.0, 2, 3)),  # user0 in 'b'
            ("a", (1, 5.0, 1, 1)),  # user1 in 'a'
        ]
        ext = pdp.PreAggregateExtractors(
            partition_extractor=lambda r: r[0],
            preaggregate_extractor=lambda r: r[1])
        backend = pdp.LocalBackend()
        pre = list(
            ch.compute_dataset_histograms_on_preaggregated_data(
                preagg, ext, backend))[0]
        raw_l0 = {b.lower: b.count
                  for b in raw.l0_contributions_histogram.bins}
        pre_l0 = {b.lower: b.count
                  for b in pre.l0_contributions_histogram.bins}
        assert raw_l0 == pre_l0
        raw_linf = {b.lower: b.count
                    for b in raw.linf_contributions_histogram.bins}
        pre_linf = {b.lower: b.count
                    for b in pre.linf_contributions_histogram.bins}
        assert raw_linf == pre_linf


class TestHistogramMethods:

    def _histogram(self, counts):
        """Builds an integer histogram from a {value: frequency} dict."""
        bins = []
        for value, freq in sorted(counts.items()):
            lower, upper = ch._to_bin_lower_upper_logarithmic(value)
            bins.append(
                hist.FrequencyBin(lower=lower, upper=upper, count=freq,
                                  sum=freq * value, max=value))
        return hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS, bins)

    def test_quantiles(self):
        h = self._histogram({1: 10, 2: 10, 3: 10, 10: 10})
        assert h.quantiles([0.0, 0.5, 0.99]) == [1, 3, 10]

    def test_total_count_sum(self):
        h = self._histogram({1: 5, 10: 2})
        assert h.total_count() == 7
        assert h.total_sum() == 25
        assert h.max_value() == 10

    def test_ratio_dropped(self):
        # 10 elements of size 1, 10 of size 4.
        h = self._histogram({1: 10, 4: 10})
        ratios = dict(hist.compute_ratio_dropped(h))
        assert ratios[0] == 1
        assert ratios[4] == 0.0
        # Threshold 1: drops 3 units from each of the 10 size-4 elements.
        assert ratios[1] == pytest.approx(30 / 50)

    def test_empty_histogram_quantiles_raises(self):
        h = hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS, [])
        with pytest.raises(ValueError):
            h.quantiles([0.5])
