"""Tests for dataset histograms (mirrors tests/dataset_histograms/ in the
reference)."""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.dataset_histograms import computing_histograms as ch
from pipelinedp_tpu.dataset_histograms import histograms as hist


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def compute(data):
    backend = pdp.LocalBackend()
    result = list(ch.compute_dataset_histograms(data, extractors(), backend))
    assert len(result) == 1
    return result[0]


class TestLogBinning:

    @pytest.mark.parametrize("value,lower,upper", [
        (1, 1, 2),
        (999, 999, 1000),
        (1000, 1000, 1010),
        (1001, 1000, 1010),
        (1234, 1230, 1240),
        (12345, 12300, 12400),
        (1000000, 1000000, 1010000),
    ])
    def test_bin_bounds(self, value, lower, upper):
        assert ch._to_bin_lower_upper_logarithmic(value) == (lower, upper)

    def test_bin_lower_index(self):
        lowers = [0.0, 1.0, 2.0, 3.0]
        assert ch._bin_lower_index(lowers, 0.0) == 0
        assert ch._bin_lower_index(lowers, 1.5) == 1
        assert ch._bin_lower_index(lowers, 3.0) == 2  # last value -> last bin


class TestDatasetHistograms:

    def test_small_dataset(self):
        # user 0: 3 contributions to 'a' (sum 6), 1 to 'b'.
        # user 1: 1 contribution to 'a'.
        data = [(0, "a", 1.0), (0, "a", 2.0), (0, "a", 3.0), (0, "b", 4.0),
                (1, "a", 5.0)]
        h = compute(data)

        # L0: user0 -> 2 partitions, user1 -> 1 partition.
        l0 = {b.lower: b.count for b in h.l0_contributions_histogram.bins}
        assert l0 == {1: 1, 2: 1}
        # L1: user0 -> 4 contributions, user1 -> 1.
        l1 = {b.lower: b.count for b in h.l1_contributions_histogram.bins}
        assert l1 == {1: 1, 4: 1}
        # Linf: pairs (0,a)->3, (0,b)->1, (1,a)->1.
        linf = {b.lower: b.count for b in h.linf_contributions_histogram.bins}
        assert linf == {1: 2, 3: 1}
        # Count per partition: a->4, b->1.
        cpp = {b.lower: b.count for b in h.count_per_partition_histogram.bins}
        assert cpp == {1: 1, 4: 1}
        # Privacy ids per partition: a->2, b->1.
        pidpp = {b.lower: b.count
                 for b in h.count_privacy_id_per_partition.bins}
        assert pidpp == {1: 1, 2: 1}
        # Sum histograms exist and account for all mass.
        assert h.linf_sum_contributions_histogram.total_count() == 3
        assert h.linf_sum_contributions_histogram.total_sum() == pytest.approx(
            15.0)
        assert h.sum_per_partition_histogram.total_count() == 2
        assert h.sum_per_partition_histogram.total_sum() == pytest.approx(15.0)

    def test_large_values_binned_logarithmically(self):
        # One user contributes 12345 times to one partition.
        data = [(0, "a", 1.0)] * 12345
        h = compute(data)
        linf_bins = h.linf_contributions_histogram.bins
        assert len(linf_bins) == 1
        assert linf_bins[0].lower == 12300
        assert linf_bins[0].max == 12345

    def test_preaggregated_matches_raw(self):
        data = [(0, "a", 1.0), (0, "a", 2.0), (0, "b", 4.0), (1, "a", 5.0)]
        raw = compute(data)
        # Pre-aggregate by hand: (pk, (count, sum, n_partitions, n_contrib)).
        preagg = [
            ("a", (2, 3.0, 2, 3)),  # user0 in 'a'
            ("b", (1, 4.0, 2, 3)),  # user0 in 'b'
            ("a", (1, 5.0, 1, 1)),  # user1 in 'a'
        ]
        ext = pdp.PreAggregateExtractors(
            partition_extractor=lambda r: r[0],
            preaggregate_extractor=lambda r: r[1])
        backend = pdp.LocalBackend()
        pre = list(
            ch.compute_dataset_histograms_on_preaggregated_data(
                preagg, ext, backend))[0]
        raw_l0 = {b.lower: b.count
                  for b in raw.l0_contributions_histogram.bins}
        pre_l0 = {b.lower: b.count
                  for b in pre.l0_contributions_histogram.bins}
        assert raw_l0 == pre_l0
        raw_linf = {b.lower: b.count
                    for b in raw.linf_contributions_histogram.bins}
        pre_linf = {b.lower: b.count
                    for b in pre.linf_contributions_histogram.bins}
        assert raw_linf == pre_linf


class TestHistogramMethods:

    def _histogram(self, counts):
        """Builds an integer histogram from a {value: frequency} dict."""
        bins = []
        for value, freq in sorted(counts.items()):
            lower, upper = ch._to_bin_lower_upper_logarithmic(value)
            bins.append(
                hist.FrequencyBin(lower=lower, upper=upper, count=freq,
                                  sum=freq * value, max=value))
        return hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS, bins)

    def test_quantiles(self):
        h = self._histogram({1: 10, 2: 10, 3: 10, 10: 10})
        assert h.quantiles([0.0, 0.5, 0.99]) == [1, 3, 10]

    def test_total_count_sum(self):
        h = self._histogram({1: 5, 10: 2})
        assert h.total_count() == 7
        assert h.total_sum() == 25
        assert h.max_value() == 10

    def test_ratio_dropped(self):
        # 10 elements of size 1, 10 of size 4.
        h = self._histogram({1: 10, 4: 10})
        ratios = dict(hist.compute_ratio_dropped(h))
        assert ratios[0] == 1
        assert ratios[4] == 0.0
        # Threshold 1: drops 3 units from each of the 10 size-4 elements.
        assert ratios[1] == pytest.approx(30 / 50)

    def test_empty_histogram_quantiles_raises(self):
        h = hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS, [])
        with pytest.raises(ValueError):
            h.quantiles([0.5])


class TestColumnarHistograms:
    """The columnar fast path must produce bit-identical Histogram objects
    to the per-row pipeline."""

    def _row_histograms(self, rows):
        result = list(
            ch.compute_dataset_histograms(rows, extractors(),
                                          pdp.LocalBackend()))
        return result[0]

    def _columnar_histograms(self, rows):
        pid = np.array([r[0] for r in rows])
        pk = np.array([r[1] for r in rows])
        value = np.array([r[2] for r in rows])
        result = list(
            ch.compute_dataset_histograms(
                pdp.ColumnarData(pid=pid, pk=pk, value=value), None, None))
        return result[0]

    def _assert_histograms_equal(self, a, b):
        import dataclasses
        for field in dataclasses.fields(a):
            ha = getattr(a, field.name)
            hb = getattr(b, field.name)
            assert (ha is None) == (hb is None), field.name
            if ha is None:
                continue
            assert len(ha.bins) == len(hb.bins), field.name
            for ba, bb in zip(ha.bins, hb.bins):
                assert ba.lower == pytest.approx(bb.lower), field.name
                assert ba.upper == pytest.approx(bb.upper), field.name
                assert ba.count == bb.count, field.name
                assert ba.sum == pytest.approx(bb.sum), field.name
                assert ba.max == pytest.approx(bb.max), field.name

    def test_matches_row_pipeline_random(self):
        rng = np.random.default_rng(0)
        rows = [(int(rng.integers(0, 40)), int(rng.integers(0, 15)),
                 float(rng.uniform(-3, 20))) for _ in range(3000)]
        self._assert_histograms_equal(self._row_histograms(rows),
                                      self._columnar_histograms(rows))

    def test_matches_row_pipeline_heavy_hitters(self):
        # Exercise the log-binning boundaries: counts beyond 1000 and
        # exact powers of ten.
        rows = []
        for i in range(1500):
            rows.append((1, 1, 1.0))
        for i in range(1000):
            rows.append((2, 2, 2.0))
        for i in range(10000):
            rows.append((3, 3, 0.5))
        rows.append((4, 4, 7.0))
        self._assert_histograms_equal(self._row_histograms(rows),
                                      self._columnar_histograms(rows))

    def test_constant_values_single_float_bin(self):
        rows = [(u, 0, 2.5) for u in range(10)]
        cols = self._columnar_histograms(rows)
        assert len(cols.linf_sum_contributions_histogram.bins) == 1
        self._assert_histograms_equal(self._row_histograms(rows),
                                      cols)

    def test_scales_to_millions(self):
        rng = np.random.default_rng(1)
        n = 3_000_000
        data = pdp.ColumnarData(pid=rng.integers(0, 300_000, n),
                                pk=rng.integers(0, 50_000, n),
                                value=rng.uniform(0, 10, n))
        result = list(ch.compute_dataset_histograms(data, None, None))[0]
        n_users = len(np.unique(data.pid))
        assert result.l0_contributions_histogram.total_count() == n_users
