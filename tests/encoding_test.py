"""Tests for vectorized host encoding (ops/encoding.py)."""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.ops import encoding


class TestFactorize:

    def test_int_keys(self):
        ids, uniques = encoding._factorize(np.array([5, 3, 5, 9, 3]))
        assert list(uniques[ids]) == [5, 3, 5, 9, 3]
        assert len(uniques) == 3

    def test_string_keys(self):
        ids, uniques = encoding._factorize(np.array(["b", "a", "b"]))
        assert list(uniques[ids]) == ["b", "a", "b"]

    def test_object_keys(self):
        col = np.empty(3, dtype=object)
        col[:] = [("x", 1), ("y", 2), ("x", 1)]
        ids, uniques = encoding._factorize(col)
        assert ids[0] == ids[2] != ids[1]
        assert uniques[ids[1]] == ("y", 2)


class TestEncodeRows:

    def test_matches_per_row_semantics(self):
        rows = [(u, f"pk{u % 7}", float(u)) for u in range(1000)]
        pid, pk, value, pid_vocab, pk_vocab = encoding.encode_rows(
            rows, lambda r: r[0], lambda r: r[1], lambda r: r[2])
        assert len(pid) == 1000
        # Round trip: decode gives back original keys.
        for i in (0, 13, 999):
            assert pk_vocab.decode(int(pk[i])) == rows[i][1]
            assert pid_vocab.decode(int(pid[i])) == rows[i][0]
            assert value[i] == pytest.approx(rows[i][2])

    def test_public_partition_filter(self):
        rows = [(1, "a", 1.0), (2, "b", 2.0), (3, "c", 3.0)]
        pid, pk, value, _, pk_vocab = encoding.encode_rows(
            rows, lambda r: r[0], lambda r: r[1], lambda r: r[2],
            public_partitions=["a", "c", "zzz"])
        assert len(pid) == 2
        assert pk_vocab.keys == ["a", "c", "zzz"]
        decoded = [pk_vocab.decode(int(p)) for p in pk]
        assert decoded == ["a", "c"]


class TestColumnarData:

    def test_raw_columns_equal_rows(self):
        n = 500
        rng = np.random.default_rng(0)
        pids = rng.integers(100, 150, n)
        pks = rng.integers(0, 11, n)
        vals = rng.uniform(0, 1, n)
        rows = list(zip(pids.tolist(), pks.tolist(), vals.tolist()))
        r1 = encoding.encode_rows(rows, lambda r: r[0], lambda r: r[1],
                                  lambda r: r[2])
        r2 = encoding.encode_rows(
            encoding.ColumnarData(pid=pids, pk=pks, value=vals),
            lambda r: r[0], lambda r: r[1], lambda r: r[2])
        # Same grouping structure (vocab order may differ).
        for (a_pid, a_pk, a_val, _, a_vocab), (b_pid, b_pk, b_val, _,
                                               b_vocab) in [(r1, r2)]:
            a_keys = [a_vocab.decode(int(i)) for i in a_pk]
            b_keys = [b_vocab.decode(int(i)) for i in b_pk]
            assert a_keys == b_keys
            np.testing.assert_allclose(a_val, b_val)

    def test_engine_accepts_columnar_without_extractors(self):
        n = 300
        rng = np.random.default_rng(1)
        data = pdp.ColumnarData(pid=rng.integers(0, 50, n),
                                pk=rng.integers(0, 3, n),
                                value=rng.uniform(0, 5, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=3,
            max_contributions_per_partition=100,
            min_value=0, max_value=5)
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-15)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(data, params, public_partitions=[0, 1, 2])
        accountant.compute_budgets()
        out = dict(result)
        raw = np.bincount(np.asarray(data.pk), minlength=3)
        for k in range(3):
            assert out[k].count == pytest.approx(raw[k], abs=0.01)


class TestEncodedColumns:

    def test_zero_copy_path(self):
        n = 200
        rng = np.random.default_rng(2)
        data = pdp.EncodedColumns(pid=rng.integers(0, 40, n, dtype=np.int32),
                                  pk=rng.integers(0, 5, n, dtype=np.int32),
                                  num_partitions=5,
                                  value=rng.uniform(0, 1, n))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=5,
            max_contributions_per_partition=100)
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-15)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(data, params,
                                  public_partitions=[0, 1, 2, 3, 4])
        accountant.compute_budgets()
        out = dict(result)
        raw = np.bincount(np.asarray(data.pk), minlength=5)
        for k in range(5):
            assert out[k].count == pytest.approx(raw[k], abs=0.01)

    def test_public_filter_drops_non_public_ids(self):
        data = pdp.EncodedColumns(pid=np.arange(6, dtype=np.int32),
                                  pk=np.array([0, 1, 2, 0, 1, 2], np.int32),
                                  num_partitions=3,
                                  pk_keys=["a", "b", "c"])
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=3,
            max_contributions_per_partition=1)
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-15)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(data, params, public_partitions=["a", "b"])
        accountant.compute_budgets()
        out = dict(result)
        assert set(out) == {"a", "b"}
        assert out["a"].count == pytest.approx(2, abs=0.01)


class TestEncodingThroughput:

    def test_vectorized_encoding_is_fast(self):
        # 2M rows must encode in well under a second (the round-1 per-row
        # loop took ~10s at this size).
        import time
        n = 2_000_000
        rng = np.random.default_rng(3)
        pid = rng.integers(0, 200_000, n)
        pk = rng.integers(0, 20_000, n)
        value = rng.uniform(0, 5, n)
        t0 = time.perf_counter()
        out = encoding.encode_columns(pid, pk, value)
        elapsed = time.perf_counter() - t0
        assert len(out[0]) == n
        assert elapsed < 2.0


class TestCompositeKeys:

    def test_tuple_partition_keys(self):
        rows = [(1, ("us", 5), 1.0), (2, ("de", 3), 2.0), (3, ("us", 5), 3.0)]
        pid, pk, value, _, pk_vocab = encoding.encode_rows(
            rows, lambda r: r[0], lambda r: r[1], lambda r: r[2])
        assert pk.shape == (3,)
        assert pk[0] == pk[2] != pk[1]
        assert pk_vocab.decode(int(pk[0])) == ("us", 5)

    def test_mixed_type_keys_not_coerced(self):
        rows = [(1, 1, 1.0), (2, "a", 2.0), (3, 1, 3.0)]
        _, pk, _, _, pk_vocab = encoding.encode_rows(
            rows, lambda r: r[0], lambda r: r[1], lambda r: r[2])
        assert pk[0] == pk[2] != pk[1]
        assert pk_vocab.decode(int(pk[0])) == 1  # stays int, not "1"


class TestBoundsAlreadyEnforced:

    def _params(self):
        return pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                   max_partitions_contributed=2,
                                   max_contributions_per_partition=1,
                                   contribution_bounds_already_enforced=True)

    def test_rows_path(self):
        rows = [("a",), ("a",), ("b",)]
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-15)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(
            rows, self._params(),
            pdp.DataExtractors(privacy_id_extractor=lambda r: None,
                               partition_extractor=lambda r: r[0],
                               value_extractor=lambda r: 0.0),
            public_partitions=["a", "b"])
        accountant.compute_budgets()
        out = dict(result)
        assert out["a"].count == pytest.approx(2, abs=0.01)

    def test_columnar_path(self):
        data = pdp.ColumnarData(pid=np.zeros(3, np.int32),
                                pk=np.array([0, 0, 1], np.int32),
                                value=np.zeros(3, np.float32))
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-15)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(data, self._params(),
                                  public_partitions=[0, 1])
        accountant.compute_budgets()
        out = dict(result)
        # Each row its own unit: both rows of pk 0 counted.
        assert out[0].count == pytest.approx(2, abs=0.01)
