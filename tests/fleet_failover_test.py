"""Fleet failover: leased single-writer sessions, hot followers, and
exactly-once releases across host death (ISSUE 19).

Two layers:

  * **Two-process failover scenario** — fresh ``python
    tests/kill_harness.py fleet_*`` subprocesses sharing only the
    filesystem: the primary is SIGKILLed mid-release (token durably
    committed, outcome record lost), a follower that tailed its WAL
    promotes and runs the catch-up tick. The released stream across
    the kill must be byte-identical to an uninterrupted run, the
    half-released window must recover with its charge exactly
    refunded, and a superseded ex-primary's append must be refused at
    the WAL (fenced + dead-lettered). Zero double-spends.
  * **In-process unit tests** — the lease protocol (acquire / renew /
    fence / release / takeover eligibility), the truncation-free WAL
    reader, read-only session refusals, the router's ownership /
    shedding / hedging rules, and decorrelated-jitter determinism.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from pipelinedp_tpu import profiler
from pipelinedp_tpu.runtime import journal as journal_lib
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import watchdog as watchdog_lib
from pipelinedp_tpu.serving import fleet as fleet_lib

_HARNESS = os.path.join(os.path.dirname(__file__), "kill_harness.py")


def _run_harness(mode: str, workdir: str,
                 mesh: bool = False) -> subprocess.CompletedProcess:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop("PDP_KH_MESH", None)
    if mesh:
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["PDP_KH_MESH"] = "8"
    return subprocess.run(
        [sys.executable, _HARNESS, mode, workdir],
        capture_output=True, text=True, env=env, timeout=300)


def _marker(proc: subprocess.CompletedProcess, prefix: str) -> str:
    lines = [line for line in proc.stdout.splitlines()
             if line.startswith(prefix)]
    assert lines, (f"no {prefix} marker in harness output;\n"
                   f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    return lines[-1]


def _json_marker(proc: subprocess.CompletedProcess, prefix: str):
    return json.loads(_marker(proc, prefix)[len(prefix):])


def _ledger(proc: subprocess.CompletedProcess) -> float:
    return float(_marker(proc, "HARNESS_LEDGER ").split()[1])


@pytest.fixture(scope="module", params=[
    "single", pytest.param("mesh8", marks=pytest.mark.slow)])
def fleet_run(request, tmp_path_factory):
    """Runs the primary-kill -> follower-promote -> stale-writer
    scenario once per leg; the tests below assert its facets."""
    mesh = request.param == "mesh8"
    clean_dir = str(tmp_path_factory.mktemp("fleet_clean"))
    kill_dir = str(tmp_path_factory.mktemp("fleet_kill"))
    clean = _run_harness("fleet_clean", clean_dir, mesh=mesh)
    assert clean.returncode == 0, clean.stderr
    primary = _run_harness("fleet_primary", kill_dir, mesh=mesh)
    follower = _run_harness("fleet_follower", kill_dir, mesh=mesh)
    assert follower.returncode == 0, (
        f"stdout:\n{follower.stdout}\nstderr:\n{follower.stderr}")
    stale = _run_harness("fleet_stale", kill_dir, mesh=mesh)
    assert stale.returncode == 0, (
        f"stdout:\n{stale.stdout}\nstderr:\n{stale.stderr}")
    return {"clean": clean, "primary": primary, "follower": follower,
            "stale": stale, "kill_dir": kill_dir}


class TestFleetFailoverScenario:
    """The two-process acceptance: host death between the release
    token commit and the outcome record, survived exactly once."""

    def test_primary_died_by_sigkill_mid_release(self, fleet_run):
        primary = fleet_run["primary"]
        assert primary.returncode == -signal.SIGKILL
        assert "HARNESS_NOT_KILLED" not in primary.stdout
        # Tick #1's window released and printed before the kill ...
        windows = _json_marker(primary, "HARNESS_LIVE_WINDOWS ")
        assert set(windows) == {"0,1"}
        # ... and the lease showed this process as the live holder.
        lease = _json_marker(primary, "HARNESS_LEASE ")
        assert lease["held"] and not lease["released"]

    def test_follower_tailed_and_observed_dead_holder(self, fleet_run):
        follower = fleet_run["follower"]
        lag = _json_marker(follower, "HARNESS_FLEET_LAG ")
        assert lag["records_behind"] == 0
        status = _json_marker(follower, "HARNESS_FLEET_STATUS ")
        assert status["role"] == "follower"
        assert status["epoch"] == 4  # all four appends digest-replayed
        assert status["applied"] >= 4
        assert status["primary_dead"] is True
        # The dead primary's unexpired, unreleased lease is still on
        # disk — only the same-host pid-liveness probe makes the
        # takeover eligible.
        assert status["holder"] is not None
        assert not status["holder"]["released"]

    def test_promotion_bumps_fencing_token(self, fleet_run):
        old = _json_marker(fleet_run["primary"], "HARNESS_LEASE ")
        new = _json_marker(fleet_run["follower"], "HARNESS_LEASE ")
        assert new["token"] > old["token"]
        assert new["held"] and not new["released"]

    def test_committed_release_recovered_uncommitted_reissued(
            self, fleet_run):
        follower = fleet_run["follower"]
        due = _json_marker(follower, "HARNESS_LIVE_DUE ")
        assert [1, 2] in due and [2, 3] in due
        assert [0, 1] not in due  # committed with outcome, not due
        outcomes = dict(
            (tuple(w), o)
            for w, o in _json_marker(follower, "HARNESS_LIVE_OUTCOMES "))
        # [1,2)'s token committed before the SIGKILL: the durable
        # journal refuses the re-run and the charge is refunded.
        assert outcomes[(1, 2)] == "recovered"
        # [2,3) was never attempted: re-issued fresh by the successor.
        assert outcomes[(2, 3)] == "released"

    def test_released_stream_byte_identical_across_host_death(
            self, fleet_run):
        clean = _json_marker(fleet_run["clean"], "HARNESS_LIVE_WINDOWS ")
        pre_kill = _json_marker(fleet_run["primary"],
                                "HARNESS_LIVE_WINDOWS ")
        post_kill = _json_marker(fleet_run["follower"],
                                 "HARNESS_LIVE_WINDOWS ")
        assert set(clean) == {"0,1", "1,2", "2,3"}
        # The stream observed by a subscriber across the failover ==
        # the primary's pre-kill windows + the successor's catch-up,
        # byte-for-byte what one uninterrupted process released.
        assert pre_kill["0,1"] == clean["0,1"]
        assert post_kill["2,3"] == clean["2,3"]

    def test_union_query_and_warm_read_byte_identical(self, fleet_run):
        clean = _json_marker(fleet_run["clean"],
                             "HARNESS_RESULT ")["columns"]
        promoted = _json_marker(fleet_run["follower"],
                                "HARNESS_RESULT ")["columns"]
        warm_ro = _json_marker(fleet_run["follower"],
                               "HARNESS_RO_RESULT ")["columns"]
        assert promoted == clean
        # The follower's pre-promotion warm read served the same bits
        # off its digest-verified replica.
        assert warm_ro == clean

    def test_no_double_spend_exact_refund(self, fleet_run):
        # clean: 3 windows @ 0.5 + union @ 1.0. Failover path: the
        # primary durably charged [0,1) and [1,2); the successor's
        # [1,2) catch-up charge was exactly refunded on refusal, then
        # [2,3) + union charged. Identical totals or money leaked.
        assert _ledger(fleet_run["follower"]) == pytest.approx(
            _ledger(fleet_run["clean"]), abs=1e-9)

    def test_stale_writer_fenced_and_deadlettered(self, fleet_run):
        fenced = _json_marker(fleet_run["stale"], "HARNESS_FENCED ")
        assert fenced["new_token"] > fenced["old_token"]
        assert fenced["fenced_appends"] >= 1
        assert fenced["deadletters"] >= 1
        assert "HARNESS_STALE_ALLOWED" not in fleet_run["stale"].stdout


# -- in-process unit tests ---------------------------------------------------


class TestSessionLease:

    def _path(self, tmp_path) -> str:
        return str(tmp_path / "lease.json")

    def test_acquire_renew_release_roundtrip(self, tmp_path):
        path = self._path(tmp_path)
        lease = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert lease.token == 1
        on_disk = fleet_lib.read_lease(path)
        assert on_disk["token"] == 1 and on_disk["pid"] == os.getpid()
        before = on_disk["expires_unix"]
        lease.renew()
        assert fleet_lib.read_lease(path)["expires_unix"] >= before
        assert lease.status()["renewals"] == 1
        lease.release()
        assert fleet_lib.read_lease(path)["released"] is True
        lease.release()  # idempotent

    def test_released_lease_taken_over_immediately(self, tmp_path):
        path = self._path(tmp_path)
        fleet_lib.SessionLease.acquire(path, ttl_s=30.0).release()
        lease = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert lease.token == 2

    def test_live_foreign_holder_refused_force_overrides(self, tmp_path):
        path = self._path(tmp_path)
        now = time.time()
        # A holder on another host with time left on the clock: no
        # pid probe can decide, so the takeover must wait (or force).
        fleet_lib._write_lease(path, {
            "token": 7, "pid": 12345, "host": "another-host",
            "ttl_s": 30.0, "renewed_unix": now,
            "expires_unix": now + 30.0, "released": False})
        with pytest.raises(fleet_lib.LeaseHeldError):
            fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        lease = fleet_lib.SessionLease.acquire(path, ttl_s=30.0,
                                               force=True)
        assert lease.token == 8  # strictly increasing across takeovers

    def test_expired_foreign_holder_taken_over(self, tmp_path):
        path = self._path(tmp_path)
        now = time.time()
        fleet_lib._write_lease(path, {
            "token": 3, "pid": 12345, "host": "another-host",
            "ttl_s": 1.0, "renewed_unix": now - 10.0,
            "expires_unix": now - 9.0, "released": False})
        lease = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert lease.token == 4

    def test_dead_same_host_holder_taken_over(self, tmp_path):
        path = self._path(tmp_path)
        # A genuinely dead same-host pid — the SIGKILL'd-primary
        # shape, with an unexpired lease.
        child = subprocess.Popen([sys.executable, "-c", "pass"])
        child.wait()
        now = time.time()
        fleet_lib._write_lease(path, {
            "token": 5, "pid": child.pid,
            "host": socket.gethostname(), "ttl_s": 300.0,
            "renewed_unix": now, "expires_unix": now + 300.0,
            "released": False})
        lease = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert lease.token == 6

    def test_admit_fences_superseded_writer(self, tmp_path):
        path = self._path(tmp_path)
        old = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert old.admit() == old.token
        new = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert new.token == old.token + 1
        before = profiler.event_count(fleet_lib.EVENT_FENCED_WRITES)
        with pytest.raises(fleet_lib.LeaseLostError):
            old.admit()
        assert profiler.event_count(
            fleet_lib.EVENT_FENCED_WRITES) == before + 1
        with pytest.raises(fleet_lib.LeaseLostError):
            old.renew()
        assert new.admit() == new.token
        # A superseded lease's release must NOT clobber the successor.
        old.release()
        assert fleet_lib.read_lease(path)["token"] == new.token
        assert not fleet_lib.read_lease(path)["released"]

    def test_admit_survives_mere_expiry_without_successor(self, tmp_path):
        # Expiry alone does not fence: until a successor claims a new
        # token there is nobody the write could race.
        path = self._path(tmp_path)
        t = [1000.0]
        lease = fleet_lib.SessionLease.acquire(
            path, ttl_s=5.0, clock=lambda: t[0])
        t[0] += 100.0
        assert lease.admit() == lease.token

    def test_stale_claim_file_swept(self, tmp_path):
        path = self._path(tmp_path)
        claim = path + ".claim.1"
        with open(claim, "w") as f:
            f.write("")
        old = time.time() - 3600.0
        os.utime(claim, (old, old))
        lease = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert lease.token == 1
        assert not os.path.exists(claim)

    def test_garbage_lease_file_treated_as_absent(self, tmp_path):
        path = self._path(tmp_path)
        with open(path, "w") as f:
            f.write("{not json")
        assert fleet_lib.read_lease(path) is None
        lease = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert lease.token == 1

    def test_maintain_paces_on_monotonic_deadline(self, tmp_path):
        path = self._path(tmp_path)
        lease = fleet_lib.SessionLease.acquire(path, ttl_s=30.0)
        assert lease.maintain() is False  # plenty of TTL left
        lease._deadline = watchdog_lib.Deadline.after(0.0)
        assert lease.maintain() is True
        assert lease.status()["renewals"] == 1


class TestDecorrelatedJitter:

    def test_default_backoff_unchanged(self):
        policy = retry_lib.RetryPolicy(max_retries=3, backoff_base_s=0.1,
                                       backoff_max_s=2.0)
        assert [policy.backoff_s(a) for a in range(3)] == [0.1, 0.2, 0.4]

    def test_decorrelated_is_deterministic_under_seed(self):
        def run():
            policy = retry_lib.RetryPolicy(
                max_retries=5, backoff_base_s=0.1, backoff_max_s=2.0,
                jitter="decorrelated", jitter_seed=42)
            return [policy.backoff_s(a) for a in range(5)]

        first, second = run(), run()
        assert first == second
        assert all(0.1 <= d <= 2.0 for d in first)
        # Jittered: not the deterministic exponential ladder.
        assert first != [0.1, 0.2, 0.4, 0.8, 1.6]

    def test_reset_backoff_restarts_the_walk(self):
        policy = retry_lib.RetryPolicy(
            max_retries=5, backoff_base_s=0.1, backoff_max_s=2.0,
            jitter="decorrelated", jitter_seed=7)
        first = policy.backoff_s(0)
        policy.reset_backoff()
        # The walk restarts from base (the rng stream continues — only
        # the "previous delay" anchor resets).
        assert policy.backoff_s(0) <= max(first * 3.0, 0.1 * 3.0)

    def test_unknown_jitter_refused(self):
        with pytest.raises(ValueError):
            retry_lib.RetryPolicy(jitter="thundering-herd")


class TestReadRecords:

    def test_reads_without_truncating_torn_tail(self, tmp_path):
        path = str(tmp_path / "tail.wal")
        wal = journal_lib.JsonlWal(path)
        wal.append({"seq": 0, "kind": "a", "n": 1})
        wal.append({"seq": 1, "kind": "a", "n": 2})
        wal.close()
        with open(path, "ab") as f:
            f.write(b'{"torn": ')  # a crash mid-write
        size_before = os.path.getsize(path)
        records = journal_lib.read_records(path)
        assert [r["n"] for r in records] == [1, 2]
        # A follower must NEVER repair the primary's file.
        assert os.path.getsize(path) == size_before

    def test_missing_file_is_empty(self, tmp_path):
        assert journal_lib.read_records(str(tmp_path / "absent")) == []


class TestFleetRouter:

    class _Host:
        def __init__(self, name, overloaded=False, broken=False):
            self.name = name
            self.overloaded = overloaded
            self.broken = broken
            self.queries = 0

        def stats(self):
            if self.broken:
                raise RuntimeError("down")
            return {}

        def query(self, params, **kwargs):
            from pipelinedp_tpu.serving.manager import \
                SessionOverloadedError
            if self.overloaded:
                raise SessionOverloadedError(8, 8)
            self.queries += 1
            return self.name

    def _router(self, *hosts, **kwargs):
        router = fleet_lib.FleetRouter(**kwargs)
        for host in hosts:
            router.add_host(host.name, host)
        return router

    def test_ownership_is_stable_and_deterministic(self):
        a, b, c = (self._Host(n) for n in ("a", "b", "c"))
        router = self._router(a, b, c)
        other = self._router(self._Host("a"), self._Host("b"),
                             self._Host("c"))
        owners = {k: router.owner_of(k) for k in range(32)}
        assert owners == {k: other.owner_of(k) for k in range(32)}
        assert len(set(owners.values())) > 1  # spreads across the ring
        for k, owner in owners.items():
            assert router.query(None, shard_key=k) == owner

    def test_sheds_across_hosts_before_surfacing_overload(self):
        a, b = self._Host("a", overloaded=True), self._Host("b",
                                                            overloaded=True)
        router = self._router(a, b)
        before = profiler.event_count(fleet_lib.EVENT_CROSS_HOST_SHEDS)
        key = next(k for k in range(64) if router.owner_of(k) == "a")
        a.overloaded = False
        assert router.query(None, shard_key=key) == "a"  # owner first
        a.overloaded = True
        b.overloaded = False
        assert router.query(None, shard_key=key) == "b"  # shed across
        assert profiler.event_count(
            fleet_lib.EVENT_CROSS_HOST_SHEDS) > before
        b.overloaded = True
        from pipelinedp_tpu.serving.manager import SessionOverloadedError
        with pytest.raises(SessionOverloadedError):
            router.query(None, shard_key=key)

    def test_unhealthy_owner_skipped(self):
        a, b = self._Host("a", broken=True), self._Host("b")
        router = self._router(a, b)
        key = next(k for k in range(64) if router.owner_of(k) == "a")
        assert router.query(None, shard_key=key) == "b"
        router.set_health("a", True)  # operator override wins
        a.broken = False
        assert router.query(None, shard_key=key) == "a"
        router.set_health("a", False)
        assert router.query(None, shard_key=key) == "b"
        router.set_health("b", False)
        with pytest.raises(RuntimeError, match="no healthy hosts"):
            router.query(None, shard_key=key)

    def test_hedges_warm_reads_near_deadline(self):
        primary = self._Host("a")

        class _Replica:
            def __init__(self):
                self.queries = 0

            def query(self, params, **kwargs):
                self.queries += 1
                return "replica"

        class _Follower:
            def __init__(self):
                self.session = _Replica()

            def statusz(self):
                return {}

        follower = _Follower()
        router = self._router(primary, hedge_fraction=0.25)
        router.add_follower(follower)
        fat = watchdog_lib.Deadline.after(1000.0)
        assert router.query(None, deadline=fat) == "a"
        assert follower.session.queries == 0
        burnt = watchdog_lib.Deadline.after(0.0)
        assert router.query(None, deadline=burnt) == "replica"
        assert follower.session.queries == 1
        # Tenant queries never hedge: ledgers are single-writer state.
        assert router.query(None, deadline=burnt, tenant="acme") == "a"
        assert follower.session.queries == 1

    def test_statusz_shape(self):
        router = self._router(self._Host("a"))
        payload = router.statusz()
        assert payload["hosts"]["a"]["healthy"] is True
        assert payload["hedge_fraction"] == 0.25


class TestFleetKnobs:

    def test_lease_ttl_env(self, monkeypatch):
        assert fleet_lib.lease_ttl_s() == 30.0
        monkeypatch.setenv(fleet_lib.LEASE_TTL_ENV, "120")
        assert fleet_lib.lease_ttl_s() == 120.0

    def test_follower_poll_env(self, monkeypatch):
        assert fleet_lib.follower_poll_s() == pytest.approx(0.1)
        monkeypatch.setenv(fleet_lib.FOLLOWER_POLL_ENV, "250")
        assert fleet_lib.follower_poll_s() == pytest.approx(0.25)

    def test_counters_surface(self):
        counters = fleet_lib.fleet_counters()
        for key in ("lease_renewals", "lease_takeovers", "fenced_writes",
                    "promotions", "follower_polls", "follower_records",
                    "hedged_reads", "hedged_hits", "cross_host_sheds"):
            assert isinstance(counters[key], int)
