"""Streaming (chunked pid-disjoint) execution path tests.

The streaming path must be *exact*: same aggregates as the single-shot
kernel when bounds don't bind, same enforced caps when they do, and the
same public API results regardless of chunking (ops/streaming.py).
"""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.ops import streaming


def _data(n=50_000, n_partitions=200, seed=0):
    rng = np.random.default_rng(seed)
    pid = rng.integers(1000, 9000, n).astype(np.int64)  # non-dense ids
    pk = rng.integers(0, n_partitions, n).astype(np.int32)
    value = rng.uniform(0, 5, n).astype(np.float32)
    return pid, pk, value


def _run(pid, pk, value, stream_chunks, *, vdtype=None, caps=(200, 1000),
         metrics=None, public=True, n_partitions=200):
    accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
    engine = pdp.JaxDPEngine(accountant,
                             seed=3,
                             stream_chunks=stream_chunks,
                             value_transfer_dtype=vdtype,
                             secure_host_noise=False)
    params = pdp.AggregateParams(
        metrics=metrics or [pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=caps[0],
        max_contributions_per_partition=caps[1],
        min_value=0.0,
        max_value=5.0)
    result = engine.aggregate(
        pdp.ColumnarData(pid=pid, pk=pk, value=value),
        params,
        public_partitions=list(range(n_partitions)) if public else None)
    accountant.compute_budgets()
    return result.to_columns()


class TestStreamingParity:

    def test_matches_groupby_when_caps_do_not_bind(self):
        pid, pk, value = _data()
        truth_count = np.zeros(200)
        truth_sum = np.zeros(200)
        np.add.at(truth_count, pk, 1)
        np.add.at(truth_sum, pk, value)
        cols = _run(pid, pk, value, stream_chunks=8)
        np.testing.assert_allclose(cols["count"], truth_count, atol=0.01)
        np.testing.assert_allclose(cols["sum"], truth_sum, rtol=1e-4)

    def test_f16_transfer_close_to_f32(self):
        pid, pk, value = _data()
        c32 = _run(pid, pk, value, stream_chunks=8)
        c16 = _run(pid, pk, value, stream_chunks=8, vdtype=np.float16)
        np.testing.assert_allclose(c16["sum"], c32["sum"], rtol=2e-3)

    def test_caps_enforced_identically_to_single_shot(self):
        pid, pk, value = _data()
        t_single = _run(pid, pk, value, 1, caps=(3, 2),
                        metrics=[pdp.Metrics.COUNT])["count"].sum()
        t_stream = _run(pid, pk, value, 8, caps=(3, 2),
                        metrics=[pdp.Metrics.COUNT])["count"].sum()
        n_users = len(np.unique(pid))
        assert t_single <= n_users * 6 + 1
        assert t_stream <= n_users * 6 + 1
        # Both paths sample with the same distribution: totals agree to <1%.
        assert abs(t_single - t_stream) / t_single < 0.01

    def test_privacy_id_count_adds_across_chunks(self):
        pid, pk, value = _data()
        truth = np.zeros(200)
        for p in set(map(tuple, np.stack([pid, pk], 1).tolist())):
            truth[p[1]] += 1
        cols = _run(pid, pk, value, 8,
                    metrics=[pdp.Metrics.PRIVACY_ID_COUNT])
        np.testing.assert_allclose(cols["privacy_id_count"], truth,
                                   atol=0.01)

    def test_private_selection_on_streamed_accumulators(self):
        pid, pk, value = _data()
        accountant = pdp.NaiveBudgetAccountant(30.0, 1e-4)
        engine = pdp.JaxDPEngine(accountant, seed=3, stream_chunks=8,
                                 secure_host_noise=False)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=10,
            max_contributions_per_partition=10,
            min_value=0.0, max_value=5.0)
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params)
        accountant.compute_budgets()
        cols = result.to_columns()
        assert cols["keep_mask"].any()
        assert np.isnan(cols["count"][~cols["keep_mask"]]).all()

    def test_single_chunk_equals_explicit_two(self):
        # Same seed, different chunking: outputs differ only by sampling
        # draws; with generous caps they are identical.
        pid, pk, value = _data(n=10_000)
        c1 = _run(pid, pk, value, 1)
        c2 = _run(pid, pk, value, 2)
        np.testing.assert_allclose(c1["count"], c2["count"], atol=0.01)
        np.testing.assert_allclose(c1["sum"], c2["sum"], rtol=1e-4)


class TestStreamingInternals:

    def test_int_bytes(self):
        assert streaming._int_bytes(0) == 1
        assert streaming._int_bytes(255) == 1
        assert streaming._int_bytes(256) == 2
        assert streaming._int_bytes(1 << 24) == 4
        with pytest.raises(ValueError):
            streaming._int_bytes(1 << 33)

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(0)
        col = rng.integers(0, 1 << 20, 1000).astype(np.uint32)
        buf = np.zeros((1000, 3), dtype=np.uint8)
        streaming._pack_ints(buf, col, 0, 3)
        import jax.numpy as jnp
        out = np.asarray(streaming._unpack_ints(jnp.asarray(buf), 0, 3))
        np.testing.assert_array_equal(out, col)

    def test_empty_input(self):
        import jax
        accs = streaming.stream_bound_and_aggregate(
            jax.random.PRNGKey(0),
            np.zeros(0, dtype=np.int32),
            np.zeros(0, dtype=np.int32),
            np.zeros(0, dtype=np.float32),
            num_partitions=7,
            linf_cap=1,
            l0_cap=1,
            row_clip_lo=0.0,
            row_clip_hi=1.0,
            middle=0.5,
            group_clip_lo=-np.inf,
            group_clip_hi=np.inf)
        assert accs.count.shape == (7,)
        assert float(accs.count.sum()) == 0.0


class TestNativePacker:
    """The C++ bucket packer must produce the same buckets and byte layout
    as the numpy fallback (row order within a bucket may differ — the
    kernel's sampling tiebreaks make order irrelevant)."""

    def test_bucket_contents_match_numpy(self):
        from pipelinedp_tpu.native import loader
        if loader.load_row_packer() is None:
            pytest.skip("native packer unavailable")
        rng = np.random.default_rng(1)
        n = 200_000
        pid = rng.integers(500, 90_000, n).astype(np.int32)
        pk = rng.integers(0, 3_000, n).astype(np.int32)
        value = rng.uniform(-2, 7, n).astype(np.float32)
        width = 3 + 2 + 4
        nat = streaming._pack_native(pid, pk, value, 500, 8, 3, 2, False,
                                     width)
        ref_bufs, ref_counts = streaming._pack_numpy(
            pid, pk, value, 500, 8, 3, 2, False, width, 4)
        assert nat is not None
        nat_bufs, nat_counts = nat
        for c in range(8):
            nb, nc = nat_bufs[c], nat_counts[c]
            rb, rc = ref_bufs[c], ref_counts[c]
            assert nc == rc
            row_t = [("b", "u1", width)]
            a = np.sort(nb[:nc].copy().view(row_t).ravel())
            b = np.sort(rb[:rc].copy().view(row_t).ravel())
            np.testing.assert_array_equal(a, b)

    def test_f16_packing_matches(self):
        from pipelinedp_tpu.native import loader
        if loader.load_row_packer() is None:
            pytest.skip("native packer unavailable")
        rng = np.random.default_rng(2)
        n = 50_000
        pid = rng.integers(0, 1000, n).astype(np.int32)
        pk = rng.integers(0, 50, n).astype(np.int32)
        value = rng.uniform(-100, 100, n).astype(np.float32)
        width = 2 + 1 + 2
        nat = streaming._pack_native(pid, pk, value, 0, 4, 2, 1, True, width)
        ref_bufs, ref_counts = streaming._pack_numpy(
            pid, pk, value, 0, 4, 2, 1, True, width, 2)
        nat_bufs, nat_counts = nat
        for c in range(4):
            nb, nc = nat_bufs[c], nat_counts[c]
            rb, rc = ref_bufs[c], ref_counts[c]
            assert nc == rc
            row_t = [("b", "u1", width)]
            a = np.sort(nb[:nc].copy().view(row_t).ravel())
            b = np.sort(rb[:rc].copy().view(row_t).ravel())
            np.testing.assert_array_equal(a, b)

    def test_overflow_retry_adversarial_ids(self):
        # All rows share one pid -> one bucket holds everything; cap must
        # grow via the retry path and results stay exact.
        n = 30_000
        pid = np.zeros(n, dtype=np.int32)
        pk = np.arange(n, dtype=np.int32) % 10
        value = np.ones(n, dtype=np.float32)
        nat = streaming._pack_native(pid, pk, value, 0, 4, 1, 1, False, 6)
        if nat is None:
            pytest.skip("native packer unavailable")
        _, counts = nat
        assert counts.sum() == n
        assert counts.max() == n


class TestStreamedQuantiles:
    """PERCENTILE on the streamed path: the quantile-tree leaf histogram is
    accumulated chunk by chunk and must reproduce the single-shot result
    exactly when contribution bounding does not bind (identical histograms,
    identical noise keys)."""

    def _percentile_cols(self, stream_chunks, seed=0, caps=(200, 1000)):
        rng = np.random.default_rng(seed)
        n, n_parts = 60_000, 50
        pid = rng.integers(0, 5_000, n).astype(np.int64)
        pk = rng.integers(0, n_parts, n).astype(np.int32)
        value = rng.uniform(0.0, 10.0, n).astype(np.float32)
        accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=7,
                                 stream_chunks=stream_chunks,
                                 secure_host_noise=False)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT,
                     pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=caps[0],
            max_contributions_per_partition=caps[1],
            min_value=0.0,
            max_value=10.0)
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=list(range(n_parts)))
        accountant.compute_budgets()
        return result.to_columns()

    def test_streamed_equals_single_shot_exactly(self):
        single = self._percentile_cols(stream_chunks=1)
        streamed = self._percentile_cols(stream_chunks=6)
        for name in ("percentile_50", "percentile_90", "count"):
            np.testing.assert_array_equal(single[name], streamed[name],
                                          err_msg=name)

    def test_streamed_quantiles_sane_with_binding_caps(self):
        cols = self._percentile_cols(stream_chunks=6, caps=(10, 4))
        p50 = cols["percentile_50"]
        p90 = cols["percentile_90"]
        # Uniform[0,10) values: medians near 5, p90 near 9.
        assert np.nanmedian(p50) == pytest.approx(5.0, abs=1.0)
        assert np.nanmedian(p90) == pytest.approx(9.0, abs=1.0)

    def test_bytes_encoding_rejects_quantile_spec(self):
        import jax
        pid = np.arange(100, dtype=np.int64)
        pk = np.zeros(100, dtype=np.int32)
        value = np.ones(100, dtype=np.float32)
        with pytest.raises(ValueError, match="quantile_spec"):
            streaming.stream_bound_and_aggregate(
                jax.random.PRNGKey(0), pid, pk, value, num_partitions=1,
                linf_cap=10, l0_cap=10, row_clip_lo=0.0, row_clip_hi=1.0,
                middle=0.5, group_clip_lo=-np.inf, group_clip_hi=np.inf,
                n_chunks=2, transfer_encoding="bytes",
                quantile_spec=(16, 0.0, 1.0))
