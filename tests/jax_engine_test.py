"""Conformance tests: JaxDPEngine vs DPEngine(LocalBackend) oracle.

The columnar engine must produce the same results as the local path: exact
equality with no noise (huge eps), matching noise calibration, matching
budget splits, and matching partition-selection behavior."""

import jax
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.parallel import sharded


@pytest.fixture(params=["single_device", "mesh8"], scope="module")
def engine_mesh(request):
    """Same assertions run on one device and on an 8-device mesh."""
    if request.param == "single_device":
        return None
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def run_jax(data, params, public=None, eps=1e8, delta=1e-15, seed=0,
            mesh=None):
    accountant = pdp.NaiveBudgetAccountant(eps, delta)
    engine = pdp.JaxDPEngine(accountant, seed=seed, mesh=mesh)
    result = engine.aggregate(data, params, extractors(),
                              public_partitions=public)
    accountant.compute_budgets()
    return dict(result), accountant, engine


def run_local(data, params, public=None, eps=1e8, delta=1e-15):
    accountant = pdp.NaiveBudgetAccountant(eps, delta)
    engine = pdp.DPEngine(accountant, pdp.LocalBackend())
    result = engine.aggregate(data, params, extractors(),
                              public_partitions=public)
    accountant.compute_budgets()
    return dict(result), accountant


def simple_data(n_users=20, partitions=("a", "b", "c")):
    return [(u, pk, float(u % 5)) for u in range(n_users) for pk in partitions]


class TestKeyStream:
    """The audited key source must reproduce the historical ad-hoc
    fold_in sequences bit-for-bit (seeded runs stay reproducible)."""

    def test_next_key_matches_fold_in_counter(self):
        from pipelinedp_tpu.jax_engine import KeyStream
        root = jax.random.PRNGKey(7)
        stream = KeyStream(root)
        for counter in range(1, 6):
            np.testing.assert_array_equal(
                np.asarray(stream.next_key()),
                np.asarray(jax.random.fold_in(root, counter)))

    def test_derive_matches_fold_in_tag(self):
        from pipelinedp_tpu.jax_engine import KeyStream, KeyTag
        key = jax.random.PRNGKey(3)
        np.testing.assert_array_equal(
            np.asarray(KeyStream.derive(key, KeyTag.QUANTILE_NOISE)),
            np.asarray(jax.random.fold_in(key, 10_000)))
        np.testing.assert_array_equal(
            np.asarray(KeyStream.derive(key, 2)),
            np.asarray(jax.random.fold_in(key, 2)))


class TestNoNoiseConformance:

    def test_count_sum_match_local(self, engine_mesh):
        data = simple_data()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=3,
            max_contributions_per_partition=1,
            min_value=0,
            max_value=5)
        jax_res, _, _ = run_jax(data, params, public=["a", "b", "c"], mesh=engine_mesh)
        local_res, _ = run_local(data, params, public=["a", "b", "c"])
        assert set(jax_res) == set(local_res)
        for pk in local_res:
            assert jax_res[pk].count == pytest.approx(local_res[pk].count,
                                                      abs=1e-2)
            assert jax_res[pk].sum == pytest.approx(local_res[pk].sum,
                                                    abs=0.1)

    def test_privacy_id_count(self, engine_mesh):
        data = simple_data(n_users=13)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=3,
            max_contributions_per_partition=1)
        jax_res, _, _ = run_jax(data, params, public=["a", "b", "c"], mesh=engine_mesh)
        for pk in "abc":
            assert jax_res[pk].privacy_id_count == pytest.approx(13,
                                                                 abs=1e-2)

    def test_mean(self, engine_mesh):
        data = [(u, "a", float(v)) for u, v in enumerate([1, 2, 6, 7])]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.MEAN, pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0,
            max_value=10)
        jax_res, _, _ = run_jax(data, params, public=["a"], mesh=engine_mesh)
        assert jax_res["a"].mean == pytest.approx(4.0, abs=0.05)
        assert jax_res["a"].count == pytest.approx(4, abs=0.05)
        assert jax_res["a"].sum == pytest.approx(16.0, abs=0.3)

    def test_variance(self, engine_mesh):
        values = [1.0, 3.0, 5.0, 7.0]
        data = [(u, "a", v) for u, v in enumerate(values)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VARIANCE,
                                              pdp.Metrics.MEAN],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0,
                                     max_value=8)
        jax_res, _, _ = run_jax(data, params, public=["a"], mesh=engine_mesh)
        assert jax_res["a"].variance == pytest.approx(np.var(values),
                                                      abs=0.2)
        assert jax_res["a"].mean == pytest.approx(4.0, abs=0.1)

    def test_vector_sum(self, engine_mesh):
        data = [(0, "a", (1.0, 2.0)), (1, "a", (3.0, -1.0))]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     vector_size=2,
                                     vector_max_norm=100.0,
                                     vector_norm_kind=pdp.NormKind.Linf)
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-15)
        engine = pdp.JaxDPEngine(accountant, mesh=engine_mesh)
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: np.asarray(r[2]))
        result = engine.aggregate(data, params, ext, public_partitions=["a"])
        accountant.compute_budgets()
        cols = result.to_columns()
        np.testing.assert_allclose(np.asarray(cols["vector_sum"])[0],
                                   [4.0, 1.0], atol=0.05)

    def test_empty_public_partition_zero(self, engine_mesh):
        data = simple_data(partitions=("a",))
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        jax_res, _, _ = run_jax(data, params, public=["a", "ghost"], mesh=engine_mesh)
        assert jax_res["ghost"].count == pytest.approx(0, abs=1e-2)

    def test_contribution_bounding(self, engine_mesh):
        data = [(0, "a", 1.0)] * 50 + [(1, "a", 1.0)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=4)
        jax_res, _, _ = run_jax(data, params, public=["a"], mesh=engine_mesh)
        assert jax_res["a"].count == pytest.approx(5, abs=1e-2)

    def test_sum_per_partition_clipping(self, engine_mesh):
        data = [(0, "a", 3.0)] * 10
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_sum_per_partition=0.0,
                                     max_sum_per_partition=7.0)
        jax_res, _, _ = run_jax(data, params, public=["a"], mesh=engine_mesh)
        assert jax_res["a"].sum == pytest.approx(7.0, abs=0.1)


class TestPercentile:
    """PERCENTILE on the columnar engine: batched per-partition quantile
    trees (ops/quantiles.py) must match the host QuantileTree path."""

    def _params(self):
        return pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=2,
            max_contributions_per_partition=200,
            min_value=0.0,
            max_value=100.0)

    def test_matches_local_engine_no_noise(self, engine_mesh):
        rng = np.random.default_rng(5)
        data = [(u, "a", float(v))
                for u, v in enumerate(rng.uniform(0, 100, 400))]
        data += [(u, "b", float(v))
                 for u, v in enumerate(rng.uniform(40, 60, 300))]
        jax_res, _, _ = run_jax(data, self._params(), public=["a", "b"],
                                mesh=engine_mesh)
        local_res, _ = run_local(data, self._params(), public=["a", "b"])
        for pk in ("a", "b"):
            assert jax_res[pk].percentile_50 == pytest.approx(
                local_res[pk].percentile_50, abs=0.5)
            assert jax_res[pk].percentile_90 == pytest.approx(
                local_res[pk].percentile_90, abs=0.5)

    def test_accuracy_against_raw_quantiles(self, engine_mesh):
        rng = np.random.default_rng(6)
        values = rng.uniform(0, 100, 500)
        data = [(u, "a", float(v)) for u, v in enumerate(values)]
        jax_res, _, _ = run_jax(data, self._params(), public=["a"],
                                mesh=engine_mesh)
        # Tree resolution is (100 - 0) / 16^4 per leaf; no-noise estimates
        # land within a leaf width of the true quantiles.
        assert jax_res["a"].percentile_50 == pytest.approx(
            np.quantile(values, 0.5), abs=1.0)
        assert jax_res["a"].percentile_90 == pytest.approx(
            np.quantile(values, 0.9), abs=1.0)

    def test_empty_partition_stays_in_range(self, engine_mesh):
        # An empty public partition has all-zero counts; the walk follows
        # residual noise (same as the host tree: max(noised, 0) rarely sums
        # to exactly 0), so the only guarantee is the output range.
        data = [(0, "a", 50.0)]
        jax_res, _, _ = run_jax(data, self._params(), public=["a", "ghost"],
                                mesh=engine_mesh)
        assert 0.0 <= jax_res["ghost"].percentile_50 <= 100.0

    def test_device_noise_mode(self, engine_mesh):
        rng = np.random.default_rng(7)
        values = rng.uniform(0, 100, 2000)
        data = [(u, "a", float(v)) for u, v in enumerate(values)]
        accountant = pdp.NaiveBudgetAccountant(5.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant, secure_host_noise=False,
                                 mesh=engine_mesh)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=100.0)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a"])
        accountant.compute_budgets()
        res = dict(result)
        assert res["a"].percentile_50 == pytest.approx(
            np.quantile(values, 0.5), abs=10.0)

    def test_mixed_with_count(self, engine_mesh):
        data = [(u, "a", float(u % 10)) for u in range(100)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.PERCENTILE(50)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=10.0)
        jax_res, _, _ = run_jax(data, params, public=["a"], mesh=engine_mesh)
        assert jax_res["a"].count == pytest.approx(100, abs=0.05)
        # The true median (4.5) sits exactly on a leaf boundary; the walk
        # resolves to the boundary leaf edge (5.0) ± residual noise.
        assert jax_res["a"].percentile_50 == pytest.approx(4.5, abs=0.6)


class TestBudgetParity:

    def test_same_budget_split_as_local_engine(self):
        data = simple_data()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=3,
            max_contributions_per_partition=1,
            min_value=0,
            max_value=5)
        _, jax_acc, _ = run_jax(data, params, eps=1.0, delta=1e-6)
        _, local_acc = run_local(data, params, eps=1.0, delta=1e-6)
        jax_specs = [(m.mechanism_spec.mechanism_type, m.mechanism_spec._eps,
                      m.mechanism_spec._delta, m.weight)
                     for m in jax_acc._mechanisms]
        local_specs = [(m.mechanism_spec.mechanism_type,
                        m.mechanism_spec._eps, m.mechanism_spec._delta,
                        m.weight) for m in local_acc._mechanisms]
        assert jax_specs == local_specs


class TestNoise:

    def test_count_noise_std(self, engine_mesh):
        eps = 1.0
        n_partitions = 256
        data = [(u, f"p{i}", 1.0) for i in range(n_partitions)
                for u in range(10)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=n_partitions,
            max_contributions_per_partition=1)
        public = [f"p{i}" for i in range(n_partitions)]
        jax_res, _, _ = run_jax(data, params, public=public, eps=eps,
                                delta=0.0, seed=7, mesh=engine_mesh)
        errors = np.array([m.count - 10 for m in jax_res.values()])
        expected_std = n_partitions * np.sqrt(2) / eps
        assert abs(errors.mean()) < expected_std / 3
        assert errors.std() == pytest.approx(expected_std, rel=0.25)

    def test_gaussian_noise_std(self, engine_mesh):
        from pipelinedp_tpu import dp_computations
        eps, delta = 1.0, 1e-6
        n_partitions = 256
        data = [(u, f"p{i}", 1.0) for i in range(n_partitions)
                for u in range(10)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=4,
            max_contributions_per_partition=1)
        public = [f"p{i}" for i in range(n_partitions)]
        jax_res, _, _ = run_jax(data, params, public=public, eps=eps,
                                delta=delta, seed=3, mesh=engine_mesh)
        errors = np.array([m.count - 10 for m in jax_res.values()])
        # Note: L0 bounding drops most contributions (users contribute to
        # 256 partitions, capped at 4), so compare std only.
        expected_std = dp_computations.compute_sigma(eps, delta, 2.0)
        assert errors.std() == pytest.approx(expected_std, rel=0.3)

    def test_different_seeds_different_noise(self, engine_mesh):
        data = simple_data()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        r1, _, _ = run_jax(data, params, public=["a"], eps=1.0, seed=1,
                            mesh=engine_mesh)
        r2, _, _ = run_jax(data, params, public=["a"], eps=1.0, seed=2,
                            mesh=engine_mesh)
        assert r1["a"].count != r2["a"].count


class TestPrivatePartitionSelection:

    def test_large_kept_small_dropped(self, engine_mesh):
        data = ([(u, "big", 1.0) for u in range(2000)] +
                [(5555, "tiny", 1.0)])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        jax_res, _, _ = run_jax(data, params, eps=1.0, delta=1e-6,
                                mesh=engine_mesh)
        assert "big" in jax_res
        assert "tiny" not in jax_res

    def test_post_aggregation_thresholding(self, engine_mesh):
        data = ([(u, "big", 1.0) for u in range(2000)] +
                [(5555, "tiny", 1.0)])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     post_aggregation_thresholding=True)
        jax_res, _, _ = run_jax(data, params, eps=1.0, delta=1e-6,
                                mesh=engine_mesh)
        assert "tiny" not in jax_res
        assert jax_res["big"].privacy_id_count == pytest.approx(2000,
                                                                rel=0.1)


class TestLazyContract:

    def test_iterating_before_compute_budgets_raises(self):
        data = simple_data()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a"])
        with pytest.raises(AssertionError, match="not calculated"):
            dict(result)

    def test_explain_report(self):
        data = simple_data()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant)
        report = pdp.ExplainComputationReport()
        engine.aggregate(data, params, extractors(),
                         public_partitions=["a"],
                         out_explain_computation_report=report)
        accountant.compute_budgets()
        text = report.text()
        assert "Cross-partition contribution bounding" in text
        assert "Computed DP count" in text


class TestAdviceFixes:
    """Regression tests for the round-1 advisor findings."""

    def test_l1_mode_selection_calibrated_to_max_contributions(self):
        # With max_contributions (L1 mode), selection must use it as the L0
        # sensitivity; calibrating for m=1 would keep small partitions far
        # too often. A single-unit partition must stay dropped ~always even
        # when that unit holds a large total-contribution budget.
        data = ([(u, "big", 1.0) for u in range(3000)] +
                [(7777, "solo", 1.0)] * 5)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_contributions=20)
        kept_solo = 0
        for seed in range(20):
            pdp.noise_core.seed_fallback_rng(seed)
            pdp.partition_selection.seed_rng(seed)
            jax_res, _, _ = run_jax(data, params, eps=1.0, delta=1e-6,
                                    seed=seed)
            kept_solo += "solo" in jax_res
        # delta'=1e-6/20-ish keep probability: 20 trials should see none.
        assert kept_solo == 0

    def test_to_columns_masks_non_kept_partitions(self):
        data = ([(u, "big", 1.0) for u in range(2000)] +
                [(5555, "tiny", 1.0)])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(data, params, extractors())
        accountant.compute_budgets()
        cols = result.to_columns()
        keep = np.asarray(cols["keep_mask"])
        counts = np.asarray(cols["count"])
        assert np.isnan(counts[~keep]).all()
        assert np.isfinite(counts[keep]).all()

    def test_host_noise_mode_std(self):
        # secure_host_noise=True (the default) must still deliver the
        # calibrated Laplace std: scale = l0*linf/eps, std = scale*sqrt(2).
        data = [(u, "a", 1.0) for u in range(1000)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        pdp.noise_core.seed_fallback_rng(123)
        samples = []
        for seed in range(300):
            jax_res, _, _ = run_jax(data, params, public=["a"], eps=1.0,
                                    delta=1e-15, seed=seed)
            samples.append(jax_res["a"].count - 1000.0)
        expected_std = np.sqrt(2.0) / 1.0  # b = 1/eps
        assert np.std(samples) == pytest.approx(expected_std, rel=0.2)

    def test_device_noise_mode_still_available(self):
        data = [(u, "a", 1.0) for u in range(100)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-15)
        engine = pdp.JaxDPEngine(accountant, secure_host_noise=False)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a"])
        accountant.compute_budgets()
        assert dict(result)["a"].count == pytest.approx(100, abs=1e-2)


class TestJaxSelectPartitions:
    """Columnar select_partitions / add_dp_noise (device fast paths)."""

    def test_select_partitions_keeps_dense_drops_sparse(self):
        rows = []
        for user in range(200):
            rows.append((user, "dense"))
        rows.append((0, "sparse"))
        accountant = pdp.NaiveBudgetAccountant(10.0, 1e-5)
        engine = pdp.JaxDPEngine(accountant, seed=0)
        params = pdp.SelectPartitionsParams(max_partitions_contributed=2)
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda r: r[0],
            partition_extractor=lambda r: r[1])
        result = engine.select_partitions(rows, params, extractors)
        accountant.compute_budgets()
        keys = list(result)
        assert "dense" in keys
        assert "sparse" not in keys

    def test_select_partitions_columnar_input(self):
        rng = np.random.default_rng(0)
        data = pdp.ColumnarData(pid=rng.integers(0, 500, 5000),
                                pk=rng.integers(0, 5, 5000))
        accountant = pdp.NaiveBudgetAccountant(10.0, 1e-5)
        engine = pdp.JaxDPEngine(accountant, seed=0)
        result = engine.select_partitions(
            data, pdp.SelectPartitionsParams(max_partitions_contributed=5))
        accountant.compute_budgets()
        assert sorted(list(result)) == [0, 1, 2, 3, 4]

    def test_select_partitions_matches_host_engine_keep_rate(self):
        # Same dataset, both engines: partitions with ~100 users kept,
        # singleton partitions dropped.
        rows = [(u, p) for p in range(20) for u in range(100)]
        rows += [(0, 100 + p) for p in range(20)]
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda r: r[0],
            partition_extractor=lambda r: r[1])
        params = pdp.SelectPartitionsParams(max_partitions_contributed=25)

        acc_j = pdp.NaiveBudgetAccountant(5.0, 1e-5)
        eng_j = pdp.JaxDPEngine(acc_j, seed=1)
        res_j = eng_j.select_partitions(rows, params, extractors)
        acc_j.compute_budgets()
        jax_keys = set(res_j)

        acc_h = pdp.NaiveBudgetAccountant(5.0, 1e-5)
        eng_h = pdp.DPEngine(acc_h, pdp.LocalBackend())
        res_h = eng_h.select_partitions(rows, params, extractors)
        acc_h.compute_budgets()
        host_keys = set(res_h)

        dense = set(range(20))
        assert dense <= jax_keys
        assert dense <= host_keys
        assert not (jax_keys & set(range(100, 120)))

    def test_add_dp_noise_pairs(self):
        pairs = [("a", 10.0), ("b", 20.0), ("c", 0.0)]
        accountant = pdp.NaiveBudgetAccountant(1e6, 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=0)
        params = pdp.AddDPNoiseParams(noise_kind=pdp.NoiseKind.LAPLACE,
                                      l0_sensitivity=2,
                                      linf_sensitivity=1.0)
        result = engine.add_dp_noise(pairs, params)
        accountant.compute_budgets()
        out = dict(result)
        assert out["a"] == pytest.approx(10.0, abs=0.1)
        assert out["b"] == pytest.approx(20.0, abs=0.1)
        assert out["c"] == pytest.approx(0.0, abs=0.1)

    def test_add_dp_noise_std_calibration(self):
        # Many values at 0: the empirical noise std must match the
        # mechanism's declared std.
        n = 20_000
        accountant = pdp.NaiveBudgetAccountant(2.0, 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=0)
        params = pdp.AddDPNoiseParams(noise_kind=pdp.NoiseKind.LAPLACE,
                                      l0_sensitivity=3,
                                      linf_sensitivity=2.0)
        data = pdp.ColumnarData(pid=np.zeros(n, dtype=np.int32),
                                pk=np.arange(n),
                                value=np.zeros(n))
        result = engine.add_dp_noise(data, params)
        accountant.compute_budgets()
        noised = result.to_columns()["value"]
        expected_scale = 3 * 2.0 / 2.0  # l1_sensitivity / eps
        expected_std = expected_scale * np.sqrt(2.0)
        assert np.std(noised) == pytest.approx(expected_std, rel=0.05)
        # Budget accounting: the noise used the full accountant epsilon.
        report = engine.explain_computations_report()[-1]
        assert "noise" in report.lower()


class TestL1ModeParity:
    """Verdict-r2 task 10a: max_contributions (L1) bounding semantics,
    JaxDPEngine vs DPEngine. Both engines take a uniform sample of at most
    k rows per privacy id, total across all partitions — the bound the L1
    noise sensitivity is calibrated to (columnar._l1_sample_mask is the
    kernel twin of SamplingPerPrivacyIdContributionBounder)."""

    def _run_both(self, rows, k):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_contributions=k)
        public = sorted({r[1] for r in rows})

        acc_j = pdp.NaiveBudgetAccountant(1e8, 1 - 1e-9)
        eng_j = pdp.JaxDPEngine(acc_j, seed=7, secure_host_noise=False)
        res_j = eng_j.aggregate(rows, params, extractors(),
                                public_partitions=public)
        acc_j.compute_budgets()
        jax_out = {k_: v.count for k_, v in res_j}

        acc_h = pdp.NaiveBudgetAccountant(1e8, 1 - 1e-9)
        eng_h = pdp.DPEngine(acc_h, pdp.LocalBackend())
        res_h = eng_h.aggregate(rows, params, extractors(),
                                public_partitions=public)
        acc_h.compute_budgets()
        host_out = {k_: v.count for k_, v in res_h}
        return jax_out, host_out

    def test_uniform_users_agree(self):
        # Each user contributes once to k distinct partitions: neither
        # engine's sampling binds, outputs equal.
        k = 3
        rows = [(u, p, 1.0) for u in range(50) for p in range(k)]
        jax_out, host_out = self._run_both(rows, k)
        for p in range(k):
            assert jax_out[p] == pytest.approx(host_out[p], abs=0.01)
            assert jax_out[p] == pytest.approx(50, abs=0.01)

    def test_single_partition_capped_at_k(self):
        # One user puts 10 contributions in one partition; k=4: both
        # engines keep a uniform sample of 4.
        rows = [(1, "a", 1.0)] * 10
        jax_out, host_out = self._run_both(rows, 4)
        assert jax_out["a"] == pytest.approx(4, abs=0.01)
        assert host_out["a"] == pytest.approx(4, abs=0.01)

    def test_concentrated_two_partitions(self):
        # User with 6 contributions in partition a, 6 in b; k=4. BOTH
        # engines keep exactly 4 total (a uniform sample of 4 of the 12
        # rows) — the L1 bound the noise sensitivity is calibrated to.
        # This pins the fix for the round-3 finding that the columnar
        # path used (linf=k, l0=k) caps, which allowed k^2 contributions
        # per user against noise calibrated for k.
        rows = [(1, "a", 1.0)] * 6 + [(1, "b", 1.0)] * 6
        jax_out, host_out = self._run_both(rows, 4)
        assert host_out["a"] + host_out["b"] == pytest.approx(4, abs=0.02)
        assert jax_out["a"] + jax_out["b"] == pytest.approx(4, abs=0.02)

    def test_l1_sample_is_uniform_across_partitions(self):
        # 8 contributions in a, 4 in b, k=6: expected kept in a = 6*8/12=4.
        # Average over seeds to check the sample is uniform over rows.
        rows = [(1, "a", 1.0)] * 8 + [(1, "b", 1.0)] * 4
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_contributions=6)
        totals_a = []
        for seed in range(40):
            acc = pdp.NaiveBudgetAccountant(1e8, 1 - 1e-9)
            eng = pdp.JaxDPEngine(acc, seed=seed, secure_host_noise=False)
            res = eng.aggregate(rows, params, extractors(),
                                public_partitions=["a", "b"])
            acc.compute_budgets()
            out = {k: v.count for k, v in res}
            assert out["a"] + out["b"] == pytest.approx(6, abs=0.02)
            totals_a.append(out["a"])
        assert np.mean(totals_a) == pytest.approx(4.0, abs=0.5)

    def test_l1_sensitivity_respected_in_noise_scale(self):
        # Both engines calibrate noise to the same declared L1 sensitivity
        # (max_contributions), verified via the explain report.
        rows = [(u, u % 2, 1.0) for u in range(20)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_contributions=2)
        acc = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        eng = pdp.JaxDPEngine(acc, secure_host_noise=False)
        res = eng.aggregate(rows, params, extractors(),
                            public_partitions=[0, 1])
        acc.compute_budgets()
        res.to_columns()
        report = eng.explain_computations_report()[0]
        assert "Laplace" in report or "laplace" in report


class TestBlockedQuantiles:
    """PERCENTILE beyond the dense device budget: the blocked path must
    release the same values as the dense path (no-noise comparison with
    the histogram budget shrunk so blocking engages)."""

    def _run(self, seed=5):
        rng = np.random.default_rng(0)
        n = 30_000
        data = [(int(u), int(p), float(v)) for u, p, v in zip(
            rng.integers(0, 3000, n), rng.integers(0, 40, n),
            rng.uniform(0.0, 10.0, n))]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=40,
            max_contributions_per_partition=100,
            min_value=0.0,
            max_value=10.0)
        # eps so large the per-node tree noise (~4e3/eps) cannot flip a
        # descent: the dense and blocked paths draw different noise, so
        # only the noise-free trees are comparable.
        accountant = pdp.NaiveBudgetAccountant(1e12, 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=seed,
                                 secure_host_noise=False)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=list(range(40)))
        accountant.compute_budgets()
        return result.to_columns()

    def test_blocked_matches_dense(self, monkeypatch):
        dense = self._run()
        from pipelinedp_tpu.ops import quantiles as quantile_ops
        # 40 partitions x 65536 leaves = 2.6M elements; budget 600k forces
        # ~10-partition blocks.
        monkeypatch.setattr(quantile_ops, "MAX_HISTOGRAM_ELEMENTS",
                            600_000)
        blocked = self._run()
        # The paths draw different (astronomically small) node noise.
        # Integer counts make exact rank==boundary ties common (~15% of
        # partitions at p50), and a tie resolves by the noise sign — those
        # flips move the estimate by less than a cell width. Most
        # partitions match exactly; all must be within a tight absolute
        # band (a real blocking bug — wrong offsets, wrong rows — would
        # be off by O(1)).
        for name in ("percentile_50", "percentile_90"):
            close = np.isclose(blocked[name], dense[name], rtol=1e-6)
            assert close.mean() >= 0.7, name
            np.testing.assert_allclose(blocked[name], dense[name],
                                       atol=0.05)

    def test_blocked_close_to_true_quantiles(self, monkeypatch):
        from pipelinedp_tpu.ops import quantiles as quantile_ops
        monkeypatch.setattr(quantile_ops, "MAX_HISTOGRAM_ELEMENTS",
                            600_000)
        cols = self._run()
        # Uniform[0, 10], ~750 samples per partition: sample-median std is
        # ~0.18, so the max over 40 partitions stays within 0.6.
        assert np.abs(cols["percentile_50"] - 5.0).max() < 0.6
        assert np.abs(cols["percentile_90"] - 9.0).max() < 0.6


class TestOutputNoiseStddev:
    """params.output_noise_stddev emits "<metric>_noise_stddev" columns."""

    def test_count_sum_stddev_columns(self):
        data = simple_data()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=3,
            max_contributions_per_partition=2,
            min_value=0,
            max_value=5,
            output_noise_stddev=True)
        jax_res, accountant, _ = run_jax(data, params, public=["a", "b", "c"],
                                         eps=2.0, delta=1e-15)
        # Two Laplace mechanisms split eps equally: scale = l0*linf/(eps/2).
        expected_count_std = (3 * 2 / 1.0) * np.sqrt(2.0)
        expected_sum_std = (3 * 2 * 5 / 1.0) * np.sqrt(2.0)
        for pk in "abc":
            assert jax_res[pk].count_noise_stddev == pytest.approx(
                expected_count_std, rel=1e-6)
            assert jax_res[pk].sum_noise_stddev == pytest.approx(
                expected_sum_std, rel=1e-6)

    def test_local_engine_matches_jax_columns(self):
        data = simple_data()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=2,
            max_contributions_per_partition=1,
            output_noise_stddev=True)
        jax_res, _, _ = run_jax(data, params, public=["a"], eps=1.0)
        local_res, _ = run_local(data, params, public=["a"], eps=1.0)
        assert jax_res["a"].count_noise_stddev == pytest.approx(
            local_res["a"].count_noise_stddev, rel=1e-9)

    def test_gaussian_stddev(self):
        data = simple_data()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=2,
            max_contributions_per_partition=1,
            output_noise_stddev=True)
        jax_res, _, _ = run_jax(data, params, public=["a"], eps=1.0,
                                delta=1e-6)
        from pipelinedp_tpu import noise_core
        expected = noise_core.analytic_gaussian_sigma(1.0, 1e-6, np.sqrt(2))
        assert jax_res["a"].count_noise_stddev == pytest.approx(expected,
                                                               rel=1e-6)

    def test_rejected_for_ratio_metrics(self):
        with pytest.raises(ValueError, match="output_noise_stddev"):
            pdp.AggregateParams(metrics=[pdp.Metrics.MEAN],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                min_value=0,
                                max_value=1,
                                output_noise_stddev=True)


class TestPerformCrossPartitionBounding:

    def test_disabled_keeps_all_partitions(self):
        # One user contributing to 10 partitions with l0 bound 2: with
        # bounding the user survives in only 2 partitions; without, all 10
        # keep their contribution (noise still calibrated to the bound).
        data = [(0, f"pk{i}", 1.0) for i in range(10)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=2,
            max_contributions_per_partition=1,
            perform_cross_partition_contribution_bounding=False)
        public = [f"pk{i}" for i in range(10)]
        jax_res, _, _ = run_jax(data, params, public=public)
        counts = np.array([jax_res[pk].count for pk in public])
        np.testing.assert_allclose(counts, 1.0, atol=1e-2)

    def test_enabled_bounds_partitions(self):
        data = [(0, f"pk{i}", 1.0) for i in range(10)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=2,
            max_contributions_per_partition=1)
        public = [f"pk{i}" for i in range(10)]
        jax_res, _, _ = run_jax(data, params, public=public)
        counts = np.array([jax_res[pk].count for pk in public])
        assert counts.sum() == pytest.approx(2.0, abs=0.05)


class _SumOfSquaresCombiner(pdp.CustomCombiner):
    """Test custom combiner: DP sum of squared values (its own Laplace
    mechanism, per the reference's experimental custom-combiners example)."""

    def __init__(self, max_value):
        self._max_value = max_value

    def request_budget(self, budget_accountant):
        self._spec = budget_accountant.request_budget(
            pdp.MechanismType.LAPLACE)

    def create_accumulator(self, values):
        return float(sum(v * v for v in values))

    def merge_accumulators(self, a, b):
        return a + b

    def compute_metrics(self, acc):
        from pipelinedp_tpu import dp_computations
        p = self._aggregate_params
        sens = dp_computations.Sensitivities(
            l0=p.max_partitions_contributed,
            linf=p.max_contributions_per_partition * self._max_value**2)
        mech = dp_computations.create_additive_mechanism(self._spec, sens)
        return {"sum_squares": mech.add_noise(acc)}

    def explain_computation(self):
        return "Custom DP sum of squares"


class TestCustomCombinersOnJaxEngine:
    """Custom combiners on the columnar engine (VERDICT-r3 task 9): device
    contribution bounding + host combiner logic, matching DPEngine."""

    def _params(self, l0=2, linf=3):
        return pdp.AggregateParams(
            metrics=None,
            custom_combiners=[_SumOfSquaresCombiner(max_value=4.0)],
            max_partitions_contributed=l0,
            max_contributions_per_partition=linf)

    def _data(self):
        rng = np.random.default_rng(4)
        return [(int(u), f"pk{int(p)}", float(v)) for u, p, v in zip(
            rng.integers(0, 50, 600), rng.integers(0, 6, 600),
            rng.uniform(0.0, 4.0, 600))]

    def _run_jax(self, data, public=None, eps=1e8, l0=2, linf=3):
        accountant = pdp.NaiveBudgetAccountant(eps, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=3)
        result = engine.aggregate(data, self._params(l0, linf), extractors(),
                                  public_partitions=public)
        accountant.compute_budgets()
        return dict(result), engine

    def _run_local(self, data, public=None, eps=1e8, l0=2, linf=3):
        accountant = pdp.NaiveBudgetAccountant(eps, 1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        result = engine.aggregate(data, self._params(l0, linf), extractors(),
                                  public_partitions=public)
        accountant.compute_budgets()
        return dict(result)

    def test_matches_local_engine_public_no_bounding_pressure(self):
        # Caps above the data bounds: no sampling randomness; values
        # match the host engine up to the near-zero (but independently
        # drawn) noise — empty public partitions release pure noise.
        data = self._data()
        public = [f"pk{i}" for i in range(8)]  # incl. 2 empty partitions
        jax_res, _ = self._run_jax(data, public, l0=10, linf=1000)
        local_res = self._run_local(data, public, l0=10, linf=1000)
        assert set(jax_res) == set(local_res)
        for pk in local_res:
            assert jax_res[pk][0]["sum_squares"] == pytest.approx(
                local_res[pk][0]["sum_squares"], rel=1e-4, abs=0.05)

    def test_bounding_applies_on_device(self):
        # One user with 100 rows in one partition, linf=3: the surviving
        # sum of squares is bounded by 3 * 16.
        data = [(1, "a", 4.0)] * 100
        jax_res, _ = self._run_jax(data, public=["a"], l0=1, linf=3)
        assert jax_res["a"][0]["sum_squares"] == pytest.approx(48.0, abs=1.0)

    def test_private_selection_drops_small_partitions(self):
        data = ([(u, "big", 1.0) for u in range(2000)] +
                [(9999, "tiny", 1.0)])
        jax_res, _ = self._run_jax(data, public=None, eps=1.0, l0=1, linf=1)
        assert "big" in jax_res and "tiny" not in jax_res

    def test_explain_report_carries_custom_stage(self):
        data = self._data()
        _, engine = self._run_jax(data, public=[f"pk{i}" for i in range(6)])
        report = engine.explain_computations_report()[0]
        assert "Custom DP sum of squares" in report

    def _run_mesh(self, data, public=None, eps=1e8, l0=2, linf=3):
        from pipelinedp_tpu.parallel import sharded
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        accountant = pdp.NaiveBudgetAccountant(eps, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=3,
                                 mesh=sharded.make_mesh(8))
        result = engine.aggregate(data, self._params(l0, linf), extractors(),
                                  public_partitions=public)
        accountant.compute_budgets()
        return dict(result)

    def test_mesh_matches_local_when_caps_do_not_bind(self):
        # Mirrors TestEngineOnMesh for the custom path (VERDICT-r4 item 5):
        # device bounding runs sharded; host combiner logic is unchanged.
        data = self._data()
        public = [f"pk{i}" for i in range(8)]
        mesh_res = self._run_mesh(data, public, l0=10, linf=1000)
        local_res = self._run_local(data, public, l0=10, linf=1000)
        assert set(mesh_res) == set(local_res)
        for pk in local_res:
            assert mesh_res[pk][0]["sum_squares"] == pytest.approx(
                local_res[pk][0]["sum_squares"], rel=1e-4, abs=0.05)

    def test_mesh_bounding_enforces_caps(self):
        # One user, 100 identical rows, linf=3 on the mesh: the combiner
        # must see at most 3 surviving rows.
        data = [(1, "a", 4.0)] * 100
        mesh_res = self._run_mesh(data, public=["a"], l0=1, linf=3)
        assert mesh_res["a"][0]["sum_squares"] == pytest.approx(48.0,
                                                               abs=1.0)

    def test_mesh_private_selection_custom(self):
        data = ([(u, "big", 1.0) for u in range(3000)] +
                [(999999, "tiny", 1.0)])
        mesh_res = self._run_mesh(data, public=None, eps=1.0, l0=1, linf=1)
        assert "big" in mesh_res and "tiny" not in mesh_res


class TestNoiseSelectionMetricCrossProduct:
    """noise kind x selection strategy x metric set, e2e on the columnar
    engine with private partition selection (VERDICT-r3 task 8): a large
    partition survives with roughly-right values, a lone-user partition is
    dropped."""

    @pytest.mark.parametrize("noise_kind",
                             [pdp.NoiseKind.LAPLACE,
                              pdp.NoiseKind.GAUSSIAN])
    @pytest.mark.parametrize(
        "strategy", [pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
                     pdp.PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
                     pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING])
    @pytest.mark.parametrize("metric_set", [
        [pdp.Metrics.COUNT, pdp.Metrics.SUM],
        [pdp.Metrics.PRIVACY_ID_COUNT],
        [pdp.Metrics.MEAN],
    ])
    def test_e2e_private_selection(self, noise_kind, strategy, metric_set):
        data = ([(u, "big", 2.0) for u in range(3000)] +
                [(777777, "lonely", 2.0)])
        needs_bounds = (pdp.Metrics.SUM in metric_set or
                        pdp.Metrics.MEAN in metric_set)
        params = pdp.AggregateParams(
            metrics=metric_set,
            noise_kind=noise_kind,
            partition_selection_strategy=strategy,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0 if needs_bounds else None,
            max_value=4.0 if needs_bounds else None)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=7)
        result = engine.aggregate(data, params, extractors())
        accountant.compute_budgets()
        res = dict(result)
        assert "big" in res, (noise_kind, strategy, metric_set)
        assert "lonely" not in res, (noise_kind, strategy, metric_set)
        m = res["big"]
        if pdp.Metrics.COUNT in metric_set:
            assert m.count == pytest.approx(3000, rel=0.1)
        if pdp.Metrics.SUM in metric_set:
            assert m.sum == pytest.approx(6000, rel=0.1)
        if pdp.Metrics.PRIVACY_ID_COUNT in metric_set:
            assert m.privacy_id_count == pytest.approx(3000, rel=0.1)
        if pdp.Metrics.MEAN in metric_set:
            assert m.mean == pytest.approx(2.0, abs=0.5)


class TestCustomCombinerParamModes:
    """Parameter combinations on the custom-combiner path must track the
    standard path's semantics (round-4 review findings)."""

    def _ext(self):
        return extractors()

    def _agg(self, data, params, public=None, eps=1e8):
        accountant = pdp.NaiveBudgetAccountant(eps, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=2)
        result = engine.aggregate(data, params, self._ext(),
                                  public_partitions=public)
        accountant.compute_budgets()
        return dict(result)

    def test_l1_mode_bounds_total_contributions(self):
        # One user, 100 rows of 1.0 in one partition; max_contributions=2
        # bounds the TOTAL sample: the custom sum sees at most 2 rows.
        class L1Sum(pdp.CustomCombiner):
            def request_budget(self, accountant):
                self._spec = accountant.request_budget(
                    pdp.MechanismType.LAPLACE)

            def create_accumulator(self, values):
                return float(np.sum(np.clip(values, -4.0, 4.0) ** 2))

            def merge_accumulators(self, a, b):
                return a + b

            def compute_metrics(self, acc):
                from pipelinedp_tpu import dp_computations
                p = self._aggregate_params
                mech = dp_computations.create_additive_mechanism(
                    self._spec,
                    dp_computations.Sensitivities(
                        l0=1, linf=p.max_contributions * 16.0))
                return {"sum_squares": mech.add_noise(acc)}

            def explain_computation(self):
                return "L1-bounded sum of squares"

        data = [(1, "a", 1.0)] * 100
        params = pdp.AggregateParams(
            metrics=None,
            custom_combiners=[L1Sum()],
            max_partitions_contributed=None,
            max_contributions_per_partition=None,
            max_contributions=2)
        res = self._agg(data, params, public=["a"])
        assert res["a"][0]["sum_squares"] == pytest.approx(2.0, abs=0.5)

    def test_float64_values_exact(self):
        # Values above 2^24 are exact (float32 encoding would round them).
        big = float(1 << 25) + 1.0

        class ExactSum(pdp.CustomCombiner):
            def request_budget(self, accountant):
                self._spec = accountant.request_budget(
                    pdp.MechanismType.LAPLACE)

            def create_accumulator(self, values):
                return float(sum(values))

            def merge_accumulators(self, a, b):
                return a + b

            def compute_metrics(self, acc):
                return {"exact_sum": acc}  # no noise: precision test only

            def explain_computation(self):
                return "exact sum"

        data = [(1, "a", big), (2, "a", big)]
        params = pdp.AggregateParams(
            metrics=None, custom_combiners=[ExactSum()],
            max_partitions_contributed=1,
            max_contributions_per_partition=1)
        res = self._agg(data, params, public=["a"])
        assert res["a"][0]["exact_sum"] == 2 * big  # bit-exact

    def test_bounds_already_enforced_selection_adjustment(self):
        # 30 rows, declared 10 rows per unit -> ~3 estimated units: with
        # eps=1 and delta=1e-6 a 3-unit partition is (nearly) always
        # dropped, while 3000 rows (~300 units) survives.
        data = ([(0, "big", 1.0)] * 3000 + [(0, "small", 1.0)] * 30)
        params = pdp.AggregateParams(
            metrics=None,
            custom_combiners=[_SumOfSquaresCombiner(max_value=4.0)],
            max_partitions_contributed=1,
            max_contributions_per_partition=10,
            contribution_bounds_already_enforced=True)
        res = self._agg(data, params, eps=1.0)
        assert "big" in res and "small" not in res

    def test_no_cross_partition_bounding_mode(self):
        # One user in 5 partitions with l0=1: with cross-partition
        # bounding off, every partition keeps its contribution.
        data = [(1, f"pk{i}", 1.0) for i in range(5)]
        params = pdp.AggregateParams(
            metrics=None,
            custom_combiners=[_SumOfSquaresCombiner(max_value=4.0)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            perform_cross_partition_contribution_bounding=False)
        res = self._agg(data, params, public=[f"pk{i}" for i in range(5)])
        values = [res[f"pk{i}"][0]["sum_squares"] for i in range(5)]
        assert all(v == pytest.approx(1.0, abs=0.3) for v in values)

    def test_post_aggregation_thresholding_rejected(self):
        params = pdp.AggregateParams(
            metrics=None,
            custom_combiners=[_SumOfSquaresCombiner(max_value=4.0)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            post_aggregation_thresholding=True)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant)
        with pytest.raises(ValueError, match="PRIVACY_ID_COUNT"):
            engine.aggregate([(1, "a", 1.0)], params, self._ext())

    def test_no_linf_stage_when_combiner_owns_bounding(self):
        class SelfBounding(_SumOfSquaresCombiner):
            def expects_per_partition_sampling(self):
                return False

        params = pdp.AggregateParams(
            metrics=None, custom_combiners=[SelfBounding(max_value=4.0)],
            max_partitions_contributed=2,
            max_contributions_per_partition=3)
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=2)
        engine.aggregate([(1, "a", 1.0)], params, self._ext(),
                         public_partitions=["a"])
        accountant.compute_budgets()
        text = engine.explain_computations_report()[0]
        assert "Per-partition contribution bounding" not in text
        assert "Cross-partition contribution bounding" in text

    def test_value_less_pipeline(self):
        # value_extractor=None (count-style custom combiner): values are
        # zeros, like DPEngine._extract_columns substitutes.
        class CountRows(pdp.CustomCombiner):
            def request_budget(self, accountant):
                self._spec = accountant.request_budget(
                    pdp.MechanismType.LAPLACE)

            def create_accumulator(self, values):
                return len(values)

            def merge_accumulators(self, a, b):
                return a + b

            def compute_metrics(self, acc):
                return {"rows": acc}

            def explain_computation(self):
                return "row count"

        data = [(u, "a", None) for u in range(10)]
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=None)
        params = pdp.AggregateParams(
            metrics=None, custom_combiners=[CountRows()],
            max_partitions_contributed=1,
            max_contributions_per_partition=1)
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=2)
        result = engine.aggregate(data, params, ext, public_partitions=["a"])
        accountant.compute_budgets()
        assert dict(result)["a"][0]["rows"] == 10

    def test_encoded_columns_input(self):
        from pipelinedp_tpu.ops import encoding
        col = encoding.EncodedColumns(
            pid=np.arange(12, dtype=np.int32) % 4,
            pk=np.arange(12, dtype=np.int32) % 3,
            num_partitions=3,
            value=np.full(12, 3.0, dtype=np.float64))
        params = pdp.AggregateParams(
            metrics=None,
            custom_combiners=[_SumOfSquaresCombiner(max_value=4.0)],
            max_partitions_contributed=3,
            max_contributions_per_partition=4)
        accountant = pdp.NaiveBudgetAccountant(1e8, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=2)
        result = engine.aggregate(col, params)
        accountant.compute_budgets()
        res = dict(result)
        assert len(res) == 3
        # 4 rows of 3.0^2 = 9 each per partition.
        for v in res.values():
            assert v[0]["sum_squares"] == pytest.approx(36.0, abs=1.0)


class TestPrivateContributionBounds:
    """JaxDPEngine.calculate_private_contribution_bounds parity vs DPEngine
    (same seeded exponential-mechanism draw => same chosen bound)."""

    def _params(self, calc_eps=20.0, upper=10):
        return pdp.CalculatePrivateContributionBoundsParams(
            aggregation_noise_kind=pdp.NoiseKind.LAPLACE,
            aggregation_eps=1.0,
            aggregation_delta=0.0,
            calculation_eps=calc_eps,
            max_partitions_contributed_upper_bound=upper)

    def _rows(self):
        # 50 users x 4 partitions each, plus a few heavy users.
        rows = [(u, f"pk{i}", 1.0) for u in range(50) for i in range(4)]
        rows += [(100 + u, f"pk{i}", 1.0) for u in range(5)
                 for i in range(8)]
        return rows

    def _extractors(self):
        return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                  partition_extractor=lambda r: r[1],
                                  value_extractor=lambda r: r[2])

    def test_parity_with_dp_engine_rows(self):
        from pipelinedp_tpu import dp_computations
        rows = self._rows()
        partitions = [f"pk{i}" for i in range(8)]

        dp_computations.ExponentialMechanism.seed_rng(7)
        host_engine = pdp.DPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6),
                                   pdp.LocalBackend())
        host = list(host_engine.calculate_private_contribution_bounds(
            rows, self._params(), self._extractors(),
            partitions=partitions))[0]

        dp_computations.ExponentialMechanism.seed_rng(7)
        jax_engine = pdp.JaxDPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6))
        col = jax_engine.calculate_private_contribution_bounds(
            rows, self._params(), self._extractors(), partitions=partitions)
        dp_computations.ExponentialMechanism.seed_rng(None)

        assert isinstance(col, pdp.PrivateContributionBounds)
        assert col.max_partitions_contributed == \
            host.max_partitions_contributed

    def test_columnar_input(self):
        from pipelinedp_tpu import dp_computations
        rows = self._rows()
        data = pdp.ColumnarData(
            pid=np.array([r[0] for r in rows]),
            pk=np.array([r[1] for r in rows]),
            value=np.array([r[2] for r in rows], dtype=np.float32))
        partitions = [f"pk{i}" for i in range(8)]

        dp_computations.ExponentialMechanism.seed_rng(11)
        jax_engine = pdp.JaxDPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6))
        got = jax_engine.calculate_private_contribution_bounds(
            data, self._params(), partitions=partitions)

        dp_computations.ExponentialMechanism.seed_rng(11)
        host_engine = pdp.DPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6),
                                   pdp.LocalBackend())
        host = list(host_engine.calculate_private_contribution_bounds(
            rows, self._params(), self._extractors(),
            partitions=partitions))[0]
        dp_computations.ExponentialMechanism.seed_rng(None)

        assert got.max_partitions_contributed == \
            host.max_partitions_contributed
        assert 1 <= got.max_partitions_contributed <= 10

    def test_encoded_columns_counts_dataless_public_partitions(self):
        # Round-5 advisor regression: with EncodedColumns, public
        # partitions that have NO data (absent from pk_keys) must still
        # count toward number_of_partitions — the exponential-mechanism
        # scoring has to match DPEngine, which sees the full user list.
        from pipelinedp_tpu import dp_computations
        # 40 users, each contributing to the same 3 data partitions; 27
        # more public partitions carry no data at all. With the count
        # taken from the full user list (30) the noise term dominates and
        # the near-deterministic mechanism picks bound 1; counting only
        # the data vocabulary (3) both caps the candidate range at 3 and
        # flips the winner to 3 — exactly the old bug.
        rows = [(u, f"pk{i}", 1.0) for u in range(40) for i in range(3)]
        pk_keys = [f"pk{i}" for i in range(3)]
        id_of = {k: i for i, k in enumerate(pk_keys)}
        data = pdp.EncodedColumns(
            pid=np.array([r[0] for r in rows], dtype=np.int32),
            pk=np.array([id_of[r[1]] for r in rows], dtype=np.int32),
            num_partitions=3,
            value=np.array([r[2] for r in rows], dtype=np.float32),
            pk_keys=pk_keys)
        partitions = pk_keys + [f"empty{i}" for i in range(27)]
        params = self._params(calc_eps=1000.0)

        dp_computations.ExponentialMechanism.seed_rng(13)
        got = pdp.JaxDPEngine(
            pdp.NaiveBudgetAccountant(1.0, 1e-6)
        ).calculate_private_contribution_bounds(
            data, params, partitions=partitions)

        dp_computations.ExponentialMechanism.seed_rng(13)
        host_engine = pdp.DPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6),
                                   pdp.LocalBackend())
        host = list(host_engine.calculate_private_contribution_bounds(
            rows, params, self._extractors(),
            partitions=partitions))[0]
        dp_computations.ExponentialMechanism.seed_rng(None)
        assert got.max_partitions_contributed == \
            host.max_partitions_contributed == 1

    def test_partition_filtering(self):
        # Rows outside `partitions` must not influence the histogram:
        # an engine fed junk rows in other partitions picks the same bound.
        from pipelinedp_tpu import dp_computations
        rows = self._rows()
        junk = [(u, "junk", 1.0) for u in range(200) for _ in range(3)]
        partitions = [f"pk{i}" for i in range(8)]

        dp_computations.ExponentialMechanism.seed_rng(3)
        eng = pdp.JaxDPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6))
        clean = eng.calculate_private_contribution_bounds(
            rows, self._params(), self._extractors(), partitions=partitions)

        dp_computations.ExponentialMechanism.seed_rng(3)
        eng2 = pdp.JaxDPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6))
        noisy = eng2.calculate_private_contribution_bounds(
            rows + junk, self._params(), self._extractors(),
            partitions=partitions)
        dp_computations.ExponentialMechanism.seed_rng(None)
        assert clean.max_partitions_contributed == \
            noisy.max_partitions_contributed

    def test_requires_partitions(self):
        eng = pdp.JaxDPEngine(pdp.NaiveBudgetAccountant(1.0, 1e-6))
        with pytest.raises(ValueError, match="partitions"):
            eng.calculate_private_contribution_bounds(
                self._rows(), self._params(), self._extractors())


class TestPLDOnColumnarEngine:
    """E2E: JaxDPEngine under PLDBudgetAccountant (VERDICT-r4 item 7). The
    lazy sigma-from-PLD resolution through _mechanism_noise_params must
    reach the device kernels: the emitted noise stddev equals the
    PLD-resolved per-unit-sensitivity std times the L1 sensitivity."""

    def _run(self, metrics, noise_kind, l0=2, linf=3, eps=1.0, delta=1e-6):
        data = [(u, pk, 1.0) for u in range(400) for pk in ("a", "b")]
        accountant = pdp.PLDBudgetAccountant(eps, delta,
                                             pld_discretization=1e-3)
        engine = pdp.JaxDPEngine(accountant, seed=5)
        params = pdp.AggregateParams(
            metrics=metrics,
            noise_kind=noise_kind,
            max_partitions_contributed=l0,
            max_contributions_per_partition=linf,
            min_value=0.0 if pdp.Metrics.SUM in metrics else None,
            max_value=2.0 if pdp.Metrics.SUM in metrics else None,
            output_noise_stddev=True)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a", "b"])
        accountant.compute_budgets()
        return dict(result), accountant

    def test_laplace_count_uses_pld_std(self):
        res, accountant = self._run([pdp.Metrics.COUNT],
                                    pdp.NoiseKind.LAPLACE)
        spec = accountant._mechanisms[0].mechanism_spec
        l1_sens = 2 * 3
        assert res["a"].count_noise_stddev == pytest.approx(
            spec.noise_standard_deviation * l1_sens, rel=1e-6)
        assert res["a"].count == pytest.approx(400, rel=0.2)

    def test_gaussian_count_sum_use_pld_std(self):
        res, accountant = self._run(
            [pdp.Metrics.COUNT, pdp.Metrics.SUM], pdp.NoiseKind.GAUSSIAN)
        specs = [m.mechanism_spec for m in accountant._mechanisms]
        # COUNT L2 sensitivity = sqrt(l0) * linf; SUM = sqrt(l0) * linf*max.
        l2_count = np.sqrt(2) * 3
        l2_sum = np.sqrt(2) * 3 * 2.0
        assert res["a"].count_noise_stddev == pytest.approx(
            specs[0].noise_standard_deviation * l2_count, rel=1e-6)
        assert res["a"].sum_noise_stddev == pytest.approx(
            specs[1].noise_standard_deviation * l2_sum, rel=1e-6)
        assert res["a"].count == pytest.approx(400, rel=0.2)
        assert res["a"].sum == pytest.approx(400, rel=0.25)

    def test_pld_noise_smaller_than_naive(self):
        # PLD composition is tighter: for multiple mechanisms the resolved
        # std must be below the naive equal-split calibration.
        res_pld, _ = self._run([pdp.Metrics.COUNT, pdp.Metrics.SUM],
                               pdp.NoiseKind.GAUSSIAN)
        data = [(u, pk, 1.0) for u in range(400) for pk in ("a", "b")]
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=5)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=2,
            max_contributions_per_partition=3,
            min_value=0.0, max_value=2.0,
            output_noise_stddev=True)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a", "b"])
        accountant.compute_budgets()
        res_naive = dict(result)
        assert (res_pld["a"].count_noise_stddev
                < res_naive["a"].count_noise_stddev)


class TestStatisticalE2E:
    """Statistical end-to-end behavior with REAL noise on the columnar
    engine (reference technique: dp_engine_test.py:755-830): selection
    keeps ~everything when partitions are fat / budget is huge, drops
    ~everything when every partition has one user, and the noise the
    secure host path adds matches its declared scale."""

    def test_private_selection_keeps_everything_large_budget(self):
        data = ([(u, "pk0", 1.0) for u in range(10)] +
                [(100 + u, "pk1", 1.0) for u in range(20)])
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=1,
            max_contributions_per_partition=1)
        accountant = pdp.NaiveBudgetAccountant(100000, 1e-10)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(data, params, extractors())
        accountant.compute_budgets()
        res = dict(result)
        assert set(res) == {"pk0", "pk1"}
        assert res["pk0"].count == pytest.approx(10, abs=1e-2)
        assert res["pk1"].count == pytest.approx(20, abs=1e-2)

    def test_private_selection_drops_singleton_partitions(self):
        # 100 partitions, one distinct user each: with eps=1 the selection
        # probability per partition is tiny — keeps < 5 w.h.p.
        data = [(u, f"pk{u}", 1.0) for u in range(100)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=1,
            max_contributions_per_partition=1)
        accountant = pdp.NaiveBudgetAccountant(1, 1e-10)
        engine = pdp.JaxDPEngine(accountant)
        result = engine.aggregate(data, params, extractors())
        accountant.compute_budgets()
        assert len(dict(result)) < 5

    def test_real_noise_matches_declared_scale(self):
        # 512 public partitions with identical truth: the empirical std of
        # (dp - truth) across partitions must match the declared stddev
        # (within ~4 sigma of the std estimator), and the mean error ~ 0.
        n_parts = 512
        data = [(u, f"p{i}", 1.0) for i in range(n_parts)
                for u in range(7)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=n_parts,
            max_contributions_per_partition=1,
            output_noise_stddev=True)
        accountant = pdp.NaiveBudgetAccountant(200.0, 1e-10)
        engine = pdp.JaxDPEngine(accountant)  # secure host noise (default)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=[f"p{i}"
                                                     for i in range(n_parts)])
        accountant.compute_budgets()
        cols = result.to_columns()
        errs = np.asarray(cols["count"]) - 7.0
        declared = float(np.asarray(cols["count_noise_stddev"])[0])
        emp = errs.std()
        assert emp == pytest.approx(declared, rel=0.35)
        assert abs(errs.mean()) < 5 * declared / np.sqrt(n_parts)

    def test_gaussian_noise_scale_streaming_path(self):
        # Same statistical check through the wire-codec streamed path.
        n_parts = 256
        rng = np.random.default_rng(0)
        pid = np.arange(n_parts * 9, dtype=np.int64)
        pk = np.tile(np.arange(n_parts, dtype=np.int32), 9)
        value = rng.integers(1, 6, n_parts * 9).astype(np.float32)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=n_parts,
            max_contributions_per_partition=1,
            output_noise_stddev=True)
        accountant = pdp.NaiveBudgetAccountant(30.0, 1e-8)
        engine = pdp.JaxDPEngine(accountant, stream_chunks=4)
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=list(range(n_parts)))
        accountant.compute_budgets()
        cols = result.to_columns()
        errs = np.asarray(cols["count"]) - 9.0
        declared = float(np.asarray(cols["count_noise_stddev"])[0])
        assert errs.std() == pytest.approx(declared, rel=0.4)
