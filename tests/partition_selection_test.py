"""Tests for private partition selection strategies.

The truncated-geometric closed forms are validated against the exact
saturated recurrence pi_{n+1} = min(e^eps pi_n + delta,
1 - e^-eps (1 - pi_n - delta), 1) — the defining DP-optimality property
(Desfontaines et al. 2022), which also pins the probabilities the way the
reference's tests pin PyDP behavior (tests/dp_engine_test.py:38-45).
"""

import math

import numpy as np
import pytest

from pipelinedp_tpu import partition_selection as ps
from pipelinedp_tpu.aggregate_params import PartitionSelectionStrategy


def reference_pi(eps, delta, max_partitions, n_max):
    """Exact recurrence for per-partition keep probabilities."""
    eps_p = eps / max_partitions
    delta_p = -math.expm1(math.log1p(-delta) / max_partitions)
    pis = [0.0]
    for _ in range(n_max):
        pi = pis[-1]
        branch_a = math.exp(eps_p) * pi + delta_p
        branch_b = 1.0 - math.exp(-eps_p) * (1.0 - pi - delta_p)
        pis.append(min(branch_a, branch_b, 1.0))
    return np.array(pis[1:])


class TestTruncatedGeometric:

    @pytest.mark.parametrize("eps,delta,m", [
        (1.0, 1e-6, 1),
        (1.0, 1e-6, 8),
        (0.1, 1e-5, 2),
        (3.0, 1e-10, 4),
        (0.5, 1e-3, 1),
    ])
    def test_matches_recurrence(self, eps, delta, m):
        strategy = ps.TruncatedGeometricPartitionSelection(eps, delta, m)
        n_max = 2000
        expected = reference_pi(eps, delta, m, n_max)
        actual = strategy.probability_of_keep_vec(np.arange(1, n_max + 1))
        np.testing.assert_allclose(actual, expected, rtol=1e-9, atol=1e-12)

    def test_zero_and_negative_counts(self):
        strategy = ps.TruncatedGeometricPartitionSelection(1.0, 1e-6, 1)
        assert strategy.probability_of_keep(0) == 0.0

    def test_single_user_probability_is_delta(self):
        # pi(1) = delta' per the recurrence.
        strategy = ps.TruncatedGeometricPartitionSelection(1.0, 1e-6, 1)
        assert strategy.probability_of_keep(1) == pytest.approx(1e-6, rel=1e-6)

    def test_monotonic_and_saturates(self):
        strategy = ps.TruncatedGeometricPartitionSelection(1.0, 1e-6, 2)
        probs = strategy.probability_of_keep_vec(np.arange(1, 500))
        assert np.all(np.diff(probs) >= -1e-15)
        assert probs[-1] == pytest.approx(1.0)

    def test_threshold_is_median_count(self):
        strategy = ps.TruncatedGeometricPartitionSelection(1.0, 1e-6, 1)
        t = int(strategy.threshold)
        assert strategy.probability_of_keep(t) >= 0.5
        assert strategy.probability_of_keep(t - 1) < 0.5

    def test_should_keep_statistical(self):
        ps.seed_rng(0)
        strategy = ps.TruncatedGeometricPartitionSelection(1.0, 1e-6, 1)
        n = int(strategy.threshold)
        keeps = sum(strategy.should_keep(n) for _ in range(2000))
        p = strategy.probability_of_keep(n)
        assert abs(keeps / 2000 - p) < 0.05

    def test_pre_threshold(self):
        base = ps.TruncatedGeometricPartitionSelection(1.0, 1e-6, 1)
        pre = ps.TruncatedGeometricPartitionSelection(1.0, 1e-6, 1,
                                                      pre_threshold=10)
        assert pre.probability_of_keep(9) == 0.0
        assert pre.probability_of_keep(14) == pytest.approx(
            base.probability_of_keep(5))


class TestThresholding:

    @pytest.mark.parametrize("strategy_enum", [
        PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
        PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
    ])
    def test_delta_bound_on_single_user(self, strategy_enum):
        """P(keep | 1 user) must be <= delta (the defining property)."""
        eps, delta, m = 1.0, 1e-6, 4
        strategy = ps.create_partition_selection_strategy(
            strategy_enum, eps, delta, m)
        p1 = strategy.probability_of_keep(1)
        assert 0 < p1 <= delta

    def test_laplace_threshold_formula(self):
        eps, delta, m = 1.0, 1e-6, 1
        strategy = ps.LaplaceThresholdingPartitionSelection(eps, delta, m)
        expected = 1.0 - (1.0 / eps) * math.log(2 * delta)
        assert strategy.threshold == pytest.approx(expected)

    def test_laplace_probability_of_keep(self):
        strategy = ps.LaplaceThresholdingPartitionSelection(1.0, 1e-6, 1)
        t = strategy.threshold
        # At the threshold count the keep probability is exactly 1/2.
        assert strategy.probability_of_keep(round(t)) == pytest.approx(
            0.5, abs=0.2)
        probs = strategy.probability_of_keep_vec(np.arange(1, 100))
        assert np.all(np.diff(probs) >= -1e-15)

    def test_noised_value_above_threshold(self):
        strategy = ps.LaplaceThresholdingPartitionSelection(1.0, 1e-6, 1)
        big_n = int(strategy.threshold) + 200
        value = strategy.noised_value_if_should_keep(big_n)
        assert value is not None
        assert value >= strategy.threshold
        assert value == pytest.approx(big_n, rel=0.2)

    def test_noised_value_for_tiny_count_usually_none(self):
        strategy = ps.LaplaceThresholdingPartitionSelection(1.0, 1e-6, 1)
        results = [
            strategy.noised_value_if_should_keep(1) for _ in range(200)
        ]
        assert sum(r is not None for r in results) == 0

    def test_gaussian_sigma_calibration(self):
        from pipelinedp_tpu import noise_core
        eps, delta, m = 1.0, 1e-6, 4
        strategy = ps.GaussianThresholdingPartitionSelection(eps, delta, m)
        # sigma must satisfy the analytic Gaussian condition for (eps, delta/2)
        # with l2 sensitivity sqrt(m).
        achieved_delta = noise_core.gaussian_delta(strategy.sigma, eps,
                                                   math.sqrt(m))
        assert achieved_delta <= delta / 2 + 1e-12

    def test_pre_threshold_shifts(self):
        strategy = ps.LaplaceThresholdingPartitionSelection(1.0, 1e-6, 1,
                                                            pre_threshold=100)
        assert strategy.probability_of_keep(99) == 0.0
        base = ps.LaplaceThresholdingPartitionSelection(1.0, 1e-6, 1)
        assert strategy.threshold == pytest.approx(base.threshold + 99)


class TestFactory:

    def test_factory_types(self):
        for enum, cls in [
            (PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
             ps.TruncatedGeometricPartitionSelection),
            (PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
             ps.LaplaceThresholdingPartitionSelection),
            (PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
             ps.GaussianThresholdingPartitionSelection),
        ]:
            assert isinstance(
                ps.create_partition_selection_strategy(enum, 1.0, 1e-6, 2),
                cls)

    def test_validation(self):
        with pytest.raises(ValueError):
            ps.TruncatedGeometricPartitionSelection(0, 1e-6, 1)
        with pytest.raises(ValueError):
            ps.TruncatedGeometricPartitionSelection(1, 0, 1)
        with pytest.raises(ValueError):
            ps.TruncatedGeometricPartitionSelection(1, 1e-6, 0)


class TestLargeEpsilonRobustness:
    """The closed forms must stay finite for arbitrarily large epsilon
    (log-space evaluation; exp(-eps') underflow handled)."""

    @pytest.mark.parametrize("eps", [100.0, 600.0, 2000.0, 1e8])
    def test_truncated_geometric_large_eps(self, eps):
        s = ps.TruncatedGeometricPartitionSelection(eps, 1e-6, 2)
        probs = [s.probability_of_keep(n) for n in (1, 2, 5, 100)]
        assert all(0.0 <= p <= 1.0 for p in probs)
        # delta' for one unit; everything else is certain at huge eps.
        assert probs[0] == pytest.approx(
            1 - (1 - 1e-6)**0.5, rel=1e-6)
        assert probs[2] == pytest.approx(1.0)
        assert s.threshold <= 3

    def test_matches_recurrence_moderate_eps(self):
        # The log-space forms equal the direct recurrence where the
        # recurrence is computable.
        eps, delta, m = 20.0, 1e-8, 3
        s = ps.TruncatedGeometricPartitionSelection(eps, delta, m)
        e = eps / m
        d = s._delta_p
        pi = 0.0
        import math
        for n in range(1, 30):
            pi = min(math.exp(e) * pi + d,
                     1 - math.exp(-e) * (1 - pi - d), 1.0)
            assert s.probability_of_keep(n) == pytest.approx(pi, abs=1e-12)
