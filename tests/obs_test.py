"""Unit tests for pipelinedp_tpu/obs/: tracer, metrics registry, audit.

Covers the PR-11 acceptance surface that doesn't need an engine:
trace-schema validation (parents resolve, spans nest within parents),
histogram bucket correctness, Prometheus exposition shape, audit-WAL
torn-tail recovery, the profiler back-compat shims, and the
reset-vs-increment atomicity hammer (the counter-hygiene satellite).
"""

import json
import math
import re
import threading

import pytest

from pipelinedp_tpu import profiler
from pipelinedp_tpu.obs import audit as audit_lib
from pipelinedp_tpu.obs import metrics as metrics_lib
from pipelinedp_tpu.obs import trace as trace_lib


@pytest.fixture
def tracer():
    t = trace_lib.install(trace_lib.Tracer())
    try:
        yield t
    finally:
        trace_lib.shutdown()


def make_registry():
    return metrics_lib.MetricsRegistry()


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------


def validate_trace_schema(spans):
    """The PR-11 trace invariants: every span has a parent except roots,
    parents resolve within the trace, children nest inside their
    parent's [t0, t0+dur] window, and ids are unique."""
    by_id = {s.span_id: s for s in spans}
    assert len(by_id) == len(spans), "duplicate span ids"
    for s in spans:
        assert s.dur_ns >= 0, f"unfinished span {s.name} exported"
        if s.parent_id is None:
            assert s.trace_id == s.span_id
            continue
        parent = by_id.get(s.parent_id)
        assert parent is not None, \
            f"span {s.name} has dangling parent {s.parent_id}"
        assert s.trace_id == parent.trace_id
        assert s.t0_ns >= parent.t0_ns
        assert s.t0_ns + s.dur_ns <= parent.t0_ns + parent.dur_ns, \
            f"span {s.name} escapes parent {parent.name}"


class TestTracer:

    def test_nesting_and_parent_links(self, tracer):
        with tracer.span("root") as root:
            with tracer.span("child") as child:
                with tracer.span("grandchild") as gc:
                    pass
        spans = tracer.spans()
        assert [s.name for s in spans] == ["grandchild", "child", "root"]
        assert child.parent_id == root.span_id
        assert gc.parent_id == child.span_id
        assert root.parent_id is None
        assert {s.trace_id for s in spans} == {root.span_id}
        validate_trace_schema(spans)

    def test_sibling_roots_get_distinct_traces(self, tracer):
        with tracer.span("a"):
            pass
        with tracer.span("b"):
            pass
        a, b = tracer.spans()
        assert a.trace_id != b.trace_id
        validate_trace_schema(tracer.spans())

    def test_events_attach_to_current_span(self, tracer):
        with tracer.span("work") as span:
            tracer.event("retry", attempt=1)
        assert [e[0] for e in span.events] == ["retry"]
        assert span.events[0][2] == {"attempt": 1}
        # No open span: dropped, never raises.
        tracer.event("orphan")

    def test_cross_thread_attach(self, tracer):
        with tracer.span("root") as root:
            def worker():
                with tracer.attach(root):
                    with tracer.span("worker-span"):
                        pass
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        worker_span = next(s for s in tracer.spans()
                           if s.name == "worker-span")
        assert worker_span.parent_id == root.span_id
        validate_trace_schema(tracer.spans())

    def test_error_span_marked(self, tracer):
        with pytest.raises(ValueError):
            with tracer.span("failing"):
                raise ValueError("boom")
        (span,) = tracer.spans()
        assert span.attrs["error"] is True

    def test_disabled_is_shared_null_context(self):
        trace_lib.shutdown()
        ctx1 = trace_lib.span("x", a=1)
        ctx2 = trace_lib.span("y")
        assert ctx1 is ctx2  # the shared singleton: zero allocation
        with ctx1 as span:
            assert span is None
        trace_lib.event("nothing")  # no-op, no error
        assert trace_lib.current() is None

    def test_chrome_export_schema(self, tracer, tmp_path):
        with tracer.span("root", knob=3):
            with tracer.span("child"):
                tracer.event("mark", detail="x")
        doc = tracer.export_chrome()
        assert set(doc) == {"traceEvents", "displayTimeUnit"}
        complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
        assert {e["name"] for e in complete} == {"root", "child"}
        assert [e["name"] for e in instants] == ["mark"]
        for e in complete:
            assert {"pid", "tid", "ts", "dur", "args"} <= set(e)
            assert "span_id" in e["args"]
        root_ev = next(e for e in complete if e["name"] == "root")
        assert root_ev["args"]["knob"] == 3
        assert "parent_id" not in root_ev["args"]
        # File form round-trips as JSON (Perfetto-loadable).
        path = tracer.write_chrome(str(tmp_path / "t.json"))
        assert json.load(open(path)) == json.loads(json.dumps(doc))

    def test_per_trace_export_filter(self, tracer):
        with tracer.span("query-1") as q1:
            with tracer.span("inner"):
                pass
        with tracer.span("query-2"):
            pass
        events = tracer.export_chrome(trace_id=q1.trace_id)["traceEvents"]
        assert {e["name"] for e in events} == {"query-1", "inner"}

    def test_forbidden_attr_keys_refused(self, tracer):
        with pytest.raises(metrics_lib.TelemetryLeakError):
            tracer.span("bad", pid=123)
        with tracer.span("ok") as span:
            with pytest.raises(metrics_lib.TelemetryLeakError):
                span.set_attribute("partition_key", "k")
            with pytest.raises(metrics_lib.TelemetryLeakError):
                span.add_event("ev", value=1.0)

    def test_non_scalar_attr_refused(self, tracer):
        with pytest.raises(metrics_lib.TelemetryLeakError):
            tracer.span("bad", rows=[1, 2, 3])

    def test_bounded_span_buffer(self):
        t = trace_lib.Tracer(max_spans=3)
        for i in range(5):
            with t.span(f"s{i}"):
                pass
        assert [s.name for s in t.spans()] == ["s2", "s3", "s4"]


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------


class TestMetricsRegistry:

    def test_counter_and_labels(self):
        reg = make_registry()
        c = reg.counter("pdp_test_queries", "help text")
        c.inc()
        c.inc(2, outcome="released")
        c.inc(outcome="released")
        assert c.value() == 1
        assert c.value(outcome="released") == 3
        # Same name returns the same family; a different type conflicts.
        assert reg.counter("pdp_test_queries") is c
        with pytest.raises(ValueError):
            reg.gauge("pdp_test_queries")

    def test_gauge(self):
        reg = make_registry()
        g = reg.gauge("pdp_test_bytes")
        g.set(100)
        g.inc(5)
        g.dec(3)
        assert g.value() == 102

    def test_histogram_bucket_correctness(self):
        reg = make_registry()
        h = reg.histogram("pdp_test_lat", buckets=(0.1, 1.0, 10.0))
        # Boundary semantics are Prometheus `le` (inclusive upper).
        for v in (0.05, 0.1, 0.10001, 1.0, 5.0, 10.0, 99.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["buckets"] == [0.1, 1.0, 10.0, math.inf]
        # cumulative: le 0.1 -> {0.05, 0.1}; le 1 -> +{0.10001, 1.0};
        # le 10 -> +{5.0, 10.0}; +Inf -> +{99.0}
        assert snap["counts"] == [2, 4, 6, 7]
        assert snap["count"] == 7
        assert snap["sum"] == pytest.approx(sum(
            (0.05, 0.1, 0.10001, 1.0, 5.0, 10.0, 99.0)))

    def test_histogram_labels_and_default_buckets(self):
        reg = make_registry()
        h = reg.histogram("pdp_test_q")
        h.observe(0.02, outcome="released")
        h.observe(3.0, outcome="shed")
        assert h.snapshot(outcome="released")["count"] == 1
        assert (len(h.snapshot(outcome="released")["buckets"])
                == len(metrics_lib.DEFAULT_LATENCY_BUCKETS_S) + 1)

    def test_prometheus_exposition_schema(self):
        reg = make_registry()
        reg.counter("pdp_c", "a counter").inc(2, kind="x")
        reg.gauge("pdp_g").set(7)
        h = reg.histogram("pdp_h", buckets=(1.0, 2.0))
        h.observe(1.5)
        reg.event_inc("serving/queries", 3)
        text = reg.to_prometheus()
        lines = text.splitlines()
        assert "# TYPE pdp_c_total counter" in lines
        assert 'pdp_c_total{kind="x"} 2' in lines
        assert "# TYPE pdp_g gauge" in lines
        assert "pdp_g 7" in lines
        assert "# TYPE pdp_h histogram" in lines
        assert 'pdp_h_bucket{le="1"} 0' in lines
        assert 'pdp_h_bucket{le="2"} 1' in lines
        assert 'pdp_h_bucket{le="+Inf"} 1' in lines
        assert "pdp_h_sum 1.5" in lines
        assert "pdp_h_count 1" in lines
        assert ('pipelinedp_tpu_events_total{event="serving/queries"} 3'
                in lines)
        # Every sample line is format-0.0.4 parseable.
        sample_re = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$')
        for line in lines:
            if line and not line.startswith("#"):
                assert sample_re.match(line), line

    def test_snapshot_is_json_able(self):
        reg = make_registry()
        reg.counter("pdp_c").inc()
        reg.histogram("pdp_h", buckets=(1.0,)).observe(0.5)
        reg.event_inc("runtime/retries")
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["events"] == {"runtime/retries": 1}
        assert snap["families"]["pdp_c"]["kind"] == "counter"
        assert snap["families"]["pdp_h"]["kind"] == "histogram"

    def test_forbidden_label_refused(self):
        reg = make_registry()
        with pytest.raises(metrics_lib.TelemetryLeakError):
            reg.counter("pdp_c").inc(pid="u1")
        with pytest.raises(metrics_lib.TelemetryLeakError):
            reg.histogram("pdp_h").observe(0.1, partition_key="k")

    def test_event_namespace_reset_prefix(self):
        reg = make_registry()
        reg.event_inc("a/x", 2)
        reg.event_inc("b/y", 3)
        reg.reset_events("a/")
        assert reg.event_values() == {"b/y": 3}
        reg.reset_events()
        assert reg.event_values() == {}


class TestProfilerShims:
    """profiler.count_event/event_count/event_counts/reset_events are
    back-compat views over the default registry's event namespace."""

    def test_count_event_lands_in_registry(self):
        profiler.reset_events("obs_shim_test/")
        profiler.count_event("obs_shim_test/hits", 4)
        assert profiler.event_count("obs_shim_test/hits") == 4
        assert metrics_lib.default_registry().event_value(
            "obs_shim_test/hits") == 4
        assert profiler.event_counts()["obs_shim_test/hits"] == 4
        profiler.reset_events("obs_shim_test/")
        assert profiler.event_count("obs_shim_test/hits") == 0

    def test_reset_vs_increment_hammer(self):
        """The counter-hygiene satellite: reset_events(prefix) racing
        count_event from many threads must be atomic — an unrelated
        prefix NEVER loses increments, and the hammered prefix never
        errors or goes negative."""
        n_threads, n_incs = 8, 2000
        profiler.reset_events("hammer/")
        profiler.reset_events("stable/")
        stop = threading.Event()

        def incrementer():
            for _ in range(n_incs):
                profiler.count_event("stable/total")
                profiler.count_event("hammer/racy")

        def resetter():
            while not stop.is_set():
                profiler.reset_events("hammer/")

        threads = [threading.Thread(target=incrementer)
                   for _ in range(n_threads)]
        killer = threading.Thread(target=resetter)
        killer.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        killer.join()
        # The unrelated prefix kept every single increment.
        assert profiler.event_count("stable/total") == n_threads * n_incs
        # The hammered counter is consistent (>= 0; exact value depends
        # on the last reset's timing).
        assert profiler.event_count("hammer/racy") >= 0
        profiler.reset_events("hammer/")
        profiler.reset_events("stable/")

    def test_snapshot_while_incrementing_never_errors(self):
        done = threading.Event()

        def writer():
            i = 0
            while not done.is_set():
                profiler.count_event(f"snaphammer/{i % 50}")
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            reg = metrics_lib.default_registry()
            for _ in range(300):
                json.dumps(reg.snapshot())
                reg.to_prometheus()
        finally:
            done.set()
            t.join()
        profiler.reset_events("snaphammer/")


# ---------------------------------------------------------------------------
# Audit trail
# ---------------------------------------------------------------------------


def _record(trail, seed=0, outcome="released", tenant="acme",
            trace_id=""):
    return trail.record(
        session="s", tenant=tenant, token=f"('fp', {seed})",
        outcome=outcome, mechanisms=["COUNT", "SUM"],
        noise_kind="laplace", epsilon=1.0, delta=1e-6,
        partitions_kept=10, partitions_dropped=5, duration_s=0.25,
        seed=seed, trace_id=trace_id)


class TestAuditTrail:

    def test_record_fields_and_tenant_filter(self):
        trail = audit_lib.AuditTrail()
        _record(trail, seed=0, tenant="acme")
        _record(trail, seed=1, tenant="bob", outcome="refunded")
        assert len(trail) == 2
        acme = trail.records(tenant="acme")
        assert len(acme) == 1
        r = acme[0]
        assert (r.seq, r.outcome, r.mechanisms) == (
            0, "released", ("COUNT", "SUM"))
        assert r.partitions_kept == 10 and r.partitions_dropped == 5
        assert trail.records()[1].outcome == "refunded"

    def test_unknown_outcome_refused(self):
        trail = audit_lib.AuditTrail()
        with pytest.raises(ValueError, match="outcome"):
            _record(trail, outcome="maybe")

    def test_durable_roundtrip(self, tmp_path):
        path = str(tmp_path / "audit.wal")
        trail = audit_lib.AuditTrail(path)
        _record(trail, seed=0)
        _record(trail, seed=1, outcome="shed")
        trail.close()
        reopened = audit_lib.AuditTrail(path)
        assert [r.to_payload() for r in reopened.records()] == \
            [r.to_payload() for r in trail.records()]
        # Appends continue the sequence.
        _record(reopened, seed=2, outcome="deadline-expired")
        assert [r.seq for r in reopened.records()] == [0, 1, 2]

    def test_torn_tail_recovery(self, tmp_path):
        path = str(tmp_path / "audit.wal")
        trail = audit_lib.AuditTrail(path)
        _record(trail, seed=0)
        _record(trail, seed=1)
        trail.close()
        with open(path, "ab") as f:
            f.write(b'{"seq": 2, "torn mid-append')
        reopened = audit_lib.AuditTrail(path)
        assert len(reopened) == 2  # the torn record was never acked
        _record(reopened, seed=2)
        reopened.close()
        # The truncated tail never fuses with the new append.
        final = audit_lib.AuditTrail(path)
        assert [r.seed for r in final.records()] == [0, 1, 2]

    def test_interior_corruption_refused(self, tmp_path):
        path = str(tmp_path / "audit.wal")
        trail = audit_lib.AuditTrail(path)
        _record(trail, seed=0)
        _record(trail, seed=1)
        trail.close()
        lines = open(path, "rb").read().splitlines(keepends=True)
        with open(path, "wb") as f:
            f.write(b'{"seq": 0, "garbage": true}\n')
            f.writelines(lines[1:])
        with pytest.raises(audit_lib.AuditCorruptError):
            audit_lib.AuditTrail(path)

    def test_bind_migrates_in_memory_records(self, tmp_path):
        path = str(tmp_path / "audit.wal")
        trail = audit_lib.AuditTrail()
        _record(trail, seed=0)
        assert not trail.durable
        trail.bind(path)
        assert trail.durable
        _record(trail, seed=1)
        trail.close()
        reopened = audit_lib.AuditTrail(path)
        assert [r.seed for r in reopened.records()] == [0, 1]
        # bind on an already-durable trail is a no-op.
        reopened.bind(str(tmp_path / "other.wal"))
        assert reopened.path == path

    def test_bind_after_prior_process_appends_after_recovery(
            self, tmp_path):
        path = str(tmp_path / "audit.wal")
        first = audit_lib.AuditTrail(path)
        _record(first, seed=0)
        first.close()
        # A fresh in-memory trail (new process, queries before save).
        second = audit_lib.AuditTrail()
        _record(second, seed=1)
        second.bind(path)
        assert [r.seed for r in second.records()] == [0, 1]
        assert [r.seq for r in second.records()] == [0, 1]

    def test_records_survive_json_roundtrip(self):
        trail = audit_lib.AuditTrail()
        r = _record(trail)
        assert audit_lib.AuditRecord.from_payload(
            json.loads(json.dumps(r.to_payload()))) == r

    def test_trace_id_recorded_and_persisted(self, tmp_path):
        path = str(tmp_path / "audit.wal")
        trail = audit_lib.AuditTrail(path)
        _record(trail, trace_id="q123-7")
        trail.close()
        reopened = audit_lib.AuditTrail(path)
        assert reopened.records()[0].trace_id == "q123-7"

    def test_pr11_records_without_trace_id_still_read(self, tmp_path):
        """Back-compat pin (ISSUE 13): a WAL written before the
        trace_id field existed must recover cleanly, reading the
        missing field as the empty string — and appends after recovery
        (which do carry trace_id) coexist in one file."""
        from pipelinedp_tpu.runtime import journal as journal_lib

        path = str(tmp_path / "audit.wal")
        trail = audit_lib.AuditTrail(path)
        pr11_payload = _record(trail, seed=0).to_payload()
        trail.close()
        # Rewrite the WAL with the PR-11 schema (no trace_id key).
        del pr11_payload["trace_id"]
        wal = journal_lib.JsonlWal(path)
        wal.rewrite([pr11_payload])
        wal.close()
        reopened = audit_lib.AuditTrail(path)
        assert len(reopened) == 1
        assert reopened.records()[0].trace_id == ""
        assert reopened.records()[0].seed == 0
        _record(reopened, seed=1, trace_id="q9-1")
        reopened.close()
        final = audit_lib.AuditTrail(path)
        assert [r.trace_id for r in final.records()] == ["", "q9-1"]
