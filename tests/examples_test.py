"""Smoke tests for the examples tree (VERDICT-r3 task 7).

The reference keeps its examples runnable as part of its teaching surface;
these tests execute the new artifacts end-to-end on their synthetic data:
the pre-aggregated-data demo, the custom-combiners demo, and every code
cell of the codelab notebook.
"""

import importlib.util
import io
import json
import os
import pathlib
import sys
from contextlib import redirect_stdout

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def _run_script(relpath):
    path = EXAMPLES / relpath
    spec = importlib.util.spec_from_file_location(
        f"example_{path.stem}", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    out = io.StringIO()
    with redirect_stdout(out):
        module.main()
    return out.getvalue()


class TestExampleScripts:

    def test_preaggregated_data_demo(self):
        out = _run_script("restaurant_visits/run_on_preaggregated_data.py")
        assert "pre-aggregated records" in out
        assert out.count("RMSE") == 3

    def test_custom_combiners_demo(self):
        out = _run_script("experimental/custom_combiners.py")
        assert "DPEngine + LocalBackend" in out
        assert "JaxDPEngine (columnar)" in out
        # Both engines release all 7 weekday partitions.
        assert out.count("sum_squares=") == 14


class TestCodelabNotebook:

    def test_all_code_cells_execute(self):
        nb = json.loads((EXAMPLES / "codelab.ipynb").read_text())
        namespace = {}
        out = io.StringIO()
        cwd = os.getcwd()
        try:
            os.chdir(EXAMPLES)
            sys.path.insert(0, str(EXAMPLES.parent))
            for cell in nb["cells"]:
                if cell["cell_type"] != "code":
                    continue
                with redirect_stdout(out):
                    exec("".join(cell["source"]), namespace)  # noqa: S102
        finally:
            os.chdir(cwd)
            sys.path.remove(str(EXAMPLES.parent))
        text = out.getvalue()
        assert "kept partitions:" in text
        assert "COUNT RMSE" in text


class TestUtilityAnalysisNotebook:

    def test_all_code_cells_execute(self):
        nb = json.loads(
            (EXAMPLES / "utility_analysis_demo.ipynb").read_text())
        namespace = {}
        out = io.StringIO()
        cwd = os.getcwd()
        try:
            os.chdir(EXAMPLES)
            sys.path.insert(0, str(EXAMPLES.parent))
            for cell in nb["cells"]:
                if cell["cell_type"] != "code":
                    continue
                with redirect_stdout(out):
                    exec("".join(cell["source"]), namespace)  # noqa: S102
        finally:
            os.chdir(cwd)
            sys.path.remove(str(EXAMPLES.parent))
        text = out.getvalue()
        assert "quantiles:" in text
        assert "count RMSE" in text
        assert "recommended: l0 =" in text
        assert "released" in text
