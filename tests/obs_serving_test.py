"""Integration tests: observability through the serving stack.

The PR-11 acceptance surface that needs a real engine:

  * released values are BIT-IDENTICAL with tracing enabled vs disabled
    — warm (session) and cold (engine) runs, single-device and mesh8;
  * one warm serving query with tracing enabled produces a loadable
    Chrome trace containing admission → replay → finalize spans, and a
    Prometheus exposition with a non-empty query-latency histogram;
  * the audit trail records every typed outcome (released / refunded /
    shed / deadline-expired / double-release-refused) with exact
    tenant-charge accounting alongside;
  * the no-private-leak scan: every span attribute, span event, metric
    label and audit field emitted by the full matrix above is a scalar
    with a non-forbidden key, and no raw pid/pk sentinel value ever
    appears in any record.
"""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import runtime, serving
from pipelinedp_tpu.obs import flight as flight_lib
from pipelinedp_tpu.obs import metrics as metrics_lib
from pipelinedp_tpu.obs import trace as trace_lib
from pipelinedp_tpu.parallel import sharded

from tests.obs_test import validate_trace_schema

N_ROWS = 30_000
N_PARTITIONS = 200
# Sentinel privacy ids: values that appear nowhere else, so the leak
# scan can assert they never surface in any obs record.
PID_LO, PID_HI = 7_654_000, 7_654_000 + 3_000


def _data():
    rng = np.random.default_rng(11)
    return pdp.ColumnarData(
        pid=rng.integers(PID_LO, PID_HI, N_ROWS).astype(np.int64),
        pk=rng.integers(0, N_PARTITIONS, N_ROWS).astype(np.int32),
        value=rng.uniform(0, 5, N_ROWS).astype(np.float32))


def _params():
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=8,
        max_contributions_per_partition=4,
        min_value=0.0,
        max_value=5.0)


@pytest.fixture
def tracer():
    t = trace_lib.install(trace_lib.Tracer())
    try:
        yield t
    finally:
        trace_lib.shutdown()


def _query_cols(session, seed=0, **kw):
    return session.query(_params(), epsilon=1.0, delta=1e-6, seed=seed,
                         secure_host_noise=False, **kw).to_columns()


def _assert_same_columns(a, b):
    assert sorted(a) == sorted(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)


class TestOpsPlaneBitIdentity:
    """The PR-13 acceptance: released DP values are BIT-IDENTICAL with
    the full operational plane enabled (tracer + live ops endpoint +
    always-on flight recording + forced slow-query captures) vs
    everything disabled — warm and cold, single-device and mesh8."""

    @pytest.mark.parametrize("topology", ["single_device", "mesh8"])
    def test_warm_and_cold_bit_identical_with_plane_on(
            self, topology, tmp_path, monkeypatch):
        mesh = sharded.make_mesh(8) if topology == "mesh8" else None
        data = _data()

        def cold():
            accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
            engine = pdp.JaxDPEngine(accountant, seed=5, mesh=mesh,
                                     stream_chunks=4,
                                     secure_host_noise=False)
            result = engine.aggregate(data, _params())
            accountant.compute_budgets()
            return result.to_columns()

        # Plane fully off.
        trace_lib.shutdown()
        monkeypatch.delenv(flight_lib.CAPTURE_DIR_ENV, raising=False)
        monkeypatch.delenv(flight_lib.SLOW_QUERY_ENV, raising=False)
        with serving.DatasetSession(data, n_chunks=4, mesh=mesh,
                                    name=f"plane-off-{topology}") as s:
            warm_off = _query_cols(s)
        cold_off = cold()

        # Plane fully on: tracer installed, ops endpoint live over a
        # manager, flight spool bound, every query captured.
        monkeypatch.setenv(flight_lib.CAPTURE_DIR_ENV,
                           str(tmp_path / "cap"))
        monkeypatch.setenv(flight_lib.SLOW_QUERY_ENV, "0.000001")
        trace_lib.install(trace_lib.Tracer())
        manager = serving.SessionManager(
            serving.SessionStore(str(tmp_path / "store")), ops_port=0)
        try:
            session = manager.create(f"plane-on-{topology}", data,
                                     n_chunks=4, mesh=mesh)
            warm_on = _query_cols(session)
            cold_on = cold()
            # The endpoint really is live while the bits are compared.
            status = urllib.request.urlopen(
                manager.ops_server.url + "/statusz", timeout=10).status
            assert status == 200
        finally:
            manager.close()
            trace_lib.shutdown()
        assert os.listdir(tmp_path / "cap"), "capture never triggered"
        _assert_same_columns(warm_off, warm_on)
        _assert_same_columns(cold_off, cold_on)


class TestBitIdentityOnOff:
    """Tracing must be observationally free: same released bits on and
    off, for warm (session) and cold (engine) paths."""

    @pytest.mark.parametrize("topology", ["single_device", "mesh8"])
    def test_warm_query_bit_identical(self, topology):
        mesh = sharded.make_mesh(8) if topology == "mesh8" else None
        data = _data()
        trace_lib.shutdown()
        with serving.DatasetSession(data, n_chunks=4, mesh=mesh,
                                    name=f"off-{topology}") as s_off:
            off = _query_cols(s_off)
        trace_lib.install(trace_lib.Tracer())
        try:
            with serving.DatasetSession(data, n_chunks=4, mesh=mesh,
                                        name=f"on-{topology}") as s_on:
                on = _query_cols(s_on)
                repeat = _query_cols(s_on)  # bound-cache hit leg
        finally:
            trace_lib.shutdown()
        _assert_same_columns(off, on)
        _assert_same_columns(off, repeat)

    @pytest.mark.parametrize("topology", ["single_device", "mesh8"])
    def test_cold_engine_bit_identical(self, topology):
        mesh = sharded.make_mesh(8) if topology == "mesh8" else None
        data = _data()

        def run():
            accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
            engine = pdp.JaxDPEngine(accountant, seed=5, mesh=mesh,
                                     stream_chunks=4,
                                     secure_host_noise=False)
            result = engine.aggregate(data, _params())
            accountant.compute_budgets()
            return result.to_columns()

        trace_lib.shutdown()
        off = run()
        trace_lib.install(trace_lib.Tracer())
        try:
            on = run()
        finally:
            trace_lib.shutdown()
        _assert_same_columns(off, on)


class TestAcceptanceTraceAndExposition:
    """One warm serving query with tracing on -> loadable Chrome trace
    with admission/replay/finalize spans + non-empty query-latency
    Prometheus histogram."""

    def test_warm_query_trace_and_histogram(self, tracer, tmp_path):
        data = _data()
        trace_file = str(tmp_path / "query_trace.json")
        with serving.DatasetSession(data, n_chunks=4,
                                    name="accept") as session:
            before = metrics_lib.query_seconds().snapshot(
                outcome="released")["count"]
            _query_cols(session, trace_path=trace_file)

        doc = json.load(open(trace_file))
        events = doc["traceEvents"]
        names = {e["name"] for e in events if e["ph"] == "X"}
        assert {"serving/query", "serving/admission", "serving/replay",
                "engine/finalize", "driver/window",
                "driver/transfer"} <= names
        # One root: the query; everything else parents into it.
        roots = [e for e in events if e["ph"] == "X"
                 and "parent_id" not in e["args"]]
        assert [e["name"] for e in roots] == ["serving/query"]
        # The in-memory span objects satisfy the schema invariants.
        validate_trace_schema(tracer.spans())

        snap = metrics_lib.query_seconds().snapshot(outcome="released")
        assert snap["count"] == before + 1
        assert snap["sum"] > 0
        prom = metrics_lib.default_registry().to_prometheus()
        assert "pipelinedp_tpu_query_seconds_bucket" in prom
        assert "pipelinedp_tpu_replay_seconds_bucket" in prom
        assert "pipelinedp_tpu_finalize_seconds_bucket" in prom

    def test_trace_disabled_trace_path_is_noop(self, tmp_path):
        trace_lib.shutdown()
        data = _data()
        trace_file = str(tmp_path / "none.json")
        with serving.DatasetSession(data, n_chunks=2,
                                    name="notrace") as session:
            _query_cols(session, trace_path=trace_file)
        assert not (tmp_path / "none.json").exists()


class TestAuditOutcomes:
    """Every typed outcome lands in the audit trail with the mechanism
    metadata and exact charge semantics."""

    def _session(self, **kw):
        return serving.DatasetSession(_data(), n_chunks=4, **kw)

    def test_released_record_carries_dp_output_counts(self):
        with self._session(name="aud-rel") as session:
            cols = _query_cols(session, seed=1)
            (rec,) = session.audit_trail.records()
        keep = np.asarray(cols["keep_mask"])
        assert rec.outcome == "released"
        assert rec.mechanisms == ("COUNT", "SUM")
        assert rec.noise_kind == "laplace"
        assert rec.epsilon == pytest.approx(1.0)
        assert rec.partitions_kept == int(keep.sum())
        assert rec.partitions_dropped == int(keep.size) - rec.partitions_kept
        assert rec.duration_s > 0
        assert rec.seed == 1

    def test_double_release_refused_recorded(self):
        with self._session(name="aud-dbl") as session:
            session.register_tenant("acme", total_epsilon=10.0, total_delta=1e-3)
            _query_cols(session, seed=2, tenant="acme")
            with pytest.raises(runtime.DoubleReleaseError):
                _query_cols(session, seed=2, tenant="acme")
            outcomes = [r.outcome for r in session.audit_trail.records()]
            assert outcomes == ["released", "double-release-refused"]
            recs = session.audit_trail.records()
            assert recs[0].token == recs[1].token
            # The refused query drew nothing: exactly one charge stands.
            assert session.tenant("acme").ledger.spent_epsilon == \
                pytest.approx(1.0)

    def test_shed_recorded(self):
        with self._session(name="aud-shed") as session:
            manager = serving.SessionManager(max_inflight=1)
            manager.attach(session)
            release = threading.Event()
            entered = threading.Event()

            def hog():
                with manager.admission():
                    entered.set()
                    release.wait(30)

            t = threading.Thread(target=hog)
            t.start()
            try:
                assert entered.wait(30)
                with pytest.raises(serving.SessionOverloadedError):
                    _query_cols(session, seed=3)
            finally:
                release.set()
                t.join()
            manager.remove(session.name)
            assert [r.outcome for r in session.audit_trail.records()] == \
                ["shed"]

    def test_deadline_expired_recorded(self):
        with self._session(name="aud-dl") as session:
            injector = runtime.FaultInjector(
                [runtime.FaultSpec("hang", at_slab=0, hang_s=15.0)])
            with pytest.raises(serving.QueryDeadlineError):
                _query_cols(session, seed=4, deadline_s=1.0,
                            fault_injector=injector)
            (rec,) = session.audit_trail.records()
            assert rec.outcome == "deadline-expired"

    def test_failed_query_recorded_as_refunded(self):
        with self._session(name="aud-ref") as session:
            session.register_tenant("acme", total_epsilon=10.0, total_delta=1e-3)
            injector = runtime.FaultInjector(
                [runtime.FaultSpec("host_crash", at_slab=0)])
            with pytest.raises(Exception):
                _query_cols(session, seed=5, tenant="acme",
                            fault_injector=injector)
            (rec,) = session.audit_trail.records()
            assert rec.outcome == "refunded"
            assert rec.tenant == "acme"
            # The charge was exactly refunded.
            assert session.tenant("acme").ledger.spent_epsilon == 0.0

    def test_query_batch_records_per_config(self):
        with self._session(name="aud-batch") as session:
            configs = [
                serving.QueryConfig(
                    metrics=[pdp.Metrics.COUNT], epsilon=1.0, delta=1e-6,
                    max_partitions_contributed=8,
                    max_contributions_per_partition=4, seed=100 + i)
                for i in range(3)
            ]
            session.query_batch(configs, secure_host_noise=False)
            recs = session.audit_trail.records()
            assert [r.outcome for r in recs] == ["released"] * 3
            assert sorted(r.seed for r in recs) == [100, 101, 102]
            assert all(r.partitions_kept >= 0 for r in recs)

    def test_trace_id_correlates_audit_span_and_capture(
            self, tracer, tmp_path, monkeypatch):
        """The PR-13 correlation satellite: one query's audit record,
        root span, flight events and slow-query capture all carry the
        same trace id."""
        cap_dir = str(tmp_path / "cap")
        monkeypatch.setenv(flight_lib.CAPTURE_DIR_ENV, cap_dir)
        monkeypatch.setenv(flight_lib.SLOW_QUERY_ENV, "0.000001")
        with self._session(name="aud-corr") as session:
            mark = flight_lib.recorder().watermark()
            _query_cols(session, seed=11)
            (rec,) = session.audit_trail.records()
        qid = rec.trace_id
        assert qid.startswith("q")
        root = next(s for s in tracer.spans()
                    if s.name == "serving/query")
        assert root.attrs["qid"] == qid
        kinds = {e.kind: e for e in
                 flight_lib.recorder().events(since_seq=mark)}
        assert kinds["query_start"].attrs["qid"] == qid
        assert kinds["query_finish"].attrs["qid"] == qid
        capture_path = os.path.join(cap_dir, f"slowquery_{qid}.json")
        assert os.path.exists(capture_path)
        capture = json.load(open(capture_path))
        assert capture["trace_id"] == qid
        assert capture["outcome"] == "released"
        assert capture["metrics_delta"].get(
            "serving/bound_cache_misses") == 1
        assert "query_start" in [e["kind"]
                                 for e in capture["flight_events"]]
        # Tracing was on: the capture embeds this query's Chrome trace.
        names = {e["name"] for e in capture["chrome_trace"]["traceEvents"]}
        assert "serving/query" in names

    def test_audit_durable_on_saved_session(self, tmp_path):
        store = serving.SessionStore(str(tmp_path))
        with self._session(name="aud-store") as session:
            _query_cols(session, seed=6)  # in-memory record pre-save
            session.save(store)
            assert session.audit_trail.durable
            _query_cols(session, seed=7)
        reopened = store.open("aud-store")
        try:
            assert [r.seed for r in reopened.audit_trail.records()] == \
                [6, 7]
            assert [r.outcome for r in reopened.audit_trail.records()] == \
                ["released", "released"]
        finally:
            reopened.close()


class TestOpsEndpointsLive:
    """The CI endpoint smoke (ISSUE 13): /metrics + /healthz + /statusz
    against a LIVE SessionManager serving real queries."""

    def test_endpoints_against_live_manager(self, tmp_path):
        data = _data()
        manager = serving.SessionManager(
            serving.SessionStore(str(tmp_path / "store")), ops_port=0)
        try:
            session = manager.create("live", data, n_chunks=4)
            session.register_tenant("acme", total_epsilon=10.0,
                                    total_delta=1e-3)
            _query_cols(session, seed=0, tenant="acme")
            _query_cols(session, seed=1)
            url = manager.ops_server.url

            prom = urllib.request.urlopen(url + "/metrics",
                                          timeout=10).read().decode()
            assert "pipelinedp_tpu_query_seconds_bucket" in prom
            assert "pipelinedp_tpu_events_total" in prom

            health = json.loads(urllib.request.urlopen(
                url + "/healthz", timeout=10).read())
            assert health["status"] == "ok"
            assert health["checks"]["sessions_resident"] == 1
            assert health["checks"]["wal_writable"] is True

            status = json.loads(urllib.request.urlopen(
                url + "/statusz", timeout=10).read())
            assert status["kind"] == "manager"
            live = status["sessions"]["live"]
            assert live["residency"] in ("device", "host")
            assert live["queries"] == 2
            acme = live["tenants"]["acme"]
            assert acme["spent_epsilon"] == pytest.approx(1.0)
            assert acme["epsilon_burn_pct"] == pytest.approx(10.0)
            assert status["counters"]["queries"] >= 2

            flightz = json.loads(urllib.request.urlopen(
                url + "/debug/flightz", timeout=10).read())
            assert "query_finish" in [e["kind"]
                                      for e in flightz["events"]]
        finally:
            manager.close()


class TestNoPrivateLeakScan:
    """Runs the serving matrix (success, batch, shed, deadline, refusal)
    with tracing on, then scans EVERY emitted obs record: span attrs,
    span events, metric label values, audit fields — and (PR 13) every
    operational-plane surface: the /statusz, /healthz and
    /debug/flightz payloads, the flight-recorder dump, and the
    slow-query capture bundles. Nothing may be array-shaped, carry a
    forbidden key, or contain a pid/pk sentinel."""

    def _scan_value(self, key, value, where):
        assert key not in metrics_lib.FORBIDDEN_KEYS, \
            f"forbidden key {key!r} in {where}"
        assert value is None or isinstance(
            value, (bool, int, float, str)), \
            f"non-scalar {type(value).__name__} under {key!r} in {where}"
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            assert not (PID_LO <= value < PID_HI), \
                f"pid sentinel {value} leaked via {key!r} in {where}"
        if isinstance(value, str):
            for sentinel in (str(PID_LO), str(PID_LO + 1)):
                assert sentinel not in value, \
                    f"pid sentinel inside string {key!r} in {where}"

    def _scan_json(self, node, where, key="root"):
        """Recursive scan of an operational-plane JSON payload: every
        dict key is checked against the forbidden set, every leaf
        against the sentinel window. One carve-out: the Chrome
        trace-event schema requires a literal ``pid`` key — it must
        hold the OS process id, never anything else."""
        if isinstance(node, dict):
            for k, v in node.items():
                if k == "pid":
                    assert v == os.getpid(), \
                        f"chrome 'pid' is not the process id in {where}"
                    continue
                assert k not in metrics_lib.FORBIDDEN_KEYS, \
                    f"forbidden key {k!r} in {where}"
                self._scan_json(v, where, key=k)
        elif isinstance(node, (list, tuple)):
            for item in node:
                self._scan_json(item, where, key=key)
        else:
            self._scan_value(key if key not in ("root",) else "leaf",
                             node, where)

    def test_full_matrix_emits_no_private_data(self, tracer, tmp_path,
                                               monkeypatch):
        cap_dir = str(tmp_path / "cap")
        monkeypatch.setenv(flight_lib.CAPTURE_DIR_ENV, cap_dir)
        monkeypatch.setenv(flight_lib.SLOW_QUERY_ENV, "0.000001")
        registry = metrics_lib.default_registry()
        data = _data()
        with serving.DatasetSession(data, n_chunks=4,
                                    name="leakscan") as session:
            session.register_tenant("acme", total_epsilon=50.0, total_delta=1e-3)
            _query_cols(session, seed=0, tenant="acme")
            _query_cols(session, seed=0)  # bound-cache hit
            session.query_batch([
                serving.QueryConfig(
                    metrics=[pdp.Metrics.COUNT], epsilon=1.0,
                    delta=1e-6, max_partitions_contributed=8,
                    max_contributions_per_partition=4, seed=50)
            ], secure_host_noise=False)
            with pytest.raises(runtime.DoubleReleaseError):
                _query_cols(session, seed=0, tenant="acme")
            injector = runtime.FaultInjector(
                [runtime.FaultSpec("hang", at_slab=0, hang_s=10.0)])
            with pytest.raises(serving.QueryDeadlineError):
                _query_cols(session, seed=9, deadline_s=0.8,
                            fault_injector=injector)

            # -- scan spans (attrs + events) -----------------------------
            spans = tracer.spans()
            assert spans, "matrix produced no spans"
            for span in spans:
                for k, v in span.attrs.items():
                    self._scan_value(k, v, f"span {span.name}")
                for ev_name, _, ev_attrs in span.events:
                    for k, v in ev_attrs.items():
                        self._scan_value(k, v,
                                         f"event {ev_name} in {span.name}")

            # -- scan the metric families (names, labels) ----------------
            snap = registry.snapshot()
            for fam_name, fam in snap["families"].items():
                for label_str in fam["series"]:
                    for pair in filter(None, label_str.split(",")):
                        k, _, v = pair.partition("=")
                        self._scan_value(k, v, f"metric {fam_name}")

            # -- scan every audit field ----------------------------------
            for rec in session.audit_trail.records():
                for k, v in rec.to_payload().items():
                    if k == "mechanisms":
                        assert all(isinstance(m, str) for m in v)
                        continue
                    self._scan_value(k, v, f"audit record {rec.seq}")

            # -- scan the operational plane (PR 13 satellite): the live
            # endpoints, a flight-recorder dump, and every slow-query
            # capture the matrix produced -------------------------------
            with serving.serve_ops(session, port=0) as srv:
                for endpoint in ("/statusz", "/healthz",
                                 "/debug/flightz"):
                    body = urllib.request.urlopen(
                        srv.url + endpoint, timeout=10).read()
                    self._scan_json(json.loads(body),
                                    f"endpoint {endpoint}")
            dump_path = flight_lib.recorder().dump(
                str(tmp_path / "flight.json"), reason="leak-scan")
            self._scan_json(flight_lib.read_dump(dump_path),
                            "flight dump")
            captures = os.listdir(cap_dir)
            assert captures, "the matrix produced no slow-query capture"
            for name in captures:
                with open(os.path.join(cap_dir, name)) as f:
                    self._scan_json(json.load(f), f"capture {name}")
