"""Tests for dp_computations (mirrors reference tests/dp_computations_test.py
coverage of sensitivity math, mechanisms, DP mean/variance, thresholding)."""

import math

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import budget_accounting as ba
from pipelinedp_tpu import dp_computations as dp
from pipelinedp_tpu import noise_core
from pipelinedp_tpu.aggregate_params import MechanismType, NormKind


class TestSensitivityMath:

    def test_l1_l2(self):
        assert dp.compute_l1_sensitivity(3, 2.0) == 6.0
        assert dp.compute_l2_sensitivity(4, 2.0) == pytest.approx(4.0)

    def test_middle_and_squares(self):
        assert dp.compute_middle(-2, 4) == 1
        assert dp.compute_squares_interval(-2, 4) == (0, 16)
        assert dp.compute_squares_interval(1, 3) == (1, 9)
        # Convention: returns (min^2, max^2) unordered for all-negative
        # ranges; downstream only uses the midpoint and |mid - lo|, which are
        # symmetric.
        assert dp.compute_squares_interval(-3, -1) == (9, 1)

    def test_sigma_satisfies_analytic_condition(self):
        eps, delta, s = 1.0, 1e-6, 2.0
        sigma = dp.compute_sigma(eps, delta, s)
        assert noise_core.gaussian_delta(sigma, eps, s) <= delta + 1e-15
        # And it is nearly tight.
        assert noise_core.gaussian_delta(sigma * 0.99, eps, s) > delta

    def test_sigma_beats_classical(self):
        eps, delta, s = 1.0, 1e-6, 1.0
        sigma = dp.compute_sigma(eps, delta, s)
        classical = math.sqrt(2 * math.log(1.25 / delta)) * s / eps
        assert sigma < classical


class TestSensitivities:

    def test_derives_l1_l2(self):
        s = dp.Sensitivities(l0=4, linf=2.0)
        assert s.l1 == 8.0
        assert s.l2 == pytest.approx(4.0)

    def test_inconsistent_raises(self):
        with pytest.raises(ValueError, match="L1"):
            dp.Sensitivities(l0=4, linf=2.0, l1=5.0)

    def test_only_l0_raises(self):
        with pytest.raises(ValueError, match="both"):
            dp.Sensitivities(l0=4)

    def test_non_positive_raises(self):
        with pytest.raises(ValueError):
            dp.Sensitivities(l0=0, linf=1)


class TestMechanisms:

    def test_laplace_properties(self):
        m = dp.LaplaceMechanism.create_from_epsilon(2.0, 3.0)
        assert m.noise_parameter == pytest.approx(1.5)
        assert m.std == pytest.approx(1.5 * math.sqrt(2))
        assert m.sensitivity == 3.0
        assert m.noise_kind == pdp.NoiseKind.LAPLACE
        assert "Laplace" in m.describe()

    def test_laplace_from_std(self):
        # normalized_stddev is the std divided by l1_sensitivity.
        m = dp.LaplaceMechanism.create_from_std_deviation(2.0, 4.0)
        assert m.std == pytest.approx(8.0)

    def test_gaussian_properties(self):
        m = dp.GaussianMechanism.create_from_epsilon_delta(1.0, 1e-6, 2.0)
        assert m.std == pytest.approx(dp.compute_sigma(1.0, 1e-6, 2.0))
        assert m.noise_kind == pdp.NoiseKind.GAUSSIAN
        assert "Gaussian" in m.describe()

    def test_gaussian_from_std(self):
        m = dp.GaussianMechanism.create_from_std_deviation(3.0, 2.0)
        assert m.std == pytest.approx(6.0)

    def test_laplace_noise_distribution(self):
        noise_core.seed_fallback_rng(0)
        m = dp.LaplaceMechanism.create_from_epsilon(1.0, 1.0)
        samples = np.array([m.add_noise(100.0) for _ in range(4000)])
        assert samples.mean() == pytest.approx(100.0, abs=0.15)
        assert samples.std() == pytest.approx(math.sqrt(2), rel=0.1)

    def test_gaussian_noise_distribution(self):
        noise_core.seed_fallback_rng(0)
        m = dp.GaussianMechanism.create_from_std_deviation(2.0, 1.0)
        samples = m.add_noise_vectorized(np.full(4000, 50.0))
        assert samples.mean() == pytest.approx(50.0, abs=0.2)
        assert samples.std() == pytest.approx(2.0, rel=0.1)

    def test_vectorized_matches_scalar_distribution(self):
        noise_core.seed_fallback_rng(1)
        m = dp.LaplaceMechanism.create_from_epsilon(1.0, 1.0)
        batch = m.add_noise_vectorized(np.zeros(4000))
        assert batch.std() == pytest.approx(math.sqrt(2), rel=0.1)

    def test_noise_is_snapped_to_granularity(self):
        m = dp.LaplaceMechanism.create_from_epsilon(1.0, 1.0)
        g = noise_core.laplace_granularity(1.0)
        value = m.add_noise(0.0)
        assert value / g == pytest.approx(round(value / g), abs=1e-6)

    def test_create_additive_mechanism_from_spec(self):
        spec = ba.MechanismSpec(MechanismType.LAPLACE)
        spec.set_eps_delta(1.0, 0.0)
        m = dp.create_additive_mechanism(spec, dp.Sensitivities(l0=2, linf=1))
        assert isinstance(m, dp.LaplaceMechanism)
        assert m.sensitivity == 2.0

        spec2 = ba.MechanismSpec(MechanismType.GAUSSIAN)
        spec2.set_noise_standard_deviation(3.0)
        m2 = dp.create_additive_mechanism(spec2,
                                          dp.Sensitivities(l0=4, linf=1))
        assert isinstance(m2, dp.GaussianMechanism)
        assert m2.std == pytest.approx(6.0)  # normalized_std * l2


class TestMeanMechanism:

    def test_no_noise_mean(self):
        # Huge eps => negligible noise: mean of values in [0, 10].
        count_spec = ba.MechanismSpec(MechanismType.LAPLACE)
        count_spec.set_eps_delta(1e6, 0.0)
        sum_spec = ba.MechanismSpec(MechanismType.LAPLACE)
        sum_spec.set_eps_delta(1e6, 0.0)
        mech = dp.create_mean_mechanism(5.0, count_spec,
                                        dp.Sensitivities(l0=1, linf=1),
                                        sum_spec,
                                        dp.Sensitivities(l0=1, linf=5))
        values = [1.0, 2.0, 6.0]
        normalized_sum = sum(v - 5.0 for v in values)
        dp_count, dp_sum, dp_mean = mech.compute_mean(len(values),
                                                      normalized_sum)
        assert dp_count == pytest.approx(3, abs=1e-3)
        assert dp_mean == pytest.approx(3.0, abs=1e-3)
        assert dp_sum == pytest.approx(9.0, abs=1e-2)


class TestVariance:

    def test_no_noise_variance(self):
        params = dp.ScalarNoiseParams(
            eps=1e8, delta=0.0,
            min_value=0.0, max_value=10.0,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            noise_kind=pdp.NoiseKind.LAPLACE)
        values = np.array([1.0, 3.0, 5.0, 7.0])
        normalized = values - 5.0
        dp_count, dp_sum, dp_mean, dp_var = dp.compute_dp_var(
            len(values), normalized.sum(), (normalized**2).sum(), params)
        assert dp_count == pytest.approx(4, abs=1e-2)
        assert dp_mean == pytest.approx(4.0, abs=1e-2)
        assert dp_var == pytest.approx(values.var(), abs=0.1)


class TestVectorNoise:

    def test_clip_linf(self):
        v = dp._clip_vector(np.array([-5.0, 0.5, 3.0]), 1.0, NormKind.Linf)
        np.testing.assert_allclose(v, [-1.0, 0.5, 1.0])

    def test_clip_l2(self):
        v = dp._clip_vector(np.array([3.0, 4.0]), 1.0, NormKind.L2)
        np.testing.assert_allclose(v, [0.6, 0.8])

    def test_clip_l1(self):
        v = dp._clip_vector(np.array([2.0, 2.0]), 2.0, NormKind.L1)
        np.testing.assert_allclose(v, [1.0, 1.0])

    def test_add_noise_vector(self):
        noise_core.seed_fallback_rng(0)
        params = dp.AdditiveVectorNoiseParams(
            eps_per_coordinate=1e6, delta_per_coordinate=0.0, max_norm=10.0,
            l0_sensitivity=1, linf_sensitivity=1.0,
            norm_kind=NormKind.Linf, noise_kind=pdp.NoiseKind.LAPLACE)
        out = dp.add_noise_vector(np.array([1.0, 2.0]), params)
        np.testing.assert_allclose(out, [1.0, 2.0], atol=1e-3)


class TestBudgetSplit:

    def test_equally_split_budget(self):
        budgets = dp.equally_split_budget(1.0, 3e-6, 3)
        assert len(budgets) == 3
        assert sum(b[0] for b in budgets) == pytest.approx(1.0)
        assert sum(b[1] for b in budgets) == pytest.approx(3e-6)

    def test_invalid(self):
        with pytest.raises(ValueError):
            dp.equally_split_budget(1.0, 0.0, 0)


class TestExponentialMechanism:

    class Scoring(dp.ExponentialMechanism.ScoringFunction):

        def score(self, k):
            return float(k)

        @property
        def global_sensitivity(self):
            return 1.0

        @property
        def is_monotonic(self):
            return True

    def test_probabilities(self):
        mech = dp.ExponentialMechanism(self.Scoring())
        probs = mech._calculate_probabilities(1.0, [0, 1, 2])
        expected = np.exp([0.0, 1.0, 2.0])
        expected /= expected.sum()
        np.testing.assert_allclose(probs, expected, rtol=1e-12)

    def test_prefers_high_scores(self):
        mech = dp.ExponentialMechanism(self.Scoring())
        picks = [mech.apply(5.0, [0, 1, 10]) for _ in range(50)]
        assert picks.count(10) > 40


class TestThresholdingMechanism:

    def test_create_and_describe(self):
        spec = ba.MechanismSpec(MechanismType.LAPLACE_THRESHOLDING)
        spec.set_eps_delta(1.0, 1e-6)
        mech = dp.create_thresholding_mechanism(
            spec, dp.Sensitivities(l0=2, linf=1), pre_threshold=None)
        assert mech.threshold() > 1
        assert "Laplace Thresholding" in mech.describe()

    def test_keeps_large_drops_small(self):
        spec = ba.MechanismSpec(MechanismType.GAUSSIAN_THRESHOLDING)
        spec.set_eps_delta(1.0, 1e-6)
        mech = dp.create_thresholding_mechanism(
            spec, dp.Sensitivities(l0=1, linf=1), pre_threshold=None)
        big = int(mech.threshold()) + 100
        assert mech.noised_value_if_should_keep(big) is not None
        assert mech.noised_value_if_should_keep(1) is None


class TestNoiseStdHelpers:

    def test_count_noise_std_laplace(self):
        params = dp.ScalarNoiseParams(
            eps=1.0, delta=0.0, min_value=None, max_value=None,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=2, max_contributions_per_partition=3,
            noise_kind=pdp.NoiseKind.LAPLACE)
        # b = l1/eps = 6, std = 6*sqrt(2)
        assert dp.compute_dp_count_noise_std(params) == pytest.approx(
            6 * math.sqrt(2))

    def test_sum_noise_std_gaussian(self):
        params = dp.ScalarNoiseParams(
            eps=1.0, delta=1e-6, min_value=None, max_value=None,
            min_sum_per_partition=-2.0, max_sum_per_partition=4.0,
            max_partitions_contributed=4, max_contributions_per_partition=None,
            noise_kind=pdp.NoiseKind.GAUSSIAN)
        expected = dp.compute_sigma(1.0, 1e-6, 4.0 * 2)  # l2 = sqrt(4)*4
        assert dp.compute_dp_sum_noise_std(params) == pytest.approx(expected)


class TestGaussianCalibrationLargeEps:
    """gaussian_delta must stay finite for arbitrarily large epsilon
    (e^eps Phi(-a-b) evaluated in log space) — huge-eps Gaussian configs
    are the standard no-noise testing pattern."""

    def test_delta_finite_at_large_eps(self):
        # Finite for ALL inputs: log_term <= 0 by AM-GM, so the exp term
        # is <= 1 (slightly negative deltas are legitimate — the
        # expression under-shoots zero when sigma over-satisfies eps).
        for eps in (10.0, 700.0, 1e4, 1e8):
            for sigma in (1e-6, 1.0, 1e6):
                d = noise_core.gaussian_delta(sigma, eps, 1.0)
                assert math.isfinite(d) and d <= 1.0

    def test_sigma_search_at_large_eps(self):
        sigma = noise_core.analytic_gaussian_sigma(1e8, 1e-9, 1.0)
        assert 0 < sigma < 1e-3
        # Small-eps calibration unchanged by the log-space rewrite
        # (Balle-Wang reference value).
        ref = noise_core.analytic_gaussian_sigma(1.0, 1e-6, 1.0)
        assert ref == pytest.approx(4.2247, abs=1e-3)

    def test_mean_gaussian_huge_eps_end_to_end(self):
        import pipelinedp_tpu as pdp
        rows = [(u, 0, float(u % 4)) for u in range(40)]
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        acc = pdp.NaiveBudgetAccountant(1e8, 1 - 1e-9)
        engine = pdp.JaxDPEngine(acc, secure_host_noise=False)
        res = engine.aggregate(
            rows,
            pdp.AggregateParams(metrics=[pdp.Metrics.MEAN],
                                noise_kind=pdp.NoiseKind.GAUSSIAN,
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                min_value=0.0,
                                max_value=3.0),
            ext, public_partitions=[0])
        acc.compute_budgets()
        out = dict(res)
        assert out[0].mean == pytest.approx(1.5, abs=0.05)
