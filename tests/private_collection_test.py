"""PrivateCollection wrapper tests.

Mirrors the reference's private_spark tests' intent (private_spark_test.py):
budget-enforced fluent aggregations over a wrapped collection, privacy-id
preserving transforms, select_partitions.
"""

import collections

import numpy as np
import pytest

import pipelinedp_tpu as pdp

Visit = collections.namedtuple("Visit", ["user", "day", "spent"])


def _visits():
    rows = []
    for user in range(30):
        for day in (1, 2):
            rows.append(Visit(user, day, 10.0))
    return rows


HUGE_EPS, HUGE_DELTA = 600.0, 1e-4


class TestPrivateCollection:

    def test_count(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        result = private.count(
            pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                            max_partitions_contributed=2,
                            max_contributions_per_partition=1,
                            partition_extractor=lambda v: v.day))
        accountant.compute_budgets()
        out = dict(result)
        assert out[1] == pytest.approx(30, abs=0.5)
        assert out[2] == pytest.approx(30, abs=0.5)

    def test_sum_and_mean_share_budget(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        s = private.sum(
            pdp.SumParams(max_partitions_contributed=2,
                          max_contributions_per_partition=1,
                          min_value=0.0,
                          max_value=20.0,
                          partition_extractor=lambda v: v.day,
                          value_extractor=lambda v: v.spent))
        m = private.mean(
            pdp.MeanParams(max_partitions_contributed=2,
                           max_contributions_per_partition=1,
                           min_value=0.0,
                           max_value=20.0,
                           partition_extractor=lambda v: v.day,
                           value_extractor=lambda v: v.spent))
        accountant.compute_budgets()
        assert dict(s)[1] == pytest.approx(300.0, rel=0.01)
        assert dict(m)[2] == pytest.approx(10.0, rel=0.01)

    def test_privacy_id_count(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        result = private.privacy_id_count(
            pdp.PrivacyIdCountParams(noise_kind=pdp.NoiseKind.GAUSSIAN,
                                     max_partitions_contributed=2,
                                     partition_extractor=lambda v: v.day))
        accountant.compute_budgets()
        assert dict(result)[1] == pytest.approx(30, abs=0.5)

    def test_variance(self):
        rng = np.random.default_rng(0)
        rows = [Visit(u, 1, float(rng.uniform(0, 10))) for u in range(400)]
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(rows, accountant, lambda v: v.user)
        result = private.variance(
            pdp.VarianceParams(max_partitions_contributed=1,
                               max_contributions_per_partition=1,
                               min_value=0.0,
                               max_value=10.0,
                               partition_extractor=lambda v: v.day,
                               value_extractor=lambda v: v.spent))
        accountant.compute_budgets()
        expected = float(np.var([v.spent for v in rows]))
        assert dict(result)[1] == pytest.approx(expected, abs=1.0)

    def test_map_preserves_privacy_ids(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        doubled = private.map(lambda v: Visit(v.user, v.day, v.spent * 2))
        s = doubled.sum(
            pdp.SumParams(max_partitions_contributed=2,
                          max_contributions_per_partition=1,
                          min_value=0.0,
                          max_value=40.0,
                          partition_extractor=lambda v: v.day,
                          value_extractor=lambda v: v.spent))
        accountant.compute_budgets()
        assert dict(s)[1] == pytest.approx(600.0, rel=0.01)

    def test_flat_map(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        exploded = private.flat_map(lambda v: [v, v])
        result = exploded.count(
            pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                            max_partitions_contributed=2,
                            max_contributions_per_partition=2,
                            partition_extractor=lambda v: v.day))
        accountant.compute_budgets()
        assert dict(result)[1] == pytest.approx(60, abs=0.5)

    def test_select_partitions(self):
        accountant = pdp.NaiveBudgetAccountant(5.0, 1e-5)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        keys = private.select_partitions(
            pdp.SelectPartitionsParams(max_partitions_contributed=2),
            partition_extractor=lambda v: v.day)
        accountant.compute_budgets()
        assert sorted(keys) == [1, 2]

    def test_budget_is_shared_across_aggregations(self):
        # Two aggregations on one accountant: each gets half the budget,
        # visible through the explain-report epsilons.
        accountant = pdp.NaiveBudgetAccountant(2.0, 1e-6)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        params = pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=2,
                                 max_contributions_per_partition=1,
                                 partition_extractor=lambda v: v.day)
        r1 = private.count(params)
        r2 = private.count(params)
        accountant.compute_budgets()
        list(r1), list(r2)
        specs = [s for s in accountant._mechanisms]
        total_eps = sum(s.mechanism_spec.eps for s in specs)
        assert total_eps == pytest.approx(2.0)

    def test_public_partitions_on_params(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        result = private.count(
            pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                            max_partitions_contributed=2,
                            max_contributions_per_partition=1,
                            partition_extractor=lambda v: v.day,
                            public_partitions=[1, 2, 3]))
        accountant.compute_budgets()
        out = dict(result)
        assert sorted(out) == [1, 2, 3]
        assert out[3] == pytest.approx(0, abs=0.5)


class _SquareSumCombiner(pdp.CustomCombiner):
    """DP sum of squared values with its own Laplace mechanism (mirrors the
    reference's PrivateCombineFn pattern, private_beam.py:491-649)."""

    def __init__(self, max_value):
        self._max_value = max_value

    def request_budget(self, budget_accountant):
        self._spec = budget_accountant.request_budget(
            pdp.MechanismType.LAPLACE)

    def create_accumulator(self, values):
        return float(sum(v * v for v in values))

    def merge_accumulators(self, a, b):
        return a + b

    def compute_metrics(self, acc):
        from pipelinedp_tpu import dp_computations
        p = self._aggregate_params
        sens = dp_computations.Sensitivities(
            l0=p.max_partitions_contributed,
            linf=p.max_contributions_per_partition * self._max_value**2)
        mech = dp_computations.create_additive_mechanism(self._spec, sens)
        return {"square_sum": mech.add_noise(acc)}

    def explain_computation(self):
        return "Custom DP sum of squares"


class TestPrivateCollectionCustomCombiners:
    """PrivateCollection.aggregate with custom combiners (VERDICT-r4 item
    6): the engine-level custom path through the high-level wrapper."""

    def _params(self):
        return pdp.AggregateParams(
            metrics=None,
            custom_combiners=[_SquareSumCombiner(max_value=10.0)],
            max_partitions_contributed=2,
            max_contributions_per_partition=2)

    def test_custom_combiner_aggregation(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        result = private.aggregate(self._params(),
                                   partition_extractor=lambda v: v.day,
                                   value_extractor=lambda v: v.spent,
                                   public_partitions=[1, 2])
        accountant.compute_budgets()
        res = dict(result)
        # 30 users x 1 visit/day at spent=10 -> square sum 3000 per day.
        assert set(res) == {1, 2}
        for day in (1, 2):
            assert res[day][0]["square_sum"] == pytest.approx(3000,
                                                              rel=0.05)

    def test_standard_metrics_through_aggregate(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=2,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=20.0)
        result = private.aggregate(params,
                                   partition_extractor=lambda v: v.day,
                                   value_extractor=lambda v: v.spent,
                                   public_partitions=[1, 2])
        accountant.compute_budgets()
        res = dict(result)
        assert res[1].count == pytest.approx(30, abs=2)
        assert res[1].sum == pytest.approx(300, rel=0.1)

    def test_budget_shared_with_other_aggregations(self):
        accountant = pdp.NaiveBudgetAccountant(HUGE_EPS, HUGE_DELTA)
        private = pdp.make_private(_visits(), accountant, lambda v: v.user)
        count = private.count(
            pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                            partition_extractor=lambda v: v.day,
                            max_partitions_contributed=2,
                            max_contributions_per_partition=1,
                            public_partitions=[1, 2]))
        custom = private.aggregate(self._params(),
                                   partition_extractor=lambda v: v.day,
                                   value_extractor=lambda v: v.spent,
                                   public_partitions=[1, 2])
        accountant.compute_budgets()
        assert dict(count)[1] == pytest.approx(30, abs=3)
        assert dict(custom)[1][0]["square_sum"] == pytest.approx(3000,
                                                                 rel=0.1)
