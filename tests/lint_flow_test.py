"""Tests for dpflow (pipelinedp_tpu/lint/flow): the symbol table /
call-graph layer, the digest cache, the dpverify effect-summary layer
(effect traces, lock graph), and the seeded-hazard contract — every
known hazard class (journal commit reordered, donated operand reuse,
unlocked pool write, non-atomic durable write, WAL fold/record
inversion, reversed lock pair, nondeterministic release epilogue) must
be caught when deliberately introduced into production-shaped code.
"""

import ast
import os
import shutil
import textwrap
import time

import pytest

from pipelinedp_tpu.lint import engine as lint_engine
from pipelinedp_tpu.lint import lint_paths
from pipelinedp_tpu.lint.flow import (
    FlowCache,
    ProjectFlow,
    extract_module,
    source_digest,
)
from pipelinedp_tpu.lint import astutils
from pipelinedp_tpu.lint.flow import summary as flow_summary

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _summaries(sources):
    """{relpath: ModuleSummary} from {dotted module: source} inputs."""
    out = {}
    for module, src in sources.items():
        tree = ast.parse(src)
        out[module.replace(".", "/") + ".py"] = extract_module(
            module, tree, astutils.build_aliases(tree))
    return out


class TestCallGraph:

    def test_cross_module_resolution_and_reaching(self):
        flow = ProjectFlow(_summaries({
            "pkg.a": ("from pkg import b\n"
                      "def f():\n"
                      "    return b.g()\n"),
            "pkg.b": ("import numpy as np\n"
                      "def g():\n"
                      "    return np.random.laplace()\n"),
        }))
        assert flow.resolve("pkg.b.g", "pkg.a") == "pkg.b.g"
        assert flow.edges("pkg.a.f") == ("pkg.b.g",)
        reaching = flow.reaching(r"^numpy\.random\.")
        assert reaching == {"pkg.a.f", "pkg.b.g"}

    def test_import_cycle_resolves(self):
        # a imports b, b imports a: resolution runs over the built index,
        # so the cycle is a non-issue and reachability crosses it.
        flow = ProjectFlow(_summaries({
            "pkg.a": ("from pkg import b\n"
                      "def f():\n"
                      "    return b.g()\n"
                      "def leaf():\n"
                      "    return 1\n"),
            "pkg.b": ("from pkg import a\n"
                      "def g():\n"
                      "    return a.leaf()\n"),
        }))
        assert flow.edges("pkg.a.f") == ("pkg.b.g",)
        assert flow.edges("pkg.b.g") == ("pkg.a.leaf",)
        assert "pkg.a.f" in flow.reaching(r"\.leaf$")

    def test_reexport_through_init(self):
        flow = ProjectFlow(_summaries({
            "pkg": "from pkg.impl import thing\n",  # pkg/__init__.py
            "pkg.impl": "def thing():\n    return 1\n",
            "pkg.user": ("import pkg\n"
                         "def call():\n"
                         "    return pkg.thing()\n"),
        }))
        assert flow.resolve("pkg.thing", "pkg.user") == "pkg.impl.thing"
        assert flow.edges("pkg.user.call") == ("pkg.impl.thing",)

    def test_assignment_alias_reexport(self):
        flow = ProjectFlow(_summaries({
            "pkg.impl": "def thing():\n    return 1\n",
            "pkg.compat": ("from pkg import impl\n"
                           "legacy_thing = impl.thing\n"),
            "pkg.user": ("from pkg import compat\n"
                         "def call():\n"
                         "    return compat.legacy_thing()\n"),
        }))
        assert flow.edges("pkg.user.call") == ("pkg.impl.thing",)

    def test_self_method_resolution_through_base(self):
        flow = ProjectFlow(_summaries({
            "pkg.base": ("class Base:\n"
                         "    def helper(self):\n"
                         "        return 1\n"),
            "pkg.eng": ("from pkg.base import Base\n"
                        "class Engine(Base):\n"
                        "    def run(self):\n"
                        "        return self.helper()\n"),
        }))
        assert flow.edges("pkg.eng.Engine.run") == \
            ("pkg.base.Base.helper",)

    def test_method_resolution_through_jax_dp_engine(self):
        """The real tree: `self._commit_release(...)` inside JaxDPEngine
        methods resolves to the method on the class."""
        path = os.path.join(REPO_ROOT, "pipelinedp_tpu", "jax_engine.py")
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        summary = extract_module("pipelinedp_tpu.jax_engine", tree,
                                 astutils.build_aliases(tree))
        flow = ProjectFlow({"pipelinedp_tpu/jax_engine.py": summary})
        resolved = flow.resolve("self:JaxDPEngine._commit_release",
                                "pipelinedp_tpu.jax_engine")
        assert resolved == \
            "pipelinedp_tpu.jax_engine.JaxDPEngine._commit_release"
        # And the engine's aggregate entry points actually carry that
        # edge (the DPL009 anchor).
        committers = [q for q in flow.functions
                      if resolved in flow.edges(q)]
        assert committers, "no JaxDPEngine method calls _commit_release"

    def test_nested_local_function_resolution(self):
        flow = ProjectFlow(_summaries({
            "pkg.m": ("def outer():\n"
                      "    def inner():\n"
                      "        return 1\n"
                      "    return inner()\n"),
        }))
        assert flow.edges("pkg.m.outer") == \
            ("pkg.m.outer.<locals>.inner",)


class TestFlowCache:

    SRC = "def f():\n    return 1\n"

    def test_round_trip_hit(self, tmp_path):
        cache_path = str(tmp_path / "cache.json")
        tree = ast.parse(self.SRC)
        summary = extract_module("m", tree, {})
        digest = source_digest(self.SRC)

        cache = FlowCache(cache_path)
        assert cache.get("m.py", digest) is None  # cold: miss
        cache.put("m.py", digest, summary)
        cache.save()

        warm = FlowCache(cache_path)
        loaded = warm.get("m.py", digest)
        assert loaded is not None and warm.hits == 1
        assert loaded.functions["f"].line == summary.functions["f"].line

    def test_digest_mismatch_is_miss(self, tmp_path):
        cache_path = str(tmp_path / "cache.json")
        cache = FlowCache(cache_path)
        cache.put("m.py", source_digest(self.SRC),
                  extract_module("m", ast.parse(self.SRC), {}))
        cache.save()
        warm = FlowCache(cache_path)
        assert warm.get("m.py", source_digest(self.SRC + "\n# edit")) \
            is None

    def test_corrupt_cache_ignored(self, tmp_path):
        cache_path = tmp_path / "cache.json"
        cache_path.write_text("{not json")
        cache = FlowCache(str(cache_path))
        assert cache.get("m.py", "x") is None

    def test_lint_paths_warm_run_hits(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text(self.SRC)
        cache_path = str(tmp_path / "flow.json")
        cold = lint_paths(["mod.py"], root=str(tmp_path),
                          flow_cache_path=cache_path)
        assert cold.flow_cache_misses == 1
        warm = lint_paths(["mod.py"], root=str(tmp_path),
                          flow_cache_path=cache_path)
        assert warm.flow_cache_hits == 1 and warm.flow_cache_misses == 0

    def test_summary_version_bump_cold_invalidates(self, tmp_path,
                                                   monkeypatch):
        """Bumping SUMMARY_VERSION (e.g. when a new effect kind lands)
        must turn every cached entry into a miss — stale summaries with
        the old effect vocabulary would silently blind the new rules."""
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text(self.SRC)
        cache_path = str(tmp_path / "flow.json")
        lint_paths(["mod.py"], root=str(tmp_path),
                   flow_cache_path=cache_path)
        warm = lint_paths(["mod.py"], root=str(tmp_path),
                          flow_cache_path=cache_path)
        assert warm.flow_cache_hits == 1
        monkeypatch.setattr(flow_summary, "SUMMARY_VERSION",
                            flow_summary.SUMMARY_VERSION + 1)
        bumped = lint_paths(["mod.py"], root=str(tmp_path),
                            flow_cache_path=cache_path)
        assert bumped.flow_cache_hits == 0
        assert bumped.flow_cache_misses == 1
        assert bumped.parse_errors == []


def _extract(src, module="pkg.m"):
    tree = ast.parse(src)
    return extract_module(module, tree, astutils.build_aliases(tree))


class TestEffectTraces:
    """Pin the dpverify effect-summary layer: the ordered per-function
    durable/concurrency effect traces the DPL012-DPL015 rules read."""

    def test_atomic_publish_trace_in_line_order(self):
        summary = _extract(
            "import json\n"
            "import os\n"
            "import tempfile\n"
            "def publish(path, payload):\n"
            "    fd, tmp = tempfile.mkstemp(dir='.')\n"
            "    with os.fdopen(fd, 'w') as fh:\n"
            "        json.dump(payload, fh)\n"
            "        fh.flush()\n"
            "        os.fsync(fh.fileno())\n"
            "    os.replace(tmp, path)\n")
        kinds = [e.kind for e in summary.functions["publish"].effects]
        assert kinds == [flow_summary.EFFECT_TMP_CREATE,
                         flow_summary.EFFECT_RAW_WRITE,
                         flow_summary.EFFECT_FSYNC,
                         flow_summary.EFFECT_RENAME]

    def test_write_in_with_context_expression_is_seen(self):
        # Regression: the walker must descend into the With item's
        # context expression, not just the body — `with open(p, 'w')`
        # is where nearly every raw write in the tree lives.
        summary = _extract(
            "import json\n"
            "def raw(path, payload):\n"
            "    with open(path, 'w') as fh:\n"
            "        json.dump(payload, fh)\n")
        kinds = [e.kind for e in summary.functions["raw"].effects]
        assert kinds == [flow_summary.EFFECT_RAW_WRITE]

    def test_eager_jnp_exempt_under_jit(self):
        summary = _extract(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def eager(x):\n"
            "    return jnp.maximum(x, 0.0)\n"
            "@jax.jit\n"
            "def compiled(x):\n"
            "    return jnp.maximum(x, 0.0)\n")
        eager = [e.kind for e in summary.functions["eager"].effects]
        compiled = [e.kind
                    for e in summary.functions["compiled"].effects]
        assert flow_summary.EFFECT_EAGER_JNP in eager
        assert flow_summary.EFFECT_EAGER_JNP not in compiled

    def test_wal_append_transaction_trace(self):
        summary = _extract(
            "class S:\n"
            "    def append(self, rec):\n"
            "        with self._append_lock:\n"
            "            self._rows.append(rec)\n"
            "            self._wal.append(rec)\n")
        effects = summary.functions["S.append"].effects
        by_kind = {}
        for e in effects:  # keep the FIRST effect of each kind
            by_kind.setdefault(e.kind, e)
        lock = by_kind[flow_summary.EFFECT_LOCK_ACQUIRE]
        wal = by_kind[flow_summary.EFFECT_WAL_APPEND]
        mutation = by_kind[flow_summary.EFFECT_STATE_MUTATION]
        assert lock.detail == "S:_append_lock"
        assert lock.end >= wal.line  # with-block span covers the append
        assert mutation.line < wal.line  # the fold precedes the record

    def test_lock_canonicalizes_through_base_class(self):
        flow = ProjectFlow(_summaries({
            "pkg.base": ("import threading\n"
                         "class Base:\n"
                         "    def __init__(self):\n"
                         "        self._lock = threading.Lock()\n"),
            "pkg.sub": ("from pkg.base import Base\n"
                        "class Sub(Base):\n"
                        "    def grab(self):\n"
                        "        with self._lock:\n"
                        "            return 1\n"),
        }))
        assert flow.canonical_lock("Sub:_lock", "pkg.sub") == \
            "pkg.base.Base._lock"
        assert "pkg.base.Base._lock" in flow.lock_sites()

    def test_lock_cycle_detected_and_consistent_order_is_clean(self):
        reversed_pair = _summaries({
            "pkg.locks": ("import threading\n"
                          "a_lock = threading.Lock()\n"
                          "b_lock = threading.Lock()\n"
                          "def ab():\n"
                          "    with a_lock:\n"
                          "        with b_lock:\n"
                          "            return 1\n"
                          "def ba():\n"
                          "    with b_lock:\n"
                          "        with a_lock:\n"
                          "            return 1\n"),
        })
        cycles = ProjectFlow(reversed_pair).lock_cycles()
        assert cycles and set(cycles[0]) == {"pkg.locks.a_lock",
                                             "pkg.locks.b_lock"}
        consistent = _summaries({
            "pkg.locks": ("import threading\n"
                          "a_lock = threading.Lock()\n"
                          "b_lock = threading.Lock()\n"
                          "def ab():\n"
                          "    with a_lock:\n"
                          "        with b_lock:\n"
                          "            return 1\n"
                          "def ab2():\n"
                          "    with a_lock:\n"
                          "        with b_lock:\n"
                          "            return 2\n"),
        })
        assert ProjectFlow(consistent).lock_cycles() == []

    def test_held_effects_crosses_calls(self):
        flow = ProjectFlow(_summaries({
            "pkg.io": ("import os\n"
                       "import threading\n"
                       "io_lock = threading.Lock()\n"
                       "def flush(fd):\n"
                       "    os.fsync(fd)\n"
                       "def locked_flush(fd):\n"
                       "    with io_lock:\n"
                       "        flush(fd)\n"),
        }))
        held = flow.held_effects(
            "pkg.io.locked_flush",
            frozenset({flow_summary.EFFECT_FSYNC}))
        assert [(acq.detail, kind) for acq, kind in held] == \
            [("io_lock", flow_summary.EFFECT_FSYNC)]


class TestChangedOnlyFocus:
    """--changed-only narrows *reporting*, not analysis: a hazard whose
    witness lives outside the changed file must still be reported when
    the changed file participates in it (the PR-16 bugfix satellite)."""

    A_SRC = (
        "from pkg import b\n"
        "class Engine:\n"
        "    def _commit_release(self, counter):\n"
        "        self._journal.commit(('t', counter))\n"
        "    def aggregate(self, accs, spec, counter):\n"
        "        cols = b.epilogue(accs, spec)\n"
        "        self._commit_release(counter)\n"
        "        return cols\n")
    B_SRC = (
        "from pipelinedp_tpu import noise_core\n"
        "def epilogue(accs, spec):\n"
        "    return noise_core.add_noise_array(\n"
        "        accs, True, 1.0 / spec.eps)\n")
    # Same hazard shape, but in a module with no edges to pkg.b.
    C_SRC = (
        "from pipelinedp_tpu import noise_core\n"
        "class Island:\n"
        "    def _commit_release(self, counter):\n"
        "        self._journal.commit(('t', counter))\n"
        "    def aggregate(self, accs, spec, counter):\n"
        "        cols = noise_core.add_noise_array(\n"
        "            accs, True, 1.0 / spec.eps)\n"
        "        self._commit_release(counter)\n"
        "        return cols\n")

    def _write_tree(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "a.py").write_text(self.A_SRC)
        (pkg / "b.py").write_text(self.B_SRC)
        (pkg / "c.py").write_text(self.C_SRC)
        return pkg

    def test_hazard_reported_when_witness_is_outside_focus(self, tmp_path):
        # The noise draw moved after commit via a helper in b.py: with
        # only b.py "changed", the finding (which anchors in a.py) must
        # still surface. The old behavior analyzed only the changed
        # file, lost the call graph, and reported nothing.
        pkg = self._write_tree(tmp_path)
        result = lint_paths([str(pkg)], root=str(tmp_path),
                            focus=[str(pkg / "b.py")])
        dpl009 = [f for f in result.findings if f.rule_id == "DPL009"]
        assert any(f.path == "pkg/a.py" for f in dpl009), \
            "\n".join(f.format() for f in result.findings)

    def test_unconnected_module_findings_are_filtered(self, tmp_path):
        # c.py has the same hazard but no call-graph connection to the
        # focus file: its finding is someone else's report.
        pkg = self._write_tree(tmp_path)
        result = lint_paths([str(pkg)], root=str(tmp_path),
                            focus=[str(pkg / "b.py")])
        assert not any(f.path == "pkg/c.py" for f in result.findings)
        # Without focus the island is reported as usual.
        full = lint_paths([str(pkg)], root=str(tmp_path))
        assert any(f.path == "pkg/c.py" and f.rule_id == "DPL009"
                   for f in full.findings)


class TestSeededHazards:
    """The acceptance contract: deliberately reintroducing each known
    hazard class into production-shaped code must be caught."""

    def _rule_ids(self, tmp_path, source):
        (tmp_path / "seeded.py").write_text(source)
        result = lint_paths([str(tmp_path / "seeded.py")],
                            root=str(tmp_path))
        return {f.rule_id for f in result.findings}

    def test_journal_commit_reordered(self, tmp_path):
        # The engine's commit-then-finalize ordering, inverted: the host
        # epilogue (a noise-drawing path) runs before _commit_release.
        src = (
            "from pipelinedp_tpu import noise_core\n"
            "class Engine:\n"
            "    def _commit_release(self, counter):\n"
            "        self._journal.commit(('t', counter))\n"
            "    def _finalize(self, accs, spec):\n"
            "        return noise_core.add_noise_array(\n"
            "            accs, True, 1.0 / spec.eps)\n"
            "    def aggregate(self, accs, spec, counter):\n"
            "        cols = self._finalize(accs, spec)\n"
            "        self._commit_release(counter)\n"
            "        return cols\n")
        assert "DPL009" in self._rule_ids(tmp_path, src)

    def test_donated_operand_reused(self, tmp_path):
        # The slab loop's donate-then-rebind pattern with the rebind
        # dropped: the second iteration reads the consumed buffer.
        src = (
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, donate_argnums=(1,))\n"
            "def chunk_step(row, accs):\n"
            "    return accs + row\n"
            "def run_slabs(rows, accs):\n"
            "    for row in rows:\n"
            "        out = chunk_step(row, accs)\n"
            "    return out\n")
        assert "DPL010" in self._rule_ids(tmp_path, src)

    def test_unlocked_pool_write(self, tmp_path):
        # The prefetch pool writing its result dict directly instead of
        # returning through the future.
        src = (
            "import concurrent.futures\n"
            "def prefetch_all(ranges, slabs):\n"
            "    def worker(r):\n"
            "        slabs[r] = r * 2\n"
            "    with concurrent.futures.ThreadPoolExecutor(2) as pool:\n"
            "        for r in ranges:\n"
            "            pool.submit(worker, r)\n"
            "    return slabs\n")
        assert "DPL008" in self._rule_ids(tmp_path, src)

    def test_unnoised_release_materialized(self, tmp_path):
        # A release path that device_gets bounded accumulators with the
        # noise step dropped.
        src = (
            "import jax\n"
            "def release(accs):\n"
            "    return jax.device_get(accs)\n")
        assert "DPL007" in self._rule_ids(tmp_path, src)


class TestDpverifySeededHazards:
    """PR-16 acceptance: seed a scratch copy of the real tree with one
    production-shaped hazard per rule and pin that DPL012-DPL015 each
    catch exactly their seeded hazard — findings land in the seeded
    file and nowhere else (the unseeded tree is clean, so any other
    location would be a false positive)."""

    def _seed(self, tmp_path, relpath, addition):
        scratch = tmp_path / "pipelinedp_tpu"
        shutil.copytree(
            os.path.join(REPO_ROOT, "pipelinedp_tpu"), str(scratch),
            ignore=shutil.ignore_patterns("__pycache__"))
        target = scratch / relpath
        target.write_text(
            target.read_text(encoding="utf-8")
            + textwrap.dedent(addition), encoding="utf-8")
        result = lint_paths([str(scratch)], root=str(tmp_path))
        assert result.parse_errors == []
        return result, "pipelinedp_tpu/" + relpath

    def _assert_caught(self, result, rule_id, relpath):
        hits = [f for f in result.findings if f.rule_id == rule_id]
        assert any(f.path == relpath for f in hits), (
            f"{rule_id} missed its seeded hazard in {relpath}; "
            "all findings:\n"
            + "\n".join(f.format() for f in result.findings))
        strays = [f for f in hits if f.path != relpath]
        assert strays == [], (
            f"{rule_id} fired outside the seeded file:\n"
            + "\n".join(f.format() for f in strays))

    def test_dpl012_raw_manifest_write_in_store(self, tmp_path):
        # The session store growing a raw open(..., 'w') manifest dump:
        # a crash mid-write leaves a torn manifest for the next reader.
        result, relpath = self._seed(tmp_path, "serving/store.py", """

            def _seeded_write_manifest(root, manifest):
                with open(os.path.join(root, "manifest.json"), "w") as fh:
                    json.dump(manifest, fh)
            """)
        self._assert_caught(result, "DPL012", relpath)

    def test_dpl013_fold_before_wal_record_in_live(self, tmp_path):
        # The live append transaction inverted: the in-memory fold runs
        # before the WAL record lands, so a crash between the two
        # replays to a state that never contained the fold.
        result, relpath = self._seed(tmp_path, "serving/live.py", """

            class _SeededLiveSession(LiveDatasetSession):

                def append_fold_first(self, payload, epoch_id):
                    with self._append_lock:
                        self._epochs.append(epoch_id)
                        self._wal.append({"kind": "append",
                                          "epoch": epoch_id})
            """)
        self._assert_caught(result, "DPL013", relpath)

    def test_dpl014_reversed_lock_pair_in_manager(self, tmp_path):
        # The manager/session lock pair nested in both orders: two
        # threads running the two methods deadlock.
        result, relpath = self._seed(tmp_path, "serving/manager.py", """

            class _SeededManager(SessionManager):

                def admit_locked(self, peer):
                    with self._lock:
                        with peer._lock:
                            return True

                def spill_locked(self, peer):
                    with peer._lock:
                        with self._lock:
                            return True
            """)
        self._assert_caught(result, "DPL014", relpath)

    def test_dpl015_eager_jnp_on_release_path_in_engine(self, tmp_path):
        # An eager jnp epilogue after the noise draw: XLA fusion bits
        # outside jit can differ from the compiled release path.
        result, relpath = self._seed(tmp_path, "jax_engine.py", """

            def _seeded_release_epilogue(totals, eps):
                noised = noise_core.add_laplace_noise_array(
                    totals, 1.0 / eps)
                return jnp.maximum(noised, 0.0)
            """)
        self._assert_caught(result, "DPL015", relpath)


class TestProductionFlowProperties:
    """Pin the dpflow facts the strict CI gates rely on."""

    def test_production_tree_flow_is_clean_and_analyzed(self):
        package = os.path.join(REPO_ROOT, "pipelinedp_tpu")
        result = lint_paths([package], root=REPO_ROOT)
        assert result.parse_errors == []
        project_findings = [
            f for f in result.findings
            if f.rule_id in ("DPL007", "DPL008", "DPL009", "DPL010",
                             "DPL011", "DPL012", "DPL013", "DPL014",
                             "DPL015")]
        assert project_findings == [], \
            "\n".join(f.format() for f in project_findings)

    def test_warm_full_tree_within_ci_budget(self, tmp_path):
        """The PR-16 wall-time satellite: a warm dpverify run over the
        whole tree must land inside the 30s CI budget."""
        package = os.path.join(REPO_ROOT, "pipelinedp_tpu")
        cache_path = str(tmp_path / "flow.json")
        lint_paths([package], root=REPO_ROOT,
                   flow_cache_path=cache_path)
        start = time.monotonic()
        warm = lint_paths([package], root=REPO_ROOT,
                          flow_cache_path=cache_path)
        elapsed = time.monotonic() - start
        assert warm.flow_cache_misses == 0 and warm.flow_cache_hits > 0
        assert elapsed < 30.0, f"warm dpverify run took {elapsed:.1f}s"

    def test_every_suppression_is_justified(self):
        """The satellite contract: zero bare `# dplint: disable` lines
        anywhere in the production tree."""
        package = os.path.join(REPO_ROOT, "pipelinedp_tpu")
        result = lint_paths([package], root=REPO_ROOT)
        bare = [f for f in result.findings if f.rule_id == "DPL000"]
        assert bare == [], "\n".join(f.format() for f in bare)
        assert result.suppressed, "expected justified suppressions"
