"""Tests for dpflow (pipelinedp_tpu/lint/flow): the symbol table /
call-graph layer, the digest cache, and the seeded-hazard contract —
the three known hazard classes (journal commit reordered, donated
operand reuse, unlocked pool write) must be caught when deliberately
introduced into production-shaped code.
"""

import ast
import os

import pytest

from pipelinedp_tpu.lint import engine as lint_engine
from pipelinedp_tpu.lint import lint_paths
from pipelinedp_tpu.lint.flow import (
    FlowCache,
    ProjectFlow,
    extract_module,
    source_digest,
)
from pipelinedp_tpu.lint import astutils

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _summaries(sources):
    """{relpath: ModuleSummary} from {dotted module: source} inputs."""
    out = {}
    for module, src in sources.items():
        tree = ast.parse(src)
        out[module.replace(".", "/") + ".py"] = extract_module(
            module, tree, astutils.build_aliases(tree))
    return out


class TestCallGraph:

    def test_cross_module_resolution_and_reaching(self):
        flow = ProjectFlow(_summaries({
            "pkg.a": ("from pkg import b\n"
                      "def f():\n"
                      "    return b.g()\n"),
            "pkg.b": ("import numpy as np\n"
                      "def g():\n"
                      "    return np.random.laplace()\n"),
        }))
        assert flow.resolve("pkg.b.g", "pkg.a") == "pkg.b.g"
        assert flow.edges("pkg.a.f") == ("pkg.b.g",)
        reaching = flow.reaching(r"^numpy\.random\.")
        assert reaching == {"pkg.a.f", "pkg.b.g"}

    def test_import_cycle_resolves(self):
        # a imports b, b imports a: resolution runs over the built index,
        # so the cycle is a non-issue and reachability crosses it.
        flow = ProjectFlow(_summaries({
            "pkg.a": ("from pkg import b\n"
                      "def f():\n"
                      "    return b.g()\n"
                      "def leaf():\n"
                      "    return 1\n"),
            "pkg.b": ("from pkg import a\n"
                      "def g():\n"
                      "    return a.leaf()\n"),
        }))
        assert flow.edges("pkg.a.f") == ("pkg.b.g",)
        assert flow.edges("pkg.b.g") == ("pkg.a.leaf",)
        assert "pkg.a.f" in flow.reaching(r"\.leaf$")

    def test_reexport_through_init(self):
        flow = ProjectFlow(_summaries({
            "pkg": "from pkg.impl import thing\n",  # pkg/__init__.py
            "pkg.impl": "def thing():\n    return 1\n",
            "pkg.user": ("import pkg\n"
                         "def call():\n"
                         "    return pkg.thing()\n"),
        }))
        assert flow.resolve("pkg.thing", "pkg.user") == "pkg.impl.thing"
        assert flow.edges("pkg.user.call") == ("pkg.impl.thing",)

    def test_assignment_alias_reexport(self):
        flow = ProjectFlow(_summaries({
            "pkg.impl": "def thing():\n    return 1\n",
            "pkg.compat": ("from pkg import impl\n"
                           "legacy_thing = impl.thing\n"),
            "pkg.user": ("from pkg import compat\n"
                         "def call():\n"
                         "    return compat.legacy_thing()\n"),
        }))
        assert flow.edges("pkg.user.call") == ("pkg.impl.thing",)

    def test_self_method_resolution_through_base(self):
        flow = ProjectFlow(_summaries({
            "pkg.base": ("class Base:\n"
                         "    def helper(self):\n"
                         "        return 1\n"),
            "pkg.eng": ("from pkg.base import Base\n"
                        "class Engine(Base):\n"
                        "    def run(self):\n"
                        "        return self.helper()\n"),
        }))
        assert flow.edges("pkg.eng.Engine.run") == \
            ("pkg.base.Base.helper",)

    def test_method_resolution_through_jax_dp_engine(self):
        """The real tree: `self._commit_release(...)` inside JaxDPEngine
        methods resolves to the method on the class."""
        path = os.path.join(REPO_ROOT, "pipelinedp_tpu", "jax_engine.py")
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        summary = extract_module("pipelinedp_tpu.jax_engine", tree,
                                 astutils.build_aliases(tree))
        flow = ProjectFlow({"pipelinedp_tpu/jax_engine.py": summary})
        resolved = flow.resolve("self:JaxDPEngine._commit_release",
                                "pipelinedp_tpu.jax_engine")
        assert resolved == \
            "pipelinedp_tpu.jax_engine.JaxDPEngine._commit_release"
        # And the engine's aggregate entry points actually carry that
        # edge (the DPL009 anchor).
        committers = [q for q in flow.functions
                      if resolved in flow.edges(q)]
        assert committers, "no JaxDPEngine method calls _commit_release"

    def test_nested_local_function_resolution(self):
        flow = ProjectFlow(_summaries({
            "pkg.m": ("def outer():\n"
                      "    def inner():\n"
                      "        return 1\n"
                      "    return inner()\n"),
        }))
        assert flow.edges("pkg.m.outer") == \
            ("pkg.m.outer.<locals>.inner",)


class TestFlowCache:

    SRC = "def f():\n    return 1\n"

    def test_round_trip_hit(self, tmp_path):
        cache_path = str(tmp_path / "cache.json")
        tree = ast.parse(self.SRC)
        summary = extract_module("m", tree, {})
        digest = source_digest(self.SRC)

        cache = FlowCache(cache_path)
        assert cache.get("m.py", digest) is None  # cold: miss
        cache.put("m.py", digest, summary)
        cache.save()

        warm = FlowCache(cache_path)
        loaded = warm.get("m.py", digest)
        assert loaded is not None and warm.hits == 1
        assert loaded.functions["f"].line == summary.functions["f"].line

    def test_digest_mismatch_is_miss(self, tmp_path):
        cache_path = str(tmp_path / "cache.json")
        cache = FlowCache(cache_path)
        cache.put("m.py", source_digest(self.SRC),
                  extract_module("m", ast.parse(self.SRC), {}))
        cache.save()
        warm = FlowCache(cache_path)
        assert warm.get("m.py", source_digest(self.SRC + "\n# edit")) \
            is None

    def test_corrupt_cache_ignored(self, tmp_path):
        cache_path = tmp_path / "cache.json"
        cache_path.write_text("{not json")
        cache = FlowCache(str(cache_path))
        assert cache.get("m.py", "x") is None

    def test_lint_paths_warm_run_hits(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text(self.SRC)
        cache_path = str(tmp_path / "flow.json")
        cold = lint_paths(["mod.py"], root=str(tmp_path),
                          flow_cache_path=cache_path)
        assert cold.flow_cache_misses == 1
        warm = lint_paths(["mod.py"], root=str(tmp_path),
                          flow_cache_path=cache_path)
        assert warm.flow_cache_hits == 1 and warm.flow_cache_misses == 0


class TestSeededHazards:
    """The acceptance contract: deliberately reintroducing each known
    hazard class into production-shaped code must be caught."""

    def _rule_ids(self, tmp_path, source):
        (tmp_path / "seeded.py").write_text(source)
        result = lint_paths([str(tmp_path / "seeded.py")],
                            root=str(tmp_path))
        return {f.rule_id for f in result.findings}

    def test_journal_commit_reordered(self, tmp_path):
        # The engine's commit-then-finalize ordering, inverted: the host
        # epilogue (a noise-drawing path) runs before _commit_release.
        src = (
            "from pipelinedp_tpu import noise_core\n"
            "class Engine:\n"
            "    def _commit_release(self, counter):\n"
            "        self._journal.commit(('t', counter))\n"
            "    def _finalize(self, accs, spec):\n"
            "        return noise_core.add_noise_array(\n"
            "            accs, True, 1.0 / spec.eps)\n"
            "    def aggregate(self, accs, spec, counter):\n"
            "        cols = self._finalize(accs, spec)\n"
            "        self._commit_release(counter)\n"
            "        return cols\n")
        assert "DPL009" in self._rule_ids(tmp_path, src)

    def test_donated_operand_reused(self, tmp_path):
        # The slab loop's donate-then-rebind pattern with the rebind
        # dropped: the second iteration reads the consumed buffer.
        src = (
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, donate_argnums=(1,))\n"
            "def chunk_step(row, accs):\n"
            "    return accs + row\n"
            "def run_slabs(rows, accs):\n"
            "    for row in rows:\n"
            "        out = chunk_step(row, accs)\n"
            "    return out\n")
        assert "DPL010" in self._rule_ids(tmp_path, src)

    def test_unlocked_pool_write(self, tmp_path):
        # The prefetch pool writing its result dict directly instead of
        # returning through the future.
        src = (
            "import concurrent.futures\n"
            "def prefetch_all(ranges, slabs):\n"
            "    def worker(r):\n"
            "        slabs[r] = r * 2\n"
            "    with concurrent.futures.ThreadPoolExecutor(2) as pool:\n"
            "        for r in ranges:\n"
            "            pool.submit(worker, r)\n"
            "    return slabs\n")
        assert "DPL008" in self._rule_ids(tmp_path, src)

    def test_unnoised_release_materialized(self, tmp_path):
        # A release path that device_gets bounded accumulators with the
        # noise step dropped.
        src = (
            "import jax\n"
            "def release(accs):\n"
            "    return jax.device_get(accs)\n")
        assert "DPL007" in self._rule_ids(tmp_path, src)


class TestProductionFlowProperties:
    """Pin the dpflow facts the strict CI gates rely on."""

    def test_production_tree_flow_is_clean_and_analyzed(self):
        package = os.path.join(REPO_ROOT, "pipelinedp_tpu")
        result = lint_paths([package], root=REPO_ROOT)
        assert result.parse_errors == []
        assert [f for f in result.findings
                if f.rule_id in ("DPL007", "DPL008", "DPL009",
                                 "DPL010")] == []

    def test_every_suppression_is_justified(self):
        """The satellite contract: zero bare `# dplint: disable` lines
        anywhere in the production tree."""
        package = os.path.join(REPO_ROOT, "pipelinedp_tpu")
        result = lint_paths([package], root=REPO_ROOT)
        bare = [f for f in result.findings if f.rule_id == "DPL000"]
        assert bare == [], "\n".join(f.format() for f in bare)
        assert result.suppressed, "expected justified suppressions"
