"""Multi-device sharding tests on the virtual 8-device CPU mesh.

Validates that the sharded step (shard_map + psum_scatter over the
('dp','mp') mesh) produces the same per-partition accumulators as the
single-device kernel."""

import jax
import numpy as np
import pytest

from pipelinedp_tpu.ops import selection as selection_ops
from pipelinedp_tpu.parallel import sharded
from pipelinedp_tpu import partition_selection as ps_lib


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


def make_inputs(n_rows=4000, n_users=300, n_parts=64, seed=0):
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n_users, n_rows).astype(np.int32)
    pk = rng.integers(0, n_parts, n_rows).astype(np.int32)
    value = rng.uniform(0, 1, n_rows).astype(np.float32)
    return pid, pk, value


class TestShardRowsByPid:

    def test_pids_stay_on_one_shard(self):
        pid, pk, value, = make_inputs()
        spid, spk, sval, svalid = sharded.shard_rows_by_pid(pid, pk, value, 8)
        shard_len = len(spid) // 8
        owner = {}
        for i in range(len(spid)):
            if svalid[i]:
                s = i // shard_len
                assert owner.setdefault(spid[i], s) == s

    def test_all_rows_preserved(self):
        pid, pk, value = make_inputs()
        spid, spk, sval, svalid = sharded.shard_rows_by_pid(pid, pk, value, 8)
        assert svalid.sum() == len(pid)
        assert sval[svalid].sum() == pytest.approx(value.sum(), rel=1e-5)


class TestShardedStep:

    def test_matches_single_device_no_caps(self, mesh):
        pid, pk, value = make_inputs()
        n_parts = 64
        spid, spk, sval, svalid = sharded.shard_rows_by_pid(pid, pk, value, 8)
        step, padded_p = sharded.build_sharded_aggregate_step(mesh, n_parts)
        host = ps_lib.TruncatedGeometricPartitionSelection(1.0, 1e-6, 4)
        sp = selection_ops.selection_params_from_strategy(host)
        sel_scalars = np.array(
            [sp.eps_p, sp.delta_p, sp.n1, sp.pi_n1, sp.pi_inf], np.float32)
        result = step(jax.random.PRNGKey(0), spid, spk, sval, svalid,
                      len(spid), padded_p, -np.inf, np.inf,
                      0.0, 2.0**-40, False, sel_scalars)
        # No caps, near-zero noise scale: counts equal plain bincount.
        np.testing.assert_allclose(
            np.asarray(result.count)[:n_parts],
            np.bincount(pk, minlength=n_parts), atol=1e-3)
        np.testing.assert_allclose(
            np.asarray(result.sum)[:n_parts],
            np.bincount(pk, weights=value, minlength=n_parts), atol=0.1)
        expected_pid_count = np.array(
            [len(set(pid[pk == p])) for p in range(n_parts)])
        np.testing.assert_allclose(
            np.asarray(result.pid_count)[:n_parts], expected_pid_count)

    def test_l0_bounding_across_shards(self, mesh):
        # Every user contributes to 16 partitions; l0 cap 4 must hold
        # globally (pids are shard-local by construction).
        n_users, n_parts = 64, 16
        pid = np.repeat(np.arange(n_users, dtype=np.int32), n_parts)
        pk = np.tile(np.arange(n_parts, dtype=np.int32), n_users)
        value = np.ones(len(pid), np.float32)
        spid, spk, sval, svalid = sharded.shard_rows_by_pid(pid, pk, value, 8)
        step, padded_p = sharded.build_sharded_aggregate_step(mesh, n_parts)
        sel_scalars = np.zeros(5, np.float32)
        host = ps_lib.TruncatedGeometricPartitionSelection(1.0, 1e-6, 4)
        sp = selection_ops.selection_params_from_strategy(host)
        sel_scalars = np.array(
            [sp.eps_p, sp.delta_p, sp.n1, sp.pi_n1, sp.pi_inf], np.float32)
        result = step(jax.random.PRNGKey(1), spid, spk, sval, svalid,
                      1, 4, -np.inf, np.inf, 0.0, 2.0**-40, False,
                      sel_scalars)
        total = np.asarray(result.count)[:n_parts].sum()
        assert total == pytest.approx(n_users * 4, abs=1e-2)

    def test_noise_applied_per_shard(self, mesh):
        pid, pk, value = make_inputs()
        spid, spk, sval, svalid = sharded.shard_rows_by_pid(pid, pk, value, 8)
        step, padded_p = sharded.build_sharded_aggregate_step(mesh, 64)
        host = ps_lib.TruncatedGeometricPartitionSelection(1.0, 1e-6, 4)
        sp = selection_ops.selection_params_from_strategy(host)
        sel_scalars = np.array(
            [sp.eps_p, sp.delta_p, sp.n1, sp.pi_n1, sp.pi_inf], np.float32)
        scale = 5.0
        result = step(jax.random.PRNGKey(2), spid, spk, sval, svalid,
                      len(spid), padded_p, -np.inf, np.inf,
                      scale, 2.0**-20, False, sel_scalars)
        errors = (np.asarray(result.count)[:64] -
                  np.bincount(pk, minlength=64))
        # Laplace(scale=5) => std ~ 7.07; all-zero errors would mean noise
        # was lost in the collective.
        assert errors.std() == pytest.approx(scale * np.sqrt(2), rel=0.4)
