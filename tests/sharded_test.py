"""Multi-device sharding tests on the virtual 8-device CPU mesh.

Validates that the sharded kernels (shard_map + psum_scatter over the
('dp','mp') mesh) produce the same per-partition accumulators as the
single-device kernel, and that JaxDPEngine(mesh=...) runs the full public
API multi-chip."""

import jax
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.ops import columnar
from pipelinedp_tpu.parallel import sharded


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


def make_inputs(n_rows=4000, n_users=300, n_parts=64, seed=0):
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n_users, n_rows).astype(np.int32)
    pk = rng.integers(0, n_parts, n_rows).astype(np.int32)
    value = rng.uniform(0, 1, n_rows).astype(np.float32)
    return pid, pk, value


class TestShardRowsByPid:

    def test_pids_stay_on_one_shard(self):
        pid, pk, value, = make_inputs()
        spid, spk, sval, svalid = sharded.shard_rows_by_pid(pid, pk, value, 8)
        shard_len = len(spid) // 8
        owner = {}
        for i in range(len(spid)):
            if svalid[i]:
                s = i // shard_len
                assert owner.setdefault(spid[i], s) == s

    def test_all_rows_preserved(self):
        pid, pk, value = make_inputs()
        spid, spk, sval, svalid = sharded.shard_rows_by_pid(pid, pk, value, 8)
        assert svalid.sum() == len(pid)
        assert sval[svalid].sum() == pytest.approx(value.sum(), rel=1e-5)

    def test_incoming_invalid_rows_stay_invalid(self):
        pid, pk, value = make_inputs(n_rows=100)
        valid = np.ones(100, dtype=bool)
        valid[::3] = False
        _, _, _, svalid = sharded.shard_rows_by_pid(pid, pk, value, 8, valid)
        assert svalid.sum() == valid.sum()


class TestShardedKernel:

    def test_matches_bincount_no_caps(self, mesh):
        pid, pk, value = make_inputs()
        n_parts = 64
        accs = sharded.bound_and_aggregate(
            mesh, jax.random.PRNGKey(0), pid, pk, value,
            np.ones(len(pid), bool),
            num_partitions=n_parts,
            linf_cap=len(pid), l0_cap=n_parts,
            row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
            group_clip_lo=-np.inf, group_clip_hi=np.inf)
        np.testing.assert_allclose(
            np.asarray(accs.count)[:n_parts],
            np.bincount(pk, minlength=n_parts))
        np.testing.assert_allclose(
            np.asarray(accs.sum)[:n_parts],
            np.bincount(pk, weights=value, minlength=n_parts), rtol=1e-4)
        expected_pid_count = np.array(
            [len(set(pid[pk == p])) for p in range(n_parts)])
        np.testing.assert_allclose(
            np.asarray(accs.pid_count)[:n_parts], expected_pid_count)

    def test_l0_bounding_across_shards(self, mesh):
        # Every user contributes to 16 partitions; l0 cap 4 must hold
        # globally (pids are shard-local by construction).
        n_users, n_parts = 64, 16
        pid = np.repeat(np.arange(n_users, dtype=np.int32), n_parts)
        pk = np.tile(np.arange(n_parts, dtype=np.int32), n_users)
        value = np.ones(len(pid), np.float32)
        accs = sharded.bound_and_aggregate(
            mesh, jax.random.PRNGKey(1), pid, pk, value,
            np.ones(len(pid), bool),
            num_partitions=n_parts,
            linf_cap=1, l0_cap=4,
            row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
            group_clip_lo=-np.inf, group_clip_hi=np.inf)
        assert float(np.asarray(accs.count)[:n_parts].sum()) == n_users * 4

    def test_output_is_sharded_over_partitions(self, mesh):
        pid, pk, value = make_inputs()
        accs = sharded.bound_and_aggregate(
            mesh, jax.random.PRNGKey(0), pid, pk, value,
            np.ones(len(pid), bool),
            num_partitions=64,
            linf_cap=4, l0_cap=8,
            row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
            group_clip_lo=-np.inf, group_clip_hi=np.inf)
        # Each device must hold a distinct 1/8 slice, not a replica.
        shards = accs.count.addressable_shards
        assert len(shards) == 8
        assert all(s.data.shape == (8,) for s in shards)

    def test_vector_kernel_matches_single_device(self, mesh):
        rng = np.random.default_rng(3)
        n_rows, n_parts, d = 500, 16, 3
        pid = rng.integers(0, 50, n_rows).astype(np.int32)
        pk = rng.integers(0, n_parts, n_rows).astype(np.int32)
        value = rng.uniform(-1, 1, (n_rows, d)).astype(np.float32)
        vec, accs = sharded.bound_and_aggregate_vector(
            mesh, jax.random.PRNGKey(0), pid, pk, value,
            np.ones(n_rows, bool),
            num_partitions=n_parts,
            linf_cap=n_rows, l0_cap=n_parts,
            max_norm=100.0, norm_ord=0)
        expected = np.zeros((n_parts, d), np.float32)
        np.add.at(expected, pk, value)
        np.testing.assert_allclose(np.asarray(vec)[:n_parts], expected,
                                   atol=1e-3)


class TestEngineOnMesh:
    """The public API end-to-end on a mesh (the VERDICT round-2 item 1)."""

    def _run(self, mesh, data, params, public=None, eps=1e8, delta=1e-15,
             secure_host_noise=True, seed=0):
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        accountant = pdp.NaiveBudgetAccountant(eps, delta)
        engine = pdp.JaxDPEngine(accountant, seed=seed, mesh=mesh,
                                 secure_host_noise=secure_host_noise)
        result = engine.aggregate(data, params, ext, public_partitions=public)
        accountant.compute_budgets()
        return dict(result)

    def test_count_sum_private_selection(self, mesh):
        data = ([(u, "big", 1.0) for u in range(2000)] +
                [(5555, "tiny", 1.0)])
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=2.0)
        res = self._run(mesh, data, params, eps=1.0, delta=1e-6)
        assert "big" in res and "tiny" not in res
        assert res["big"].count == pytest.approx(2000, rel=0.1)

    def test_device_noise_std_on_mesh(self, mesh):
        # The noise statistical check of TestNoise, but on the mesh with
        # device-side noise — per-shard streams must deliver the calibrated
        # std (noise lost in the collective would show as std ~ 0).
        eps = 1.0
        n_partitions = 512
        data = [(u, f"p{i}", 1.0) for i in range(n_partitions)
                for u in range(5)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=n_partitions,
            max_contributions_per_partition=1)
        public = [f"p{i}" for i in range(n_partitions)]
        res = self._run(mesh, data, params, public=public, eps=eps,
                        delta=1e-15, secure_host_noise=False, seed=11)
        errors = np.array([m.count - 5 for m in res.values()])
        expected_std = n_partitions * np.sqrt(2) / eps
        assert abs(errors.mean()) < expected_std / 3
        assert errors.std() == pytest.approx(expected_std, rel=0.25)

    def test_mesh_matches_single_device_no_noise(self, mesh):
        pid, pk, value = make_inputs(n_rows=2000, n_users=100, n_parts=32)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.MEAN],
            max_partitions_contributed=32,
            max_contributions_per_partition=100,
            min_value=0.0,
            max_value=1.0)
        public = list(range(32))

        def run(m):
            accountant = pdp.NaiveBudgetAccountant(1e8, 1e-15)
            engine = pdp.JaxDPEngine(accountant, mesh=m)
            result = engine.aggregate(
                pdp.ColumnarData(pid=pid.copy(), pk=pk.copy(),
                                 value=value.copy()), params,
                public_partitions=public)
            accountant.compute_budgets()
            return dict(result)

        mesh_res, single_res = run(mesh), run(None)
        assert set(mesh_res) == set(single_res)
        for k in single_res:
            assert mesh_res[k].count == pytest.approx(single_res[k].count,
                                                      abs=0.05)
            assert mesh_res[k].sum == pytest.approx(single_res[k].sum,
                                                    abs=0.2)


class TestMultiSliceMesh:
    """Multi-slice ('dcn', 'dp', 'mp') meshes: cross-slice reduction over
    the DCN axis after intra-slice ICI reduce-scatter, same results as a
    flat mesh."""

    def test_make_mesh_axes(self):
        mesh = sharded.make_mesh(8, n_slices=2)
        assert mesh.axis_names == ("dcn", "dp", "mp")
        assert mesh.devices.shape[0] == 2

    def test_invalid_slice_count(self):
        with pytest.raises(ValueError, match="divisible"):
            sharded.make_mesh(8, n_slices=3)

    def test_engine_on_multislice_matches_truth(self):
        rng = np.random.default_rng(0)
        pid = rng.integers(0, 500, 20_000)
        pk = rng.integers(0, 16, 20_000).astype(np.int32)
        value = rng.uniform(0, 5, 20_000).astype(np.float32)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=16,
                                     max_contributions_per_partition=100,
                                     min_value=0.0,
                                     max_value=5.0)
        accountant = pdp.NaiveBudgetAccountant(1e6, 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=2,
                                 mesh=sharded.make_mesh(8, n_slices=2),
                                 secure_host_noise=False)
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=list(range(16)))
        accountant.compute_budgets()
        cols = result.to_columns()
        truth = np.bincount(pk, minlength=16)
        np.testing.assert_allclose(cols["count"], truth, atol=0.1)
        truth_sum = np.bincount(pk, weights=value.astype(np.float64),
                                minlength=16)
        np.testing.assert_allclose(cols["sum"], truth_sum, rtol=1e-3)


class TestMeshBlockedQuantiles:
    """PERCENTILE on a mesh with partitions exceeding the dense histogram
    budget (VERDICT-r3 task 6): the partition-blocked sharded path must
    release the same values as the dense mesh path."""

    def _run(self, mesh, seed=5):
        rng = np.random.default_rng(0)
        n = 20_000
        n_parts = 40
        data = [(int(u), int(p), float(v)) for u, p, v in zip(
            rng.integers(0, 2000, n), rng.integers(0, n_parts, n),
            rng.uniform(0.0, 10.0, n))]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=n_parts,
            max_contributions_per_partition=100,
            min_value=0.0,
            max_value=10.0)
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        accountant = pdp.NaiveBudgetAccountant(1e12, 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=seed, mesh=mesh,
                                 secure_host_noise=False)
        result = engine.aggregate(data, params, ext,
                                  public_partitions=list(range(n_parts)))
        accountant.compute_budgets()
        return result.to_columns()

    def test_blocked_matches_dense_on_mesh(self, mesh, monkeypatch):
        dense = self._run(mesh)
        from pipelinedp_tpu.ops import quantiles as quantile_ops
        # 40 partitions x 65536 leaves = 2.6M elements; budget 600k forces
        # 8-partition-multiple blocks on the 8-device mesh.
        monkeypatch.setattr(quantile_ops, "MAX_HISTOGRAM_ELEMENTS", 600_000)
        blocked = self._run(mesh)
        # Different (astronomically small) node noise per path; ties at
        # integer rank boundaries may flip by a cell width — see
        # TestBlockedQuantiles in jax_engine_test.py.
        for name in ("percentile_50", "percentile_90"):
            close = np.isclose(blocked[name], dense[name], rtol=1e-6)
            assert close.mean() >= 0.7, name
            np.testing.assert_allclose(blocked[name], dense[name],
                                       atol=0.05)

    def test_blocked_mesh_close_to_true_quantiles(self, mesh, monkeypatch):
        from pipelinedp_tpu.ops import quantiles as quantile_ops
        monkeypatch.setattr(quantile_ops, "MAX_HISTOGRAM_ELEMENTS", 600_000)
        cols = self._run(mesh)
        # ~500 samples per partition: sample-median std ~0.22, so the
        # max over 40 partitions can reach ~4 sigma.
        assert np.abs(cols["percentile_50"] - 5.0).max() < 1.0
        assert np.abs(cols["percentile_90"] - 9.0).max() < 1.0


class TestMeshStreaming:
    """Chunked wire-codec ingest on the mesh (VERDICT-r4 item 8): each
    chunk's sharded transfer overlaps the previous chunk's kernels; the
    results must match the single-shot mesh kernel."""

    def test_stream_matches_single_shot_when_caps_do_not_bind(self, mesh):
        pid, pk, value = make_inputs(n_rows=6000, n_users=500, n_parts=32)
        value = np.round(value * 4) / 4  # affine-int encodable
        import jax.random as jrandom
        key = jrandom.PRNGKey(0)
        kw = dict(num_partitions=32, linf_cap=10**6, l0_cap=32,
                  row_clip_lo=0.0, row_clip_hi=1.0, middle=0.5,
                  group_clip_lo=-np.inf, group_clip_hi=np.inf,
                  has_group_clip=False)
        streamed = sharded.stream_bound_and_aggregate(
            mesh, key, pid, pk, value, n_chunks=3, **kw)
        single = sharded.bound_and_aggregate(
            mesh, key, pid, pk, value, np.ones(len(pid), dtype=bool), **kw)
        np.testing.assert_array_equal(np.asarray(streamed.count),
                                      np.asarray(single.count))
        np.testing.assert_array_equal(np.asarray(streamed.pid_count),
                                      np.asarray(single.pid_count))
        np.testing.assert_allclose(np.asarray(streamed.sum),
                                   np.asarray(single.sum), rtol=1e-5)

    def test_stream_enforces_caps(self, mesh):
        import jax.random as jrandom
        # One user with 200 rows in one partition, linf=3.
        pid = np.zeros(200, dtype=np.int32)
        pk = np.zeros(200, dtype=np.int32)
        value = np.ones(200, dtype=np.float32)
        out = sharded.stream_bound_and_aggregate(
            mesh, jrandom.PRNGKey(1), pid, pk, value, n_chunks=2,
            num_partitions=8, linf_cap=3, l0_cap=1, row_clip_lo=0.0,
            row_clip_hi=1.0, middle=0.5, group_clip_lo=-np.inf,
            group_clip_hi=np.inf, has_group_clip=False)
        assert float(np.asarray(out.count).sum()) == 3.0
        assert float(np.asarray(out.pid_count).sum()) == 1.0

    def test_engine_mesh_streaming_end_to_end(self, mesh):
        # Public API: mesh engine with streaming forced == unstreamed.
        rng = np.random.default_rng(5)
        n = 5000
        pid = rng.integers(0, 800, n, dtype=np.int32)
        pk = rng.integers(0, 20, n, dtype=np.int32)
        value = rng.integers(0, 6, n).astype(np.float32)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=20,
            max_contributions_per_partition=10**6,
            min_value=0.0, max_value=5.0)

        def run(chunks):
            acc = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
            eng = pdp.JaxDPEngine(acc, seed=2, mesh=mesh,
                                  stream_chunks=chunks,
                                  secure_host_noise=False)
            res = eng.aggregate(
                pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
                public_partitions=list(range(20)))
            acc.compute_budgets()
            return res.to_columns()

        a = run(1)   # single-shot staged path
        b = run(3)   # streamed codec path
        np.testing.assert_allclose(a["count"], b["count"], atol=0.5)
        np.testing.assert_allclose(a["sum"], b["sum"], rtol=1e-3, atol=2.0)
