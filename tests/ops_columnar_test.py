"""Tests for the fused columnar kernels against numpy oracles."""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipelinedp_tpu.ops import columnar, selection
from pipelinedp_tpu import partition_selection as ps_lib


def oracle_bound_aggregate(pid, pk, value, P, linf, l0, lo, hi, middle,
                           rng):
    """Reference implementation with explicit Python sampling."""
    groups = collections.defaultdict(list)
    for i in range(len(pid)):
        groups[(pid[i], pk[i])].append(value[i])
    # Linf sampling + group accumulators.
    gaccs = {}
    for (u, p), vals in groups.items():
        if len(vals) > linf:
            vals = list(rng.choice(vals, linf, replace=False))
        clipped = np.clip(vals, lo, hi)
        gaccs[(u, p)] = (len(vals), clipped.sum(),
                         (clipped - middle).sum(),
                         ((clipped - middle)**2).sum())
    # L0 sampling per pid.
    per_pid = collections.defaultdict(list)
    for (u, p) in gaccs:
        per_pid[u].append(p)
    kept = set()
    for u, pks in per_pid.items():
        chosen = pks if len(pks) <= l0 else list(
            rng.choice(pks, l0, replace=False))
        kept.update((u, p) for p in chosen)
    out = np.zeros((5, P))
    for (u, p), (cnt, s, ns, nss) in gaccs.items():
        if (u, p) in kept:
            out[0, p] += 1
            out[1, p] += cnt
            out[2, p] += s
            out[3, p] += ns
            out[4, p] += nss
    return out


class TestBoundAndAggregate:

    def _run(self, pid, pk, value, P, linf, l0, lo=-np.inf, hi=np.inf,
             middle=0.0, glo=-np.inf, ghi=np.inf, seed=0):
        n = len(pid)
        return columnar.bound_and_aggregate(
            jax.random.PRNGKey(seed),
            np.asarray(pid, np.int32), np.asarray(pk, np.int32),
            np.asarray(value, np.float32), np.ones(n, bool),
            num_partitions=P, linf_cap=linf, l0_cap=l0,
            row_clip_lo=lo, row_clip_hi=hi, middle=middle,
            group_clip_lo=glo, group_clip_hi=ghi)

    def test_no_caps_matches_plain_groupby(self):
        rng = np.random.default_rng(0)
        n, P, U = 5000, 13, 97
        pid = rng.integers(0, U, n)
        pk = rng.integers(0, P, n)
        value = rng.uniform(-1, 2, n)
        accs = self._run(pid, pk, value, P, linf=n, l0=P)
        np.testing.assert_allclose(
            np.asarray(accs.count),
            np.bincount(pk, minlength=P), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(accs.sum),
            np.bincount(pk, weights=value, minlength=P), rtol=1e-4, atol=1e-3)
        expected_pid_count = np.zeros(P)
        for p in range(P):
            expected_pid_count[p] = len(set(pid[pk == p]))
        np.testing.assert_allclose(np.asarray(accs.pid_count),
                                   expected_pid_count)

    def test_linf_cap(self):
        # One user, one partition, 100 rows, cap 7.
        accs = self._run([3] * 100, [2] * 100, [1.0] * 100, P=5, linf=7,
                         l0=5)
        assert accs.count[2] == 7
        assert accs.sum[2] == pytest.approx(7.0)
        assert accs.pid_count[2] == 1

    def test_l0_cap(self):
        # One user contributes once to each of 10 partitions, cap 4.
        accs = self._run(
            [1] * 10, list(range(10)), [1.0] * 10, P=10, linf=5, l0=4)
        assert np.asarray(accs.count).sum() == 4
        assert np.asarray(accs.pid_count).sum() == 4
        # Each kept partition has exactly one contribution.
        assert set(np.asarray(accs.count)) <= {0.0, 1.0}

    def test_l0_sampling_is_uniform(self):
        # Across many seeds, each partition kept ~ l0/n_partitions of runs.
        keeps = np.zeros(5)
        for seed in range(200):
            accs = self._run([1] * 5, list(range(5)), [1.0] * 5, P=5,
                             linf=1, l0=2, seed=seed)
            keeps += np.asarray(accs.count)
        np.testing.assert_allclose(keeps / 200, [0.4] * 5, atol=0.12)

    def test_clipping(self):
        accs = self._run([0, 1, 2], [0, 0, 0], [-5.0, 0.5, 9.0], P=1,
                         linf=1, l0=1, lo=0.0, hi=1.0, middle=0.5)
        assert accs.sum[0] == pytest.approx(0.0 + 0.5 + 1.0)
        assert accs.norm_sum[0] == pytest.approx(-0.5 + 0.0 + 0.5)
        assert accs.norm_sq_sum[0] == pytest.approx(0.25 + 0 + 0.25)

    def test_group_clip_per_partition_sum(self):
        # User 0 contributes 10 to pk0 (sum clipped to 4), user 1 adds 1.
        accs = self._run([0] * 10 + [1], [0] * 11, [1.0] * 11, P=1,
                         linf=100, l0=1, glo=0.0, ghi=4.0)
        assert accs.sum[0] == pytest.approx(5.0)

    def test_padding_rows_ignored(self):
        pid = np.array([0, 1, 2, 3], np.int32)
        pk = np.array([0, 0, 0, 0], np.int32)
        value = np.array([1.0, 1.0, 50.0, 50.0], np.float32)
        valid = np.array([True, True, False, False])
        accs = columnar.bound_and_aggregate(
            jax.random.PRNGKey(0), pid, pk, value, valid,
            num_partitions=1, linf_cap=10, l0_cap=10,
            row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
            group_clip_lo=-np.inf, group_clip_hi=np.inf)
        assert accs.count[0] == 2
        assert accs.sum[0] == pytest.approx(2.0)
        assert accs.pid_count[0] == 2

    def test_statistical_match_with_oracle(self):
        rng = np.random.default_rng(42)
        n, P, U = 2000, 7, 29
        pid = rng.integers(0, U, n)
        pk = rng.integers(0, P, n)
        value = rng.uniform(0, 1, n)
        linf, l0 = 3, 2
        # Aggregate totals are random (sampling), but expected totals match
        # across many seeds.
        device_total = np.zeros(P)
        oracle_total = np.zeros(P)
        for seed in range(20):
            accs = self._run(pid, pk, value, P, linf, l0, seed=seed)
            device_total += np.asarray(accs.count)
            oracle = oracle_bound_aggregate(pid, pk, value, P, linf, l0,
                                            -np.inf, np.inf, 0.0,
                                            np.random.default_rng(seed))
            oracle_total += oracle[1]
        # Both sides are 20-draw Monte-Carlo means; compare loosely.
        np.testing.assert_allclose(device_total / 20, oracle_total / 20,
                                   rtol=0.3)
        assert device_total.sum() / 20 == pytest.approx(
            oracle_total.sum() / 20, rel=0.05)


class TestVectorKernel:

    def test_vector_sum_linf_clip(self):
        pid = np.array([0, 0, 1], np.int32)
        pk = np.array([0, 0, 0], np.int32)
        value = np.array([[1.0, 5.0], [1.0, 1.0], [2.0, -3.0]], np.float32)
        out = columnar.bound_and_aggregate_vector(
            jax.random.PRNGKey(0), pid, pk, value, np.ones(3, bool),
            num_partitions=1, linf_cap=10, l0_cap=10, max_norm=2.0,
            norm_ord=0)
        np.testing.assert_allclose(np.asarray(out[0])[0], [4.0, 1.0])

    def test_vector_sum_l2_clip(self):
        pid = np.array([0], np.int32)
        pk = np.array([0], np.int32)
        value = np.array([[3.0, 4.0]], np.float32)
        out = columnar.bound_and_aggregate_vector(
            jax.random.PRNGKey(0), pid, pk, value, np.ones(1, bool),
            num_partitions=1, linf_cap=10, l0_cap=10, max_norm=1.0,
            norm_ord=2)
        np.testing.assert_allclose(np.asarray(out[0])[0], [0.6, 0.8],
                                   rtol=1e-5)


class TestSelectionKernel:

    @pytest.mark.parametrize("strategy_cls,kind", [
        (ps_lib.TruncatedGeometricPartitionSelection,
         selection.TRUNCATED_GEOMETRIC),
        (ps_lib.LaplaceThresholdingPartitionSelection,
         selection.LAPLACE_THRESHOLDING),
        (ps_lib.GaussianThresholdingPartitionSelection,
         selection.GAUSSIAN_THRESHOLDING),
    ])
    def test_keep_rates_match_host_probabilities(self, strategy_cls, kind):
        host = strategy_cls(1.0, 1e-4, 2)
        params = selection.selection_params_from_strategy(host)
        assert params.kind == kind
        counts = np.arange(1, 200, dtype=np.float32)
        # Empirical keep rate over many seeds ~ host probability.
        n_trials = 500
        valid = np.ones(len(counts), bool)
        keys = jax.random.split(jax.random.PRNGKey(0), n_trials)
        keep = jax.jit(jax.vmap(
            lambda k: selection.select_partitions(k, counts, params, valid)[0]
        ))(keys)
        keeps = np.asarray(keep).sum(axis=0)
        expected = host.probability_of_keep_vec(counts.astype(int))
        np.testing.assert_allclose(keeps / n_trials, expected, atol=0.08)

    def test_truncated_geometric_probs_exact(self):
        host = ps_lib.TruncatedGeometricPartitionSelection(1.0, 1e-6, 4)
        params = selection.selection_params_from_strategy(host)
        counts = np.arange(1, 500, dtype=np.float32)
        probs = selection.truncated_geometric_keep_prob(
            counts, params.eps_p, params.delta_p, params.n1, params.pi_n1,
            params.pi_inf)
        expected = host.probability_of_keep_vec(counts.astype(int))
        np.testing.assert_allclose(np.asarray(probs), expected, rtol=2e-4,
                                   atol=1e-9)

    def test_invalid_partitions_never_kept(self):
        host = ps_lib.TruncatedGeometricPartitionSelection(1.0, 1e-2, 1)
        params = selection.selection_params_from_strategy(host)
        counts = np.full(10, 1e6, np.float32)
        valid = np.zeros(10, bool)
        keep, _ = selection.select_partitions(jax.random.PRNGKey(0), counts,
                                              params, valid)
        assert not np.asarray(keep).any()

    def test_pre_threshold(self):
        host = ps_lib.TruncatedGeometricPartitionSelection(1.0, 1e-2, 1,
                                                           pre_threshold=50)
        params = selection.selection_params_from_strategy(host)
        counts = np.array([49.0, 1e6], np.float32)
        keep, _ = selection.select_partitions(jax.random.PRNGKey(0), counts,
                                              params, np.ones(2, bool))
        assert not bool(keep[0])
        assert bool(keep[1])


class TestNoiseSnapping:

    def test_f32_effective_granularity_is_representable(self):
        from pipelinedp_tpu.ops import noise as noise_ops
        scale = 16.0
        host_g = 16.0 * 2.0**-40
        g = float(noise_ops.effective_granularity(scale, host_g, jnp.float32))
        assert g == scale * 2.0**-noise_ops.F32_GRANULARITY_BITS
        # The snap must be non-identity on typical noise magnitudes.
        vals = jnp.asarray([1.2345678, -3.3219], jnp.float32) * scale
        snapped = noise_ops.snap(vals, g)
        assert not np.array_equal(np.asarray(snapped), np.asarray(vals))
        # And every snapped value is an exact multiple of g.
        ratio = np.asarray(snapped, np.float64) / g
        np.testing.assert_allclose(ratio, np.round(ratio), atol=1e-6)

    def test_device_noise_std_matches_scale(self):
        from pipelinedp_tpu.ops import noise as noise_ops
        key = jax.random.PRNGKey(0)
        zeros = jnp.zeros(200_000, jnp.float32)
        lap = np.asarray(noise_ops.add_laplace_noise(key, zeros, 3.0,
                                                     3.0 * 2.0**-40))
        assert np.std(lap) == pytest.approx(3.0 * np.sqrt(2.0), rel=0.02)
        gau = np.asarray(noise_ops.add_gaussian_noise(key, zeros, 2.5,
                                                      2.5 * 2.0**-57))
        assert np.std(gau) == pytest.approx(2.5, rel=0.02)


class TestHashGroupSampling:
    """The single-sort design orders each privacy id's groups by a keyed
    hash (columnar._group_hash): the induced L0 sample must be uniform not
    just marginally but jointly — a structured hash bias would correlate
    which partition PAIRS get selected together."""

    def test_selected_pairs_are_uniform(self):
        import jax
        import jax.numpy as jnp
        from pipelinedp_tpu.ops import columnar
        from itertools import combinations

        n_parts = 5
        pid = jnp.zeros(n_parts, dtype=jnp.int32)
        pk = jnp.arange(n_parts, dtype=jnp.int32)
        valid = jnp.ones(n_parts, dtype=bool)
        pair_counts = {pair: 0 for pair in combinations(range(n_parts), 2)}
        trials = 400
        for seed in range(trials):
            mask = np.asarray(
                columnar.bound_row_mask(jax.random.PRNGKey(seed), pid, pk,
                                        valid, 1, 2))
            kept = tuple(sorted(np.flatnonzero(mask).tolist()))
            assert len(kept) == 2
            pair_counts[kept] += 1
        # 10 pairs, each with probability 1/10; binomial std ~ 0.015.
        for pair, count in pair_counts.items():
            assert abs(count / trials - 0.1) < 0.06, (pair, count)

    def test_distinct_keys_give_distinct_samples(self):
        import jax
        import jax.numpy as jnp
        from pipelinedp_tpu.ops import columnar

        pid = jnp.zeros(30, dtype=jnp.int32)
        pk = jnp.arange(30, dtype=jnp.int32)
        valid = jnp.ones(30, dtype=bool)
        masks = {
            tuple(np.asarray(
                columnar.bound_row_mask(jax.random.PRNGKey(seed), pid, pk,
                                        valid, 1, 5)).tolist())
            for seed in range(20)
        }
        assert len(masks) > 10  # the salt really re-randomizes the order


class TestNarrowValueDtype:
    """float16 value columns must not degrade counts or partition routing:
    accumulation promotes to float32 (round-4 review regression test —
    pk ids >= 2048 are not representable in float16)."""

    def test_f16_values_route_and_count_exactly(self):
        import jax
        import jax.numpy as jnp
        from pipelinedp_tpu.ops import columnar

        n_parts = 4000
        pk = np.arange(n_parts, dtype=np.int32)
        pid = np.arange(n_parts, dtype=np.int32)
        value16 = np.full(n_parts, 1.5, dtype=np.float16)

        def run(val):
            return columnar.bound_and_aggregate(
                jax.random.PRNGKey(0), jnp.asarray(pid), jnp.asarray(pk),
                jnp.asarray(val), jnp.ones(n_parts, dtype=bool),
                num_partitions=n_parts, linf_cap=4, l0_cap=n_parts,
                row_clip_lo=0.0, row_clip_hi=5.0, middle=0.0,
                group_clip_lo=-jnp.inf, group_clip_hi=jnp.inf)

        accs16 = run(value16)
        accs32 = run(value16.astype(np.float32))
        np.testing.assert_array_equal(np.asarray(accs16.count),
                                      np.ones(n_parts))
        np.testing.assert_array_equal(np.asarray(accs16.count),
                                      np.asarray(accs32.count))
        np.testing.assert_allclose(np.asarray(accs16.sum),
                                   np.asarray(accs32.sum))
        assert np.asarray(accs16.count).dtype == np.float32


class TestPresortedKernel:
    """The packed-3-key presorted sampler (pid_sorted=True) must be a
    drop-in for the general 4-key sort: same aggregates whenever the
    decisions are forced (caps don't bind, or totals are permutation-
    invariant), uniform sampling when they are not, and exact suffix
    padding handling — the contract the wire-codec decode relies on."""

    def _run(self, pid, pk, value, P, linf, l0, *, pid_sorted, seed=0,
             max_segments=None, valid=None, **kw):
        import jax.numpy as jnp
        n = len(pid)
        return columnar.bound_and_aggregate(
            jax.random.PRNGKey(seed),
            jnp.asarray(np.asarray(pid, np.int32)),
            jnp.asarray(np.asarray(pk, np.int32)),
            jnp.asarray(np.asarray(value, np.float32)),
            jnp.asarray(np.ones(n, bool) if valid is None else valid),
            num_partitions=P, linf_cap=linf, l0_cap=l0,
            row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
            group_clip_lo=-np.inf, group_clip_hi=np.inf,
            pid_sorted=pid_sorted, max_segments=max_segments, **kw)

    def _data(self, n=30_000, P=64, U=900, seed=0):
        rng = np.random.default_rng(seed)
        pid = np.sort(rng.integers(0, U, n)).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        value = rng.uniform(-1, 4, n).astype(np.float32)
        return pid, pk, value

    def test_matches_general_when_caps_do_not_bind(self):
        pid, pk, value = self._data()
        a = self._run(pid, pk, value, 64, len(pid), 64, pid_sorted=False)
        b = self._run(pid, pk, value, 64, len(pid), 64, pid_sorted=True,
                      max_segments=900)
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-5)

    def test_binding_cap_totals_are_permutation_invariant(self):
        # Which rows/groups survive is a different draw, but the TOTALS
        # (min(c, linf) per group, min(m, l0) groups per pid) are not —
        # both samplers must land on exactly the same sums.
        pid, pk, value = self._data()
        ta = np.asarray(
            self._run(pid, pk, value, 64, 2, 3, pid_sorted=False).count)
        tb = np.asarray(
            self._run(pid, pk, value, 64, 2, 3, pid_sorted=True,
                      max_segments=900).count)
        assert ta.sum() == tb.sum() > 0

    def test_l0_sampling_is_uniform(self):
        keeps = np.zeros(5)
        for seed in range(200):
            accs = self._run([1] * 5, list(range(5)), [1.0] * 5, 5, 1, 2,
                             pid_sorted=True, max_segments=1, seed=seed)
            keeps += np.asarray(accs.count)
        np.testing.assert_allclose(keeps / 200, [0.4] * 5, atol=0.12)

    def test_linf_sampling_is_uniform_over_rows(self):
        # 1 user, 1 partition, 10 rows with distinct values, keep 3: the
        # kept count is always exactly 3, and across seeds the mean kept
        # sum matches a uniform 3-subset of 0..9 (3 * 4.5 = 13.5).
        vals = np.arange(10, dtype=np.float32)
        sums = []
        for seed in range(300):
            accs = self._run([7] * 10, [0] * 10, vals, 1, 3, 1,
                             pid_sorted=True, max_segments=1, seed=seed)
            assert float(np.asarray(accs.count)[0]) == 3
            sums.append(float(np.asarray(accs.sum)[0]))
        assert abs(np.mean(sums) - 13.5) < 0.8

    def test_padding_suffix_ignored(self):
        import jax.numpy as jnp
        pid, pk, value = self._data(n=5_000)
        npad = 128
        pid_p = np.concatenate([pid, np.zeros(npad, np.int32)])
        pk_p = np.concatenate([pk, np.full(npad, 63, np.int32)])
        val_p = np.concatenate([value, np.full(npad, 99.0, np.float32)])
        valid = np.concatenate(
            [np.ones(len(pid), bool), np.zeros(npad, bool)])
        a = self._run(pid, pk, value, 64, len(pid), 64, pid_sorted=True,
                      max_segments=900)
        b = self._run(pid_p, pk_p, val_p, 64, len(pid), 64,
                      pid_sorted=True, max_segments=900,
                      valid=jnp.asarray(valid))
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-5)

    def test_row_mask_parity_with_aggregate(self):
        import jax.numpy as jnp
        pid, pk, value = self._data()
        key = jax.random.PRNGKey(11)
        mask = np.asarray(columnar.bound_row_mask(
            key, jnp.asarray(pid), jnp.asarray(pk),
            jnp.ones(len(pid), bool), 2, 3, pid_sorted=True,
            max_segments=900, num_partitions=64))
        accs = columnar.bound_and_aggregate(
            key, jnp.asarray(pid), jnp.asarray(pk), jnp.asarray(value),
            jnp.ones(len(pid), bool), num_partitions=64, linf_cap=2,
            l0_cap=3, row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
            group_clip_lo=-np.inf, group_clip_hi=np.inf, pid_sorted=True,
            max_segments=900, need_norm=False, need_norm_sq=False,
            has_group_clip=False)
        np.testing.assert_allclose(
            np.asarray(accs.count), np.bincount(pk[mask], minlength=64))
        np.testing.assert_allclose(
            np.asarray(accs.sum),
            np.bincount(pk[mask], weights=value[mask], minlength=64),
            rtol=1e-5)

    def test_group_clip_path_matches_general(self):
        pid, pk, value = self._data()
        a = self._run(pid, pk, value, 64, len(pid), 64, pid_sorted=False,
                      has_group_clip=True)
        b = self._run(pid, pk, value, 64, len(pid), 64, pid_sorted=True,
                      max_segments=900, has_group_clip=True)
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-5)

    def test_infeasible_bits_fall_back_to_general(self):
        # A partition vocabulary too wide for the packed keys must not
        # break pid_sorted=True calls — the general sampler takes over.
        assert not columnar.presorted_fits(10**9, 1 << 31, 10**9)
        pid, pk, value = self._data(n=2_000, P=64)
        accs = self._run(pid, pk, value, 64, len(pid), 64,
                         pid_sorted=True, max_segments=1 << 40)
        np.testing.assert_allclose(np.asarray(accs.count),
                                   np.bincount(pk, minlength=64))

    def test_key_packing_roundtrip(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        seg = rng.integers(0, 1 << 21, 500).astype(np.uint32)
        gh = rng.integers(0, 1 << 32, 500, dtype=np.uint64).astype(
            np.uint32)
        pk = rng.integers(0, 1 << 20, 500).astype(np.uint32)
        rnd = rng.integers(0, 1 << 23, 500).astype(np.uint32)
        keys = columnar._pack_key_bits([
            (jnp.asarray(seg), 21), (jnp.asarray(gh), 32),
            (jnp.asarray(pk), 20), (jnp.asarray(rnd), 23)])
        assert len(keys) == 3
        np.testing.assert_array_equal(
            np.asarray(columnar._extract_key_bits(keys, 0, 21)), seg)
        np.testing.assert_array_equal(
            np.asarray(columnar._extract_key_bits(keys, 53, 20)), pk)
        np.testing.assert_array_equal(
            np.asarray(columnar._extract_key_bits(keys, 73, 23)), rnd)
