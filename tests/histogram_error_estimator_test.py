"""CountErrorEstimator tests.

Mirrors the reference's estimator semantics
(histogram_error_estimator.py:44-158): ratio-dropped interpolation, noise
std scaling per noise kind, RMSE averaging over the partition histogram.
"""

import math

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.backends import LocalBackend
from pipelinedp_tpu.dataset_histograms import computing_histograms
from pipelinedp_tpu.dataset_histograms import histogram_error_estimator


def _histograms():
    # 4 users: user u contributes to partitions 0..u (l0 = 1..4), one
    # contribution each except user 3 contributes twice per partition.
    rows = []
    for user in range(4):
        for p in range(user + 1):
            rows.append((user, p, 1.0))
            if user == 3:
                rows.append((user, p, 1.0))
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    return list(
        computing_histograms.compute_dataset_histograms(
            rows, extractors, LocalBackend()))[0]


class TestCountErrorEstimator:

    def test_only_count_metrics_supported(self):
        with pytest.raises(ValueError, match="COUNT"):
            histogram_error_estimator.create_error_estimator(
                _histograms(), 1.0, pdp.Metrics.SUM, pdp.NoiseKind.LAPLACE)

    def test_ratio_dropped_bounds(self):
        est = histogram_error_estimator.create_error_estimator(
            _histograms(), 1.0, pdp.Metrics.COUNT, pdp.NoiseKind.LAPLACE)
        # Bound above the max contribution drops nothing; 0 drops all.
        assert est.get_ratio_dropped_l0(10) == 0
        assert est.get_ratio_dropped_l0(0) == 1
        assert est.get_ratio_dropped_linf(10) == 0
        # Monotone decreasing in the bound.
        r = [est.get_ratio_dropped_l0(b) for b in range(1, 5)]
        assert all(a >= b for a, b in zip(r, r[1:]))

    def test_rmse_noise_only_when_nothing_dropped(self):
        # With bounds covering the data fully, RMSE == noise std.
        est = histogram_error_estimator.create_error_estimator(
            _histograms(), 2.0, pdp.Metrics.COUNT, pdp.NoiseKind.LAPLACE)
        rmse = est.estimate_rmse(l0_bound=4, linf_bound=2)
        assert rmse == pytest.approx(2.0 * 4 * 2)

    def test_gaussian_std_scaling(self):
        est = histogram_error_estimator.create_error_estimator(
            _histograms(), 2.0, pdp.Metrics.COUNT, pdp.NoiseKind.GAUSSIAN)
        rmse = est.estimate_rmse(l0_bound=4, linf_bound=2)
        assert rmse == pytest.approx(2.0 * math.sqrt(4) * 2)

    def test_privacy_id_count_ignores_linf(self):
        est = histogram_error_estimator.create_error_estimator(
            _histograms(), 1.0, pdp.Metrics.PRIVACY_ID_COUNT,
            pdp.NoiseKind.LAPLACE)
        assert est.estimate_rmse(4) == pytest.approx(est.estimate_rmse(4, 7))

    def test_count_requires_linf(self):
        est = histogram_error_estimator.create_error_estimator(
            _histograms(), 1.0, pdp.Metrics.COUNT, pdp.NoiseKind.LAPLACE)
        with pytest.raises(ValueError, match="linf"):
            est.estimate_rmse(2)

    def test_dropped_data_increases_rmse(self):
        est = histogram_error_estimator.create_error_estimator(
            _histograms(), 0.1, pdp.Metrics.COUNT, pdp.NoiseKind.LAPLACE)
        # Tight l0 bound drops data -> bigger error than noise alone for
        # the same std... compare normalized by the noise std.
        rmse_tight = est.estimate_rmse(1, 2)
        noise_tight = 0.1 * 1 * 2
        assert rmse_tight > noise_tight

    def test_vectorized_matches_scalar(self):
        est = histogram_error_estimator.create_error_estimator(
            _histograms(), 0.5, pdp.Metrics.COUNT, pdp.NoiseKind.GAUSSIAN)
        l0s = np.array([1, 2, 3, 4])
        linfs = np.array([1, 2, 1, 2])
        vec = est.estimate_rmse_vec(l0s, linfs)
        for i in range(4):
            assert vec[i] == pytest.approx(
                est.estimate_rmse(int(l0s[i]), int(linfs[i])))

    def test_interpolation_between_thresholds(self):
        ratios = [(0, 1.0), (2, 0.5), (4, 0.0)]
        out = histogram_error_estimator._interp_ratio_dropped(
            ratios, np.array([1.0, 3.0]))
        assert out[0] == pytest.approx(0.75)
        assert out[1] == pytest.approx(0.25)
