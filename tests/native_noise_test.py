"""Native secure-noise library tests.

Build + load + distribution cross-checks: the discrete samplers must match
their continuous targets at the configured granularity (the granularity is
~2^-40 relative, far below any statistical test's resolution), return exact
granularity multiples, and reject bad parameters. Role parity:
/root/reference/tests/dp_computations_test.py test_secure_laplace_noise_is_used
(the reference verifies C++ noise is wired; here the C++ lives in-repo).
"""

import numpy as np
import pytest
from scipy import stats

from pipelinedp_tpu import noise_core
from pipelinedp_tpu.native import loader


@pytest.fixture(scope="module")
def lib():
    # install() (not just load()): earlier test files may have routed
    # sampling to the seedable fallback via seed_fallback_rng.
    if not loader.install():
        pytest.skip("native library unavailable (no compiler)")
    return loader.load()


class TestNativeSamplers:

    def test_loader_installed_into_noise_core(self, lib):
        assert loader.is_loaded()
        assert noise_core.using_native_sampling()

    def test_laplace_distribution(self, lib):
        scale = 3.0
        s = noise_core.sample_laplace(scale, (200_000,))
        # KS against the continuous Laplace: the 2^-40-relative granularity
        # is invisible at this sample size.
        _, p = stats.kstest(s, stats.laplace(scale=scale).cdf)
        assert p > 1e-4
        assert abs(s.std() / (scale * np.sqrt(2)) - 1) < 0.02

    def test_gaussian_distribution(self, lib):
        stddev = 7.5
        s = noise_core.sample_gaussian(stddev, (200_000,))
        _, p = stats.kstest(s, stats.norm(scale=stddev).cdf)
        assert p > 1e-4
        assert abs(s.std() / stddev - 1) < 0.02

    def test_granularity_multiples(self, lib):
        for scale in (0.1, 17.0, 1e6):
            g = noise_core.laplace_granularity(scale)
            s = noise_core.sample_laplace(scale, (1000,))
            np.testing.assert_array_equal(np.round(s / g) * g, s)

    def test_scalar_sampling(self, lib):
        out = noise_core.sample_laplace(2.0)
        assert isinstance(out, float)

    def test_not_replayable(self, lib):
        # Secure noise must differ across draws (no seeding surface).
        a = noise_core.sample_laplace(1.0, (100,))
        b = noise_core.sample_laplace(1.0, (100,))
        assert not np.array_equal(a, b)

    def test_invalid_parameters_rejected(self, lib):
        import ctypes
        out = np.empty(1, dtype=np.int64)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        assert lib.pdp_sample_discrete_laplace(ptr, 1, 0.0) != 0
        assert lib.pdp_sample_discrete_laplace(ptr, 1, float("nan")) != 0
        assert lib.pdp_sample_discrete_gaussian(ptr, 1, -1.0) != 0

    def test_uniform_distribution(self, lib):
        s = noise_core.sample_uniform((200_000,))
        _, p = stats.kstest(s, stats.uniform().cdf)
        assert p > 1e-4
        assert (s >= 0).all() and (s < 1).all()

    def test_uniform_scalar(self, lib):
        u = noise_core.sample_uniform()
        assert isinstance(u, float)
        assert 0.0 <= u < 1.0

    def test_selection_draws_not_replayable(self, lib):
        # Keep decisions must come from the secure source: with no seed
        # installed, two identical selection batches at keep probability
        # ~1/2 per partition must not agree everywhere.
        from pipelinedp_tpu import partition_selection as ps
        ps.seed_rng(None)
        strategy = ps.TruncatedGeometricPartitionSelection(
            epsilon=1.0, delta=1e-5, max_partitions_contributed=1)
        counts = np.full(2000, int(strategy.threshold))
        keep_a, _ = strategy.select_vec(counts)
        keep_b, _ = strategy.select_vec(counts)
        assert not np.array_equal(keep_a, keep_b)
        # And the draw itself rides the native sampler, not numpy.
        assert noise_core.using_native_sampling()

    def test_exponential_mechanism_secure_draw(self, lib):
        from pipelinedp_tpu import dp_computations

        class Flat(dp_computations.ExponentialMechanism.ScoringFunction):
            def score(self, k):
                return 0.0

            @property
            def global_sensitivity(self):
                return 1.0

            @property
            def is_monotonic(self):
                return True

        dp_computations.ExponentialMechanism.seed_rng(None)
        mech = dp_computations.ExponentialMechanism(Flat())
        draws = {mech.apply(1.0, list(range(50))) for _ in range(300)}
        # Uniform over 50 candidates: 300 draws hit many distinct ones.
        assert len(draws) > 20

    def test_add_noise_array_uses_float64(self, lib):
        values = np.arange(1000, dtype=np.float32)
        out = noise_core.add_laplace_noise_array(values, 0.5)
        assert out.dtype == np.float64
        assert abs((out - values).mean()) < 0.2

    def test_engine_secure_path_end_to_end(self, lib):
        # The default JaxDPEngine path releases native noise.
        import pipelinedp_tpu as pdp
        rng = np.random.default_rng(0)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant)  # secure_host_noise=True
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=2,
            max_contributions_per_partition=1)
        result = engine.aggregate(
            pdp.ColumnarData(pid=rng.integers(0, 3000, 10_000),
                             pk=rng.integers(0, 10, 10_000)),
            params, public_partitions=list(range(10)))
        accountant.compute_budgets()
        counts = result.to_columns()["count"]
        assert np.isfinite(counts).all()
        # Values are granularity multiples of the calibrated scale
        # (scale = l0 * linf / eps = 2 / 1.0 after the full-budget split).
        scale = 2 / 1.0
        g = noise_core.laplace_granularity(scale)
        np.testing.assert_allclose(np.round(counts / g) * g, counts,
                                   atol=1e-9)
