"""Tests for dplint (pipelinedp_tpu/lint): rule engine, rules, CLI.

The last test class doubles as the CI lint gate: the production tree must
be clean, so any new DPL finding fails the tier-1 suite.
"""

import os
import subprocess
import sys

import pytest

from pipelinedp_tpu.lint import engine as lint_engine
from pipelinedp_tpu.lint import lint_paths
from pipelinedp_tpu.lint.cli import main as lint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "lint")
PACKAGE = os.path.join(REPO_ROOT, "pipelinedp_tpu")

# Minimum finding count per rule in its bad fixture (each fixture contains
# several distinct violation shapes).
MIN_BAD_FINDINGS = {
    "DPL001": 3,  # double draw, loop reuse, double hand-off
    "DPL002": 1,
    "DPL003": 4,  # .item(), traced branch, np-on-traced, float()
    "DPL004": 3,  # np.random x2, stdlib random
    "DPL005": 5,  # eps=-1, delta=1.5, eps=0, eps/2, 0.5*delta
    "DPL006": 1,
    "DPL007": 3,  # raw sink, interprocedural sink, bounded-only sink
    "DPL008": 3,  # element write, mutator call, attribute write
    "DPL009": 2,  # direct draw before commit, draw via helper
    "DPL010": 3,  # read after donate, loop carry, exception path
    "DPL011": 4,  # span attr, metric observe (direct + via helper), audit
    "DPL012": 3,  # raw manifest write, raw snapshot write, no-fsync rename
    "DPL013": 2,  # payload saved after the record, fold before the record
    "DPL014": 2,  # reversed lock pair cycle, fsync under lock
    "DPL015": 3,  # wall-clock seed, listdir order, eager jnp clip
}
ALL_RULE_IDS = sorted(MIN_BAD_FINDINGS)


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def findings_for(path: str, rule_id=None):
    result = lint_paths([path], root=REPO_ROOT)
    assert result.parse_errors == []
    if rule_id is None:
        return result.findings
    return [f for f in result.findings if f.rule_id == rule_id]


class TestRuleFixtures:

    @pytest.mark.parametrize("rule_id", ALL_RULE_IDS)
    def test_bad_fixture_triggers(self, rule_id):
        path = fixture(f"{rule_id.lower()}_bad.py")
        found = findings_for(path, rule_id)
        assert len(found) >= MIN_BAD_FINDINGS[rule_id], (
            f"{rule_id} bad fixture produced {len(found)} findings: "
            f"{[f.format() for f in found]}")
        for f in found:
            assert f.line > 0 and f.message

    @pytest.mark.parametrize("rule_id", ALL_RULE_IDS)
    def test_good_fixture_clean_under_every_rule(self, rule_id):
        path = fixture(f"{rule_id.lower()}_good.py")
        found = findings_for(path)
        assert found == [], [f.format() for f in found]


class TestKeyReuseSpecifics:

    def _lint_source(self, tmp_path, source):
        mod = tmp_path / "mod.py"
        mod.write_text(source)
        return findings_for(str(mod), "DPL001")

    def test_exclusive_branches_do_not_conflict(self, tmp_path):
        src = ("import jax\n"
               "def f(key, g):\n"
               "    if g:\n"
               "        return jax.random.uniform(key, ())\n"
               "    return jax.random.bits(key, ())\n")
        assert self._lint_source(tmp_path, src) == []

    def test_consumption_after_branch_consumption_flags(self, tmp_path):
        src = ("import jax\n"
               "def f(key, g):\n"
               "    if g:\n"
               "        a = jax.random.uniform(key, ())\n"
               "    return jax.random.bits(key, ())\n")
        found = self._lint_source(tmp_path, src)
        assert len(found) == 1 and found[0].line == 5

    def test_reassignment_resets(self, tmp_path):
        src = ("import jax\n"
               "def f(key):\n"
               "    a = jax.random.uniform(key, ())\n"
               "    key = jax.random.split(key)[0]\n"
               "    return a + jax.random.uniform(key, ())\n")
        assert self._lint_source(tmp_path, src) == []

    def test_keystream_idiom_is_blessed(self, tmp_path):
        src = ("import jax\n"
               "from pipelinedp_tpu.jax_engine import KeyStream\n"
               "def f(key, n):\n"
               "    out = []\n"
               "    for i in range(n):\n"
               "        out.append(jax.random.uniform("
               "KeyStream.derive(key, i), ()))\n"
               "    return out\n")
        assert self._lint_source(tmp_path, src) == []

    def test_dict_keys_named_key_ignored(self, tmp_path):
        src = ("def f(vocab, items):\n"
               "    for key in items:\n"
               "        vocab.setdefault(key, len(vocab))\n"
               "        vocab.lookup(key)\n"
               "    return vocab\n")
        assert self._lint_source(tmp_path, src) == []


class TestSuppressions:

    BAD = "def f(run):\n    return run(eps=-1.0)\n"

    def _lint_file(self, tmp_path, source):
        mod = tmp_path / "mod.py"
        mod.write_text(source)
        return lint_paths([str(mod)], root=str(tmp_path))

    def test_same_line_suppression(self, tmp_path):
        src = ("def f(run):\n"
               "    return run(eps=-1.0)  # dplint: disable=DPL005 — test\n")
        result = self._lint_file(tmp_path, src)
        assert result.findings == []
        assert [f.rule_id for f in result.suppressed] == ["DPL005"]

    def test_comment_line_above_suppression(self, tmp_path):
        src = ("def f(run):\n"
               "    # dplint: disable=DPL005 — justified\n"
               "    return run(eps=-1.0)\n")
        result = self._lint_file(tmp_path, src)
        assert result.findings == []
        assert len(result.suppressed) == 1

    def test_file_level_suppression(self, tmp_path):
        src = "# dplint: disable-file=DPL005 — fixture-wide\n" + self.BAD
        result = self._lint_file(tmp_path, src)
        assert result.findings == []

    def test_disable_all(self, tmp_path):
        src = ("def f(run):\n"
               "    return run(eps=-1.0)  # dplint: disable=all — test\n")
        result = self._lint_file(tmp_path, src)
        assert result.findings == []

    def test_wrong_rule_id_does_not_suppress(self, tmp_path):
        src = ("def f(run):\n"
               "    return run(eps=-1.0)"
               "  # dplint: disable=DPL001 — wrong id on purpose\n")
        result = self._lint_file(tmp_path, src)
        assert [f.rule_id for f in result.findings] == ["DPL005"]

    def test_bare_suppression_becomes_dpl000(self, tmp_path):
        # The directive still silences its target, but the missing
        # justification surfaces as an unsuppressible DPL000 finding.
        src = ("def f(run):\n"
               "    return run(eps=-1.0)  # dplint: disable=DPL005\n")
        result = self._lint_file(tmp_path, src)
        assert [f.rule_id for f in result.findings] == ["DPL000"]
        assert "justification" in result.findings[0].message
        assert [f.rule_id for f in result.suppressed] == ["DPL005"]

    def test_bare_file_level_suppression_flagged(self, tmp_path):
        src = "# dplint: disable-file=DPL005\n" + self.BAD
        result = self._lint_file(tmp_path, src)
        assert [f.rule_id for f in result.findings] == ["DPL000"]

    def test_separator_alone_is_not_a_justification(self, tmp_path):
        src = ("def f(run):\n"
               "    return run(eps=-1.0)  # dplint: disable=DPL005 —\n")
        result = self._lint_file(tmp_path, src)
        assert [f.rule_id for f in result.findings] == ["DPL000"]


class TestBaseline:

    BAD = "def f(run):\n    return run(eps=-1.0)\n"
    MORE = "\n\ndef g(run):\n    return run(delta=2.0)\n"

    def test_round_trip_and_ratchet(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text(self.BAD)
        assert lint_main(["mod.py"]) == 1
        assert lint_main(["mod.py", "--baseline", "b.json",
                         "--write-baseline"]) == 0
        # Baselined: clean exit.
        assert lint_main(["mod.py", "--baseline", "b.json"]) == 0
        # A new violation is not masked by the baseline.
        (tmp_path / "mod.py").write_text(self.BAD + self.MORE)
        assert lint_main(["mod.py", "--baseline", "b.json"]) == 1

    def test_fingerprints_survive_line_shifts(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text(self.BAD)
        assert lint_main(["mod.py", "--baseline", "b.json",
                         "--write-baseline"]) == 0
        (tmp_path / "mod.py").write_text("# pushed down two lines\n\n" +
                                         self.BAD)
        assert lint_main(["mod.py", "--baseline", "b.json"]) == 0

    def test_duplicate_violations_need_two_entries(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.chdir(tmp_path)
        one = "def f(run):\n    return run(eps=-1.0)\n"
        (tmp_path / "mod.py").write_text(one)
        assert lint_main(["mod.py", "--baseline", "b.json",
                         "--write-baseline"]) == 0
        # The same violation line appearing twice: one occurrence is
        # baselined, the second is new.
        (tmp_path / "mod.py").write_text(
            one + "\n\ndef g(run):\n    return run(eps=-1.0)\n")
        assert lint_main(["mod.py", "--baseline", "b.json"]) == 1

    def test_default_baseline_discovery(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text(self.BAD)
        assert lint_main(["mod.py", "--baseline",
                          lint_engine.os.path.join(str(tmp_path),
                                                   "dplint-baseline.json"),
                          "--write-baseline"]) == 0
        # No --baseline flag: ./dplint-baseline.json is picked up.
        assert lint_main(["mod.py"]) == 0


class TestCli:

    def test_exit_zero_on_clean_file(self):
        assert lint_main([fixture("dpl005_good.py"), "--no-baseline"]) == 0

    def test_exit_one_on_findings(self):
        assert lint_main([fixture("dpl005_bad.py"), "--no-baseline"]) == 1

    def test_exit_two_on_missing_path(self):
        assert lint_main(["definitely/not/a/path.py"]) == 2

    def test_exit_two_on_unknown_rule(self):
        assert lint_main([fixture("dpl005_bad.py"), "--rules", "DPL999"]) == 2

    def test_list_rules(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule_id in ALL_RULE_IDS:
            assert rule_id in out

    def test_rule_filter(self):
        # dpl005_bad has only DPL005 violations; filtering to DPL001 is
        # clean.
        assert lint_main([fixture("dpl005_bad.py"), "--rules", "DPL001",
                          "--no-baseline"]) == 0

    def test_json_format(self, capsys):
        import json
        assert lint_main([fixture("dpl006_bad.py"), "--format", "json",
                          "--no-baseline"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload and payload[0]["rule"] == "DPL006"
        assert payload[0]["line"] > 0

    def test_sarif_format(self, capsys):
        """--format=sarif emits structurally valid SARIF 2.1.0: the
        required top-level keys, a tool.driver with a rule catalog, and
        results whose ruleIndex/locations resolve."""
        import json
        assert lint_main([fixture("dpl007_bad.py"), "--format", "sarif",
                          "--no-baseline", "--no-flow-cache"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == "2.1.0"
        assert "sarif-schema-2.1.0" in doc["$schema"]
        run = doc["runs"][0]
        driver = run["tool"]["driver"]
        assert driver["name"] == "pipelinedp-tpu-lint"
        rule_ids = [r["id"] for r in driver["rules"]]
        assert "DPL007" in rule_ids
        for rule in driver["rules"]:
            assert rule["shortDescription"]["text"]
        assert run["results"], "findings expected"
        for res in run["results"]:
            assert res["ruleId"] == driver["rules"][res["ruleIndex"]]["id"]
            assert res["level"] == "error"
            assert res["message"]["text"]
            region = res["locations"][0]["physicalLocation"]["region"]
            assert region["startLine"] >= 1
            assert region["startColumn"] >= 1

    def test_forbid_suppressions_reports_suppressed(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text(
            "def f(run):\n"
            "    return run(eps=-1.0)"
            "  # dplint: disable=DPL005 — justified\n")
        assert lint_main(["mod.py", "--no-baseline"]) == 0
        assert lint_main(["mod.py", "--no-baseline",
                          "--forbid-suppressions"]) == 1

    def test_changed_only_clean_when_nothing_changed(self, tmp_path,
                                                     monkeypatch, capsys):
        import subprocess
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text(
            "def f(run):\n    return run(eps=-1.0)\n")
        env = dict(os.environ,
                   GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
        for cmd in (["git", "init", "-q"], ["git", "add", "."],
                    ["git", "commit", "-qm", "seed"]):
            subprocess.run(cmd, cwd=tmp_path, env=env, check=True,
                           capture_output=True)
        # Committed violation, nothing changed: the fast gate passes.
        assert lint_main(["mod.py", "--changed-only"]) == 0
        # Touch the file: the violation is now in the changed set.
        (tmp_path / "mod.py").write_text(
            "def f(run):\n    return run(eps=-1.0)  # touched\n")
        assert lint_main(["mod.py", "--changed-only",
                          "--no-baseline"]) == 1

    def test_module_entry_point_subprocess(self):
        """Acceptance: `python -m pipelinedp_tpu.lint` exits 0 on the
        shipped tree and nonzero on a violating fixture."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        clean = subprocess.run(
            [sys.executable, "-m", "pipelinedp_tpu.lint",
             "pipelinedp_tpu"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=300)
        assert clean.returncode == 0, clean.stdout + clean.stderr
        bad = subprocess.run(
            [sys.executable, "-m", "pipelinedp_tpu.lint",
             fixture("dpl004_bad.py"), "--no-baseline"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=300)
        assert bad.returncode == 1, bad.stdout + bad.stderr
        assert "DPL004" in bad.stdout


class TestEngineInternals:

    def test_module_name_anchors_at_package(self):
        assert lint_engine.module_name(
            "pipelinedp_tpu/ops/noise.py") == "pipelinedp_tpu.ops.noise"
        assert lint_engine.module_name(
            "src/pipelinedp_tpu/lint/__init__.py") == "pipelinedp_tpu.lint"
        assert lint_engine.module_name(
            "tests/fixtures/lint/dpl001_bad.py") == \
            "tests.fixtures.lint.dpl001_bad"

    def test_finding_format(self):
        f = lint_engine.Finding("DPL001", "a/b.py", 3, 7, "msg", "do this")
        assert f.format() == "a/b.py:3:7: DPL001 msg"
        assert "hint: do this" in f.format(verbose=True)

    def test_parse_error_reported_not_crashing(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n")
        result = lint_paths([str(bad)], root=str(tmp_path))
        assert len(result.parse_errors) == 1
        assert result.parse_errors[0].rule_id == "DPL000"


class TestProductionTreeGate:
    """The CI lint job: a new DPL violation in pipelinedp_tpu/ fails here."""

    def test_production_tree_is_clean(self):
        result = lint_paths([PACKAGE], root=REPO_ROOT)
        assert result.parse_errors == []
        assert result.findings == [], "\n".join(
            f.format(verbose=True) for f in result.findings)

    def test_shipped_baseline_is_empty(self):
        baseline = lint_engine.load_baseline(
            os.path.join(REPO_ROOT, "dplint-baseline.json"))
        assert sum(baseline.values()) == 0
