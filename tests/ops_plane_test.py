"""Unit tests for the PR-13 operational plane: the flight recorder
(ring/spool/dump/captures), the ops HTTP endpoints, and the
self-diagnosing hang errors — everything that doesn't need an engine
(the serving integration matrix lives in tests/obs_serving_test.py,
the SIGKILL leg in tests/process_kill_test.py)."""

import json
import os
import threading
import urllib.error
import urllib.request

import pytest

from pipelinedp_tpu.obs import flight as flight_lib
from pipelinedp_tpu.obs import metrics as metrics_lib
from pipelinedp_tpu.obs import ops_plane
from pipelinedp_tpu.runtime import watchdog as watchdog_lib


# ---------------------------------------------------------------------------
# FlightRecorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:

    def test_ring_is_bounded_newest_win(self):
        rec = flight_lib.FlightRecorder(max_events=4)
        for i in range(10):
            rec.record("e", i=i)
        events = rec.events()
        assert len(events) == 4
        assert [e.attrs["i"] for e in events] == [6, 7, 8, 9]
        # seq keeps counting past evictions (watermark semantics).
        assert rec.watermark() == 10

    def test_payload_gate_refuses_private_shapes(self):
        rec = flight_lib.FlightRecorder(max_events=8)
        with pytest.raises(metrics_lib.TelemetryLeakError):
            rec.record("bad", pid=123)
        with pytest.raises(metrics_lib.TelemetryLeakError):
            rec.record("bad", rows=[1, 2, 3])
        assert rec.events() == []

    def test_since_seq_slicing(self):
        rec = flight_lib.FlightRecorder(max_events=16)
        rec.record("a")
        mark = rec.watermark()
        rec.record("b")
        rec.record("c")
        assert [e.kind for e in rec.events(since_seq=mark)] == ["b", "c"]

    def test_dump_roundtrip_and_atomicity(self, tmp_path):
        rec = flight_lib.FlightRecorder(max_events=8)
        rec.record("x", n=1)
        path = rec.dump(str(tmp_path / "f.json"), reason="test")
        doc = flight_lib.read_dump(path)
        assert doc["reason"] == "test"
        assert doc["process_id"] == os.getpid()
        assert [e["kind"] for e in doc["events"]] == ["x"]
        # No stray tmp files: the write is tmp+rename.
        assert [p.name for p in tmp_path.iterdir()] == ["f.json"]

    def test_dump_without_destination_is_none(self):
        rec = flight_lib.FlightRecorder(max_events=8)
        assert rec.dump(reason="nowhere") is None

    def test_spool_survives_torn_tail(self, tmp_path):
        rec = flight_lib.FlightRecorder(max_events=8)
        spool = rec.bind_spool(str(tmp_path / "s.jsonl"))
        rec.record("one", n=1)
        rec.record("two", n=2)
        with open(spool, "a") as f:
            f.write('{"kind":"torn-mid-wri')  # the kill point
        doc = flight_lib.read_dump(spool)
        assert [e["kind"] for e in doc["events"]] == ["one", "two"]

    def test_spool_interior_corruption_refused(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        path.write_text('garbage\n{"kind":"late","seq":1}\n')
        with pytest.raises(flight_lib.FlightDumpError):
            flight_lib.read_dump(str(path))

    def test_concurrent_records_all_land(self):
        rec = flight_lib.FlightRecorder(max_events=10_000)
        def worker(t):
            for i in range(200):
                rec.record("hammer", t=t, i=i)
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = rec.events()
        assert len(events) == 1600
        assert len({e.seq for e in events}) == 1600

    def test_postmortem_names_recent_events(self, tmp_path):
        rec = flight_lib.FlightRecorder(max_events=8)
        rec.record("retry")
        rec.record("watchdog_timeout")
        text = rec.postmortem("/some/dump.json")
        assert "retry" in text and "watchdog_timeout" in text
        assert "/some/dump.json" in text


class TestSpoolRotation:
    """The size-capped spool (ISSUE 15 satellite): an always-on
    recorder must hold a bounded recent-history window on disk, not
    grow without bound next to the WALs."""

    def _recorder(self, tmp_path, monkeypatch, budget, segments):
        monkeypatch.setenv(flight_lib.SPOOL_BYTES_ENV, str(budget))
        monkeypatch.setenv(flight_lib.SPOOL_SEGMENTS_ENV, str(segments))
        rec = flight_lib.FlightRecorder(max_events=64)
        path = rec.bind_spool(str(tmp_path / "s.jsonl"))
        return rec, path

    def test_rotation_keeps_last_k_segments(self, tmp_path, monkeypatch):
        rec, path = self._recorder(tmp_path, monkeypatch,
                                   budget=8192, segments=3)
        for i in range(400):
            rec.record("tick", i=i, pad="x" * 64)
        segs = flight_lib.spool_segment_paths(path)
        # Exactly the configured chain: .2 (oldest), .1, active.
        assert [os.path.basename(p) for p in segs] == \
            ["s.jsonl.2", "s.jsonl.1", "s.jsonl"]
        assert not os.path.exists(path + ".3")
        # Total disk stays in the cap's neighborhood: each segment
        # rotates at max(4096, budget/K) bytes (the floor keeps a
        # pathological budget from thrashing), overshooting by at most
        # one event line.
        per_segment = max(4096, 8192 // 3)
        total = sum(os.path.getsize(p) for p in segs)
        assert total <= 3 * (per_segment + 256)

    def test_read_spool_is_one_ordered_stream(self, tmp_path,
                                              monkeypatch):
        rec, path = self._recorder(tmp_path, monkeypatch,
                                   budget=8192, segments=3)
        for i in range(400):
            rec.record("tick", i=i, pad="x" * 64)
        doc = flight_lib.read_spool(path)
        seqs = [e["seq"] for e in doc["events"]]
        # Oldest-first across segments, contiguous, newest retained.
        assert seqs == list(range(seqs[0], 400))

    def test_torn_tail_tolerated_per_segment(self, tmp_path,
                                             monkeypatch):
        rec, path = self._recorder(tmp_path, monkeypatch,
                                   budget=8192, segments=3)
        for i in range(400):
            rec.record("tick", i=i, pad="x" * 64)
        # A line torn by a kill just before rotation stays torn in the
        # rotated segment; reading tolerates it in EVERY segment.
        with open(path + ".1", "a") as f:
            f.write('{"kind":"torn-mid')
        n = len(flight_lib.read_spool(path)["events"])
        assert n > 0
        # Interior corruption is still refused, per segment.
        with open(path + ".2", "r+") as f:
            f.write("garbage")
        with pytest.raises(flight_lib.FlightDumpError):
            flight_lib.read_spool(path)

    def test_rebind_resumes_byte_counter(self, tmp_path, monkeypatch):
        rec, path = self._recorder(tmp_path, monkeypatch,
                                   budget=8192, segments=2)
        rec.record("tick", pad="x" * 64)
        rec.close_spool()
        rec2 = flight_lib.FlightRecorder(max_events=64)
        rec2.bind_spool(path)
        # The restarted process picks up mid-segment, not at zero: the
        # rotation point lands where it would have without the restart.
        assert rec2._spool_bytes == os.path.getsize(path)

    def test_single_segment_truncates_in_place(self, tmp_path,
                                               monkeypatch):
        rec, path = self._recorder(tmp_path, monkeypatch,
                                   budget=4096, segments=1)
        for i in range(200):
            rec.record("tick", i=i, pad="x" * 64)
        assert flight_lib.spool_segment_paths(path) == [path]
        assert os.path.getsize(path) <= 4096 + 1024

    def test_env_knob_validation(self, monkeypatch):
        monkeypatch.delenv(flight_lib.SPOOL_BYTES_ENV, raising=False)
        monkeypatch.delenv(flight_lib.SPOOL_SEGMENTS_ENV, raising=False)
        assert flight_lib.spool_byte_budget() == 64 << 20
        assert flight_lib.spool_segment_count() == 4
        monkeypatch.setenv(flight_lib.SPOOL_BYTES_ENV, "12")
        with pytest.raises(ValueError):
            flight_lib.spool_byte_budget()
        monkeypatch.setenv(flight_lib.SPOOL_SEGMENTS_ENV, "0")
        with pytest.raises(ValueError):
            flight_lib.spool_segment_count()


class TestSlowQueryCaptures:

    def test_capture_written_and_pruned(self, tmp_path, monkeypatch):
        d = str(tmp_path / "cap")
        monkeypatch.setenv(flight_lib.CAPTURE_DIR_ENV, d)
        monkeypatch.setenv(flight_lib.CAPTURE_LIMIT_ENV, "3")
        paths = []
        for i in range(6):
            p = flight_lib.write_capture(f"q-{i}", {"trace_id": f"q-{i}"})
            paths.append(p)
            os.utime(p, (i, i))  # deterministic mtime order
        kept = sorted(os.listdir(d))
        assert len(kept) == 3
        assert kept == ["slowquery_q-3.json", "slowquery_q-4.json",
                        "slowquery_q-5.json"]
        assert json.load(open(paths[-1]))["trace_id"] == "q-5"

    def test_capture_disabled_without_dir(self, monkeypatch):
        monkeypatch.delenv(flight_lib.CAPTURE_DIR_ENV, raising=False)
        assert flight_lib.write_capture("q", {"a": 1}) is None

    def test_slow_query_env_validation(self, monkeypatch):
        monkeypatch.delenv(flight_lib.SLOW_QUERY_ENV, raising=False)
        assert flight_lib.slow_query_threshold_s() is None
        monkeypatch.setenv(flight_lib.SLOW_QUERY_ENV, "0")
        assert flight_lib.slow_query_threshold_s() is None
        monkeypatch.setenv(flight_lib.SLOW_QUERY_ENV, "1.5")
        assert flight_lib.slow_query_threshold_s() == 1.5
        monkeypatch.setenv(flight_lib.SLOW_QUERY_ENV, "junk")
        with pytest.raises(ValueError):
            flight_lib.slow_query_threshold_s()


# ---------------------------------------------------------------------------
# Self-diagnosing hang errors (satellite: dump path + last events in
# the message)
# ---------------------------------------------------------------------------


class TestSelfDiagnosingHangErrors:

    def test_watchdog_timeout_message_carries_postmortem(self, tmp_path):
        flight_lib.recorder().set_dump_dir(str(tmp_path))
        flight_lib.record("pre_hang_marker_event")
        wd = watchdog_lib.DispatchWatchdog(0.05)
        hang = threading.Event()
        try:
            with pytest.raises(watchdog_lib.DispatchHangError) as exc_info:
                wd.call("test op", lambda: hang.wait(5))
        finally:
            hang.set()
            wd.close()
        msg = str(exc_info.value)
        assert "flight recorder" in msg
        assert "pre_hang_marker_event" in exc_info.value.postmortem
        # The dump landed and parses.
        dump_path = os.path.join(str(tmp_path),
                                 f"flight_{os.getpid()}.json")
        assert os.path.exists(dump_path)
        doc = flight_lib.read_dump(dump_path)
        assert doc["reason"] == "watchdog_timeout"
        assert "watchdog_timeout" in [e["kind"] for e in doc["events"]]

    def test_deadline_error_message_carries_postmortem(self):
        deadline = watchdog_lib.Deadline.after(-1.0)  # already expired
        with pytest.raises(watchdog_lib.QueryDeadlineError) as exc_info:
            deadline.check("slab window at chunk 3")
        assert "flight recorder" in str(exc_info.value)
        assert exc_info.value.postmortem


# ---------------------------------------------------------------------------
# Ops endpoints
# ---------------------------------------------------------------------------


class _FakeSession:
    """A stats()-shaped stand-in so endpoint tests need no engine."""

    name = "fake"
    store_binding = None

    def stats(self):
        return {
            "wire_host_bytes": 1000, "wire_device_bytes": 0,
            "bound_cache_bytes": 10, "bound_cache_entries": 1,
            "resident_bytes": 1010, "byte_budget": 1 << 20,
            "queries": 3, "n_chunks": 2, "spilled": False,
            "active_queries": 0, "store": None,
            "tenants": {"acme": {"total_epsilon": 4.0,
                                 "spent_epsilon": 1.0,
                                 "remaining_epsilon": 3.0,
                                 "total_delta": 1e-3,
                                 "spent_delta": 1e-6,
                                 "releases": 1}},
        }


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode(), r.headers.get("Content-Type")


class TestOpsEndpoints:

    @pytest.fixture
    def server(self):
        with ops_plane.serve_ops(_FakeSession(), port=0) as srv:
            yield srv

    def test_metrics_is_prometheus_text(self, server):
        metrics_lib.default_registry().event_inc("ops_test/ping")
        status, body, ctype = _get(server.url + "/metrics")
        assert status == 200
        assert ctype.startswith("text/plain")
        assert "pipelinedp_tpu_events_total" in body

    def test_statusz_shape(self, server):
        status, body, _ = _get(server.url + "/statusz")
        assert status == 200
        doc = json.loads(body)
        assert doc["kind"] == "session"
        sess = doc["sessions"]["fake"]
        assert sess["residency"] == "host"
        acme = sess["tenants"]["acme"]
        assert acme["epsilon_burn_pct"] == 25.0
        assert "counters" in doc
        assert "bound_cache_hit_rate" in doc["counters"]

    def test_healthz_ok(self, server):
        status, body, _ = _get(server.url + "/healthz")
        assert status == 200
        doc = json.loads(body)
        assert doc["status"] == "ok"
        assert doc["checks"]["sessions_resident"] == 1
        assert doc["checks"]["sessions_spilled"] == 0
        assert "watchdog" in doc["checks"]

    def test_flightz_serves_recent_events(self, server):
        flight_lib.record("flightz_probe_event")
        status, body, _ = _get(server.url + "/debug/flightz")
        assert status == 200
        doc = json.loads(body)
        assert "flightz_probe_event" in [e["kind"] for e in doc["events"]]

    def test_unknown_path_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _get(server.url + "/nope")
        assert exc_info.value.code == 404

    def test_ephemeral_port_and_close(self):
        srv = ops_plane.serve_ops(_FakeSession(), port=0)
        port = srv.port
        assert port > 0
        srv.close()
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                   timeout=2)

    def test_env_port_validation(self, monkeypatch):
        monkeypatch.delenv(ops_plane.OPS_PORT_ENV, raising=False)
        assert ops_plane.env_ops_port() is None
        monkeypatch.setenv(ops_plane.OPS_PORT_ENV, "0")
        assert ops_plane.env_ops_port() is None
        monkeypatch.setenv(ops_plane.OPS_PORT_ENV, "8123")
        assert ops_plane.env_ops_port() == 8123
        monkeypatch.setenv(ops_plane.OPS_PORT_ENV, "junk")
        with pytest.raises(ValueError):
            ops_plane.env_ops_port()
