"""Query-planner unit tests (ISSUE 17): dedupe / fusion / cache-skip
decisions as pure plan objects — no session, no device, no launches."""

import pytest

from pipelinedp_tpu.serving import planner

F_ALL = (True, True, True, True)
F_CNT = (True, False, False, False)
F_SUM = (False, True, False, False)


def entry(i, bound_key, fusion_key="fk", need_flags=F_CNT, cached=False):
    return planner.PlanEntry(index=i, bound_key=bound_key,
                             fusion_key=fusion_key, need_flags=need_flags,
                             cached=cached)


class TestAdmission:

    def test_cached_entries_skip_replay(self):
        plan = planner.compile_plan(
            [entry(0, "a", cached=True), entry(1, "b")], max_width=8)
        assert plan.cached_indexes == (0,)
        assert plan.n_lanes == 1
        assert plan.stats["cache_skips"] == 1
        assert plan.stats["lanes"] == 1

    def test_all_cached_means_no_groups(self):
        plan = planner.compile_plan(
            [entry(i, str(i), cached=True) for i in range(3)], max_width=8)
        assert plan.groups == ()
        assert plan.cached_indexes == (0, 1, 2)

    def test_empty_batch(self):
        plan = planner.compile_plan([], max_width=8)
        assert plan.groups == () and plan.cached_indexes == ()


class TestDedupe:

    def test_identical_bound_keys_share_one_lane(self):
        plan = planner.compile_plan(
            [entry(0, "a"), entry(1, "a"), entry(2, "b"), entry(3, "a")],
            max_width=8)
        assert plan.stats["dedupes"] == 2
        assert plan.n_lanes == 2
        (group,) = plan.groups
        assert group.lanes[0].owner == 0
        assert group.lanes[0].followers == (1, 3)
        assert group.lanes[1].indexes == (2,)

    def test_none_bound_key_never_dedupes(self):
        plan = planner.compile_plan(
            [entry(0, None), entry(1, None)], max_width=8)
        assert plan.stats["dedupes"] == 0
        assert plan.n_lanes == 2

    def test_duplicate_indexes_refused(self):
        with pytest.raises(ValueError, match="duplicate entry indexes"):
            planner.compile_plan([entry(0, "a"), entry(0, "b")],
                                 max_width=8)


class TestFusion:

    def test_distinct_fusion_keys_split_groups(self):
        plan = planner.compile_plan(
            [entry(0, "a", fusion_key="x"), entry(1, "b", fusion_key="y"),
             entry(2, "c", fusion_key="x")], max_width=8)
        assert plan.stats["fused_groups"] == 2
        by_key = {g.fusion_key: g for g in plan.groups}
        assert [l.owner for l in by_key["x"].lanes] == [0, 2]
        assert [l.owner for l in by_key["y"].lanes] == [1]

    def test_max_width_splits_within_fusion_key(self):
        plan = planner.compile_plan(
            [entry(i, str(i)) for i in range(5)], max_width=2)
        assert plan.stats["fused_groups"] == 3
        assert [len(g.lanes) for g in plan.groups] == [2, 2, 1]

    def test_union_flags_cover_all_members_including_followers(self):
        # The follower (index 2) needs SUM; the union must include it
        # even though lane owners only need COUNT.
        plan = planner.compile_plan(
            [entry(0, "a", need_flags=F_CNT),
             entry(1, "b", need_flags=F_CNT),
             entry(2, "a", need_flags=F_SUM)], max_width=8)
        (group,) = plan.groups
        assert group.union_flags == (True, True, False, False)

    def test_max_width_below_one_refused(self):
        with pytest.raises(ValueError, match="max_width"):
            planner.compile_plan([entry(0, "a")], max_width=0)


class TestFlagsExact:
    """Only lanes whose own need_flags equal the group union may
    populate the bound cache — a solo replay of that config would have
    produced exactly those columns."""

    def test_exact_lane_marked(self):
        plan = planner.compile_plan(
            [entry(0, "a", need_flags=F_ALL),
             entry(1, "b", need_flags=F_CNT)], max_width=8)
        (group,) = plan.groups
        assert group.union_flags == F_ALL
        assert group.flags_exact == (True, False)

    def test_none_bound_key_never_cacheable(self):
        plan = planner.compile_plan(
            [entry(0, None, need_flags=F_CNT)], max_width=8)
        (group,) = plan.groups
        assert group.flags_exact == (False,)

    def test_homogeneous_group_all_exact(self):
        plan = planner.compile_plan(
            [entry(i, str(i), need_flags=F_CNT) for i in range(3)],
            max_width=8)
        (group,) = plan.groups
        assert group.flags_exact == (True, True, True)


class TestStats:

    def test_stats_account_for_every_config(self):
        plan = planner.compile_plan(
            [entry(0, "a", cached=True), entry(1, "b"), entry(2, "b"),
             entry(3, "c", fusion_key="other")], max_width=8)
        st = plan.stats
        assert st == {"configs": 4, "cache_skips": 1, "dedupes": 1,
                      "lanes": 2, "fused_groups": 2}
        routed = len(plan.cached_indexes) + sum(
            len(l.indexes) for g in plan.groups for l in g.lanes)
        assert routed == st["configs"]
