"""dplint fixture — DPL013 clean: payload -> record -> fold.

``wal`` is a runtime.journal.JsonlWal (serving/live.py append shape).
"""

import os
import tempfile


class LiveSession:

    def __init__(self, wal, root):
        self._wal = wal
        self._root = root
        self._epochs = []

    def _save_epoch(self, epoch_id, payload):
        fd, tmp = tempfile.mkstemp(dir=self._root, suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._root, f"{epoch_id}.bin"))

    def append(self, epoch_id, payload):
        self._save_epoch(epoch_id, payload)
        self._wal.append({"epoch": epoch_id})
        self._epochs.append(epoch_id)
