"""dplint fixture — DPL009 violations: noise drawn before the commit.

``spec`` is a resolved budget_accounting.MechanismSpec; the journal is a
runtime.ReleaseJournal. The commit must precede every draw so a crash
lands on the zero-release side (RESILIENCE.md).
"""

from pipelinedp_tpu import noise_core


def release_after_draw(journal, token, totals, spec):
    noised = noise_core.add_laplace_noise_array(totals, 1.0 / spec.eps)
    journal.commit(token)
    return noised


def _draw(totals, spec):
    return noise_core.add_gaussian_noise_array(totals, spec.std)


def release_via_helper(journal, token, totals, spec):
    noised = _draw(totals, spec)
    journal.commit(token)
    return noised
