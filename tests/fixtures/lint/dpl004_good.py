"""dplint fixture — DPL004 clean: secure sampler + CSPRNG seed material."""

import secrets

from pipelinedp_tpu import noise_core


def secure_noise(spec, l1_sensitivity, size):
    """``spec`` is a resolved budget_accounting.MechanismSpec."""
    return noise_core.sample_laplace(l1_sensitivity / spec.eps, size)


def secure_uniform():
    return noise_core.sample_uniform()


def secure_seed():
    return secrets.randbits(31)
