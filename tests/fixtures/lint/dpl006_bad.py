"""dplint fixture — DPL006 violation: jnp.float64 with no x64 guard."""

import jax.numpy as jnp


def unguarded(values):
    # Silently float32 unless 64-bit mode was turned on at process start.
    return jnp.asarray(values, dtype=jnp.float64)
