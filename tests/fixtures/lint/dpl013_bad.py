"""dplint fixture — DPL013 violations: effects on the wrong side of the
WAL record.

``wal`` is a runtime.journal.JsonlWal; the append transaction must run
payload -> record -> fold (serving/live.py, RESILIENCE.md).
"""

import os
import tempfile


class LiveSession:

    def __init__(self, wal, root):
        self._wal = wal
        self._root = root
        self._epochs = []

    def _save_epoch(self, epoch_id, payload):
        fd, tmp = tempfile.mkstemp(dir=self._root, suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._root, f"{epoch_id}.bin"))

    def append_record_first(self, epoch_id, payload):
        self._wal.append({"epoch": epoch_id})
        self._save_epoch(epoch_id, payload)
        self._epochs.append(epoch_id)

    def fold_before_commit(self, epoch_id, payload):
        self._save_epoch(epoch_id, payload)
        self._epochs.append(epoch_id)
        self._wal.append({"epoch": epoch_id})
