"""dplint fixture — DPL008 clean: locked writes + adopt_sinks handoff."""

import concurrent.futures
import threading

from pipelinedp_tpu import profiler


def locked_pipeline(stats, results):
    lock = threading.Lock()
    parent_sinks = profiler.current_sinks()

    def worker(i):
        with profiler.adopt_sinks(parent_sinks):
            payload = i * 2
        with lock:
            stats["chunks"] = stats.get("chunks", 0) + 1
            results.append(payload)
        return payload

    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        futures = [pool.submit(worker, i) for i in range(4)]
        done = [f.result() for f in futures]
    stats["total"] = len(done)
    return results
