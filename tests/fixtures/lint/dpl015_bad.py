"""dplint fixture — DPL015 violations: nondeterminism on the release
path.

``spec`` is a resolved budget_accounting.MechanismSpec; releases must
be a pure function of (data, params, seed).
"""

import os
import time

import jax.numpy as jnp

from pipelinedp_tpu import noise_core


def release_with_clock_seed(totals, spec):
    seed = int(time.time())
    return noise_core.add_laplace_noise_array(totals, 1.0 / spec.eps), seed


def release_in_listdir_order(root, totals, spec):
    names = []
    for name in os.listdir(root):
        names.append(name)
    return names, noise_core.add_gaussian_noise_array(totals, spec.std)


def release_after_eager_clip(totals, spec):
    clipped = jnp.maximum(totals, 0.0)
    return noise_core.add_laplace_noise_array(clipped, 1.0 / spec.eps)
