"""dplint fixture — DPL002 violation: noise with no MechanismSpec."""

import numpy as np

from pipelinedp_tpu import noise_core


def leak_count(values):
    # The scale is invented locally; no budget was ever requested.
    return noise_core.add_laplace_noise_array(np.asarray(values), 1.0)
