"""dplint fixture — DPL006 clean: guarded jnp.float64, host np.float64."""

import jax
import jax.numpy as jnp
import numpy as np


def guarded(values):
    assert jax.config.x64_enabled, "requires jax_enable_x64"
    return jnp.asarray(values, dtype=jnp.float64)


def host_f64(values):
    # Host-side float64 (the secure finalization path) needs no guard.
    return np.asarray(values, dtype=np.float64)
