"""dplint fixture — DPL014 clean: one global lock order, fsync outside
the critical section.
"""

import os
import threading

manager_lock = threading.Lock()
store_lock = threading.Lock()


def admit_then_save(session):
    with manager_lock:
        with store_lock:
            session.save()


def save_more(session):
    with manager_lock:
        with store_lock:
            session.admit()


def flush_outside_lock(fd):
    with store_lock:
        pending = True
    if pending:
        os.fsync(fd)
