"""dplint fixture — DPL009 clean: the journal commit precedes the draw.

``spec`` is a resolved budget_accounting.MechanismSpec; the journal is a
runtime.ReleaseJournal.
"""

from pipelinedp_tpu import noise_core


def release_with_commit_first(journal, token, totals, spec):
    journal.commit(token)
    noised = noise_core.add_laplace_noise_array(totals, 1.0 / spec.eps)
    return noised
