"""dplint fixture — DPL014 violations: a reversed lock pair and an
fsync under a lock (the serving manager/store shape).
"""

import os
import threading

manager_lock = threading.Lock()
store_lock = threading.Lock()


def admit_then_save(session):
    with manager_lock:
        with store_lock:
            session.save()


def save_then_admit(session):
    with store_lock:
        with manager_lock:
            session.admit()


def flush_under_lock(fd):
    with store_lock:
        os.fsync(fd)
