"""dplint fixture — DPL012 clean: the tmp+fsync+rename idiom.

``store_dir`` is a serving store root (serving/store.py).
"""

import json
import os
import tempfile


def publish_manifest(store_dir, manifest):
    path = os.path.join(store_dir, "manifest.json")
    fd, tmp = tempfile.mkstemp(dir=store_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
