"""dplint fixture — DPL007 clean: bounded + noised before the host sync.

``spec`` is a resolved budget_accounting.MechanismSpec (the noise scale
derives from the accountant, satisfying DPL002 as well).
"""

import jax
import numpy as np

from pipelinedp_tpu import noise_core
from pipelinedp_tpu.ops import columnar


def released_metrics(key, pid, pk, value, spec, n):
    accs = columnar.bound_and_aggregate(key, pid, pk, value,
                                        num_partitions=n)
    noised = noise_core.add_laplace_noise_array(accs, 1.0 / spec.eps)
    return jax.device_get(noised)


def host_shape_only(value):
    # Shape metadata never materializes the column itself.
    return np.asarray(value).shape
