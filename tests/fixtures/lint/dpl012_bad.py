"""dplint fixture — DPL012 violations: durable writes that bypass the
tmp+fsync+rename idiom.

``store_dir`` is a serving store root (serving/store.py); everything
under it is read back by crash recovery, so torn files are trusted.
"""

import json
import os
import tempfile


def write_manifest(store_dir, manifest):
    path = os.path.join(store_dir, "manifest.json")
    with open(path, "w") as f:
        json.dump(manifest, f)


def publish_snapshot(store_dir, payload):
    fd, tmp = tempfile.mkstemp(dir=store_dir)
    with os.fdopen(fd, "wb") as f:
        f.write(payload)
    os.replace(tmp, os.path.join(store_dir, "snapshot.bin"))
