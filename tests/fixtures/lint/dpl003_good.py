"""dplint fixture — DPL003 clean: static branching, jnp ops, local jit."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("n", "mode"))
def static_branch(x, n, mode):
    if mode == "scaled" and n > 2:  # static args: trace-time dispatch is
        return x * n                # exactly what static_argnames is for
    return jnp.where(x > 0, x, -x)


@functools.partial(jax.jit, static_argnames=("size",))
def static_host_math(x, size):
    pad = np.zeros(size)  # np on a *static* value: computed at trace time
    return jnp.concatenate([x, jnp.asarray(pad)])


def make_kernel():
    def fn(x, threshold):
        if threshold is None:  # `is None` checks are trace-safe
            return jnp.maximum(x, 0.0)
        return jnp.minimum(x, threshold)

    return jax.jit(fn)
