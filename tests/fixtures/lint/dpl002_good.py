"""dplint fixture — DPL002 clean: noise calibrated from a MechanismSpec."""

import numpy as np

from pipelinedp_tpu import noise_core


def noised_count(values, spec, l1_sensitivity):
    """``spec`` is a resolved budget_accounting.MechanismSpec."""
    scale = l1_sensitivity / spec.eps
    return noise_core.add_laplace_noise_array(np.asarray(values), scale)
