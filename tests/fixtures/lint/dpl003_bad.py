"""dplint fixture — DPL003 violations: jit-hostile constructs."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def host_sync(x):
    return x.sum().item()  # forces a device sync; fails under jit


@functools.partial(jax.jit, static_argnames=("n",))
def trace_branch(x, n):
    if x > 0:  # x is traced: branch freezes at trace time
        return x * n
    return -x


@jax.jit
def numpy_on_traced(x):
    return jnp.asarray(np.clip(x, 0.0, 1.0))  # np on a tracer


@jax.jit
def concretize(x):
    return float(x) * 2.0
