"""dplint fixture — DPL008 violations: unlocked pool-shared writes."""

import concurrent.futures


def racy_pipeline(stats, results, state):

    def worker(i):
        stats["chunks"] = stats.get("chunks", 0) + 1
        results.append(i)
        state.cursor = i

    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        for i in range(4):
            pool.submit(worker, i)
    stats["total"] = len(results)
    return state.cursor
