"""dplint fixture — DPL001 clean: one consumption per derived key.

Uses uniform/bits draws (not laplace/normal) so the module stays out of
DPL002's scope — this fixture exercises key discipline only.
"""

import jax


def split_draw(key, shape):
    k1, k2 = jax.random.split(key)
    a = jax.random.uniform(k1, shape)
    b = jax.random.bits(k2, shape)
    return a, b


def branch_draw(key, shape, low_bits):
    if low_bits:
        return jax.random.bits(key, shape)
    return jax.random.uniform(key, shape)


def loop_fold(key, n):
    out = []
    for i in range(n):
        sub_key = jax.random.fold_in(key, i)
        out.append(jax.random.uniform(sub_key, ()))
    return out


def rederive_between_draws(key, shape):
    a = jax.random.uniform(key, shape)
    key = jax.random.fold_in(key, 1)
    b = jax.random.uniform(key, shape)
    return a + b
