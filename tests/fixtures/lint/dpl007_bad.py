"""dplint fixture — DPL007 violations: private columns reach the host."""

import jax
import numpy as np

from pipelinedp_tpu.ops import columnar


def leak_raw_column(value):
    # Raw private values synced to host: no bounding, no noise.
    return jax.device_get(value)


def _host_rows(values):
    return values.tolist()


def leak_via_helper(pid, n):
    totals = np.bincount(pid, minlength=n)
    return _host_rows(totals)


def leak_bounded_only(key, pid, pk, value, n):
    accs = columnar.bound_and_aggregate(key, pid, pk, value,
                                        num_partitions=n)
    # Bounded but un-noised aggregates are still a raw statistic.
    return jax.device_get(accs)
