"""dplint fixture — DPL011 violations: private data enters telemetry."""

import numpy as np

from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.ops import columnar


def leak_span_attribute(pid):
    # A raw privacy-id column attached to a span attribute.
    with obs_trace.span("serving/query", first_pid=pid[0]):
        return None


def _record_metric(histogram, values):
    histogram.observe(values)


def leak_via_helper(histogram, value):
    scaled = np.abs(value)
    return _record_metric(histogram, scaled)


def leak_bounded_only(key, pid, pk, value, n, span):
    accs = columnar.bound_and_aggregate(key, pid, pk, value,
                                        num_partitions=n)
    # Bounded but PRE-NOISE: still unreleased — telemetry may only
    # carry fully released statistics.
    span.set_attribute("partition_total", accs)


def leak_audit_field(audit, pk):
    audit.record(partition_keys=pk)
