"""dplint fixture — DPL004 violations: insecure RNG on the release path."""

import random

import numpy as np


def insecure_noise(scale, size):
    return np.random.laplace(0.0, scale, size)


def insecure_seed():
    return np.random.default_rng().integers(0, 2**31)


def insecure_uniform():
    return random.random()
