"""dplint fixture — DPL005 violations: bad eps/delta literals, hand splits."""


def invalid_literals(run_query):
    return run_query(eps=-1.0, delta=1.5)


def zero_epsilon(run_query):
    return run_query(eps=0)


def manual_split(eps, delta, run_query):
    # Budget shares belong to the accountant, not inline arithmetic.
    return run_query(eps=eps / 2, delta=0.5 * delta)
