"""dplint fixture — DPL015 clean: sorted iteration and seed-derived
randomness on the release path.

``spec`` is a resolved budget_accounting.MechanismSpec.
"""

from pipelinedp_tpu import noise_core


def release_in_sorted_order(vocab, totals, spec):
    names = []
    for name in sorted(vocab):
        names.append(name)
    return names, noise_core.add_laplace_noise_array(totals, 1.0 / spec.eps)
