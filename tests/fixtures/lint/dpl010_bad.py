"""dplint fixture — DPL010 violations: donated operands read again."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def step(accs, delta):
    return accs + delta


def double_count(accs, delta):
    out = step(accs, delta)
    # `accs` was donated into step: this read double-counts the buffer.
    return out + accs


def loop_without_rebind(accs, deltas):
    out = None
    for d in deltas:
        out = step(accs, d)
    return out


def poisoned_exception_path(accs, delta):
    try:
        accs = step(accs, delta)
    except RuntimeError:
        # The raise can land after the donation consumed the buffer but
        # before the rebinding assignment took effect.
        return jnp.sum(accs)
    return accs
