"""dplint fixture — DPL001 violations: PRNG key reuse.

Uses uniform draws (not laplace/normal) so the module stays out of
DPL002's scope — this fixture exercises key discipline only.
"""

import jax


def double_draw(key, shape):
    a = jax.random.uniform(key, shape)
    b = jax.random.uniform(key, shape)  # second draw from the same key
    return a + b


def loop_draw(key, n):
    out = []
    for _ in range(n):
        out.append(jax.random.uniform(key, ()))  # same key every iteration
    return out


def handoff_twice(key, values, kernel_a, kernel_b):
    k_init = jax.random.fold_in(key, 0)
    masked = kernel_a(k_init, values)
    return kernel_b(k_init, masked)  # both callees sample the same stream
