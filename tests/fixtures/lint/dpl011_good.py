"""dplint fixture — DPL011 clean: telemetry carries operational
aggregates and fully released statistics only.

``spec`` is a resolved budget_accounting.MechanismSpec.
"""

import time

from pipelinedp_tpu import noise_core
from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.ops import columnar


def record_released_stat(key, pid, pk, value, spec, n, span):
    accs = columnar.bound_and_aggregate(key, pid, pk, value,
                                        num_partitions=n)
    # Bounded AND noised: a released statistic may enter telemetry.
    noised = noise_core.add_laplace_noise_array(accs, 1.0 / spec.eps)
    span.set_attribute("released_total", float(noised))


def record_operational_metrics(histogram, n_chunks):
    # Timings and structural counts are operational, not private.
    t0 = time.perf_counter()
    with obs_trace.span("driver/window", chunk0=0, chunk1=n_chunks):
        pass
    histogram.observe(time.perf_counter() - t0)


def record_row_count_metadata(n_rows, span):
    # Plain operational scalars (row counts arriving as config, not
    # derived from a private column) never taint.
    span.set_attribute("n_rows", int(n_rows))
