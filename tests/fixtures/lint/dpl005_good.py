"""dplint fixture — DPL005 clean: budget splits via the accountant."""


def accounted_aggregation(budget_accountant, mechanism_type):
    # Shares come from weight normalization inside the accountant scope.
    spec = budget_accountant.request_budget(mechanism_type, weight=0.5)
    other = budget_accountant.request_budget(mechanism_type, weight=0.5)
    return spec, other


def valid_literals(run_query):
    return run_query(eps=1.0, delta=1e-9)
