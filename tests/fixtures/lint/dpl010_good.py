"""dplint fixture — DPL010 clean: rebind or restore, never reuse."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def step(accs, delta):
    return accs + delta


def rebind_each_step(accs, deltas):
    for d in deltas:
        accs = step(accs, d)
    return accs


def restore_on_failure(accs, delta, checkpoint):
    try:
        accs = step(accs, delta)
    except RuntimeError:
        accs = jnp.asarray(checkpoint)
    return accs
