"""Compact chunk merge (ISSUE 5 tentpole 3): streamed chunks emit compact
per-group subtotal columns and ONE final set of [num_partitions] scatters
merges all chunks.

Contracts pinned here:

  * structural — profiler op counters show the row/group-scale full-[P]
    partition-scatter passes per streamed aggregate drop from
    (1 + needed) * k chunks to 0, replaced by ONE compact-input merge
    scatter per accumulator (single-device) / per accumulator per chunk
    with compact inputs (mesh, which keeps its per-chunk reduce-scatter
    for bit parity);
  * bit parity — released accumulators are bit-identical to the legacy
    per-chunk scatter path under a fixed seed when the group stage is
    active (has_group_clip=True), single-device and mesh8; the
    no-group-clip mode agrees exactly for integer-valued accumulators
    and to float32 tolerance otherwise (association differs);
  * the compact path composes with the engine (public API), resumes
    bit-identically through checkpoints, and falls back to the legacy
    path where its static group bound does not exist (PID_PLANES).
"""

import jax
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler
from pipelinedp_tpu import runtime
from pipelinedp_tpu.ops import columnar, streaming, wirecodec
from pipelinedp_tpu.parallel import sharded


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


@pytest.fixture(autouse=True)
def _reset_ops_counters():
    profiler.reset_events("ops/")
    yield


def _data(n=50_000, n_parts=200, seed=0, ratings=True):
    rng = np.random.default_rng(seed)
    pid = rng.integers(1000, 9000, n).astype(np.int64)
    pk = rng.integers(0, n_parts, n).astype(np.int32)
    if ratings:
        value = rng.integers(1, 6, n).astype(np.float32)
    else:
        value = rng.uniform(0, 5, n).astype(np.float32)
    return pid, pk, value


def _stream(pid, pk, value, compact, **over):
    kw = dict(num_partitions=200, linf_cap=1000, l0_cap=100,
              row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
              group_clip_lo=-np.inf, group_clip_hi=np.inf, n_chunks=8)
    kw.update(over)
    return streaming.stream_bound_and_aggregate(
        jax.random.PRNGKey(7), pid, pk, value, compact_merge=compact, **kw)


class TestScatterPassCounters:
    """Acceptance: full-[P] row/group-input scatter passes drop from
    (1 + needed) * k to 0, replaced by (1 + needed) compact-input merge
    scatters for the whole aggregate."""

    def test_headline_shape_3k_to_3(self):
        # COUNT+SUM, no group clip: 1 (pid_count) + 2 needed = 3 passes.
        pid, pk, value = _data()
        kw = dict(need_flags=(True, True, False, False),
                  has_group_clip=False)
        _stream(pid, pk, value, compact=False, **kw)
        assert profiler.event_count(
            streaming.EVENT_PARTITION_SCATTERS) == 3 * 8
        assert profiler.event_count(
            streaming.EVENT_COMPACT_MERGE_SCATTERS) == 0
        profiler.reset_events("ops/")
        _stream(pid, pk, value, compact=True, **kw)
        assert profiler.event_count(
            streaming.EVENT_PARTITION_SCATTERS) == 0
        assert profiler.event_count(
            streaming.EVENT_COMPACT_MERGE_SCATTERS) == 3
        assert profiler.event_count(streaming.EVENT_COMPACT_CHUNKS) == 8

    def test_all_flags_5k_to_5(self):
        pid, pk, value = _data()
        _stream(pid, pk, value, compact=True)
        assert profiler.event_count(
            streaming.EVENT_COMPACT_MERGE_SCATTERS) == 5
        assert profiler.event_count(
            streaming.EVENT_PARTITION_SCATTERS) == 0

    def test_mesh_row_scale_passes_drop_to_zero(self, mesh):
        pid, pk, value = _data()
        kw = dict(num_partitions=200, linf_cap=1000, l0_cap=100,
                  row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                  group_clip_lo=-np.inf, group_clip_hi=np.inf, n_chunks=4,
                  need_flags=(True, True, False, False),
                  has_group_clip=False)
        sharded.stream_bound_and_aggregate(
            mesh, jax.random.PRNGKey(7), pid, pk, value,
            compact_merge=False, **kw)
        assert profiler.event_count(
            streaming.EVENT_PARTITION_SCATTERS) == 3 * 4
        profiler.reset_events("ops/")
        sharded.stream_bound_and_aggregate(
            mesh, jax.random.PRNGKey(7), pid, pk, value,
            compact_merge=True, **kw)
        assert profiler.event_count(
            streaming.EVENT_PARTITION_SCATTERS) == 0
        # The mesh merge keeps one compact-input scatter per accumulator
        # per chunk (its reduce-scatter fold is per chunk for bit parity).
        assert profiler.event_count(
            streaming.EVENT_COMPACT_MERGE_SCATTERS) == 3 * 4


class TestBitParity:
    """Acceptance: released values bit-identical to the pre-merge path
    under a fixed seed (single-device and mesh8)."""

    def test_group_clip_bitwise_single_device(self):
        pid, pk, value = _data(ratings=False)
        kw = dict(group_clip_lo=0.0, group_clip_hi=50.0,
                  has_group_clip=True, linf_cap=7, l0_cap=13)
        legacy = _stream(pid, pk, value, compact=False, **kw)
        compact = _stream(pid, pk, value, compact=True, **kw)
        for name, a, b in zip(legacy._fields, legacy, compact):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)

    def test_group_clip_bitwise_mesh(self, mesh):
        pid, pk, value = _data(ratings=False)
        kw = dict(num_partitions=200, linf_cap=7, l0_cap=13,
                  row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                  group_clip_lo=0.0, group_clip_hi=50.0, n_chunks=4,
                  has_group_clip=True)
        legacy = sharded.stream_bound_and_aggregate(
            mesh, jax.random.PRNGKey(7), pid, pk, value,
            compact_merge=False, **kw)
        compact = sharded.stream_bound_and_aggregate(
            mesh, jax.random.PRNGKey(7), pid, pk, value,
            compact_merge=True, **kw)
        for name, a, b in zip(legacy._fields, legacy, compact):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)

    def test_no_group_clip_close_and_counts_exact(self):
        # Without the group stage the legacy path folds rows directly;
        # the compact path folds per-group subtotals — equal in exact
        # arithmetic, so integer accumulators (counts) stay bitwise and
        # float sums agree to ulp-level tolerance.
        pid, pk, value = _data(ratings=False)
        kw = dict(has_group_clip=False, linf_cap=7, l0_cap=13)
        legacy = _stream(pid, pk, value, compact=False, **kw)
        compact = _stream(pid, pk, value, compact=True, **kw)
        np.testing.assert_array_equal(np.asarray(legacy.count),
                                      np.asarray(compact.count))
        np.testing.assert_array_equal(np.asarray(legacy.pid_count),
                                      np.asarray(compact.pid_count))
        for name, a, b in zip(legacy._fields, legacy, compact):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-4, err_msg=name)

    def test_value_none_count_exact(self):
        pid, pk, _ = _data()
        kw = dict(need_flags=(True, False, False, False),
                  has_group_clip=False)
        legacy = _stream(pid, pk, None, compact=False, **kw)
        compact = _stream(pid, pk, None, compact=True, **kw)
        np.testing.assert_array_equal(np.asarray(legacy.count),
                                      np.asarray(compact.count))
        np.testing.assert_array_equal(np.asarray(legacy.pid_count),
                                      np.asarray(compact.pid_count))

    def test_engine_release_bitwise_group_clip(self):
        # Full public API with per-partition sum bounds (group clip):
        # released columns identical between compact and legacy engines.
        pid, pk, value = _data(n=30_000)

        def run(compact):
            accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
            engine = pdp.JaxDPEngine(accountant, seed=3, stream_chunks=8,
                                     secure_host_noise=False,
                                     compact_merge=compact)
            params = pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                max_partitions_contributed=20,
                max_contributions_per_partition=50,
                min_sum_per_partition=0.0,
                max_sum_per_partition=100.0)
            result = engine.aggregate(
                pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
                public_partitions=list(range(200)))
            accountant.compute_budgets()
            return result.to_columns()

        legacy, compact = run(False), run(True)
        for name in legacy:
            np.testing.assert_array_equal(legacy[name], compact[name],
                                          err_msg=name)


class TestCompactResilience:
    """The compact path must keep the checkpoint/resume bit-identity
    contract: merges happen at checkpoints, a resumed run folds its
    remaining chunks onto the restored dense base in the same order."""

    def _stream(self, pid, pk, value, **kw):
        return streaming.stream_bound_and_aggregate(
            jax.random.PRNGKey(7), pid, pk, value, num_partitions=100,
            linf_cap=1000, l0_cap=100, row_clip_lo=0.0, row_clip_hi=5.0,
            middle=2.5, group_clip_lo=0.0, group_clip_hi=500.0,
            has_group_clip=True, n_chunks=8, compact_merge=True, **kw)

    def test_resume_mid_stream_bitwise(self):
        pid, pk, value = _data(n=30_000, n_parts=100)
        full = self._stream(pid, pk, value)
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="compact",
                                          delete_on_success=False)
        self._stream(pid, pk, value,
                     resilience=runtime.StreamResilience(
                         checkpoint_policy=policy))
        checkpoint = store.load("compact")
        assert 0 < checkpoint.next_chunk < checkpoint.n_chunks
        resumed = self._stream(pid, pk, value, resume_from=checkpoint)
        for a, b in zip(full, resumed):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_oom_degradation_bitwise(self):
        pid, pk, value = _data(n=30_000, n_parts=100)
        clean = self._stream(pid, pk, value)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("oom", at_slab=1)])
        degraded = self._stream(
            pid, pk, value,
            resilience=runtime.StreamResilience(
                retry_policy=runtime.RetryPolicy(sleep=lambda s: None),
                fault_injector=injector))
        assert injector.pending == 0
        for a, b in zip(clean, degraded):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestCompactApplicability:

    def test_pid_planes_falls_back_to_legacy(self):
        # Near-unique ids choose PID_PLANES, which has no per-chunk pid
        # bound: the compact path must not engage (and results stay sane).
        n = 40_000
        rng = np.random.default_rng(5)
        pid = rng.permutation(n).astype(np.int64)
        pk = rng.integers(0, 100, n).astype(np.int32)
        value = np.ones(n, dtype=np.float32)
        accs = streaming.stream_bound_and_aggregate(
            jax.random.PRNGKey(0), pid, pk, value, num_partitions=100,
            linf_cap=n, l0_cap=100, row_clip_lo=0.0, row_clip_hi=1.0,
            middle=0.5, group_clip_lo=-np.inf, group_clip_hi=np.inf,
            n_chunks=4, has_group_clip=False, compact_merge=True)
        assert profiler.event_count(streaming.EVENT_COMPACT_CHUNKS) == 0
        assert profiler.event_count(
            streaming.EVENT_PARTITION_SCATTERS) > 0
        np.testing.assert_allclose(np.asarray(accs.count),
                                   np.bincount(pk, minlength=100))

    def test_auto_threshold(self):
        # "auto" engages only where the [P]-output passes dominate.
        assert streaming._compact_enabled("auto",
                                          streaming.COMPACT_MIN_PARTITIONS)
        assert not streaming._compact_enabled("auto", 30_000)
        assert streaming._compact_enabled(True, 1)
        assert not streaming._compact_enabled(False, 1 << 20)

    def test_compact_group_bound(self):
        assert columnar.compact_group_bound(1024, 16, 4) == 64
        assert columnar.compact_group_bound(48, 16, 100) == 48
        assert columnar.compact_group_bound(1024, 16, 0) is None
        assert columnar.compact_group_bound(
            1024, 16, jax.numpy.arange(3)) is None

    def test_merge_guard_refuses_truncation(self):
        # A CompactGroups claiming more kept groups than its static bound
        # must refuse to merge (wire-contract violation).
        import jax.numpy as jnp
        cg = columnar.CompactGroups(
            pk=jnp.zeros(8, jnp.int32),
            pid_count=jnp.zeros(8), count=jnp.zeros(8), sum=jnp.zeros(8),
            norm_sum=jnp.zeros(8), norm_sq_sum=jnp.zeros(8),
            n_kept=jnp.asarray(9, jnp.int32))
        accs = columnar.PartitionAccumulators(
            *(jnp.zeros(4) for _ in range(5)))
        with pytest.raises(RuntimeError, match="static bound"):
            streaming._merge_pending(accs, [cg], 4, (True,) * 4)

    def test_quantile_path_stays_legacy(self):
        # quantile_spec accumulates a dense [P, leaves] histogram; the
        # compact merge must not engage there.
        pid, pk, value = _data(n=20_000, n_parts=50)
        accs, qhist = streaming.stream_bound_and_aggregate(
            jax.random.PRNGKey(1), pid, pk, value, num_partitions=50,
            linf_cap=1000, l0_cap=50, row_clip_lo=0.0, row_clip_hi=5.0,
            middle=2.5, group_clip_lo=-np.inf, group_clip_hi=np.inf,
            n_chunks=4, quantile_spec=(16, 0.0, 5.0), compact_merge=True)
        assert profiler.event_count(streaming.EVENT_COMPACT_CHUNKS) == 0
        assert qhist.shape == (50, 16)


class TestCompactKernelUnit:
    """bound_and_aggregate_compact against bound_and_aggregate directly:
    merging ONE chunk's compact columns must reproduce the dense kernel
    bitwise (group-clip mode)."""

    @pytest.mark.parametrize("pid_sorted", [False, True])
    def test_single_chunk_roundtrip(self, pid_sorted):
        import jax.numpy as jnp
        rng = np.random.default_rng(2)
        n, P = 4096, 64
        pid = np.sort(rng.integers(0, 300, n)) if pid_sorted else \
            rng.integers(0, 300, n)
        pk = rng.integers(0, P, n).astype(np.int32)
        value = rng.uniform(0, 5, n).astype(np.float32)
        valid = np.ones(n, dtype=bool)
        key = jax.random.PRNGKey(9)
        kw = dict(num_partitions=P, linf_cap=5, l0_cap=7,
                  row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                  group_clip_lo=0.0, group_clip_hi=20.0,
                  has_group_clip=True, pid_sorted=pid_sorted,
                  max_segments=512 if pid_sorted else None)
        dense = columnar.bound_and_aggregate(
            key, jnp.asarray(pid.astype(np.int32)), jnp.asarray(pk),
            jnp.asarray(value), jnp.asarray(valid), **kw)
        max_groups = columnar.compact_group_bound(n, 300, kw["l0_cap"])
        cg = columnar.bound_and_aggregate_compact(
            key, jnp.asarray(pid.astype(np.int32)), jnp.asarray(pk),
            jnp.asarray(value), jnp.asarray(valid),
            max_groups=max_groups, **kw)
        assert int(cg.n_kept) <= max_groups
        base = columnar.PartitionAccumulators(
            *(jnp.zeros(P, jnp.float32) for _ in range(5)))
        merged = columnar.merge_compact_chunks(
            base, *(jnp.stack([c]) for c in cg[:6]), num_partitions=P,
            need_flags=(True, True, True, True))
        for name, a, b in zip(dense._fields, dense, merged):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
