"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual 8-device CPU mesh (the driver separately dry-run
compiles the multi-chip path via __graft_entry__.dryrun_multichip).

The environment may pre-register a hardware TPU platform at interpreter
startup, so setting JAX_PLATFORMS here can be too late; instead the flags
are set before the (lazy) CPU client initializes and the default platform is
switched via jax.config.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
