"""Backend conformance suite.

The same primitive-op assertions run against every host backend, mirroring
the reference's cross-backend suite
(/root/reference/tests/pipeline_backend_test.py:170-420). Any new backend
must pass this unchanged — it is the contract the DP engine builds on.
"""

import collections

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.backends import base


def _backends():
    return [
        pytest.param(lambda: pdp.LocalBackend(), id="local"),
        pytest.param(lambda: pdp.MultiProcLocalBackend(n_jobs=2), id="mp"),
        # chunksize=3 forces multi-chunk paths on tiny inputs.
        pytest.param(lambda: pdp.MultiProcLocalBackend(n_jobs=2,
                                                       chunksize=3),
                     id="mp-small-chunks"),
        pytest.param(lambda: pdp.JaxBackend(), id="jax"),
    ]


@pytest.fixture(params=_backends())
def backend(request):
    return request.param()


class TestConformance:

    def test_to_collection(self, backend):
        assert list(backend.to_collection([1, 2], [0], "s")) == [1, 2]

    def test_to_multi_transformable_collection(self, backend):
        col = backend.to_multi_transformable_collection(iter([1, 2, 3]))
        # Must be iterable more than once (unlike raw generators).
        assert list(col) == [1, 2, 3]
        assert list(col) == [1, 2, 3]

    def test_map(self, backend):
        out = backend.map(range(10), lambda x: x * 2, "map")
        assert list(out) == [2 * x for x in range(10)]

    def test_map_preserves_order_across_chunks(self, backend):
        out = backend.map(range(1000), lambda x: x + 1, "map")
        assert list(out) == list(range(1, 1001))

    def test_map_with_side_inputs(self, backend):
        side = [10, 20]
        out = backend.map_with_side_inputs(
            [1, 2], lambda x, s: x + sum(s), [iter(side)], "m")
        assert list(out) == [31, 32]

    def test_flat_map(self, backend):
        out = backend.flat_map([[1, 2], [3]], lambda x: x, "fm")
        assert list(out) == [1, 2, 3]

    def test_flat_map_with_side_inputs(self, backend):
        out = backend.flat_map_with_side_inputs(
            [[1, 2], [3]], lambda x, s: [v + s[0] for v in x], [iter([5])],
            "fm")
        assert list(out) == [6, 7, 8]

    def test_map_tuple(self, backend):
        out = backend.map_tuple([(1, 2), (3, 4)], lambda a, b: a + b, "mt")
        assert list(out) == [3, 7]

    def test_map_values(self, backend):
        out = backend.map_values([("a", 1), ("b", 2)], lambda v: v * 10,
                                 "mv")
        assert list(out) == [("a", 10), ("b", 20)]

    def test_group_by_key(self, backend):
        out = backend.group_by_key([("a", 1), ("b", 2), ("a", 3)], "g")
        grouped = {k: sorted(v) for k, v in out}
        assert grouped == {"a": [1, 3], "b": [2]}

    def test_filter(self, backend):
        out = backend.filter(range(10), lambda x: x % 3 == 0, "f")
        assert list(out) == [0, 3, 6, 9]

    def test_filter_by_key(self, backend):
        col = [("a", 1), ("b", 2), ("c", 3)]
        out = backend.filter_by_key(col, ["a", "c"], "fbk")
        assert sorted(out) == [("a", 1), ("c", 3)]

    def test_filter_by_key_lazy_keys(self, backend):
        col = [(1, "x"), (2, "y"), (3, "z")]
        out = backend.filter_by_key(col, iter([2]), "fbk")
        assert list(out) == [(2, "y")]

    def test_keys_values(self, backend):
        col = [("a", 1), ("b", 2)]
        assert list(backend.keys(iter(col), "k")) == ["a", "b"]
        assert list(backend.values(iter(col), "v")) == [1, 2]

    def test_sample_fixed_per_key(self, backend):
        col = [("a", i) for i in range(100)] + [("b", 1)]
        out = dict(backend.sample_fixed_per_key(col, 10, "s"))
        assert len(out["a"]) == 10
        assert set(out["a"]) <= set(range(100))
        assert out["b"] == [1]

    def test_count_per_element(self, backend):
        out = backend.count_per_element(["x", "y", "x", "x"], "c")
        assert dict(out) == {"x": 3, "y": 1}

    def test_sum_per_key(self, backend):
        out = backend.sum_per_key([("a", 1), ("b", 5), ("a", 2)], "s")
        assert dict(out) == {"a": 3, "b": 5}

    def test_sum_per_key_many_chunks(self, backend):
        col = [(i % 7, 1) for i in range(5000)]
        out = dict(backend.sum_per_key(col, "s"))
        expected = collections.Counter(i % 7 for i in range(5000))
        assert out == dict(expected)

    def test_reduce_per_key_non_commutative_order(self, backend):
        # fn is associative but NOT commutative (string concat): backends
        # must preserve per-key encounter order when reducing.
        col = [("k", "a"), ("q", "x"), ("k", "b"), ("k", "c"), ("q", "y")]
        out = dict(backend.reduce_per_key(col, lambda a, b: a + b, "r"))
        assert out == {"k": "abc", "q": "xy"}

    def test_combine_accumulators_per_key(self, backend):
        class SumCombiner:
            def merge_accumulators(self, a, b):
                return a + b

        col = [("a", 1), ("a", 2), ("b", 10)]
        out = dict(
            backend.combine_accumulators_per_key(col, SumCombiner(), "c"))
        assert out == {"a": 3, "b": 10}

    def test_flatten(self, backend):
        out = backend.flatten((iter([1, 2]), iter([3])), "fl")
        assert list(out) == [1, 2, 3]

    def test_distinct(self, backend):
        out = backend.distinct([1, 2, 1, 3, 2], "d")
        assert sorted(out) == [1, 2, 3]

    def test_to_list(self, backend):
        out = backend.to_list(iter([3, 1, 2]), "tl")
        assert list(out) == [[3, 1, 2]]

    def test_annotate_passthrough(self, backend):
        out = backend.annotate(iter([1, 2]), "an", budget=None)
        assert list(out) == [1, 2]

    def test_laziness(self, backend):
        # Ops must not consume the input at graph-construction time.
        def explosive():
            raise RuntimeError("consumed eagerly")
            yield  # pragma: no cover

        backend.map(explosive(), lambda x: x, "m")
        backend.filter(explosive(), lambda x: True, "f")
        backend.group_by_key(explosive(), "g")
        backend.reduce_per_key(explosive(), lambda a, b: a, "r")

    def test_engine_e2e_on_backend(self, backend):
        # The whole aggregation graph on this backend: the ultimate
        # conformance check (mirrors the reference's per-backend e2e
        # smoke tests, dp_engine_test.py:1170-1256).
        rows = [(u, u % 5, 1.0) for u in range(100)]
        accountant = pdp.NaiveBudgetAccountant(1e6, 1e-9)
        engine = pdp.DPEngine(accountant, backend)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=1.0)
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda r: r[0],
            partition_extractor=lambda r: r[1],
            value_extractor=lambda r: r[2])
        result = engine.aggregate(rows, params, extractors,
                                  public_partitions=list(range(5)))
        accountant.compute_budgets()
        out = dict(result)
        assert set(out) == set(range(5))
        for pk in range(5):
            assert out[pk].count == pytest.approx(20, abs=0.5)


class TestUniqueLabels:

    def test_unique_labels_generator(self):
        gen = base.UniqueLabelsGenerator("suffix")
        a = gen.unique("stage")
        b = gen.unique("stage")
        assert a != b
        assert "stage" in a and "stage" in b


def _double(x):
    return x * 2


def _concat(a, b):
    return a + b


class TestMultiProcProcessesMode:
    """'processes' mode needs picklable functions; the chunk-fn classes in
    backends/local.py are module-level so fork-based pools work."""

    def test_map_and_reduce(self):
        backend = pdp.MultiProcLocalBackend(n_jobs=2, mode="processes",
                                            chunksize=5)
        out = list(backend.map(range(100), _double, "map"))
        assert out == [2 * x for x in range(100)]
        pairs = [(i % 3, "x") for i in range(30)]
        reduced = dict(backend.reduce_per_key(pairs, _concat, "reduce"))
        assert reduced == {0: "x" * 10, 1: "x" * 10, 2: "x" * 10}

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            pdp.MultiProcLocalBackend(mode="fibers")

    def test_sum_per_key_processes_mode(self):
        # sum_per_key's reducer must be picklable (was a lambda).
        backend = pdp.MultiProcLocalBackend(n_jobs=2, mode="processes",
                                            chunksize=7)
        out = dict(backend.sum_per_key([(i % 5, 2) for i in range(1000)],
                                       "s"))
        assert out == {k: 400 for k in range(5)}


class TestJaxBackendOffload:
    """The device-offloaded ops of JaxBackend (VERDICT-r4 item 9): the
    sampling hot-spot and recognizable numeric reductions."""

    def test_sample_fixed_per_key_device_path(self, monkeypatch):
        import numpy as np
        from pipelinedp_tpu.backends.jax_backend import JaxBackend
        backend = JaxBackend()
        monkeypatch.setattr(JaxBackend, "SAMPLE_DEVICE_MIN_ROWS", 1)
        rng = np.random.default_rng(0)
        pairs = [(int(k), (int(k), i))
                 for i, k in enumerate(rng.integers(0, 40, 2000))]
        out = dict(backend.sample_fixed_per_key(pairs, 5, "s"))
        from collections import Counter
        totals = Counter(k for k, _ in pairs)
        assert set(out) == set(totals)
        for k, sampled in out.items():
            assert len(sampled) == min(totals[k], 5)
            # Sampled values are genuine rows of this key.
            assert all(v[0] == k for v in sampled)
            assert len(set(sampled)) == len(sampled)

    def test_sample_fixed_per_key_string_keys_device(self, monkeypatch):
        from pipelinedp_tpu.backends.jax_backend import JaxBackend
        backend = JaxBackend()
        monkeypatch.setattr(JaxBackend, "SAMPLE_DEVICE_MIN_ROWS", 1)
        pairs = [(f"k{i % 3}", i) for i in range(90)]
        out = dict(backend.sample_fixed_per_key(pairs, 10, "s"))
        assert set(out) == {"k0", "k1", "k2"}
        assert all(len(v) == 10 for v in out.values())

    def test_reduce_per_key_operator_add_offloads(self):
        import operator
        from pipelinedp_tpu.backends.jax_backend import JaxBackend
        backend = JaxBackend()
        pairs = [(i % 7, i) for i in range(5000)]
        got = dict(backend.reduce_per_key(pairs, operator.add, "r"))
        want = {}
        for k, v in pairs:
            want[k] = want.get(k, 0) + v
        assert got == want

    def test_reduce_per_key_min_max(self):
        from pipelinedp_tpu.backends.jax_backend import JaxBackend
        backend = JaxBackend()
        pairs = [(i % 5, (i * 37) % 101 - 50) for i in range(3000)]
        got_min = dict(backend.reduce_per_key(list(pairs), min, "m"))
        got_max = dict(backend.reduce_per_key(list(pairs), max, "M"))
        want_min, want_max = {}, {}
        for k, v in pairs:
            want_min[k] = min(want_min.get(k, 10**9), v)
            want_max[k] = max(want_max.get(k, -10**9), v)
        assert got_min == want_min
        assert got_max == want_max

    def test_reduce_per_key_min_max_floats_exact(self):
        from pipelinedp_tpu.backends.jax_backend import JaxBackend
        backend = JaxBackend()
        pairs = [(i % 3, float(i) * 1e-7 + 1.0) for i in range(1000)]
        got = dict(backend.reduce_per_key(list(pairs), max, "M"))
        want = {}
        for k, v in pairs:
            want[k] = max(want.get(k, -1e18), v)
        assert got == pytest.approx(want)

    def test_reduce_per_key_arbitrary_fn_stays_host(self):
        from pipelinedp_tpu.backends.jax_backend import JaxBackend
        backend = JaxBackend()
        pairs = [(1, "a"), (1, "b"), (2, "c")]
        got = dict(backend.reduce_per_key(pairs, lambda a, b: a + b, "r"))
        assert got == {1: "ab", 2: "c"}
