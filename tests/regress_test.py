"""The bench-trajectory regression gate (obs/regress.py): the
checked-in trajectory passes, a synthetically degraded copy fails, and
rounds with different workload shapes never compare."""

import copy
import glob
import json
import os
import subprocess
import sys

import pytest

from pipelinedp_tpu.obs import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAJECTORY = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))


def _row(n, cmd="BENCH_ROWS=1000 python bench.py", **parsed):
    return {"n": n, "cmd": cmd, "parsed": parsed, "_path": f"r{n}"}


class TestCompare:

    def test_regression_flagged_beyond_tolerance(self):
        rows = [_row(1, value=10_000.0), _row(2, value=7_000.0)]
        findings, summary = regress.compare(rows)
        (f,) = [x for x in findings if x["metric"] ==
                "e2e_partitions_per_sec"]
        assert f["status"] == "REGRESSION"
        assert summary["regressions"] == 1

    def test_within_tolerance_is_ok(self):
        rows = [_row(1, value=10_000.0), _row(2, value=9_200.0)]
        findings, _ = regress.compare(rows)
        (f,) = [x for x in findings if x["metric"] ==
                "e2e_partitions_per_sec"]
        assert f["status"] == "OK"

    def test_best_prior_not_latest_prior_is_the_bar(self):
        # A slow middle round must not lower the bar.
        rows = [_row(1, value=10_000.0), _row(2, value=6_000.0),
                _row(3, value=7_000.0)]
        findings, summary = regress.compare(rows)
        (f,) = [x for x in findings if x["metric"] ==
                "e2e_partitions_per_sec"]
        assert f["best_prior"] == 10_000.0
        assert f["status"] == "REGRESSION"

    def test_different_shapes_never_compare(self):
        rows = [_row(1, cmd="BENCH_ROWS=9 python bench.py",
                     value=99_000.0),
                _row(2, cmd="BENCH_ROWS=1000 python bench.py",
                     value=10.0)]
        findings, summary = regress.compare(rows)
        (f,) = [x for x in findings if x["metric"] ==
                "e2e_partitions_per_sec"]
        assert f["status"] == "NEW"
        assert summary["regressions"] == 0

    def test_explicit_shape_key_wins_over_cmd(self):
        a = _row(1, value=10_000.0)
        b = _row(2, value=10_000.0)
        a["shape"] = {"BENCH_ROWS": "1"}
        b["shape"] = {"BENCH_ROWS": "2"}
        findings, _ = regress.compare([a, b])
        (f,) = [x for x in findings if x["metric"] ==
                "e2e_partitions_per_sec"]
        assert f["status"] == "NEW"

    def test_superset_shape_compares_against_leaner_prior(self):
        # bench.py grows knobs over time: a newer round that records
        # MORE knobs (each defaulted in the prior's run) still compares
        # as long as every shared knob agrees — a richer recording of
        # the same workload must not orphan the trajectory.
        rows = [_row(1, cmd="BENCH_ROWS=1000 python bench.py",
                     value=10_000.0),
                _row(2, cmd="BENCH_ROWS=1000 python bench.py",
                     value=6_000.0)]
        rows[1]["parsed"]["shape"] = {"BENCH_ROWS": "1000",
                                      "BENCH_LIVE_EPOCHS": "6"}
        findings, summary = regress.compare(rows)
        (f,) = [x for x in findings if x["metric"] ==
                "e2e_partitions_per_sec"]
        assert summary["comparable_priors"] == [1]
        assert f["status"] == "REGRESSION"

    def test_shared_knob_disagreement_never_compares(self):
        # The superset rule only covers agreement: one shared knob with
        # a different value keeps the rounds apart, and an empty
        # signature only matches another empty one.
        assert not regress.shapes_comparable(
            (("BENCH_ROWS", "9"), ("BENCH_LIVE_EPOCHS", "6")),
            (("BENCH_ROWS", "1000"),))
        assert not regress.shapes_comparable(
            (), (("BENCH_ROWS", "1000"),))
        assert regress.shapes_comparable((), ())

    def test_noise_aware_tolerance_widens_with_cv(self):
        # Three jittery priors -> tolerance grows to 2*cv and a drop
        # inside that band passes.
        rows = [_row(1, value=8_000.0), _row(2, value=12_000.0),
                _row(3, value=10_000.0), _row(4, value=8_200.0)]
        findings, _ = regress.compare(rows)
        (f,) = [x for x in findings if x["metric"] ==
                "e2e_partitions_per_sec"]
        assert f["tolerance"] > 0.15
        assert f["status"] == "OK"

    def test_gone_metric_reported_not_failed(self):
        rows = [_row(1, value=10.0, kernel_partitions_per_sec=5.0),
                _row(2, value=10.0)]
        findings, summary = regress.compare(rows)
        (f,) = [x for x in findings if x["metric"] ==
                "kernel_partitions_per_sec"]
        assert f["status"] == "GONE"
        assert summary["regressions"] == 0


@pytest.mark.skipif(not TRAJECTORY, reason="no checked-in trajectory")
class TestCheckedInTrajectory:
    """The acceptance pins: exit 0 on the real trajectory, nonzero on a
    degraded copy — through the same `python -m` entry point CI runs."""

    def _run(self, files):
        return subprocess.run(
            [sys.executable, "-m", "pipelinedp_tpu.obs.regress"] + files,
            capture_output=True, text=True, cwd=REPO, timeout=120)

    def test_current_trajectory_passes(self):
        proc = self._run(TRAJECTORY)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "Bench regression gate" in proc.stdout
        assert "REGRESSION" not in proc.stdout

    def test_degraded_copy_fails(self, tmp_path):
        files = []
        for path in TRAJECTORY:
            row = json.load(open(path))
            out = tmp_path / os.path.basename(path)
            files.append(str(out))
            out.write_text(json.dumps(row))
        # Halve the latest round's e2e headline: an unambiguous
        # regression at any sane tolerance.
        latest = json.load(open(files[-1]))
        latest["parsed"]["value"] *= 0.5
        with open(files[-1], "w") as f:
            json.dump(latest, f)
        proc = self._run(files)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "REGRESSION" in proc.stdout

    def test_markdown_report_written(self, tmp_path):
        out = tmp_path / "report.md"
        rc = regress.main(TRAJECTORY + ["--out", str(out)])
        assert rc == 0
        text = out.read_text()
        assert text.startswith("# Bench regression gate")
        assert "| metric |" in text
