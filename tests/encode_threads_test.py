"""Thread-parallel native encode (ISSUE 5 tentpole 1): determinism matrix.

The native worker pool processes pid-disjoint buckets concurrently
(row_packer.cc RunPool; width forced by PIPELINEDP_TPU_ENCODE_THREADS).
The contract pinned here: emitted slabs are BYTE-IDENTICAL across thread
counts {1, 4, hardware-auto} and equal to the numpy reference encoder,
for the RLE, PID_PLANES, and raw-float value wire modes. CI runs this
file core-pinned (taskset -c 0,1) as well, to catch any output that
depends on the scheduler rather than the input.
"""

import numpy as np
import pytest

from pipelinedp_tpu.native import loader
from pipelinedp_tpu.ops import streaming, wirecodec

THREAD_MATRIX = ("1", "4", "")  # "" = auto (hardware concurrency)


def _require_native():
    lib = loader.load_row_packer()
    if lib is None:
        pytest.skip("native packer unavailable")
    return lib


def _dataset(kind, n=120_000, seed=0):
    rng = np.random.default_rng(seed)
    if kind == "rle_planes_values":
        # Repetitive ids (~12 rows/user) -> PID_RLE; integer star
        # ratings -> affine-integer value planes.
        pid = rng.integers(0, n // 12, n).astype(np.int32)
        value = rng.integers(1, 6, n).astype(np.float32)
    elif kind == "rle_raw_float":
        pid = rng.integers(0, n // 12, n).astype(np.int32)
        value = rng.uniform(0, 5, n).astype(np.float32)  # defeats planes
    elif kind == "pid_planes":
        pid = rng.permutation(n).astype(np.int32)  # unique -> PID_PLANES
        value = rng.uniform(-2, 2, n).astype(np.float32)
    else:
        raise AssertionError(kind)
    pk = rng.integers(0, 700, n).astype(np.int32)
    return pid, pk, value


def _encode_native(pid, pk, value, k, monkeypatch, threads):
    if threads:
        monkeypatch.setenv(loader.ENCODE_THREADS_ENV, threads)
    else:
        monkeypatch.delenv(loader.ENCODE_THREADS_ENV, raising=False)
    enc, info = wirecodec.make_encoder(pid, pk, value,
                                       num_partitions=700, k=k)
    if enc is None:
        pytest.skip("native encoder unavailable")
    with enc:
        cap = wirecodec._round8(int(enc.counts.max()))
        if info.pid_mode == wirecodec.PID_PLANES:
            fmt = wirecodec.WireFormat(
                bytes_pid=info.bytes_pid, bits_pk=info.bits_pk, cap=cap,
                ucap=8, value=info.plan, pid_mode=wirecodec.PID_PLANES,
                bits_pid=info.bits_pid)
            n_uniq = np.zeros(k, dtype=np.int64)
        else:
            n_uniq = enc.sort_range(0, k)
            fmt = wirecodec.WireFormat(
                bytes_pid=info.bytes_pid, bits_pk=info.bits_pk, cap=cap,
                ucap=wirecodec._round8(int(n_uniq.max())), value=info.plan)
        slab = enc.emit_range(0, k, fmt)
        return slab, np.array(enc.counts), np.array(n_uniq), fmt, info


class TestDeterminismMatrix:

    @pytest.mark.parametrize(
        "kind", ["rle_planes_values", "rle_raw_float", "pid_planes"])
    def test_slabs_identical_across_thread_counts_and_numpy(
            self, kind, monkeypatch):
        _require_native()
        pid, pk, value = _dataset(kind)
        k = 6
        slabs = {}
        fmts = {}
        for threads in THREAD_MATRIX:
            slab, counts, n_uniq, fmt, info = _encode_native(
                pid, pk, value, k, monkeypatch, threads)
            slabs[threads], fmts[threads] = slab, fmt
        ref = slabs[THREAD_MATRIX[0]]
        for threads in THREAD_MATRIX[1:]:
            assert fmts[threads] == fmts[THREAD_MATRIX[0]]
            np.testing.assert_array_equal(
                ref, slabs[threads],
                err_msg=f"thread count {threads or 'auto'} changed bytes")
        # The numpy reference is the oracle: same bytes, any width.
        ref_slab, _, _, ref_fmt = wirecodec.encode_buckets_numpy(
            pid, pk, value, pid_lo=info.pid_lo, k=k,
            bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
            plan=info.plan, pid_mode=info.pid_mode,
            bits_pid=info.bits_pid)
        assert ref_fmt == fmts[THREAD_MATRIX[0]]
        np.testing.assert_array_equal(ref, ref_slab)

    def test_pack_buckets_identical_across_thread_counts(self, monkeypatch):
        _require_native()
        rng = np.random.default_rng(1)
        n = 150_000
        pid = rng.integers(500, 90_000, n).astype(np.int32)
        pk = rng.integers(0, 3_000, n).astype(np.int32)
        value = rng.uniform(-2, 7, n).astype(np.float32)
        outs = []
        for threads in THREAD_MATRIX:
            if threads:
                monkeypatch.setenv(loader.ENCODE_THREADS_ENV, threads)
            else:
                monkeypatch.delenv(loader.ENCODE_THREADS_ENV,
                                   raising=False)
            packed = streaming._pack_native(pid, pk, value, 500, 8, 3, 2,
                                            False, 9)
            assert packed is not None
            outs.append(packed)
        for bufs, counts in outs[1:]:
            np.testing.assert_array_equal(outs[0][1], counts)
            np.testing.assert_array_equal(outs[0][0], bufs)


class TestEncodeThreadsKnob:

    def test_env_validation(self, monkeypatch):
        monkeypatch.setenv(loader.ENCODE_THREADS_ENV, "junk")
        with pytest.raises(ValueError, match="must be an integer"):
            loader.encode_threads()
        monkeypatch.setenv(loader.ENCODE_THREADS_ENV, "65")
        with pytest.raises(ValueError, match=r"\[0, 64\]"):
            loader.encode_threads()
        monkeypatch.setenv(loader.ENCODE_THREADS_ENV, "-1")
        with pytest.raises(ValueError):
            loader.encode_threads()
        monkeypatch.setenv(loader.ENCODE_THREADS_ENV, "  8 ")
        assert loader.encode_threads() == 8
        monkeypatch.delenv(loader.ENCODE_THREADS_ENV, raising=False)
        assert loader.encode_threads() == 0

    def test_override_reaches_native(self, monkeypatch):
        lib = _require_native()
        monkeypatch.setenv(loader.ENCODE_THREADS_ENV, "5")
        assert loader.apply_encode_threads(lib) == 5
        assert lib.pdp_get_encode_threads() == 5
        monkeypatch.delenv(loader.ENCODE_THREADS_ENV, raising=False)
        assert loader.apply_encode_threads(lib) == 0
        assert lib.pdp_get_encode_threads() == 0

    def test_prefetch_and_slab_env_validation(self, monkeypatch):
        monkeypatch.setenv(streaming.PREFETCH_ENV, "9")
        with pytest.raises(ValueError, match=r"\[0, 4\]"):
            streaming.prefetch_depth()
        monkeypatch.setenv(streaming.PREFETCH_ENV, "0")
        assert streaming.prefetch_depth() == 0
        monkeypatch.delenv(streaming.PREFETCH_ENV, raising=False)
        assert streaming.prefetch_depth() == 1
        monkeypatch.setenv(streaming.SLAB_BYTES_ENV, "12")
        with pytest.raises(ValueError):
            streaming.slab_byte_budget(True)
        monkeypatch.setenv(streaming.SLAB_BYTES_ENV, str(32 << 20))
        assert streaming.slab_byte_budget(True) == 32 << 20
        assert streaming.slab_byte_budget(False) == 32 << 20
        monkeypatch.delenv(streaming.SLAB_BYTES_ENV, raising=False)
        assert (streaming.slab_byte_budget(True)
                == streaming.PIPELINED_SLAB_BYTE_BUDGET)


class TestStreamedParityAcrossThreadCounts:
    """End-to-end: the streamed accumulators are bit-identical whatever
    the encode worker width (slabs identical => kernels see identical
    bytes)."""

    def test_stream_bitwise_across_thread_counts(self, monkeypatch):
        _require_native()
        import jax
        rng = np.random.default_rng(4)
        n = 60_000
        pid = rng.integers(0, 4_000, n).astype(np.int64)
        pk = rng.integers(0, 150, n).astype(np.int32)
        value = rng.integers(1, 6, n).astype(np.float32)
        results = []
        for threads in THREAD_MATRIX:
            if threads:
                monkeypatch.setenv(loader.ENCODE_THREADS_ENV, threads)
            else:
                monkeypatch.delenv(loader.ENCODE_THREADS_ENV,
                                   raising=False)
            accs = streaming.stream_bound_and_aggregate(
                jax.random.PRNGKey(11), pid, pk, value,
                num_partitions=150, linf_cap=5, l0_cap=10,
                row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                group_clip_lo=-np.inf, group_clip_hi=np.inf, n_chunks=6)
            results.append([np.asarray(a) for a in accs])
        for other in results[1:]:
            for a, b in zip(results[0], other):
                np.testing.assert_array_equal(a, b)
