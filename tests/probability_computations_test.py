"""Tests for Laplace+Gaussian sum quantiles.

Parity intent: /root/reference/analysis/tests/probability_computations_test.py
— quantiles of the noise-sum distribution; here the exact inverse-CDF path
is additionally cross-checked against Monte Carlo and against the pure
single-distribution limits.
"""

import numpy as np
import pytest
from scipy import stats

from pipelinedp_tpu.analysis import compute_sum_laplace_gaussian_quantiles
from pipelinedp_tpu.analysis.probability_computations import _sum_cdf


class TestSumLaplaceGaussianQuantiles:

    def test_pure_gaussian_limit(self):
        qs = [0.05, 0.5, 0.95]
        out = compute_sum_laplace_gaussian_quantiles(0.0, 2.0, qs)
        np.testing.assert_allclose(out, stats.norm.ppf(qs, scale=2.0),
                                   atol=1e-9)

    def test_pure_laplace_limit(self):
        qs = [0.1, 0.5, 0.9]
        out = compute_sum_laplace_gaussian_quantiles(3.0, 0.0, qs)
        np.testing.assert_allclose(out, stats.laplace.ppf(qs, scale=3.0),
                                   rtol=1e-9, atol=1e-9)

    def test_symmetry_and_monotonicity(self):
        qs = [0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99]
        out = compute_sum_laplace_gaussian_quantiles(1.5, 2.5, qs)
        assert out[3] == pytest.approx(0.0, abs=1e-9)
        np.testing.assert_allclose(out, -np.asarray(out)[::-1], atol=1e-8)
        assert all(a < b for a, b in zip(out, out[1:]))

    def test_cdf_roundtrip(self):
        qs = np.linspace(0.01, 0.99, 25)
        out = compute_sum_laplace_gaussian_quantiles(1.0, 1.0, qs)
        np.testing.assert_allclose(_sum_cdf(np.asarray(out), 1.0, 1.0), qs,
                                   atol=1e-10)

    def test_exact_matches_monte_carlo(self):
        qs = [0.1, 0.5, 0.9]
        exact = compute_sum_laplace_gaussian_quantiles(2.0, 1.0, qs)
        mc = compute_sum_laplace_gaussian_quantiles(
            2.0, 1.0, qs, num_samples=200_000, method="monte_carlo",
            rng=np.random.default_rng(0))
        np.testing.assert_allclose(exact, mc, atol=0.05)

    def test_extreme_quantiles_stable(self):
        out = compute_sum_laplace_gaussian_quantiles(1.0, 1.0,
                                                     [1e-9, 1 - 1e-9])
        assert np.isfinite(out).all()
        assert out[0] < -15 and out[1] > 15

    def test_unknown_method(self):
        with pytest.raises(ValueError, match="method"):
            compute_sum_laplace_gaussian_quantiles(1, 1, [0.5],
                                                   method="nope")
