"""Fused finalization epilogue tests (ops/finalize.py).

Two contracts:
  * Parity — the fused epilogue must be bit-identical to the legacy
    per-combiner loop for seeded device-noise runs (every metric kind,
    selection mode, public/private, mesh and single-device), and
    equivalent on the secure-host-noise path (bit-identical under the
    seeded fallback RNG since the draw order is preserved; distributional
    when the native secure sampler is installed).
  * Executable cache — a second aggregate with identical shapes performs
    ZERO new jit traces (finalize.trace_count is the hook); a shape or
    plan change misses cleanly (one new trace, one cache miss).
"""

import jax
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.ops import finalize
from pipelinedp_tpu.parallel import sharded

M = pdp.Metrics
S = pdp.PartitionSelectionStrategy

ADDITIVE = {M.COUNT, M.PRIVACY_ID_COUNT, M.SUM, M.VECTOR_SUM}


@pytest.fixture(params=["single_device", "mesh8"], scope="module")
def engine_mesh(request):
    """Same assertions run on one device and on an 8-device mesh."""
    if request.param == "single_device":
        return None
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def run_engine(fused,
               metrics,
               *,
               secure=False,
               mesh=None,
               seed=3,
               public=None,
               post_thresh=False,
               noise_kind=pdp.NoiseKind.LAPLACE,
               strategy=None,
               vector=False,
               n=800,
               nparts=11,
               host_seed=17):
    if vector:
        data = [(u, f"p{u % nparts}", np.array([1.0, 2.0, 3.0]) * (u % 3))
                for u in range(n)]
    else:
        data = [(u, f"p{u % nparts}", float(u % 5)) for u in range(n)]
    pdp.noise_core.seed_fallback_rng(host_seed)
    pdp.partition_selection.seed_rng(host_seed)
    accountant = pdp.NaiveBudgetAccountant(2.0, 1e-6)
    engine = pdp.JaxDPEngine(accountant,
                             seed=seed,
                             secure_host_noise=secure,
                             mesh=mesh,
                             fused_epilogue=fused)
    kwargs = dict(metrics=metrics,
                  noise_kind=noise_kind,
                  max_partitions_contributed=3,
                  max_contributions_per_partition=2,
                  post_aggregation_thresholding=post_thresh,
                  output_noise_stddev=all(m in ADDITIVE for m in metrics))
    if strategy is not None:
        kwargs["partition_selection_strategy"] = strategy
    if vector:
        kwargs.update(vector_size=3,
                      vector_max_norm=5.0,
                      vector_norm_kind=pdp.NormKind.Linf)
    else:
        kwargs.update(min_value=0.0, max_value=5.0)
    result = engine.aggregate(data, pdp.AggregateParams(**kwargs),
                              extractors(), public_partitions=public)
    accountant.compute_budgets()
    return result


def assert_columns_identical(a: dict, b: dict):
    assert list(a) == list(b)  # same columns, same insertion order
    for name in a:
        np.testing.assert_array_equal(np.asarray(a[name]),
                                      np.asarray(b[name]),
                                      err_msg=name)


PARITY_CONFIGS = {
    "count_sum_private": dict(metrics=[M.COUNT, M.SUM]),
    "count_sum_public": dict(metrics=[M.COUNT, M.SUM],
                             public=[f"p{i}" for i in range(14)]),
    "mean_count_sum": dict(metrics=[M.MEAN, M.COUNT, M.SUM]),
    "variance_all": dict(metrics=[M.VARIANCE, M.MEAN, M.COUNT, M.SUM]),
    "variance_gaussian": dict(metrics=[M.VARIANCE],
                              noise_kind=pdp.NoiseKind.GAUSSIAN),
    "privacy_id_count": dict(metrics=[M.PRIVACY_ID_COUNT]),
    "post_agg_thresholding": dict(metrics=[M.COUNT, M.PRIVACY_ID_COUNT],
                                  post_thresh=True),
    "gaussian_count_sum": dict(metrics=[M.COUNT, M.SUM],
                               noise_kind=pdp.NoiseKind.GAUSSIAN),
    "laplace_thresholding_selection": dict(metrics=[M.COUNT],
                                           strategy=S.LAPLACE_THRESHOLDING),
    "gaussian_thresholding_selection": dict(
        metrics=[M.COUNT],
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        strategy=S.GAUSSIAN_THRESHOLDING),
    "percentile_mix": dict(metrics=[M.COUNT, M.PERCENTILE(50),
                                    M.PERCENTILE(90)]),
}


class TestDeviceNoiseParity:
    """Fused epilogue == legacy per-combiner loop, bit for bit, for seeded
    device-noise runs (secure_host_noise=False)."""

    @pytest.mark.parametrize("config", sorted(PARITY_CONFIGS))
    def test_bit_identical(self, engine_mesh, config):
        kwargs = PARITY_CONFIGS[config]
        fused = run_engine(True, mesh=engine_mesh, **kwargs).to_columns()
        legacy = run_engine(False, mesh=engine_mesh, **kwargs).to_columns()
        assert_columns_identical(fused, legacy)

    def test_vector_sum_bit_identical(self, engine_mesh):
        fused = run_engine(True, [M.VECTOR_SUM], vector=True,
                           mesh=engine_mesh).to_columns()
        legacy = run_engine(False, [M.VECTOR_SUM], vector=True,
                            mesh=engine_mesh).to_columns()
        assert_columns_identical(fused, legacy)

    def test_mesh_matches_single_device(self):
        """The mesh epilogue draws globally-keyed noise: when the partition
        count shards evenly (no mesh padding, which would change the draw
        shapes), the same seed releases the same values as the
        single-device epilogue."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = sharded.make_mesh(8)
        on_mesh = run_engine(True, [M.COUNT, M.SUM], nparts=40,
                             mesh=mesh).to_columns()
        single = run_engine(True, [M.COUNT, M.SUM],
                            nparts=40).to_columns()
        assert_columns_identical(on_mesh, single)

    def test_iterator_matches_columns(self):
        result = run_engine(True, [M.COUNT, M.SUM])
        cols = result.to_columns()
        rows = list(result)
        keep = np.asarray(cols["keep_mask"])
        kept_idx = np.flatnonzero(keep)
        assert len(rows) == len(kept_idx)
        for (_, metrics), i in zip(rows, kept_idx):
            assert metrics.count == pytest.approx(
                float(np.asarray(cols["count"])[i]))
            assert metrics.sum == pytest.approx(
                float(np.asarray(cols["sum"])[i]))


class TestHostNoiseParity:
    """Secure-host-noise path: the fused epilogue preserves the exact
    host-RNG draw order, so the seeded fallback RNG gives bit-identical
    releases; with the native (unseedable) sampler only distributional
    equivalence is checkable."""

    HOST_CONFIGS = ["count_sum_private", "count_sum_public", "mean_count_sum",
                    "variance_all", "post_agg_thresholding"]

    @pytest.mark.parametrize("config", HOST_CONFIGS)
    def test_seeded_fallback_identical(self, engine_mesh, config):
        if pdp.noise_core.using_native_sampling():
            pytest.skip("native secure sampler is not seedable")
        kwargs = PARITY_CONFIGS[config]
        fused = run_engine(True, secure=True, mesh=engine_mesh,
                           **kwargs).to_columns()
        legacy = run_engine(False, secure=True, mesh=engine_mesh,
                            **kwargs).to_columns()
        assert_columns_identical(fused, legacy)

    def test_noise_std_distribution(self):
        """Released COUNT noise std matches the calibrated Laplace std on
        the fused host path (the distributional contract that holds even
        with the native sampler)."""
        data = [(u, "a", 1.0) for u in range(1000)]
        params = pdp.AggregateParams(metrics=[M.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        samples = []
        for seed in range(300):
            accountant = pdp.NaiveBudgetAccountant(1.0, 1e-15)
            engine = pdp.JaxDPEngine(accountant, seed=seed,
                                     fused_epilogue=True)
            result = engine.aggregate(data, params, extractors(),
                                      public_partitions=["a"])
            accountant.compute_budgets()
            samples.append(dict(result)["a"].count - 1000.0)
        expected_std = np.sqrt(2.0)  # b = 1/eps, eps = 1
        assert np.std(samples) == pytest.approx(expected_std, rel=0.2)


class TestExecutableCache:
    """Second identical aggregate call: zero new jit traces. Shape or plan
    change: exactly one clean miss."""

    @staticmethod
    def _aggregate(n=500, nparts=7, metrics=(M.COUNT, M.SUM), seed=0,
                   cache=None):
        data = [(u, f"p{u % nparts}", float(u % 5)) for u in range(n)]
        accountant = pdp.NaiveBudgetAccountant(2.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant, seed=seed,
                                 secure_host_noise=False,
                                 epilogue_cache=cache)
        params = pdp.AggregateParams(metrics=list(metrics),
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=2,
                                     min_value=0.0, max_value=5.0)
        result = engine.aggregate(data, params, extractors())
        accountant.compute_budgets()
        return result.to_columns()

    def test_second_call_zero_retraces(self):
        cache = finalize.EpilogueCache()
        self._aggregate(seed=0, cache=cache)
        traces_before = finalize.trace_count()
        hits_before = cache.hits
        self._aggregate(seed=1, cache=cache)
        assert finalize.trace_count() == traces_before
        assert cache.hits == hits_before + 1

    def test_shared_default_cache_across_engines(self):
        # Fresh engines share the default cache: a repeated query shape
        # stays warm without threading a cache object through callers.
        self._aggregate(n=303, nparts=9, seed=0)
        traces_before = finalize.trace_count()
        self._aggregate(n=303, nparts=9, seed=1)
        assert finalize.trace_count() == traces_before

    def test_shape_change_misses_cleanly(self):
        cache = finalize.EpilogueCache()
        self._aggregate(nparts=7, seed=0, cache=cache)
        traces_before = finalize.trace_count()
        misses_before = cache.misses
        self._aggregate(nparts=13, seed=0, cache=cache)
        assert finalize.trace_count() == traces_before + 1
        assert cache.misses == misses_before + 1

    def test_plan_change_misses_cleanly(self):
        cache = finalize.EpilogueCache()
        self._aggregate(metrics=(M.COUNT, M.SUM), seed=0, cache=cache)
        traces_before = finalize.trace_count()
        misses_before = cache.misses
        self._aggregate(metrics=(M.COUNT,), seed=0, cache=cache)
        assert finalize.trace_count() == traces_before + 1
        assert cache.misses == misses_before + 1

    def test_host_noise_path_never_traces(self):
        traces_before = finalize.trace_count()
        data = [(u, f"p{u % 7}", 1.0) for u in range(300)]
        accountant = pdp.NaiveBudgetAccountant(2.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant)  # secure_host_noise default
        params = pdp.AggregateParams(metrics=[M.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=2)
        result = engine.aggregate(data, params, extractors())
        accountant.compute_budgets()
        result.to_columns()
        assert finalize.trace_count() == traces_before


class TestStddevScalars:
    """output_noise_stddev rides the plan as a scalar and expands to a
    column only at materialization — values and masking must match the
    legacy per-combiner np.full columns."""

    def test_stddev_columns_constant_and_masked(self):
        cols = run_engine(True, [M.COUNT, M.SUM]).to_columns()
        keep = cols["keep_mask"]
        for name in ("count_noise_stddev", "sum_noise_stddev"):
            col = np.asarray(cols[name])
            assert col.dtype == np.float64
            kept_vals = col[keep]
            assert len(np.unique(kept_vals)) == 1 and kept_vals[0] > 0
            assert np.isnan(col[~keep]).all()


class TestBatchedIterators:
    """The output iterators materialize columns once (batched decode /
    tolist) instead of per-row host calls."""

    def test_add_dp_noise_pairs_iterate(self):
        accountant = pdp.NaiveBudgetAccountant(1e6, 1e-9)
        engine = pdp.JaxDPEngine(accountant)
        pairs = [("a", 10.0), ("b", 20.0), ("c", 30.0)]
        params = pdp.AddDPNoiseParams(noise_kind=pdp.NoiseKind.LAPLACE,
                                      l0_sensitivity=1,
                                      linf_sensitivity=1.0)
        result = engine.add_dp_noise(pairs, params)
        accountant.compute_budgets()
        out = list(result)
        assert [pk for pk, _ in out] == ["a", "b", "c"]
        for (_, noised), (_, raw) in zip(out, pairs):
            assert isinstance(noised, float)
            assert noised == pytest.approx(raw, abs=0.1)

    def test_result_iterator_vector_rows(self):
        result = run_engine(True, [M.VECTOR_SUM], vector=True,
                            public=[f"p{i}" for i in range(11)])
        for _, metrics in result:
            assert np.asarray(metrics.vector_sum).shape == (3,)
