"""Durable serving fleet tests (ISSUE 10; SERVING.md "Fleet operation").

Contracts:
  * Store round-trip — a session saved and reopened (same process or,
    in tests/process_kill_test.py, after SIGKILL) serves warm queries
    BIT-identical to the original session, single-device and mesh8;
    corrupted wire payloads refuse to open, corrupted bound-cache
    entries are dropped and recompute via kernel replay.
  * Tenant durability — release journals and budget ledgers ride
    fsync'd WALs under the store: cross-restart replays are refused and
    spent budget stays spent.
  * Exact refunds — a query that fails before its release token commits
    refunds its tenant charge exactly (exhaust → refund → succeed), and
    leaves the session, bound cache and journal unpoisoned.
  * Fleet ladder — the SessionManager demotes LRU sessions
    device → host → disk under one budget and re-hydrates on demand,
    bit-identically.
  * Overload — queries beyond the in-flight gate shed with a typed
    SessionOverloadedError (never queue); a hung replay trips
    QueryDeadlineError within its deadline; RESOURCE_EXHAUSTED on a
    device-resident replay falls back to host shipping.
  * Concurrency — a tenant hammer with shedding shows no cross-tenant
    ledger or journal corruption.
"""

import glob
import os
import threading
import time
from unittest import mock

import jax
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler, runtime, serving
from pipelinedp_tpu.ops import streaming
from pipelinedp_tpu.parallel import sharded
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import watchdog as watchdog_lib

M = pdp.Metrics

N_ROWS = 8_000
N_USERS = 500
N_PARTS = 32  # divides 8: the mesh pads nothing, mesh == single-device
N_CHUNKS = 3


@pytest.fixture(params=["single_device", "mesh8"], scope="module")
def engine_mesh(request):
    if request.param == "single_device":
        return None
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


def make_columns(seed=0, n=N_ROWS, nparts=N_PARTS):
    rng = np.random.default_rng(seed)
    return pdp.ColumnarData(
        pid=rng.integers(0, N_USERS, n).astype(np.int32),
        pk=rng.integers(0, nparts, n).astype(np.int32),
        value=rng.integers(1, 6, n).astype(np.float32))


def count_sum_params(l0=8, linf=4):
    return pdp.AggregateParams(metrics=[M.COUNT, M.SUM],
                               max_partitions_contributed=l0,
                               max_contributions_per_partition=linf,
                               min_value=0.0,
                               max_value=5.0)


def assert_columns_identical(a: dict, b: dict):
    assert list(a) == list(b)
    for name in a:
        np.testing.assert_array_equal(np.asarray(a[name]),
                                      np.asarray(b[name]), err_msg=name)


def q(session, seed, **kw):
    kw.setdefault("epsilon", 1.0)
    kw.setdefault("delta", 1e-6)
    kw.setdefault("secure_host_noise", False)
    return session.query(count_sum_params(), seed=seed, **kw).to_columns()


class TestSessionStoreRoundTrip:
    """save() / SessionStore.open() — reopened sessions are the same
    session, bit for bit."""

    def test_reopen_warm_parity(self, tmp_path, engine_mesh):
        session = serving.DatasetSession(make_columns(), mesh=engine_mesh,
                                         n_chunks=N_CHUNKS, name="rt")
        want = q(session, seed=3)
        store = serving.SessionStore(str(tmp_path))
        session.save(store)
        reopened = store.open("rt", mesh=engine_mesh)
        got = q(reopened, seed=3)
        assert_columns_identical(want, got)
        # A seed the original session never ran matches too (full
        # replay through the restored wire, not a cached result).
        assert_columns_identical(q(session, seed=4), q(reopened, seed=4))

    def test_reopen_preserves_identity_and_refuses_wrong_mesh(
            self, tmp_path):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="id")
        store = serving.SessionStore(str(tmp_path))
        session.save(store)
        reopened = store.open("id")
        assert reopened.fingerprint == session.fingerprint
        assert reopened.n_chunks == session.n_chunks
        assert reopened.num_partitions == session.num_partitions
        if len(jax.devices()) >= 8:
            with pytest.raises(ValueError, match="n_dev"):
                store.open("id", mesh=sharded.make_mesh(8))

    def test_string_partition_keys_roundtrip(self, tmp_path):
        rng = np.random.default_rng(1)
        n = 2_000
        data = pdp.ColumnarData(
            pid=rng.integers(0, 200, n).astype(np.int32),
            pk=np.array([f"key_{i % 10}" for i in range(n)]),
            value=rng.integers(1, 6, n).astype(np.float32))
        session = serving.DatasetSession(data, n_chunks=2, name="strkeys")
        store = serving.SessionStore(str(tmp_path))
        session.save(store)
        reopened = store.open("strkeys")
        assert reopened.pk_vocab.keys == session.pk_vocab.keys
        a = session.query(count_sum_params(), epsilon=1.0, delta=1e-6,
                          seed=2, secure_host_noise=False)
        b = reopened.query(count_sum_params(), epsilon=1.0, delta=1e-6,
                           seed=2, secure_host_noise=False)
        assert a.partition_keys() == b.partition_keys()

    def test_missing_session_and_store_listing(self, tmp_path):
        store = serving.SessionStore(str(tmp_path))
        with pytest.raises(serving.SessionNotFoundError):
            store.open("nope")
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="listed")
        session.save(store)
        assert store.names() == ["listed"]
        assert store.exists("listed")
        store.delete("listed")
        assert store.names() == []

    def test_corrupted_wire_refuses_to_open(self, tmp_path):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="corrupt")
        store = serving.SessionStore(str(tmp_path))
        session.save(store)
        wire_path = os.path.join(store.path("corrupt"), "wire.npz")
        blob = bytearray(open(wire_path, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(wire_path, "wb").write(bytes(blob))
        with pytest.raises(serving.SessionCorruptError):
            store.open("corrupt")

    def test_corrupted_bound_entry_dropped_and_recomputed(self, tmp_path):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="bc")
        want = q(session, seed=3)  # populates the bound cache
        store = serving.SessionStore(str(tmp_path))
        session.save(store)
        entries = glob.glob(os.path.join(store.path("bc"), "bound",
                                         "*.npz"))
        assert entries  # the cached accumulators were spilled
        for path in entries:
            with open(path, "r+b") as f:
                f.seek(120)
                f.write(b"\xff\xff\xff\xff")
        before = profiler.event_count(serving.EVENT_BOUND_DROPPED)
        reopened = store.open("bc")
        assert (profiler.event_count(serving.EVENT_BOUND_DROPPED)
                > before)
        assert len(reopened._bound_cache) == 0
        # The corrupted accumulators recompute via kernel replay —
        # bit-identical, never wrong bits, never a crash.
        assert_columns_identical(want, q(reopened, seed=3))

    def test_save_requires_hydrated_session(self, tmp_path):
        store = serving.SessionStore(str(tmp_path))
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="sp")
        assert session.spill(store)
        assert session.is_spilled
        with pytest.raises(serving.SessionStoreError, match="spilled"):
            store.save(session)
        session.rehydrate()
        assert not session.is_spilled


class TestTenantDurability:
    """Per-tenant WAL journals and ledgers reattach across restarts."""

    def test_cross_restart_replay_refused_and_spend_survives(
            self, tmp_path):
        store = serving.SessionStore(str(tmp_path))
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="tenants")
        session.register_tenant("acme", total_epsilon=3.0,
                                total_delta=1e-5)
        session.save(store)
        want = q(session, seed=5, tenant="acme")
        reopened = store.open("tenants")
        state = reopened.tenant("acme")
        assert state.ledger.spent_epsilon == pytest.approx(1.0)
        assert len(state.release_journal) == 1
        # Same tenant, same seed, across the "restart": refused before
        # any noise is drawn — and the refused charge refunds exactly.
        with pytest.raises(runtime.DoubleReleaseError):
            q(reopened, seed=5, tenant="acme")
        assert state.ledger.spent_epsilon == pytest.approx(1.0)
        # A fresh seed is a fresh release, bit-identical across
        # sessions of the same wire.
        assert_columns_identical(q(session, seed=6, tenant="acme"),
                                 q(reopened, seed=6, tenant="acme"))

    def test_exhaustion_carries_across_reopen(self, tmp_path):
        store = serving.SessionStore(str(tmp_path))
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="exh")
        session.save(store)
        session.register_tenant("acme", total_epsilon=1.0,
                                total_delta=1e-6)
        q(session, seed=1, tenant="acme")  # spends the whole budget
        reopened = store.open("exh")
        with pytest.raises(serving.BudgetExhaustedError):
            q(reopened, seed=2, tenant="acme")

    def test_migration_replays_refunds_in_place(self, tmp_path):
        # A refunded charge freed budget a later charge reused; saving
        # the session (which migrates the in-memory ledger onto a WAL)
        # must replay that history without spuriously overdrawing.
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="mig")
        session.register_tenant("acme", total_epsilon=1.0,
                                total_delta=1e-6)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("host_crash", at_slab=0)])
        with pytest.raises(runtime.HostCrash):
            q(session, seed=1, tenant="acme", fault_injector=injector)
        q(session, seed=1, tenant="acme")  # reuses the refunded budget
        store = serving.SessionStore(str(tmp_path))
        session.save(store)  # must not raise BudgetExhaustedError
        reopened = store.open("mig")
        assert reopened.tenant("acme").ledger.spent_epsilon \
            == pytest.approx(1.0)

    def test_register_after_open_is_durable(self, tmp_path):
        store = serving.SessionStore(str(tmp_path))
        serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                               name="late").save(store)
        reopened = store.open("late")
        reopened.register_tenant("newco", total_epsilon=2.0,
                                 total_delta=1e-6)
        # No save() in between: the registration was recorded in the
        # manifest immediately, so a third process still sees it.
        third = store.open("late")
        assert third.tenant("newco").ledger.total_epsilon == 2.0


class TestExactRefunds:
    """Charge-before-run stays at-most-once; an uncommitted failure
    refunds exactly."""

    def test_exhaust_refund_succeed(self):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="refund")
        session.register_tenant("acme", total_epsilon=1.0,
                                total_delta=1e-6)
        state = session.tenant("acme")
        cache_before = len(session._bound_cache)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("host_crash", at_slab=0)])
        # The failing charge takes the ENTIRE budget: only an exact
        # refund lets the retry below fit.
        with pytest.raises(runtime.HostCrash):
            q(session, seed=5, tenant="acme", fault_injector=injector)
        assert state.ledger.spent_epsilon == 0.0
        assert state.ledger.spent_delta == 0.0
        assert len(state.release_journal) == 0  # journal unpoisoned
        assert len(session._bound_cache) == cache_before  # cache too
        q(session, seed=5, tenant="acme")  # exhausts, exactly
        assert state.ledger.spent_epsilon == pytest.approx(1.0)
        with pytest.raises(serving.BudgetExhaustedError):
            q(session, seed=6, tenant="acme")

    def test_ledger_refund_invariants(self):
        ledger = serving.TenantBudgetLedger("t", 2.0, 1e-6)
        charge = ledger.charge(1.5, 0.0)
        ledger.refund(charge)
        assert ledger.spent_epsilon == 0.0
        with pytest.raises(pdp.budget_accounting.BudgetAccountantError):
            ledger.refund(charge)  # double refund
        other = serving.TenantBudgetLedger("u", 2.0, 1e-6)
        foreign = other.charge(1.0, 0.0)
        with pytest.raises(pdp.budget_accounting.BudgetAccountantError):
            ledger.refund(foreign)  # never committed here

    def test_ledger_wal_roundtrip_with_refunds(self, tmp_path):
        wal_path = str(tmp_path / "ledger.wal")
        wal = runtime.FileReleaseJournal(wal_path)
        ledger = serving.TenantBudgetLedger("t", 5.0, 0.0, wal=wal)
        kept = ledger.charge(2.0, 0.0, note="kept")
        refunded = ledger.charge(1.0, 0.0, note="refunded")
        ledger.refund(refunded)
        wal.close()
        recovered = serving.TenantBudgetLedger(
            "t", 5.0, 0.0, wal=runtime.FileReleaseJournal(wal_path))
        assert recovered.spent_epsilon == pytest.approx(2.0)
        assert recovered.charges[0].note == "kept"
        assert recovered.refunded_indices == {refunded.index}

    def test_batch_prepare_failure_refunds_earlier_configs(self):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS,
                                         name="batchref")
        session.register_tenant("acme", total_epsilon=1.5,
                                total_delta=1e-5)
        state = session.tenant("acme")
        cfg = dict(metrics=[M.COUNT], epsilon=1.0, delta=1e-6,
                   max_partitions_contributed=8,
                   max_contributions_per_partition=4, tenant="acme")
        # Config 2's charge overdraws during preparation — config 1's
        # already-committed charge must refund (its launch never ran).
        with pytest.raises(serving.BudgetExhaustedError):
            session.query_batch([serving.QueryConfig(seed=1, **cfg),
                                 serving.QueryConfig(seed=2, **cfg)])
        assert state.ledger.spent_epsilon == 0.0


class TestManagerLadder:
    """LRU demotion (device → host → disk) under one fleet budget."""

    def test_demotion_spill_and_rehydration_parity(self, tmp_path):
        store = serving.SessionStore(str(tmp_path))
        # A 1-byte budget forces every admitted session down the full
        # ladder as soon as another needs the space.
        manager = serving.SessionManager(store, budget_bytes=1,
                                         max_inflight=4)
        manager.create("a", make_columns(1), n_chunks=N_CHUNKS)
        manager.create("b", make_columns(2), n_chunks=N_CHUNKS)
        counters = serving.fleet_counters(manager)
        assert counters["demotions"] > 0
        assert counters["sessions_spilled"] >= 1
        # Querying the spilled LRU session re-hydrates it on demand —
        # bit-identical to a never-spilled session over the same data.
        want = q(serving.DatasetSession(make_columns(1), n_chunks=N_CHUNKS),
                 seed=3)
        before = profiler.event_count(serving.EVENT_REHYDRATIONS)
        got = manager.query("a", count_sum_params(), epsilon=1.0,
                            delta=1e-6, seed=3, secure_host_noise=False
                            ).to_columns()
        assert profiler.event_count(serving.EVENT_REHYDRATIONS) > before
        assert_columns_identical(want, got)
        manager.close()

    def test_rich_budget_keeps_sessions_resident(self, tmp_path):
        store = serving.SessionStore(str(tmp_path))
        manager = serving.SessionManager(store, budget_bytes=1 << 30)
        session = manager.create("only", make_columns(3),
                                 n_chunks=N_CHUNKS)
        assert not session.is_spilled
        counters = serving.fleet_counters(manager)
        assert counters["sessions_resident"] == 1
        assert counters["sessions_spilled"] == 0
        assert manager.get("only") is session
        manager.remove("only")
        with pytest.raises(KeyError):
            manager.get("only")
        session.close()

    def test_attach_rejects_duplicate_names(self, tmp_path):
        manager = serving.SessionManager(
            serving.SessionStore(str(tmp_path)), budget_bytes=1 << 30)
        manager.create("dup", make_columns(4), n_chunks=N_CHUNKS)
        with pytest.raises(ValueError, match="already"):
            manager.attach(serving.DatasetSession(
                make_columns(5), n_chunks=N_CHUNKS, name="dup"))
        manager.close()


class TestAdmissionControl:
    """The bounded in-flight gate sheds typed, never queues."""

    def test_overload_sheds_typed_then_recovers(self, tmp_path):
        manager = serving.SessionManager(
            serving.SessionStore(str(tmp_path)), budget_bytes=1 << 30,
            max_inflight=1)
        session = manager.create("gate", make_columns(6),
                                 n_chunks=N_CHUNKS)
        q(session, seed=1)  # compile outside the timed window
        release = threading.Event()
        entered = threading.Event()
        errors = []

        orig = streaming._ResidentReplayPlacement.transfer

        def blocking(placement, slab, s0, s1):
            entered.set()
            release.wait(timeout=30)
            return orig(placement, slab, s0, s1)

        def occupant():
            try:
                with mock.patch.object(streaming._ResidentReplayPlacement,
                                       "transfer", blocking):
                    q(session, seed=2)
            except Exception as exc:  # surfaced to the main thread
                errors.append(exc)

        thread = threading.Thread(target=occupant)
        thread.start()
        assert entered.wait(timeout=30)
        before = profiler.event_count(serving.EVENT_QUERIES)
        shed_before = profiler.event_count(serving.EVENT_SHED)
        with pytest.raises(serving.SessionOverloadedError):
            q(session, seed=3)
        assert profiler.event_count(serving.EVENT_SHED) == shed_before + 1
        # Shed means shed: nothing ran, nothing queued.
        assert profiler.event_count(serving.EVENT_QUERIES) == before
        release.set()
        thread.join(timeout=60)
        assert not errors
        # The gate freed: the same query now succeeds.
        q(session, seed=3)
        manager.close()

    def test_shed_tenant_charge_refunds(self, tmp_path):
        manager = serving.SessionManager(
            serving.SessionStore(str(tmp_path)), budget_bytes=1 << 30,
            max_inflight=1)
        session = manager.create("gate2", make_columns(7),
                                 n_chunks=N_CHUNKS)
        session.register_tenant("acme", total_epsilon=10.0,
                                total_delta=1e-5)
        state = session.tenant("acme")
        with manager.admission():  # fill the gate from this thread
            with pytest.raises(serving.SessionOverloadedError):
                q(session, seed=4, tenant="acme")
        assert state.ledger.spent_epsilon == 0.0  # exact refund
        manager.close()


class TestQueryDeadlines:
    """Per-query deadlines ride the DispatchWatchdog and the driver's
    cooperative between-window check."""

    def test_hung_replay_trips_deadline_within_budget(self):
        session = serving.DatasetSession(make_columns(8),
                                         n_chunks=N_CHUNKS, name="dl")
        q(session, seed=1)  # compile first: the deadline times the hang
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("hang", at_slab=0, hang_s=30.0)])
        before = profiler.event_count(serving.EVENT_DEADLINE_HITS)
        t0 = time.monotonic()
        with pytest.raises(serving.QueryDeadlineError):
            q(session, seed=2, deadline_s=1.0, fault_injector=injector)
        elapsed = time.monotonic() - t0
        assert elapsed < 10.0, f"deadline took {elapsed:.1f}s"
        assert profiler.event_count(serving.EVENT_DEADLINE_HITS) \
            == before + 1

    def test_driver_cooperative_deadline_check(self):
        # An already-expired Deadline in the resilience bundle trips at
        # the first window boundary — no watchdog, no hang needed.
        session = serving.DatasetSession(make_columns(9),
                                         n_chunks=N_CHUNKS, name="coop")
        resilience = runtime.StreamResilience(
            deadline=runtime.Deadline.after(-1.0))
        key = jax.random.PRNGKey(0)
        with pytest.raises(serving.QueryDeadlineError):
            streaming.replay_resident_wire(
                key, session._wire, linf_cap=4, l0_cap=8,
                row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                group_clip_lo=0.0, group_clip_hi=20.0,
                resilience=resilience)

    def test_deadline_is_classified_retryable(self):
        err = watchdog_lib.QueryDeadlineError("query", 1.0)
        assert retry_lib.classify(err) == retry_lib.TRANSIENT

    def test_deadline_keeps_tenant_charge_conservatively(self):
        session = serving.DatasetSession(make_columns(10),
                                         n_chunks=N_CHUNKS, name="dlt")
        session.register_tenant("acme", total_epsilon=10.0,
                                total_delta=1e-5)
        state = session.tenant("acme")
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("hang", at_slab=0, hang_s=15.0)])
        with pytest.raises(serving.QueryDeadlineError):
            q(session, seed=2, tenant="acme", deadline_s=0.5,
              fault_injector=injector)
        # The abandoned worker could still commit a release: the charge
        # stays (err toward spent, never toward double-release).
        assert state.ledger.spent_epsilon == pytest.approx(1.0)


class TestDeviceOomFallback:
    """RESOURCE_EXHAUSTED on a device-resident replay degrades to host
    shipping instead of failing the query."""

    def test_fallback_serves_bit_identical(self):
        data = make_columns(11)
        session = serving.DatasetSession(data, n_chunks=N_CHUNKS,
                                         name="oom")
        assert session._wire.device_resident
        want = q(serving.DatasetSession(data, n_chunks=N_CHUNKS), seed=7)

        orig = streaming._ResidentReplayPlacement.transfer

        def oom_when_resident(placement, slab, s0, s1):
            if placement._device_slab is not None:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: out of device memory")
            return orig(placement, slab, s0, s1)

        before = profiler.event_count(serving.EVENT_DEVICE_FALLBACKS)
        with mock.patch.object(streaming._ResidentReplayPlacement,
                               "transfer", oom_when_resident):
            got = q(session, seed=7)
        assert profiler.event_count(serving.EVENT_DEVICE_FALLBACKS) \
            == before + 1
        assert not session._wire.device_resident
        assert_columns_identical(want, got)


class TestConcurrentTenantHammer:
    """Shedding + concurrent tenants: every ledger and journal stays
    exactly consistent with the set of successful queries."""

    def test_no_cross_tenant_corruption_under_shedding(self, tmp_path):
        manager = serving.SessionManager(
            serving.SessionStore(str(tmp_path)), budget_bytes=1 << 30,
            max_inflight=2)
        session = manager.create("hammer", make_columns(12),
                                 n_chunks=N_CHUNKS)
        tenants = ["t0", "t1", "t2"]
        for tid in tenants:
            session.register_tenant(tid, total_epsilon=100.0,
                                    total_delta=1e-3)
        q(session, seed=999)  # compile up front
        outcomes = {tid: {"ok": 0, "shed": 0} for tid in tenants}
        outcome_lock = threading.Lock()
        errors = []

        def worker(tid, seed):
            try:
                q(session, seed=seed, tenant=tid)
                with outcome_lock:
                    outcomes[tid]["ok"] += 1
            except serving.SessionOverloadedError:
                with outcome_lock:
                    outcomes[tid]["shed"] += 1
            except Exception as exc:
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(tid, 100 * i + j))
            for i, tid in enumerate(tenants) for j in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        total = sum(o["ok"] + o["shed"] for o in outcomes.values())
        assert total == len(threads)
        for tid in tenants:
            state = session.tenant(tid)
            # Ledger: exactly one epsilon per successful query (sheds
            # refunded exactly); journal: exactly one release per
            # successful query, none leaked across tenants.
            assert state.ledger.spent_epsilon == pytest.approx(
                float(outcomes[tid]["ok"]))
            assert len(state.release_journal) == outcomes[tid]["ok"]
        manager.close()
