"""End-to-end DPEngine tests on LocalBackend.

Mirrors the reference's techniques (tests/dp_engine_test.py): huge-eps
no-noise runs compared against plain groupby, computation-graph assertions
via explain reports, statistical partition-selection tests, and mock
partition selection strategies."""

from unittest import mock

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import partition_selection as ps_module


def make_engine(eps=1e8, delta=1e-15, accountant_out=None):
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                           total_delta=delta)
    engine = pdp.DPEngine(accountant, pdp.LocalBackend())
    if accountant_out is not None:
        accountant_out.append(accountant)
    return engine, accountant


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda row: row[0],
                              partition_extractor=lambda row: row[1],
                              value_extractor=lambda row: row[2])


def dataset(n_users=10, partitions=("a", "b", "c"), value=2.0):
    # Each user contributes `value` once to every partition.
    return [(uid, pk, value) for uid in range(n_users) for pk in partitions]


class TestAggregatePublicPartitions:

    def test_count_sum_no_noise_equals_raw(self):
        engine, accountant = make_engine()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=3,
            max_contributions_per_partition=1,
            min_value=0,
            max_value=3)
        result = engine.aggregate(dataset(), params, extractors(),
                                  public_partitions=["a", "b", "c"])
        accountant.compute_budgets()
        result = dict(result)
        assert set(result) == {"a", "b", "c"}
        for pk in "abc":
            assert result[pk].count == pytest.approx(10, abs=1e-2)
            assert result[pk].sum == pytest.approx(20.0, abs=0.1)

    def test_empty_public_partitions_present_in_output(self):
        engine, accountant = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(partitions=("a",)), params,
                                  extractors(),
                                  public_partitions=["a", "empty_pk"])
        accountant.compute_budgets()
        result = dict(result)
        assert result["empty_pk"].count == pytest.approx(0, abs=1e-2)

    def test_non_public_partitions_dropped(self):
        engine, accountant = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(dataset(), params, extractors(),
                                  public_partitions=["a"])
        accountant.compute_budgets()
        assert set(dict(result)) == {"a"}

    def test_contribution_bounding_caps_count(self):
        engine, accountant = make_engine()
        # One user contributes 100 times to one partition; Linf bound is 5.
        data = [(0, "a", 1.0)] * 100
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=5)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a"])
        accountant.compute_budgets()
        assert dict(result)["a"].count == pytest.approx(5, abs=1e-2)

    def test_cross_partition_bounding_caps_partitions(self):
        engine, accountant = make_engine()
        # One user contributes to 10 partitions, L0 bound is 2.
        data = [(0, f"pk{i}", 1.0) for i in range(10)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=1)
        public = [f"pk{i}" for i in range(10)]
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=public)
        accountant.compute_budgets()
        total = sum(m.count for m in dict(result).values())
        assert total == pytest.approx(2, abs=0.1)

    def test_mean_no_noise(self):
        engine, accountant = make_engine()
        data = [(u, "a", float(v)) for u, v in enumerate([1, 2, 6])]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.MEAN],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0,
                                     max_value=10)
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=["a"])
        accountant.compute_budgets()
        assert dict(result)["a"].mean == pytest.approx(3.0, abs=0.1)

    def test_multiple_aggregations_same_accountant(self):
        engine, accountant = make_engine(eps=1e8)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        r1 = engine.aggregate(dataset(partitions=("a",)), params, extractors(),
                              public_partitions=["a"])
        r2 = engine.aggregate(dataset(partitions=("a",)), params, extractors(),
                              public_partitions=["a"])
        accountant.compute_budgets()
        assert dict(r1)["a"].count == pytest.approx(10, abs=1e-2)
        assert dict(r2)["a"].count == pytest.approx(10, abs=1e-2)

    def test_contribution_bounds_already_enforced(self):
        engine, accountant = make_engine()
        data = [("ignored", "a", 1.0)] * 7
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     contribution_bounds_already_enforced=True)
        ext = pdp.DataExtractors(partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        result = engine.aggregate(data, params, ext, public_partitions=["a"])
        accountant.compute_budgets()
        assert dict(result)["a"].count == pytest.approx(7, abs=1e-2)


class TestAggregatePrivatePartitions:

    def test_small_partitions_dropped_large_kept(self):
        ps_module.seed_rng(0)
        engine, accountant = make_engine(eps=1.0, delta=1e-6)
        # 'big' has 1000 users, 'tiny' has 1.
        data = ([(u, "big", 1.0) for u in range(1000)] +
                [(9999, "tiny", 1.0)])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        result = engine.aggregate(data, params, extractors())
        accountant.compute_budgets()
        kept = set(dict(result))
        assert "big" in kept
        assert "tiny" not in kept

    def test_mock_partition_selection(self):
        engine, accountant = make_engine()

        class KeepAll:

            def should_keep(self, n):
                return True

        with mock.patch.object(ps_module,
                               "create_partition_selection_strategy",
                               return_value=KeepAll()):
            data = [(u, "pk", 1.0) for u in range(3)]
            params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                         max_partitions_contributed=1,
                                         max_contributions_per_partition=1)
            result = engine.aggregate(data, params, extractors())
            accountant.compute_budgets()
            assert dict(result)["pk"].count == pytest.approx(3, abs=1e-2)

    def test_post_aggregation_thresholding(self):
        engine, accountant = make_engine(eps=1.0, delta=1e-6)
        data = ([(u, "big", 1.0) for u in range(1000)] +
                [(7777, "tiny", 1.0)])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     post_aggregation_thresholding=True)
        result = engine.aggregate(data, params, extractors())
        accountant.compute_budgets()
        result = dict(result)
        assert "tiny" not in result
        assert result["big"].privacy_id_count == pytest.approx(1000, rel=0.2)


class TestSelectPartitions:

    def test_large_partitions_selected(self):
        ps_module.seed_rng(0)
        engine, accountant = make_engine(eps=1.0, delta=1e-6)
        data = ([(u, "big1", 0) for u in range(500)] +
                [(u, "big2", 0) for u in range(500)] +
                [(1, "tiny", 0)])
        params = pdp.SelectPartitionsParams(max_partitions_contributed=2)
        result = engine.select_partitions(data, params, extractors())
        accountant.compute_budgets()
        selected = set(result)
        assert {"big1", "big2"} <= selected
        assert "tiny" not in selected

    def test_validation(self):
        engine, _ = make_engine()
        with pytest.raises(ValueError, match="non-empty"):
            engine.select_partitions(
                [], pdp.SelectPartitionsParams(max_partitions_contributed=1),
                extractors())


class TestAddDPNoise:

    def test_no_noise_passthrough(self):
        engine, accountant = make_engine()
        data = [("a", 10.0), ("b", 20.0)]
        params = pdp.AddDPNoiseParams(noise_kind=pdp.NoiseKind.LAPLACE,
                                      l0_sensitivity=1,
                                      linf_sensitivity=1.0)
        result = engine.add_dp_noise(data, params)
        accountant.compute_budgets()
        result = dict(result)
        assert result["a"] == pytest.approx(10.0, abs=1e-2)
        assert result["b"] == pytest.approx(20.0, abs=1e-2)


class TestExplainComputation:

    def test_report_stages(self):
        engine, accountant = make_engine()
        report = pdp.ExplainComputationReport()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=3)
        engine.aggregate(dataset(), params, extractors(),
                         public_partitions=["a"],
                         out_explain_computation_report=report)
        accountant.compute_budgets()
        text = report.text()
        assert "DPEngine method: aggregate" in text
        assert "Per-partition contribution bounding" in text
        assert "Cross-partition contribution bounding" in text
        assert "Computed DP count" in text
        assert "public partitions" in text

    def test_private_partition_report_mentions_strategy(self):
        engine, accountant = make_engine(eps=1.0, delta=1e-6)
        report = pdp.ExplainComputationReport()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        engine.aggregate(dataset(), params, extractors(),
                         out_explain_computation_report=report)
        accountant.compute_budgets()
        assert "Truncated Geometric" in report.text()

    def test_report_before_compute_budgets_raises(self):
        engine, accountant = make_engine()
        report = pdp.ExplainComputationReport()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=3)
        engine.aggregate(dataset(), params, extractors(),
                         public_partitions=["a"],
                         out_explain_computation_report=report)
        with pytest.raises(ValueError, match="compute_budgets"):
            report.text()


class TestValidation:

    def test_empty_col(self):
        engine, _ = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        with pytest.raises(ValueError, match="non-empty"):
            engine.aggregate([], params, extractors())

    def test_bad_params_type(self):
        engine, _ = make_engine()
        with pytest.raises(TypeError):
            engine.aggregate([1], "not params", extractors())

    def test_post_agg_thresholding_requires_privacy_id_count(self):
        engine, _ = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     post_aggregation_thresholding=True)
        with pytest.raises(ValueError, match="PRIVACY_ID_COUNT"):
            engine.aggregate([1], params, extractors())

    def test_pld_with_private_partitions_unsupported(self):
        accountant = pdp.PLDBudgetAccountant(1.0, 1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        with pytest.raises(NotImplementedError, match="PLD"):
            engine.aggregate(dataset(), params, extractors())


class TestStatistical:

    def test_count_noise_distribution(self):
        """e2e with real noise: std of DP count error matches mechanism."""
        from pipelinedp_tpu import noise_core
        noise_core.seed_fallback_rng(5)
        eps = 1.0
        n_partitions = 300
        engine, accountant = make_engine(eps=eps, delta=0)
        data = [(u, f"pk{p}", 1.0) for p in range(n_partitions)
                for u in range(10)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=n_partitions,
                                     max_contributions_per_partition=1)
        public = [f"pk{p}" for p in range(n_partitions)]
        result = engine.aggregate(data, params, extractors(),
                                  public_partitions=public)
        accountant.compute_budgets()
        errors = np.array([m.count - 10 for _, m in result])
        # Laplace noise: b = l1/eps = n_partitions, std = b*sqrt(2).
        expected_std = n_partitions * np.sqrt(2)
        assert abs(errors.mean()) < expected_std / 3
        assert errors.std() == pytest.approx(expected_std, rel=0.25)


class TestComputationGraph:
    """Stage-sequence assertions on the explain report (the reference's
    computation-graph tests, tests/dp_engine_test.py:528-630): the report
    is the contract for WHAT the engine did to the data."""

    def _report(self, params, public=None, data=None):
        engine, accountant = make_engine()
        report = pdp.ExplainComputationReport()
        engine.aggregate(data or dataset(), params, extractors(),
                         public_partitions=public,
                         out_explain_computation_report=report)
        accountant.compute_budgets()
        return report.text()

    def test_standard_graph_stage_order(self):
        text = self._report(
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_partitions_contributed=2,
                                max_contributions_per_partition=3),
            public=["a", "b"])
        stages = text.splitlines()
        idx = {}
        for marker in ("Per-partition contribution bounding",
                       "Cross-partition contribution bounding",
                       "Computed DP count"):
            idx[marker] = next(i for i, s in enumerate(stages) if marker in s)
        assert (idx["Per-partition contribution bounding"] <
                idx["Cross-partition contribution bounding"] <
                idx["Computed DP count"])

    def test_l1_mode_graph(self):
        text = self._report(
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_partitions_contributed=None,
                                max_contributions_per_partition=None,
                                max_contributions=5),
            public=["a", "b"])
        assert "max_contributions" in text or "Total contribution" in text
        assert "Cross-partition contribution bounding" not in text

    def test_per_partition_sum_bounds_graph(self):
        text = self._report(
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=2,
                                max_contributions_per_partition=3,
                                min_sum_per_partition=0.0,
                                max_sum_per_partition=5.0),
            public=["a", "b"])
        # Linf sampling is the combiner's job in this mode (per-partition
        # sum clipping): only the cross-partition stage appears.
        assert "Cross-partition contribution bounding" in text
        assert "Per-partition contribution bounding" not in text

    def test_private_selection_graph(self):
        engine, accountant = make_engine(eps=1.0, delta=1e-6)
        report = pdp.ExplainComputationReport()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        engine.aggregate(dataset(), params, extractors(),
                         out_explain_computation_report=report)
        accountant.compute_budgets()
        text = report.text()
        assert "Private Partition selection" in text
        assert "Truncated Geometric" in text

    def test_post_aggregation_thresholding_graph(self):
        engine, accountant = make_engine(eps=1.0, delta=1e-6)
        report = pdp.ExplainComputationReport()
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            post_aggregation_thresholding=True)
        engine.aggregate(dataset(), params, extractors(),
                         out_explain_computation_report=report)
        accountant.compute_budgets()
        assert "threshold" in report.text().lower()


class TestValidationMatrix:
    """Engine-level rejection of invalid requests (reference
    tests/dp_engine_test.py validation coverage)."""

    def test_row_input_requires_extractors(self):
        engine, _ = make_engine()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        with pytest.raises((TypeError, ValueError)):
            engine.aggregate(dataset(), params, None)

    def test_select_partitions_validation(self):
        engine, _ = make_engine()
        with pytest.raises(ValueError):
            engine.select_partitions(
                dataset(), pdp.SelectPartitionsParams(
                    max_partitions_contributed=0), extractors())

    def test_sum_requires_bounds(self):
        with pytest.raises(ValueError):
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1)

    def test_min_above_max_rejected(self):
        with pytest.raises(ValueError):
            pdp.AggregateParams(metrics=[pdp.Metrics.MEAN],
                                max_partitions_contributed=1,
                                max_contributions_per_partition=1,
                                min_value=2.0, max_value=1.0)

    def test_l1_mode_excludes_l0_linf(self):
        with pytest.raises(ValueError):
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_partitions_contributed=2,
                                max_contributions_per_partition=1,
                                max_contributions=5)

    def test_second_aggregation_shares_budget(self):
        # Two aggregations on one accountant: both resolve, splitting eps.
        accountant = pdp.NaiveBudgetAccountant(2.0, 1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        r1 = engine.aggregate(dataset(), params, extractors(),
                              public_partitions=["a"])
        r2 = engine.aggregate(dataset(), params, extractors(),
                              public_partitions=["a"])
        accountant.compute_budgets()
        assert len(list(r1)) == 1 and len(list(r2)) == 1
