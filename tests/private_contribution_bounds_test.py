"""Tests for DP contribution-bound calculation."""

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import private_contribution_bounds as pcb
from pipelinedp_tpu.dataset_histograms import computing_histograms as ch
from pipelinedp_tpu.dataset_histograms import histograms as hist


def params(calc_eps=10.0, upper=100):
    return pdp.CalculatePrivateContributionBoundsParams(
        aggregation_noise_kind=pdp.NoiseKind.LAPLACE,
        aggregation_eps=1.0,
        aggregation_delta=0.0,
        calculation_eps=calc_eps,
        max_partitions_contributed_upper_bound=upper)


def l0_histogram(counts):
    bins = []
    for value, freq in sorted(counts.items()):
        lower, upper = ch._to_bin_lower_upper_logarithmic(value)
        bins.append(
            hist.FrequencyBin(lower=lower, upper=upper, count=freq,
                              sum=freq * value, max=value))
    return hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS, bins)


class TestGenerateBounds:

    def test_small(self):
        bounds = pcb.generate_possible_contribution_bounds(12)
        assert bounds == list(range(1, 13))

    def test_three_significant_digits(self):
        bounds = pcb.generate_possible_contribution_bounds(10200)
        assert 999 in bounds
        assert 1000 in bounds
        assert 1001 not in bounds
        assert 1010 in bounds
        assert 10100 in bounds
        assert bounds[-1] == 10200

    def test_logarithmic_size(self):
        bounds = pcb.generate_possible_contribution_bounds(10**7)
        assert len(bounds) < 5000


class TestL0ScoringFunction:

    def test_monotonic_tradeoff(self):
        # Most users contribute to ~10 partitions.
        scoring = pcb.L0ScoringFunction(params(), 50, l0_histogram({10: 100}))
        # Dropped data decreases with k, noise increases with k.
        assert scoring._l0_impact_dropped(1) > scoring._l0_impact_dropped(5)
        assert scoring._l0_impact_dropped(10) == 0
        assert scoring._l0_impact_noise(10) > scoring._l0_impact_noise(1)

    def test_noise_impact_formula(self):
        scoring = pcb.L0ScoringFunction(params(), 50, l0_histogram({10: 100}))
        noise_params = dp_computations.ScalarNoiseParams(
            eps=1.0, delta=0.0, min_value=None, max_value=None,
            min_sum_per_partition=None, max_sum_per_partition=None,
            max_partitions_contributed=5, max_contributions_per_partition=1,
            noise_kind=pdp.NoiseKind.LAPLACE)
        expected = 50 * dp_computations.compute_dp_count_noise_std(
            noise_params)
        assert scoring._l0_impact_noise(5) == pytest.approx(expected)

    def test_upper_bound_capped_by_partitions(self):
        scoring = pcb.L0ScoringFunction(params(upper=1000), 7,
                                        l0_histogram({3: 10}))
        assert scoring.max_partitions_contributed_best_upper_bound() == 7
        assert scoring.global_sensitivity == 7


class TestPrivateL0Calculator:

    def test_picks_reasonable_bound(self):
        dp_computations.ExponentialMechanism.seed_rng(0)
        # 100 users each contributing to exactly 8 partitions of 20.
        data = [(u, f"pk{i}", 1.0) for u in range(100) for i in range(8)]
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda r: r[0],
            partition_extractor=lambda r: r[1],
            value_extractor=lambda r: r[2])
        backend = pdp.LocalBackend()
        histograms = ch.compute_dataset_histograms(data, extractors, backend)
        partitions = [f"pk{i}" for i in range(20)]
        calc = pcb.PrivateL0Calculator(params(calc_eps=20.0), partitions,
                                       histograms, backend)
        result = list(calc.calculate())
        assert len(result) == 1
        # With high calculation eps the mechanism should pick close to the
        # true optimum (8 = actual contributions; more just adds noise).
        assert 4 <= result[0] <= 10

    def test_engine_integration(self):
        dp_computations.ExponentialMechanism.seed_rng(0)
        data = [(u, f"pk{i}", 1.0) for u in range(50) for i in range(4)]
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda r: r[0],
            partition_extractor=lambda r: r[1],
            value_extractor=lambda r: r[2])
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        result = engine.calculate_private_contribution_bounds(
            data, params(calc_eps=20.0, upper=10), extractors,
            partitions=[f"pk{i}" for i in range(4)])
        bounds = list(result)[0]
        assert isinstance(bounds, pdp.PrivateContributionBounds)
        assert 1 <= bounds.max_partitions_contributed <= 10
