"""Tests for combiners (mirrors reference tests/combiners_test.py technique:
no-noise specs with huge eps so DP output ~ raw output)."""

import pickle

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import budget_accounting as ba
from pipelinedp_tpu import combiners
from pipelinedp_tpu.aggregate_params import MechanismType


def no_noise_spec(mechanism_type=MechanismType.LAPLACE):
    spec = ba.MechanismSpec(mechanism_type)
    spec.set_eps_delta(1e8, 1e-15 if mechanism_type != MechanismType.LAPLACE
                       else 0.0)
    return spec


def count_params(**overrides):
    kwargs = dict(metrics=[pdp.Metrics.COUNT],
                  max_partitions_contributed=2,
                  max_contributions_per_partition=3)
    kwargs.update(overrides)
    return pdp.AggregateParams(**kwargs)


class TestCountCombiner:

    def test_accumulator_algebra(self):
        combiner = combiners.CountCombiner(no_noise_spec(), count_params())
        acc1 = combiner.create_accumulator([1, 2, 3])
        acc2 = combiner.create_accumulator([4])
        assert combiner.merge_accumulators(acc1, acc2) == 4

    def test_compute_metrics_no_noise(self):
        combiner = combiners.CountCombiner(no_noise_spec(), count_params())
        assert combiner.compute_metrics(7)["count"] == pytest.approx(7,
                                                                     abs=1e-3)

    def test_pickles_without_mechanism(self):
        combiner = combiners.CountCombiner(no_noise_spec(), count_params())
        combiner.compute_metrics(1)  # instantiate the lazy mechanism
        assert hasattr(combiner, "_mechanism")
        restored = pickle.loads(pickle.dumps(combiner))
        assert not hasattr(restored, "_mechanism")
        # And it still works, recreating the mechanism on demand.
        assert restored.compute_metrics(5)["count"] == pytest.approx(5,
                                                                     abs=1e-3)


class TestSumCombiner:

    def test_per_contribution_clipping(self):
        params = count_params(metrics=[pdp.Metrics.SUM],
                              min_value=0,
                              max_value=2)
        combiner = combiners.SumCombiner(no_noise_spec(), params)
        # 5 clipped to 2, -1 clipped to 0.
        assert combiner.create_accumulator([1, 5, -1]) == pytest.approx(3.0)
        assert combiner.expects_per_partition_sampling()

    def test_per_partition_clipping(self):
        params = count_params(metrics=[pdp.Metrics.SUM],
                              min_sum_per_partition=0,
                              max_sum_per_partition=4)
        combiner = combiners.SumCombiner(no_noise_spec(), params)
        # Sum 1+5-1=5 clipped to 4.
        assert combiner.create_accumulator([1, 5, -1]) == pytest.approx(4.0)
        assert not combiner.expects_per_partition_sampling()

    def test_compute_metrics_no_noise(self):
        params = count_params(metrics=[pdp.Metrics.SUM],
                              min_value=0,
                              max_value=10)
        combiner = combiners.SumCombiner(no_noise_spec(), params)
        assert combiner.compute_metrics(42.0)["sum"] == pytest.approx(42,
                                                                      abs=1e-2)


class TestPrivacyIdCountCombiner:

    def test_accumulator(self):
        combiner = combiners.PrivacyIdCountCombiner(
            no_noise_spec(), count_params(metrics=[pdp.Metrics.PRIVACY_ID_COUNT]))
        assert combiner.create_accumulator([1, 2]) == 1
        assert combiner.create_accumulator([]) == 0
        assert combiner.merge_accumulators(1, 1) == 2
        assert not combiner.expects_per_partition_sampling()


class TestMeanCombiner:

    def test_mean_no_noise(self):
        params = count_params(metrics=[pdp.Metrics.MEAN],
                              min_value=0,
                              max_value=10)
        combiner = combiners.MeanCombiner(no_noise_spec(), no_noise_spec(),
                                          params, ["mean", "count", "sum"])
        acc = combiner.create_accumulator([1.0, 2.0, 6.0])
        assert acc[0] == 3
        assert acc[1] == pytest.approx(-6.0)  # (1-5)+(2-5)+(6-5)
        metrics = combiner.compute_metrics(acc)
        assert metrics["mean"] == pytest.approx(3.0, abs=1e-2)
        assert metrics["count"] == pytest.approx(3, abs=1e-2)
        assert metrics["sum"] == pytest.approx(9.0, abs=0.1)

    def test_validation(self):
        params = count_params(metrics=[pdp.Metrics.MEAN],
                              min_value=0,
                              max_value=10)
        with pytest.raises(ValueError, match="mean"):
            combiners.MeanCombiner(no_noise_spec(), no_noise_spec(), params,
                                   ["count"])
        with pytest.raises(ValueError, match="duplicates"):
            combiners.MeanCombiner(no_noise_spec(), no_noise_spec(), params,
                                   ["mean", "mean"])


class TestVarianceCombiner:

    def test_variance_no_noise(self):
        params = count_params(metrics=[pdp.Metrics.VARIANCE],
                              min_value=0,
                              max_value=8)
        combiner = combiners.VarianceCombiner(
            combiners.CombinerParams(no_noise_spec(), params),
            ["variance", "mean"])
        values = [1.0, 3.0, 5.0, 7.0]
        acc = combiner.create_accumulator(values)
        metrics = combiner.compute_metrics(acc)
        assert metrics["variance"] == pytest.approx(np.var(values), abs=0.1)
        assert metrics["mean"] == pytest.approx(4.0, abs=0.1)


class TestQuantileCombiner:

    def test_quantiles_no_noise(self):
        params = count_params(metrics=[pdp.Metrics.PERCENTILE(50)],
                              min_value=0,
                              max_value=100)
        combiner = combiners.QuantileCombiner(
            combiners.CombinerParams(no_noise_spec(), params), [10, 50, 90])
        values = list(range(101))
        acc = combiner.create_accumulator(values)
        metrics = combiner.compute_metrics(acc)
        assert metrics["percentile_10"] == pytest.approx(10, abs=2)
        assert metrics["percentile_50"] == pytest.approx(50, abs=2)
        assert metrics["percentile_90"] == pytest.approx(90, abs=2)

    def test_merge(self):
        params = count_params(metrics=[pdp.Metrics.PERCENTILE(50)],
                              min_value=0,
                              max_value=10)
        combiner = combiners.QuantileCombiner(
            combiners.CombinerParams(no_noise_spec(), params), [50])
        acc1 = combiner.create_accumulator([1.0] * 50)
        acc2 = combiner.create_accumulator([9.0] * 50)
        merged = combiner.merge_accumulators(acc1, acc2)
        median = combiner.compute_metrics(merged)["percentile_50"]
        assert 1.0 <= median <= 9.1

    def test_metric_names(self):
        params = count_params(metrics=[pdp.Metrics.PERCENTILE(50)],
                              min_value=0,
                              max_value=10)
        combiner = combiners.QuantileCombiner(
            combiners.CombinerParams(no_noise_spec(), params), [90, 99.9])
        assert combiner.metrics_names() == [
            "percentile_90", "percentile_99_9"
        ]


class TestVectorSumCombiner:

    def test_accumulate_and_noise(self):
        params = count_params(metrics=[pdp.Metrics.VECTOR_SUM],
                              vector_size=2,
                              vector_max_norm=100.0,
                              vector_norm_kind=pdp.NormKind.Linf)
        combiner = combiners.VectorSumCombiner(
            combiners.CombinerParams(no_noise_spec(), params))
        acc = combiner.create_accumulator([np.array([1.0, 2.0]),
                                           np.array([3.0, 4.0])])
        np.testing.assert_allclose(acc, [4.0, 6.0])
        result = combiner.compute_metrics(acc)["vector_sum"]
        np.testing.assert_allclose(result, [4.0, 6.0], atol=0.1)

    def test_shape_mismatch(self):
        params = count_params(metrics=[pdp.Metrics.VECTOR_SUM],
                              vector_size=2,
                              vector_max_norm=1.0,
                              vector_norm_kind=pdp.NormKind.Linf)
        combiner = combiners.VectorSumCombiner(
            combiners.CombinerParams(no_noise_spec(), params))
        with pytest.raises(TypeError, match="Shape mismatch"):
            combiner.create_accumulator([np.array([1.0, 2.0, 3.0])])


class TestCompoundCombiner:

    def _compound(self):
        params = count_params(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                              min_value=0,
                              max_value=10)
        acc = ba.NaiveBudgetAccountant(1e8, 1e-15)
        compound = combiners.create_compound_combiner(params, acc)
        acc.compute_budgets()
        return compound

    def test_accumulator_structure(self):
        compound = self._compound()
        row_count, children = compound.create_accumulator([1.0, 2.0])
        assert row_count == 1
        assert children == (2, 3.0)

    def test_merge_and_compute(self):
        compound = self._compound()
        acc = compound.merge_accumulators(
            compound.create_accumulator([1.0, 2.0]),
            compound.create_accumulator([3.0]))
        assert acc[0] == 2
        metrics = compound.compute_metrics(acc)
        assert metrics.count == pytest.approx(3, abs=1e-2)
        assert metrics.sum == pytest.approx(6.0, abs=0.1)

    def test_metrics_names(self):
        assert self._compound().metrics_names() == ("count", "sum")

    def test_namedtuple_pickles(self):
        compound = self._compound()
        metrics = compound.compute_metrics(compound.create_accumulator([1.0]))
        restored = pickle.loads(pickle.dumps(metrics))
        assert restored.count == metrics.count


class TestCreateCompoundCombiner:

    def test_budget_requests_per_metric(self):
        acc = ba.NaiveBudgetAccountant(1.0, 1e-6)
        params = count_params(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            min_value=0,
            max_value=1)
        combiners.create_compound_combiner(params, acc)
        assert len(acc._mechanisms) == 3

    def test_variance_subsumes(self):
        acc = ba.NaiveBudgetAccountant(1.0, 1e-6)
        params = count_params(metrics=[
            pdp.Metrics.VARIANCE, pdp.Metrics.MEAN, pdp.Metrics.COUNT,
            pdp.Metrics.SUM
        ],
                              min_value=0,
                              max_value=1)
        compound = combiners.create_compound_combiner(params, acc)
        # One budget for variance (it computes everything itself).
        assert len(acc._mechanisms) == 1
        assert len(compound.combiners) == 1

    def test_mean_two_budgets(self):
        acc = ba.NaiveBudgetAccountant(1.0, 1e-6)
        params = count_params(metrics=[pdp.Metrics.MEAN, pdp.Metrics.COUNT],
                              min_value=0,
                              max_value=1)
        combiners.create_compound_combiner(params, acc)
        assert len(acc._mechanisms) == 2

    def test_post_aggregation_thresholding(self):
        acc = ba.NaiveBudgetAccountant(1.0, 1e-6)
        params = count_params(metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
                              post_aggregation_thresholding=True)
        compound = combiners.create_compound_combiner(params, acc)
        assert isinstance(compound.combiners[0],
                          combiners.PostAggregationThresholdingCombiner)
        assert (acc._mechanisms[0].mechanism_spec.mechanism_type ==
                MechanismType.LAPLACE_THRESHOLDING)


def value_params(**overrides):
    kwargs = dict(metrics=[pdp.Metrics.MEAN],
                  max_partitions_contributed=2,
                  max_contributions_per_partition=3,
                  min_value=0.0,
                  max_value=10.0)
    kwargs.update(overrides)
    return pdp.AggregateParams(**kwargs)


class TestMergeAlgebra:
    """Merge must be associative and commutative for every combiner —
    the property the distributed reduce relies on (reference
    tests/combiners_test.py's merge coverage)."""

    def _combiners(self):
        params = value_params()
        yield combiners.CountCombiner(no_noise_spec(), count_params())
        yield combiners.SumCombiner(
            no_noise_spec(),
            count_params(metrics=[pdp.Metrics.SUM], min_value=0.0,
                         max_value=5.0))
        yield combiners.PrivacyIdCountCombiner(
            no_noise_spec(), count_params(
                metrics=[pdp.Metrics.PRIVACY_ID_COUNT]))
        yield combiners.MeanCombiner(no_noise_spec(), no_noise_spec(),
                                     params, ["mean"])
        yield combiners.VarianceCombiner(
            combiners.CombinerParams(
                no_noise_spec(),
                value_params(metrics=[pdp.Metrics.VARIANCE])),
            ["variance"])
        yield combiners.VectorSumCombiner(
            combiners.CombinerParams(
                no_noise_spec(),
                count_params(metrics=[pdp.Metrics.VECTOR_SUM],
                             vector_size=3,
                             vector_max_norm=10.0)))

    def _random_batches(self, combiner, rng):
        if isinstance(combiner, combiners.VectorSumCombiner):
            return [[rng.uniform(0, 1, 3)] for _ in range(3)]
        return [list(rng.uniform(0, 10, rng.integers(1, 6)))
                for _ in range(3)]

    def _flat(self, acc):
        leaves = acc if isinstance(acc, tuple) else (acc,)
        return np.concatenate([np.atleast_1d(np.asarray(leaf, dtype=float))
                               for leaf in leaves])

    def test_associative_and_commutative(self):
        rng = np.random.default_rng(0)
        for combiner in self._combiners():
            a, b, c = (combiner.create_accumulator(batch)
                       for batch in self._random_batches(combiner, rng))
            left = combiner.merge_accumulators(
                combiner.merge_accumulators(a, b), c)
            right = combiner.merge_accumulators(
                a, combiner.merge_accumulators(b, c))
            np.testing.assert_allclose(self._flat(left), self._flat(right),
                                       err_msg=type(combiner).__name__)
            ab = combiner.merge_accumulators(a, b)
            ba_merge = combiner.merge_accumulators(b, a)
            np.testing.assert_allclose(self._flat(ab), self._flat(ba_merge),
                                       err_msg=type(combiner).__name__)

    def test_quantile_merge_associative(self):
        params = combiners.CombinerParams(
            no_noise_spec(),
            value_params(metrics=[pdp.Metrics.PERCENTILE(50)]))
        combiner = combiners.QuantileCombiner(params, [50])
        rng = np.random.default_rng(1)
        a, b, c = (combiner.create_accumulator(list(rng.uniform(0, 10, 20)))
                   for _ in range(3))
        left = combiner.merge_accumulators(
            combiner.merge_accumulators(a, b), c)
        right = combiner.merge_accumulators(
            a, combiner.merge_accumulators(b, c))
        assert left == right  # serialized summaries are bytes: exact


class TestAccumulatorSerialization:
    """Accumulators and combiners cross the driver/worker pickle boundary
    (reference combiners.py:203-217 contract)."""

    def test_all_accumulators_pickle_roundtrip(self):
        params = value_params()
        cases = [
            (combiners.CountCombiner(no_noise_spec(), count_params()),
             [1.0, 2.0]),
            (combiners.MeanCombiner(no_noise_spec(), no_noise_spec(), params,
                                    ["mean", "count", "sum"]), [3.0, 4.0]),
            (combiners.VarianceCombiner(
                combiners.CombinerParams(
                    no_noise_spec(),
                    value_params(metrics=[pdp.Metrics.VARIANCE])),
                ["variance"]), [3.0, 4.0, 5.0]),
            (combiners.QuantileCombiner(
                combiners.CombinerParams(
                    no_noise_spec(),
                    value_params(metrics=[pdp.Metrics.PERCENTILE(50)])),
                [50]), list(range(10))),
        ]
        for combiner, values in cases:
            acc = combiner.create_accumulator(values)
            restored = pickle.loads(pickle.dumps(acc))
            merged = combiner.merge_accumulators(acc, restored)
            # The round-tripped accumulator is still mergeable and
            # produces finite metrics.
            metrics = combiner.compute_metrics(merged)
            assert all(np.isfinite(v) for v in np.atleast_1d(
                list(metrics.values()) if isinstance(metrics, dict)
                else metrics))

    def test_mean_combiner_pickles_without_mechanism(self):
        params = value_params()
        combiner = combiners.MeanCombiner(no_noise_spec(), no_noise_spec(),
                                          params, ["mean"])
        combiner.compute_metrics((5, 2.0))  # instantiate the mechanism
        restored = pickle.loads(pickle.dumps(combiner))
        assert not hasattr(restored, "_mechanism")
        result = restored.compute_metrics((5, 2.0))
        assert result["mean"] == pytest.approx(5.4, abs=0.1)


class TestBudgetSplitsPerMetric:
    """(eps, delta) splits across metrics resolve exactly (reference
    tests/combiners_test.py budget assertions + budget_accounting math)."""

    def test_equal_split_three_metrics(self):
        acc = ba.NaiveBudgetAccountant(3.0, 3e-6)
        params = count_params(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            min_value=0.0, max_value=1.0)
        compound = combiners.create_compound_combiner(params, acc)
        acc.compute_budgets()
        for combiner in compound.combiners:
            spec = combiner.mechanism_spec()
            assert spec.eps == pytest.approx(1.0)
            assert spec.delta == pytest.approx(1e-6)

    def test_laplace_consumes_no_delta(self):
        acc = ba.NaiveBudgetAccountant(2.0, 1e-6)
        params = count_params(metrics=[pdp.Metrics.COUNT])
        compound = combiners.create_compound_combiner(params, acc)
        selection = acc.request_budget(MechanismType.GENERIC)
        acc.compute_budgets()
        # eps splits evenly; all delta goes to the GENERIC selection.
        assert compound.combiners[0].mechanism_spec().eps == pytest.approx(
            1.0)
        assert compound.combiners[0].mechanism_spec().delta == 0.0
        assert selection.delta == pytest.approx(1e-6)

    def test_budget_weight_scales_share(self):
        acc = ba.NaiveBudgetAccountant(3.0, 0.0)
        with acc.scope(weight=1.0):
            spec_a = acc.request_budget(MechanismType.LAPLACE)
        with acc.scope(weight=2.0):
            spec_b = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        assert spec_a.eps == pytest.approx(1.0)
        assert spec_b.eps == pytest.approx(2.0)

    def test_mean_splits_between_count_and_sum(self):
        acc = ba.NaiveBudgetAccountant(1.0, 0.0)
        params = value_params(metrics=[pdp.Metrics.MEAN])
        compound = combiners.create_compound_combiner(params, acc)
        acc.compute_budgets()
        count_spec, sum_spec = compound.combiners[0].mechanism_spec()
        assert count_spec.eps + sum_spec.eps == pytest.approx(1.0)
