"""Tests for aggregate_params validation semantics.

Mirrors the validation checks exercised by the reference's
tests/aggregate_params_test.py against aggregate_params.py:281-395.
"""

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.aggregate_params import (Metric, noise_to_thresholding,
                                             parameters_to_readable_string)


def valid_params(**overrides):
    kwargs = dict(
        metrics=[pdp.Metrics.COUNT],
        max_partitions_contributed=2,
        max_contributions_per_partition=3,
    )
    kwargs.update(overrides)
    return pdp.AggregateParams(**kwargs)


class TestMetrics:

    def test_equality_and_hash(self):
        assert pdp.Metrics.COUNT == Metric("COUNT")
        assert pdp.Metrics.PERCENTILE(90) == Metric("PERCENTILE", 90)
        assert pdp.Metrics.PERCENTILE(90) != pdp.Metrics.PERCENTILE(50)
        assert len({pdp.Metrics.COUNT, Metric("COUNT")}) == 1

    def test_str(self):
        assert str(pdp.Metrics.COUNT) == "COUNT"
        assert str(pdp.Metrics.PERCENTILE(90)) == "PERCENTILE(90)"

    def test_is_percentile(self):
        assert pdp.Metrics.PERCENTILE(5).is_percentile
        assert not pdp.Metrics.SUM.is_percentile


class TestEnums:

    def test_noise_kind_to_mechanism_type(self):
        assert (pdp.NoiseKind.LAPLACE.convert_to_mechanism_type() ==
                pdp.MechanismType.LAPLACE)
        assert (pdp.NoiseKind.GAUSSIAN.convert_to_mechanism_type() ==
                pdp.MechanismType.GAUSSIAN)

    def test_mechanism_type_to_noise_kind(self):
        assert pdp.MechanismType.LAPLACE.to_noise_kind() == pdp.NoiseKind.LAPLACE
        assert (pdp.MechanismType.GAUSSIAN_THRESHOLDING.to_noise_kind() ==
                pdp.NoiseKind.GAUSSIAN)
        with pytest.raises(ValueError):
            pdp.MechanismType.GENERIC.to_noise_kind()

    def test_noise_to_thresholding(self):
        assert (noise_to_thresholding(pdp.NoiseKind.LAPLACE) ==
                pdp.MechanismType.LAPLACE_THRESHOLDING)
        assert (noise_to_thresholding(pdp.NoiseKind.GAUSSIAN) ==
                pdp.MechanismType.GAUSSIAN_THRESHOLDING)


class TestAggregateParamsValidation:

    def test_valid(self):
        valid_params()

    def test_missing_contribution_bounds(self):
        with pytest.raises(ValueError, match="max_contributions must be set"):
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT])

    def test_only_one_bound_set(self):
        with pytest.raises(ValueError, match="none or both"):
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                max_partitions_contributed=2)

    def test_max_contributions_conflicts(self):
        with pytest.raises(ValueError, match="only one"):
            valid_params(max_contributions=5)

    def test_max_contributions_alone_ok(self):
        pdp.AggregateParams(metrics=[pdp.Metrics.COUNT], max_contributions=5)

    def test_non_positive_bounds(self):
        with pytest.raises(ValueError):
            valid_params(max_partitions_contributed=0)
        with pytest.raises(ValueError):
            valid_params(max_contributions_per_partition=-1)

    def test_min_without_max_value(self):
        with pytest.raises(ValueError, match="both set or both None"):
            valid_params(min_value=1)

    def test_min_greater_than_max(self):
        with pytest.raises(ValueError, match="equal to or greater"):
            valid_params(metrics=[pdp.Metrics.SUM], min_value=2, max_value=1)

    def test_nan_bounds(self):
        with pytest.raises(ValueError, match="finite"):
            valid_params(metrics=[pdp.Metrics.SUM],
                         min_value=float("nan"),
                         max_value=1)

    def test_value_and_partition_bounds_conflict(self):
        with pytest.raises(ValueError, match="not be both set"):
            valid_params(metrics=[pdp.Metrics.SUM],
                         min_value=0,
                         max_value=1,
                         min_sum_per_partition=0,
                         max_sum_per_partition=2)

    def test_sum_requires_bounds(self):
        with pytest.raises(ValueError, match="bounds per partition"):
            valid_params(metrics=[pdp.Metrics.SUM])

    def test_partition_bounds_not_for_mean(self):
        with pytest.raises(ValueError, match="min_sum_per_partition"):
            valid_params(metrics=[pdp.Metrics.MEAN],
                         min_sum_per_partition=0,
                         max_sum_per_partition=1)

    def test_vector_sum_with_scalar_metrics(self):
        with pytest.raises(ValueError, match="vector sum"):
            valid_params(metrics=[pdp.Metrics.VECTOR_SUM, pdp.Metrics.SUM],
                         min_value=0,
                         max_value=1)

    def test_privacy_id_count_with_bounds_already_enforced(self):
        with pytest.raises(ValueError, match="PRIVACY_ID_COUNT"):
            valid_params(metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
                         contribution_bounds_already_enforced=True)

    def test_custom_combiners_with_metrics(self):
        with pytest.raises(ValueError, match="[Cc]ustom combiners"):
            valid_params(custom_combiners=[object()])

    def test_pre_threshold_validation(self):
        with pytest.raises(ValueError, match="pre_threshold"):
            valid_params(pre_threshold=0)


class TestOtherParams:

    def test_select_partitions_params(self):
        pdp.SelectPartitionsParams(max_partitions_contributed=2)
        with pytest.raises(ValueError):
            pdp.SelectPartitionsParams(max_partitions_contributed=0)

    def test_add_dp_noise_params(self):
        pdp.AddDPNoiseParams(noise_kind=pdp.NoiseKind.LAPLACE,
                             l0_sensitivity=2,
                             linf_sensitivity=1.5)
        with pytest.raises(ValueError, match="positive"):
            pdp.AddDPNoiseParams(noise_kind=pdp.NoiseKind.LAPLACE,
                                 l0_sensitivity=0,
                                 linf_sensitivity=1.0)

    def test_calculate_private_contribution_bounds_params(self):
        pdp.CalculatePrivateContributionBoundsParams(
            aggregation_noise_kind=pdp.NoiseKind.GAUSSIAN,
            aggregation_eps=1.0,
            aggregation_delta=1e-6,
            calculation_eps=0.5,
            max_partitions_contributed_upper_bound=100)
        with pytest.raises(ValueError, match="positive aggregation_delta"):
            pdp.CalculatePrivateContributionBoundsParams(
                aggregation_noise_kind=pdp.NoiseKind.GAUSSIAN,
                aggregation_eps=1.0,
                aggregation_delta=0,
                calculation_eps=0.5,
                max_partitions_contributed_upper_bound=100)


class TestReadableString:

    def test_contains_key_fields(self):
        params = valid_params(metrics=[pdp.Metrics.SUM],
                              min_value=1,
                              max_value=5)
        text = parameters_to_readable_string(params, is_public_partition=False)
        assert "AggregateParams" in text
        assert "max_partitions_contributed=2" in text
        assert "min_value=1" in text
        assert "noise_kind=laplace" in text
        assert "private partitions" in text
