"""Tests for the vectorized utility-analysis layer.

Mirrors the reference's analysis/tests strategy: per-partition error
models pinned against hand-computed values, exact Poisson-binomial
cross-checks, tolerance-compared report dataclasses, and an e2e tune() on
movie-view-shaped data."""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
import pipelinedp_tpu.analysis as analysis
from pipelinedp_tpu import partition_selection as ps_lib
from pipelinedp_tpu.analysis import (cross_partition, per_partition,
                                     poisson_binomial, pre_aggregation)
from pipelinedp_tpu.dataset_histograms import computing_histograms


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def count_params(l0=1, linf=1, **kwargs):
    return pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                               max_partitions_contributed=l0,
                               max_contributions_per_partition=linf,
                               **kwargs)


class TestPoissonBinomial:

    def test_exact_pmf_two_bernoullis(self):
        pmf = poisson_binomial.compute_pmf([0.5, 0.5])
        np.testing.assert_allclose(pmf.probabilities, [0.25, 0.5, 0.25])

    def test_exact_pmf_sums_to_one(self):
        rng = np.random.default_rng(0)
        pmf = poisson_binomial.compute_pmf(rng.uniform(0, 1, 30))
        assert pmf.probabilities.sum() == pytest.approx(1.0)

    def test_approximation_close_to_exact(self):
        rng = np.random.default_rng(1)
        probs = rng.uniform(0.3, 0.9, 80)
        exact = poisson_binomial.compute_pmf(probs)
        exp, std, skew = poisson_binomial.compute_exp_std_skewness(probs)
        approx = poisson_binomial.compute_pmf_approximation(
            exp, std, skew, len(probs))
        # Compare on the approximation's support.
        exact_slice = exact.probabilities[approx.start:approx.start +
                                          len(approx.probabilities)]
        np.testing.assert_allclose(approx.probabilities, exact_slice,
                                   atol=2e-3)


class TestPreAggregation:

    def test_groups_and_n_partitions(self):
        # user 1 -> pk a (2 contributions), pk b (1); user 2 -> pk a (1).
        rows = [(1, "a", 1.0), (1, "a", 2.0), (1, "b", 3.0), (2, "a", 4.0)]
        result = analysis.preaggregate(rows, data_extractors=extractors())
        as_dict = {}
        for pk, (count, s, n_part) in result:
            as_dict.setdefault(pk, []).append((count, s, n_part))
        assert sorted(as_dict["a"]) == [(1, 4.0, 1), (2, 3.0, 2)]
        assert as_dict["b"] == [(1, 3.0, 2)]

    def test_partition_sampling_deterministic(self):
        rows = [(u, f"pk{u % 50}", 1.0) for u in range(500)]
        r1 = analysis.preaggregate(rows, data_extractors=extractors(),
                                   partitions_sampling_prob=0.5)
        r2 = analysis.preaggregate(rows, data_extractors=extractors(),
                                   partitions_sampling_prob=0.5)
        assert [pk for pk, _ in r1] == [pk for pk, _ in r2]
        kept = {pk for pk, _ in r1}
        assert 0 < len(kept) < 50


class TestPerPartitionErrorModel:

    def _analyze(self, rows, params, eps=1.0, delta=1e-6, public=None,
                 multi=None):
        options = analysis.UtilityAnalysisOptions(
            epsilon=eps, delta=delta, aggregate_params=params,
            multi_param_configuration=multi)
        engine = analysis.UtilityAnalysisEngine()
        return engine.analyze(rows, options, extractors(),
                              public_partitions=public)

    def test_count_clipping_and_l0_errors(self):
        # One user contributes 5 rows to "a" and 1 row to "b"; linf=3, l0=1.
        rows = [(1, "a", 0.0)] * 5 + [(1, "b", 0.0)]
        result = self._analyze(rows, count_params(l0=1, linf=3),
                               public=["a", "b"])
        per_pk = dict(result)
        err_a = per_pk["a"][0].metric_errors[0]
        assert err_a.sum == 5.0
        # count 5 clipped to 3: clipping_to_max_error = -2.
        assert err_a.clipping_to_max_error == pytest.approx(-2.0)
        # q = 1/2 (2 partitions, l0=1): E[l0 err] = -3 * 0.5.
        assert err_a.expected_l0_bounding_error == pytest.approx(-1.5)
        # Var = 3^2 * 0.25.
        assert err_a.std_l0_bounding_error == pytest.approx(1.5)

    def test_count_noise_std_matches_mechanism(self):
        rows = [(1, "a", 0.0)]
        result = self._analyze(rows, count_params(l0=2, linf=3),
                               eps=2.0, delta=1e-8, public=["a"])
        err = dict(result)["a"][0].metric_errors[0]
        # All budget to COUNT (public partitions, one metric): Laplace
        # b = l0*linf/eps, std = sqrt(2) b.
        expected = np.sqrt(2.0) * 2 * 3 / 2.0
        assert err.std_noise == pytest.approx(expected)

    def test_sum_clipping(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_sum_per_partition=0.0,
                                     max_sum_per_partition=2.0)
        rows = [(1, "a", 5.0), (2, "a", -1.0)]
        result = self._analyze(rows, params, public=["a"])
        err = dict(result)["a"][0].metric_errors[0]
        assert err.sum == 4.0
        assert err.clipping_to_max_error == pytest.approx(-3.0)
        assert err.clipping_to_min_error == pytest.approx(1.0)

    def test_keep_probability_exact_matches_strategy(self):
        # 20 users, each contributing to exactly this partition (q=1):
        # the keep probability equals the strategy's probability_of_keep(20).
        rows = [(u, "a", 0.0) for u in range(20)]
        result = self._analyze(rows, count_params(), eps=1.0, delta=1e-4)
        ppm = dict(result)["a"][0]
        # Budget split: eps halved between GENERIC selection and COUNT;
        # Laplace COUNT consumes no delta, so selection gets all of it.
        strategy = ps_lib.create_partition_selection_strategy(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 0.5, 1e-4, 1)
        assert ppm.partition_selection_probability_to_keep == pytest.approx(
            strategy.probability_of_keep(20), rel=1e-6)

    def test_keep_probability_approx_matches_exact(self):
        # 150 users (above the exact cutoff) with q=1: approximation must
        # agree with the exact strategy value.
        rows = [(u, "a", 0.0) for u in range(150)]
        result = self._analyze(rows, count_params(), eps=1.0, delta=1e-4)
        ppm = dict(result)["a"][0]
        strategy = ps_lib.create_partition_selection_strategy(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 0.5, 5e-5, 1)
        assert ppm.partition_selection_probability_to_keep == pytest.approx(
            strategy.probability_of_keep(150), rel=1e-3)

    def test_multi_config_sweep_shapes(self):
        rows = [(u, f"pk{u % 3}", 1.0) for u in range(30)]
        multi = analysis.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 3],
            max_contributions_per_partition=[1, 1, 2])
        result = self._analyze(rows, count_params(), multi=multi)
        arrays = result.arrays
        assert arrays.n_configs == 3
        assert arrays.metric_errors[0].raw.shape == (3, 3)
        per_config = dict(result)["pk0"]
        assert len(per_config) == 3

    def test_raw_statistics(self):
        rows = [(1, "a", 0.0), (1, "a", 0.0), (2, "a", 0.0)]
        result = self._analyze(rows, count_params(), public=["a"])
        stats = dict(result)["a"][0].raw_statistics
        assert stats.privacy_id_count == 2
        assert stats.count == 3


class TestPerformUtilityAnalysis:

    def test_public_report_averaging(self):
        # Two partitions, both kept (public): report averages per-partition
        # errors equally.
        rows = ([(u, "a", 0.0) for u in range(4)] +
                [(u + 100, "b", 0.0) for u in range(2)])
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=count_params())
        reports, per_partition_result = analysis.perform_utility_analysis(
            rows, options=options, data_extractors=extractors(),
            public_partitions=["a", "b"])
        assert len(reports) == 1
        report = reports[0]
        assert report.partitions_info.public_partitions
        assert report.partitions_info.num_dataset_partitions == 2
        err = report.metric_errors[0]
        # No clipping/l0 error (l0=1 but each user contributes to exactly 1
        # partition): bias 0, variance = noise^2, rmse = noise std.
        assert err.absolute_error.mean == pytest.approx(0.0)
        assert err.absolute_error.rmse == pytest.approx(err.noise_std)
        # ((pk, config), PerPartitionMetrics) entries: 2 partitions x 1 cfg.
        assert len(per_partition_result) == 2

    def test_private_report_weighted_by_keep_prob(self):
        rows = ([(u, "big", 0.0) for u in range(1000)] +
                [(1, "small", 0.0)])
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-4, aggregate_params=count_params())
        reports, _ = analysis.perform_utility_analysis(
            rows, options=options, data_extractors=extractors())
        info = reports[0].partitions_info
        assert not info.public_partitions
        assert info.num_dataset_partitions == 2
        # big is kept ~surely, small ~never.
        assert info.kept_partitions.mean == pytest.approx(1.0, abs=0.05)
        assert info.strategy is not None

    def test_histogram_buckets(self):
        sizes = np.array([0, 1, 5, 10, 20, 50, 100, 999])
        buckets = cross_partition.partition_size_buckets(sizes)
        assert list(buckets) == [0, 1, 1, 10, 20, 50, 100, 500]
        assert cross_partition.bucket_upper_bound(10) == 20


class TestDPStrategySelector:

    def test_gaussian_wins_for_large_l0(self):
        selector = analysis.DPStrategySelector(
            epsilon=1.0, delta=1e-6, metric=pdp.Metrics.COUNT,
            is_public_partitions=True)
        import pipelinedp_tpu.dp_computations as dp_computations
        strategy = selector.get_dp_strategy(
            dp_computations.Sensitivities(l0=100, linf=1))
        assert strategy.noise_kind == pdp.NoiseKind.GAUSSIAN

    def test_laplace_wins_for_small_l0(self):
        selector = analysis.DPStrategySelector(
            epsilon=1.0, delta=1e-6, metric=pdp.Metrics.COUNT,
            is_public_partitions=True)
        import pipelinedp_tpu.dp_computations as dp_computations
        strategy = selector.get_dp_strategy(
            dp_computations.Sensitivities(l0=1, linf=1))
        assert strategy.noise_kind == pdp.NoiseKind.LAPLACE

    def test_privacy_id_count_uses_post_aggregation_thresholding(self):
        selector = analysis.DPStrategySelector(
            epsilon=1.0, delta=1e-6, metric=pdp.Metrics.PRIVACY_ID_COUNT,
            is_public_partitions=False)
        import pipelinedp_tpu.dp_computations as dp_computations
        strategy = selector.get_dp_strategy(
            dp_computations.Sensitivities(l0=10, linf=1))
        assert strategy.post_aggregation_thresholding
        assert strategy.partition_selection_strategy is not None

    def test_select_partitions_case(self):
        selector = analysis.DPStrategySelector(epsilon=1.0, delta=1e-6,
                                               metric=None,
                                               is_public_partitions=False)
        import pipelinedp_tpu.dp_computations as dp_computations
        strategy = selector.get_dp_strategy(
            dp_computations.Sensitivities(l0=5, linf=1))
        assert strategy.noise_kind is None
        assert strategy.partition_selection_strategy is not None


class TestTune:

    def _movie_shaped_rows(self, n_users=400, n_movies=40, seed=0):
        rng = np.random.default_rng(seed)
        rows = []
        for u in range(n_users):
            n_watched = 1 + rng.integers(0, 8)
            movies = rng.choice(n_movies, size=min(n_watched, n_movies),
                                replace=False)
            for m in movies:
                rows.append((u, int(m), float(rng.integers(1, 6))))
        return rows

    def test_tune_count_returns_rmse_ranked_result(self):
        rows = self._movie_shaped_rows()
        histograms = list(computing_histograms.compute_dataset_histograms(
            rows, extractors(), pdp.LocalBackend()))[0]
        options = analysis.TuneOptions(
            epsilon=1.0,
            delta=1e-6,
            aggregate_params=count_params(l0=1, linf=1),
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True,
                max_contributions_per_partition=True),
            number_of_parameter_candidates=16)
        result, per_partition_result = analysis.tune(
            rows, contribution_histograms=histograms, options=options,
            data_extractors=extractors())
        assert isinstance(result, analysis.TuneResult)
        candidates = result.utility_analysis_parameters
        assert candidates.size <= 16
        assert len(result.utility_reports) == candidates.size
        assert 0 <= result.index_best < candidates.size
        # Reports carry RMSE; best really is the argmin.
        rmse = [r.metric_errors[0].absolute_error.rmse
                for r in result.utility_reports]
        assert result.index_best == int(np.argmin(rmse))
        # Strategies were attached per candidate.
        assert len(candidates.noise_kind) == candidates.size
        assert len(candidates.partition_selection_strategy) == candidates.size
        assert per_partition_result

    def test_tune_sum(self):
        rows = self._movie_shaped_rows()
        histograms = list(computing_histograms.compute_dataset_histograms(
            rows, extractors(), pdp.LocalBackend()))[0]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_sum_per_partition=0.0,
                                     max_sum_per_partition=1.0)
        options = analysis.TuneOptions(
            epsilon=1.0,
            delta=1e-6,
            aggregate_params=params,
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True,
                max_sum_per_partition=True),
            number_of_parameter_candidates=9)
        result, _ = analysis.tune(rows, contribution_histograms=histograms,
                                  options=options,
                                  data_extractors=extractors())
        assert result.index_best >= 0
        best = result.utility_analysis_parameters.get_aggregate_params(
            params, result.index_best)
        assert best.max_sum_per_partition > 0

    def test_tune_rejects_two_metrics(self):
        options_kwargs = dict(
            epsilon=1.0, delta=1e-6,
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0, max_value=1)
        with pytest.raises(ValueError, match="one metric"):
            analysis.tune(
                [], contribution_histograms=None,
                options=analysis.TuneOptions(aggregate_params=params,
                                             **options_kwargs),
                data_extractors=extractors())


class TestCandidateGeneration:

    def test_constant_relative_step(self):
        from pipelinedp_tpu.dataset_histograms import histograms as h
        bins = [h.FrequencyBin(1, 2, 10, 5, 1), h.FrequencyBin(
            99, 100, 3, 1, 100)]
        hist = h.Histogram(h.HistogramType.L0_CONTRIBUTIONS, bins)
        candidates = analysis.parameter_tuning.\
            candidates_constant_relative_step(hist, 5)
        assert candidates[0] == 1
        assert candidates[-1] == 100
        assert candidates == sorted(set(candidates))

    def test_2d_grid_size(self):
        from pipelinedp_tpu.analysis.parameter_tuning import candidates_2d_grid
        fn = lambda hist, k: list(range(1, k + 1))
        g1, g2 = candidates_2d_grid(None, None, fn, fn, 16)
        assert len(g1) == len(g2) == 16


class TestDatasetSummary:

    def test_overlap_counts(self):
        rows = [(1, "a", 0.0), (2, "b", 0.0), (3, "c", 0.0)]
        summary = analysis.compute_public_partitions_summary(
            rows, extractors=extractors(),
            public_partitions=["a", "b", "zzz"])
        assert summary.num_dataset_public_partitions == 2
        assert summary.num_dataset_non_public_partitions == 1
        assert summary.num_empty_public_partitions == 1


class TestMultiParameterConfiguration:

    def test_size_validation(self):
        with pytest.raises(ValueError, match="same length"):
            analysis.MultiParameterConfiguration(
                max_partitions_contributed=[1, 2],
                max_contributions_per_partition=[1])

    def test_get_aggregate_params(self):
        config = analysis.MultiParameterConfiguration(
            max_partitions_contributed=[1, 5],
            noise_kind=[pdp.NoiseKind.LAPLACE, pdp.NoiseKind.GAUSSIAN])
        params = config.get_aggregate_params(count_params(), 1)
        assert params.max_partitions_contributed == 5
        assert params.noise_kind == pdp.NoiseKind.GAUSSIAN


class TestPostAggregationThresholdingAnalysis:
    """Verdict-r2 task 8: the analysis models post-aggregation thresholding
    so the tuner can honor the strategy selector's PRIVACY_ID_COUNT
    recommendation."""

    def _pid_params(self, post_agg):
        return pdp.AggregateParams(
            metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            post_aggregation_thresholding=post_agg)

    def test_keep_prob_matches_thresholding_strategy(self):
        # 40 users, all in one partition, each contributing once: N is
        # deterministic, so the modeled keep probability must equal the
        # thresholding strategy's probability_of_keep(40) exactly.
        rows = [(u, "p", 1.0) for u in range(40)]
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6,
            aggregate_params=self._pid_params(True))
        engine = analysis.UtilityAnalysisEngine()
        result = engine.analyze(rows, options, extractors())
        keep_prob = result.arrays.keep_prob[0, 0]
        configs = per_partition.resolve_config_budgets(options, False)
        assert configs[0].post_agg_thresholding
        strategy = per_partition._thresholding_strategy(configs[0])
        assert keep_prob == pytest.approx(strategy.probability_of_keep(40),
                                          abs=1e-9)
        # The modeled noise std is the thresholding strategy's noise.
        pid_errors = [
            e for e in result.arrays.metric_errors
            if e.metric == pdp.Metrics.PRIVACY_ID_COUNT
        ][0]
        assert pid_errors.std_noise[0] == pytest.approx(
            strategy.noise_stddev)

    def test_thresholding_gets_full_budget(self):
        # Without post-agg thresholding the budget is split between
        # selection and noise; with it, the thresholding mechanism gets
        # everything — its noise must be strictly smaller.
        rows = [(u, u % 3, 1.0) for u in range(60)]
        def std_of(post_agg):
            options = analysis.UtilityAnalysisOptions(
                epsilon=1.0, delta=1e-6,
                aggregate_params=self._pid_params(post_agg))
            engine = analysis.UtilityAnalysisEngine()
            result = engine.analyze(rows, options, extractors())
            return [
                e for e in result.arrays.metric_errors
                if e.metric == pdp.Metrics.PRIVACY_ID_COUNT
            ][0].std_noise[0]
        assert std_of(True) < std_of(False)

    def test_tune_privacy_id_count_analyzes_selector_strategy(self):
        # The selector recommends post-aggregation thresholding for
        # PRIVACY_ID_COUNT; tune() must attach and analyze that bit
        # instead of dropping it.
        rng = np.random.default_rng(0)
        rows = [(int(u), int(rng.integers(0, 20)), 1.0)
                for u in range(500)]
        hists = list(
            computing_histograms.compute_dataset_histograms(
                rows, extractors(), pdp.LocalBackend()))[0]
        options = analysis.TuneOptions(
            epsilon=1.0,
            delta=1e-6,
            aggregate_params=self._pid_params(False),
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True),
            number_of_parameter_candidates=5)
        tune_result, _ = analysis.tune(rows,
                                       contribution_histograms=hists,
                                       options=options,
                                       data_extractors=extractors())
        candidates = tune_result.utility_analysis_parameters
        assert candidates.post_aggregation_thresholding is not None
        assert all(candidates.post_aggregation_thresholding)
        assert 0 <= tune_result.index_best < candidates.size


class TestVectorizedExactKeepProbabilities:
    """Verdict-r2 task 4: the exact Poisson-binomial path is batched, with
    exactness pinned against the scalar PGF and approx agreement pinned at
    the exact/approx boundary."""

    def _pre_and_config(self, rows, l0=2):
        from pipelinedp_tpu.analysis import pre_aggregation
        ext = extractors()
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     noise_kind=pdp.NoiseKind.LAPLACE,
                                     max_partitions_contributed=l0,
                                     max_contributions_per_partition=2)
        options = analysis.UtilityAnalysisOptions(epsilon=1.0, delta=1e-6,
                                                  aggregate_params=params)
        pre = pre_aggregation.preaggregate_from_rows(rows, ext)
        configs = per_partition.resolve_config_budgets(options, False)
        return pre, configs, params

    def test_batch_matches_scalar_exact(self):
        rng = np.random.default_rng(3)
        rows = []
        for p in range(60):
            for u in range(int(rng.integers(1, 40))):
                uid = p * 1000 + u
                rows.append((uid, p, 1.0))
                # Vary each user's partition load so q < 1 varies.
                for extra in range(int(rng.integers(0, 4))):
                    rows.append((uid, 500 + extra, 1.0))
        pre, configs, params = self._pre_and_config(rows)
        n_partitions = max(len(pre.pk_vocab), 1)
        out = per_partition.compute_keep_probabilities(
            pre, configs, n_partitions)
        spec = configs[0].selection_spec
        strategy = ps_lib.create_partition_selection_strategy(
            params.partition_selection_strategy, spec.eps, spec.delta,
            params.max_partitions_contributed, None)
        q = np.minimum(
            1.0, params.max_partitions_contributed /
            np.maximum(pre.n_partitions, 1))
        order = np.argsort(pre.pk_ids, kind="stable")
        spk = pre.pk_ids[order]
        bounds = np.searchsorted(spk, np.arange(n_partitions + 1))
        for p in range(n_partitions):
            qs = q[order[bounds[p]:bounds[p + 1]]]
            if not len(qs) or len(qs) > per_partition.MAX_EXACT_PROBABILITIES:
                continue
            ref = per_partition._keep_prob_exact(qs, strategy)
            assert out[0, p] == pytest.approx(ref, abs=1e-12), p

    def test_exact_and_approx_agree_at_boundary(self):
        # Two partitions straddling MAX_EXACT_PROBABILITIES with identical
        # per-unit survival probabilities: the exact PGF (n=100) and the
        # refined-normal lattice (n=101) must agree closely.
        m = per_partition.MAX_EXACT_PROBABILITIES
        rows = []
        for u in range(m):
            rows.append((u, "exact", 1.0))
            rows.append((u, "other_a", 1.0))  # load 3 -> q = 2/3
            rows.append((u, "other_b", 1.0))
        for u in range(m + 1):
            uid = 10_000 + u
            rows.append((uid, "approx", 1.0))
            rows.append((uid, "other_a", 1.0))
            rows.append((uid, "other_b", 1.0))
        pre, configs, params = self._pre_and_config(rows, l0=2)
        n_partitions = max(len(pre.pk_vocab), 1)
        out = per_partition.compute_keep_probabilities(
            pre, configs, n_partitions)
        keys = pre.pk_vocab.keys
        p_exact = out[0, keys.index("exact")]
        p_approx = out[0, keys.index("approx")]
        # n differs by one unit; both ~ kept with the same probability.
        assert p_approx == pytest.approx(p_exact, abs=0.01)
        assert 0 < p_exact < 1


class TestSumPerContributionBounds:
    """Verdict-r2 task 10b: SUM analysis under per-contribution bounds.

    Pinned semantics: the error model clips each (pid, partition) group's
    sum at count-scaled bounds [min_value*linf, max_value*linf] — what the
    engine's per-contribution clipping + Linf sampling actually bounds.
    (Deliberate deviation from the reference, whose analysis SumCombiner
    applies no clipping in this mode; see per_partition.py.)"""

    def _params(self, linf=2):
        return pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                   noise_kind=pdp.NoiseKind.LAPLACE,
                                   max_partitions_contributed=1,
                                   max_contributions_per_partition=linf,
                                   min_value=0.0,
                                   max_value=3.0)

    def _analyze(self, rows):
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=self._params())
        engine = analysis.UtilityAnalysisEngine()
        return engine.analyze(rows, options, extractors(),
                              public_partitions=["a"])

    def test_clipping_at_count_scaled_bounds(self):
        # One user, 4 contributions of 3.0 to "a": raw group sum 12;
        # count-scaled cap = max_value * linf = 6 -> clip error -6.
        rows = [(1, "a", 3.0)] * 4
        result = self._analyze(rows)
        err = dict(result)["a"][0].metric_errors[0]
        assert err.sum == pytest.approx(12.0)
        assert err.clipping_to_max_error == pytest.approx(-6.0)
        assert err.clipping_to_min_error == pytest.approx(0.0)

    def test_no_clipping_within_bounds(self):
        rows = [(1, "a", 2.0), (1, "a", 1.0)]  # sum 3 <= 6
        result = self._analyze(rows)
        err = dict(result)["a"][0].metric_errors[0]
        assert err.clipping_to_max_error == pytest.approx(0.0)
        assert err.clipping_to_min_error == pytest.approx(0.0)

    def test_noise_std_uses_per_contribution_sensitivity(self):
        rows = [(1, "a", 1.0)]
        result = self._analyze(rows)
        err = dict(result)["a"][0].metric_errors[0]
        # Public partitions, one metric: full eps to SUM. Laplace scale =
        # l0 * linf * max_abs / eps = 1*2*3/1.
        assert err.std_noise == pytest.approx(np.sqrt(2.0) * 6.0)


class TestDeviceSweep:
    """Conformance of the jitted device sweep (analysis/device_sweep.py)
    against the host numpy error model (VERDICT-r3 task 1): the two paths
    must agree on every [n_configs, n_partitions] grid."""

    def _random_rows(self, n_users=80, n_partitions=7, rows_per_user=6,
                     seed=7):
        rng = np.random.default_rng(seed)
        rows = []
        for u in range(n_users):
            for _ in range(rng.integers(1, rows_per_user + 1)):
                pk = f"pk{rng.integers(0, n_partitions)}"
                rows.append((u, pk, float(rng.normal(2.0, 3.0))))
        return rows

    def _options(self, public, use_device, post_agg=False, mesh=None):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=2,
            max_contributions_per_partition=3,
            min_sum_per_partition=0.0,
            max_sum_per_partition=5.0,
            post_aggregation_thresholding=post_agg)
        multi = analysis.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 3, 5],
            max_contributions_per_partition=[1, 2, 3, 4],
            min_sum_per_partition=[0.0, -1.0, 0.0, -2.0],
            max_sum_per_partition=[2.0, 5.0, 10.0, 3.0])
        return analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-5, aggregate_params=params,
            multi_param_configuration=multi, use_device_sweep=use_device,
            device_mesh=mesh)

    def _arrays(self, rows, public, use_device, post_agg=False, mesh=None):
        engine = analysis.UtilityAnalysisEngine()
        result = engine.analyze(
            rows,
            self._options(public is not None, use_device, post_agg, mesh),
            extractors(), public_partitions=public)
        return result.arrays

    def _make_mesh(self):
        import jax
        from pipelinedp_tpu.parallel import sharded
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        return sharded.make_mesh(8)

    def _assert_grids_match(self, host, dev):
        assert dev.n_configs == host.n_configs
        assert dev.n_partitions == host.n_partitions
        for he, de in zip(host.metric_errors, dev.metric_errors):
            assert de.metric == he.metric
            for field in ("raw", "clip_min_err", "clip_max_err",
                          "exp_l0_err", "var_l0_err"):
                np.testing.assert_allclose(getattr(de, field),
                                           getattr(he, field),
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=f"{he.metric} {field}")
            np.testing.assert_allclose(de.std_noise, he.std_noise)
        if host.keep_prob is None:
            assert dev.keep_prob is None
        else:
            np.testing.assert_allclose(dev.keep_prob, host.keep_prob,
                                       rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(dev.raw_pid_count, host.raw_pid_count)
        np.testing.assert_allclose(dev.raw_count, host.raw_count)

    def test_device_matches_host_public(self):
        rows = self._random_rows()
        public = [f"pk{i}" for i in range(9)]  # incl. 2 empty partitions
        host = self._arrays(rows, public, use_device=False)
        dev = self._arrays(rows, public, use_device=True)
        self._assert_grids_match(host, dev)

    def test_device_matches_host_private_selection(self):
        rows = self._random_rows()
        host = self._arrays(rows, None, use_device=False)
        dev = self._arrays(rows, None, use_device=True)
        self._assert_grids_match(host, dev)

    def test_device_moments_drive_refined_normal_path(self):
        # One partition with 150 users (above MAX_EXACT_PROBABILITIES) so
        # the keep probability rides the approximate path, whose moments
        # come from the device kernel when the sweep is on-device.
        rows = [(u, "big", 1.0) for u in range(150)]
        rows += [(u, f"pk{u % 3}", 1.0) for u in range(30)]
        host = self._arrays(rows, None, use_device=False)
        dev = self._arrays(rows, None, use_device=True)
        self._assert_grids_match(host, dev)

    def test_device_matches_host_post_aggregation_thresholding(self):
        rows = self._random_rows(n_users=40)
        host = self._arrays(rows, None, use_device=False, post_agg=True)
        dev = self._arrays(rows, None, use_device=True, post_agg=True)
        self._assert_grids_match(host, dev)

    def test_empty_dataset(self):
        host = self._arrays([], ["pk0"], use_device=False)
        dev = self._arrays([], ["pk0"], use_device=True)
        self._assert_grids_match(host, dev)

    def test_auto_dispatch_is_host_on_cpu(self):
        from pipelinedp_tpu.analysis import device_sweep
        # The test environment is a CPU mesh: auto must not engage.
        assert not device_sweep.should_use_device(1 << 22, 64)

    # -- mesh sweep (VERDICT-r4 item 2): mesh == single-device == host ----

    def test_mesh_matches_host_and_single_device_public(self):
        mesh = self._make_mesh()
        rows = self._random_rows()
        public = [f"pk{i}" for i in range(9)]
        host = self._arrays(rows, public, use_device=False)
        dev = self._arrays(rows, public, use_device=True)
        mesh_arrays = self._arrays(rows, public, use_device=True, mesh=mesh)
        self._assert_grids_match(host, mesh_arrays)
        self._assert_grids_match(dev, mesh_arrays)

    def test_mesh_matches_host_private_selection(self):
        mesh = self._make_mesh()
        rows = self._random_rows()
        host = self._arrays(rows, None, use_device=False)
        mesh_arrays = self._arrays(rows, None, use_device=True, mesh=mesh)
        self._assert_grids_match(host, mesh_arrays)

    def test_mesh_moments_refined_normal(self):
        mesh = self._make_mesh()
        rows = [(u, "big", 1.0) for u in range(150)]
        rows += [(u, f"pk{u % 3}", 1.0) for u in range(30)]
        host = self._arrays(rows, None, use_device=False)
        mesh_arrays = self._arrays(rows, None, use_device=True, mesh=mesh)
        self._assert_grids_match(host, mesh_arrays)

    def test_mesh_report_reduction_matches_host(self):
        # The fused report reduction through build_reports_with_histogram
        # on the mesh: shard-local bucket sums + psum must reproduce the
        # host reports.
        mesh = self._make_mesh()
        rows = self._random_rows(n_users=50, n_partitions=10)
        public = [f"pk{i}" for i in range(10)]
        options_host = self._options(True, False)
        options_mesh = self._options(True, True, mesh=mesh)
        host_reports, _ = analysis.perform_utility_analysis(
            rows, options=options_host, data_extractors=extractors(),
            public_partitions=public)
        mesh_reports, _ = analysis.perform_utility_analysis(
            rows, options=options_mesh, data_extractors=extractors(),
            public_partitions=public)
        assert len(host_reports) == len(mesh_reports)
        for h, m in zip(host_reports, mesh_reports):
            _assert_dataclass_close(h, m, rtol=1e-3, atol=1e-4)


def _assert_dataclass_close(a, b, path="", rtol=1e-4, atol=1e-6):
    import dataclasses as _dc
    assert type(a) is type(b), f"{path}: {type(a)} vs {type(b)}"
    if _dc.is_dataclass(a):
        for f in _dc.fields(a):
            _assert_dataclass_close(getattr(a, f.name), getattr(b, f.name),
                                    f"{path}.{f.name}", rtol, atol)
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), f"{path}: len {len(a)} vs {len(b)}"
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_dataclass_close(x, y, f"{path}[{i}]", rtol, atol)
    elif isinstance(a, float):
        assert b == pytest.approx(a, rel=rtol, abs=atol), f"{path}: {a} vs {b}"
    else:
        assert a == b, f"{path}: {a} vs {b}"


class TestDeviceReportReduction:
    """The fused on-device cross-partition report reduction
    (cross_partition._build_reports_device) must reproduce the host report
    builder field for field."""

    def _reports(self, rows, public, use_device):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=2,
            max_contributions_per_partition=3,
            min_sum_per_partition=0.0,
            max_sum_per_partition=5.0)
        multi = analysis.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 4],
            max_contributions_per_partition=[1, 2, 3])
        options = analysis.UtilityAnalysisOptions(
            epsilon=2.0, delta=1e-5, aggregate_params=params,
            multi_param_configuration=multi, use_device_sweep=use_device)
        return analysis.perform_utility_analysis(
            rows, options=options, data_extractors=extractors(),
            public_partitions=public)

    def _rows(self):
        rng = np.random.default_rng(3)
        rows = []
        for u in range(60):
            for _ in range(rng.integers(1, 6)):
                rows.append((u, f"pk{rng.integers(0, 12)}",
                             float(rng.normal(2.0, 2.0))))
        # A large partition so size buckets span several decades.
        rows += [(1000 + u, "huge", 1.0) for u in range(400)]
        return rows

    def test_public_reports_match(self):
        rows = self._rows()
        public = [f"pk{i}" for i in range(14)] + ["huge"]  # 2 empty
        host_reports, _ = self._reports(rows, public, use_device=False)
        dev_reports, _ = self._reports(rows, public, use_device=True)
        _assert_dataclass_close(host_reports, dev_reports)

    def test_private_reports_match(self):
        rows = self._rows()
        host_reports, host_pp = self._reports(rows, None, use_device=False)
        dev_reports, dev_pp = self._reports(rows, None, use_device=True)
        _assert_dataclass_close(host_reports, dev_reports)
        # The lazy per-partition rows materialize consistently too.
        assert len(dev_pp) == len(host_pp)
        _assert_dataclass_close(host_pp[0][1], dev_pp[0][1])

    def test_tune_runs_on_device_sweep(self):
        # parameter_tuning consumes only reports: the device path must
        # carry a full tune() end-to-end.
        rows = self._rows()
        data_extractors = extractors()
        hist = list(computing_histograms.compute_dataset_histograms(
            rows, data_extractors, pdp.LocalBackend()))[0]
        options = analysis.TuneOptions(
            epsilon=2.0, delta=1e-5,
            aggregate_params=count_params(l0=2, linf=2),
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True,
                max_contributions_per_partition=True),
            number_of_parameter_candidates=8,
            use_device_sweep=True)
        result, _ = analysis.tune(rows, contribution_histograms=hist,
                                  options=options,
                                  data_extractors=data_extractors)
        assert result.utility_reports
        rmse = [r.metric_errors[0].absolute_error.rmse
                for r in result.utility_reports]
        assert result.index_best == int(np.argmin(rmse))

    def test_release_device_after_materialize(self):
        # Access through the lazy per-partition rows after releasing the
        # device grids with materialization: still works.
        rows = self._rows()
        engine = analysis.UtilityAnalysisEngine()
        opts = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=count_params(l0=2),
            use_device_sweep=True)
        result = engine.analyze(rows, opts, extractors())
        result.arrays.release_device(materialize=True)
        assert result.arrays.device is None
        first = next(iter(result))
        assert first[1][0].metric_errors
