"""Tests for the vectorized utility-analysis layer.

Mirrors the reference's analysis/tests strategy: per-partition error
models pinned against hand-computed values, exact Poisson-binomial
cross-checks, tolerance-compared report dataclasses, and an e2e tune() on
movie-view-shaped data."""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
import pipelinedp_tpu.analysis as analysis
from pipelinedp_tpu import partition_selection as ps_lib
from pipelinedp_tpu.analysis import (cross_partition, per_partition,
                                     poisson_binomial, pre_aggregation)
from pipelinedp_tpu.dataset_histograms import computing_histograms


def extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def count_params(l0=1, linf=1, **kwargs):
    return pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                               max_partitions_contributed=l0,
                               max_contributions_per_partition=linf,
                               **kwargs)


class TestPoissonBinomial:

    def test_exact_pmf_two_bernoullis(self):
        pmf = poisson_binomial.compute_pmf([0.5, 0.5])
        np.testing.assert_allclose(pmf.probabilities, [0.25, 0.5, 0.25])

    def test_exact_pmf_sums_to_one(self):
        rng = np.random.default_rng(0)
        pmf = poisson_binomial.compute_pmf(rng.uniform(0, 1, 30))
        assert pmf.probabilities.sum() == pytest.approx(1.0)

    def test_approximation_close_to_exact(self):
        rng = np.random.default_rng(1)
        probs = rng.uniform(0.3, 0.9, 80)
        exact = poisson_binomial.compute_pmf(probs)
        exp, std, skew = poisson_binomial.compute_exp_std_skewness(probs)
        approx = poisson_binomial.compute_pmf_approximation(
            exp, std, skew, len(probs))
        # Compare on the approximation's support.
        exact_slice = exact.probabilities[approx.start:approx.start +
                                          len(approx.probabilities)]
        np.testing.assert_allclose(approx.probabilities, exact_slice,
                                   atol=2e-3)


class TestPreAggregation:

    def test_groups_and_n_partitions(self):
        # user 1 -> pk a (2 contributions), pk b (1); user 2 -> pk a (1).
        rows = [(1, "a", 1.0), (1, "a", 2.0), (1, "b", 3.0), (2, "a", 4.0)]
        result = analysis.preaggregate(rows, data_extractors=extractors())
        as_dict = {}
        for pk, (count, s, n_part) in result:
            as_dict.setdefault(pk, []).append((count, s, n_part))
        assert sorted(as_dict["a"]) == [(1, 4.0, 1), (2, 3.0, 2)]
        assert as_dict["b"] == [(1, 3.0, 2)]

    def test_partition_sampling_deterministic(self):
        rows = [(u, f"pk{u % 50}", 1.0) for u in range(500)]
        r1 = analysis.preaggregate(rows, data_extractors=extractors(),
                                   partitions_sampling_prob=0.5)
        r2 = analysis.preaggregate(rows, data_extractors=extractors(),
                                   partitions_sampling_prob=0.5)
        assert [pk for pk, _ in r1] == [pk for pk, _ in r2]
        kept = {pk for pk, _ in r1}
        assert 0 < len(kept) < 50


class TestPerPartitionErrorModel:

    def _analyze(self, rows, params, eps=1.0, delta=1e-6, public=None,
                 multi=None):
        options = analysis.UtilityAnalysisOptions(
            epsilon=eps, delta=delta, aggregate_params=params,
            multi_param_configuration=multi)
        engine = analysis.UtilityAnalysisEngine()
        return engine.analyze(rows, options, extractors(),
                              public_partitions=public)

    def test_count_clipping_and_l0_errors(self):
        # One user contributes 5 rows to "a" and 1 row to "b"; linf=3, l0=1.
        rows = [(1, "a", 0.0)] * 5 + [(1, "b", 0.0)]
        result = self._analyze(rows, count_params(l0=1, linf=3),
                               public=["a", "b"])
        per_pk = dict(result)
        err_a = per_pk["a"][0].metric_errors[0]
        assert err_a.sum == 5.0
        # count 5 clipped to 3: clipping_to_max_error = -2.
        assert err_a.clipping_to_max_error == pytest.approx(-2.0)
        # q = 1/2 (2 partitions, l0=1): E[l0 err] = -3 * 0.5.
        assert err_a.expected_l0_bounding_error == pytest.approx(-1.5)
        # Var = 3^2 * 0.25.
        assert err_a.std_l0_bounding_error == pytest.approx(1.5)

    def test_count_noise_std_matches_mechanism(self):
        rows = [(1, "a", 0.0)]
        result = self._analyze(rows, count_params(l0=2, linf=3),
                               eps=2.0, delta=1e-8, public=["a"])
        err = dict(result)["a"][0].metric_errors[0]
        # All budget to COUNT (public partitions, one metric): Laplace
        # b = l0*linf/eps, std = sqrt(2) b.
        expected = np.sqrt(2.0) * 2 * 3 / 2.0
        assert err.std_noise == pytest.approx(expected)

    def test_sum_clipping(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_sum_per_partition=0.0,
                                     max_sum_per_partition=2.0)
        rows = [(1, "a", 5.0), (2, "a", -1.0)]
        result = self._analyze(rows, params, public=["a"])
        err = dict(result)["a"][0].metric_errors[0]
        assert err.sum == 4.0
        assert err.clipping_to_max_error == pytest.approx(-3.0)
        assert err.clipping_to_min_error == pytest.approx(1.0)

    def test_keep_probability_exact_matches_strategy(self):
        # 20 users, each contributing to exactly this partition (q=1):
        # the keep probability equals the strategy's probability_of_keep(20).
        rows = [(u, "a", 0.0) for u in range(20)]
        result = self._analyze(rows, count_params(), eps=1.0, delta=1e-4)
        ppm = dict(result)["a"][0]
        # Budget split: eps halved between GENERIC selection and COUNT;
        # Laplace COUNT consumes no delta, so selection gets all of it.
        strategy = ps_lib.create_partition_selection_strategy(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 0.5, 1e-4, 1)
        assert ppm.partition_selection_probability_to_keep == pytest.approx(
            strategy.probability_of_keep(20), rel=1e-6)

    def test_keep_probability_approx_matches_exact(self):
        # 150 users (above the exact cutoff) with q=1: approximation must
        # agree with the exact strategy value.
        rows = [(u, "a", 0.0) for u in range(150)]
        result = self._analyze(rows, count_params(), eps=1.0, delta=1e-4)
        ppm = dict(result)["a"][0]
        strategy = ps_lib.create_partition_selection_strategy(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 0.5, 5e-5, 1)
        assert ppm.partition_selection_probability_to_keep == pytest.approx(
            strategy.probability_of_keep(150), rel=1e-3)

    def test_multi_config_sweep_shapes(self):
        rows = [(u, f"pk{u % 3}", 1.0) for u in range(30)]
        multi = analysis.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 3],
            max_contributions_per_partition=[1, 1, 2])
        result = self._analyze(rows, count_params(), multi=multi)
        arrays = result.arrays
        assert arrays.n_configs == 3
        assert arrays.metric_errors[0].raw.shape == (3, 3)
        per_config = dict(result)["pk0"]
        assert len(per_config) == 3

    def test_raw_statistics(self):
        rows = [(1, "a", 0.0), (1, "a", 0.0), (2, "a", 0.0)]
        result = self._analyze(rows, count_params(), public=["a"])
        stats = dict(result)["a"][0].raw_statistics
        assert stats.privacy_id_count == 2
        assert stats.count == 3


class TestPerformUtilityAnalysis:

    def test_public_report_averaging(self):
        # Two partitions, both kept (public): report averages per-partition
        # errors equally.
        rows = ([(u, "a", 0.0) for u in range(4)] +
                [(u + 100, "b", 0.0) for u in range(2)])
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=count_params())
        reports, per_partition_result = analysis.perform_utility_analysis(
            rows, options=options, data_extractors=extractors(),
            public_partitions=["a", "b"])
        assert len(reports) == 1
        report = reports[0]
        assert report.partitions_info.public_partitions
        assert report.partitions_info.num_dataset_partitions == 2
        err = report.metric_errors[0]
        # No clipping/l0 error (l0=1 but each user contributes to exactly 1
        # partition): bias 0, variance = noise^2, rmse = noise std.
        assert err.absolute_error.mean == pytest.approx(0.0)
        assert err.absolute_error.rmse == pytest.approx(err.noise_std)
        # ((pk, config), PerPartitionMetrics) entries: 2 partitions x 1 cfg.
        assert len(per_partition_result) == 2

    def test_private_report_weighted_by_keep_prob(self):
        rows = ([(u, "big", 0.0) for u in range(1000)] +
                [(1, "small", 0.0)])
        options = analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-4, aggregate_params=count_params())
        reports, _ = analysis.perform_utility_analysis(
            rows, options=options, data_extractors=extractors())
        info = reports[0].partitions_info
        assert not info.public_partitions
        assert info.num_dataset_partitions == 2
        # big is kept ~surely, small ~never.
        assert info.kept_partitions.mean == pytest.approx(1.0, abs=0.05)
        assert info.strategy is not None

    def test_histogram_buckets(self):
        sizes = np.array([0, 1, 5, 10, 20, 50, 100, 999])
        buckets = cross_partition.partition_size_buckets(sizes)
        assert list(buckets) == [0, 1, 1, 10, 20, 50, 100, 500]
        assert cross_partition.bucket_upper_bound(10) == 20


class TestDPStrategySelector:

    def test_gaussian_wins_for_large_l0(self):
        selector = analysis.DPStrategySelector(
            epsilon=1.0, delta=1e-6, metric=pdp.Metrics.COUNT,
            is_public_partitions=True)
        import pipelinedp_tpu.dp_computations as dp_computations
        strategy = selector.get_dp_strategy(
            dp_computations.Sensitivities(l0=100, linf=1))
        assert strategy.noise_kind == pdp.NoiseKind.GAUSSIAN

    def test_laplace_wins_for_small_l0(self):
        selector = analysis.DPStrategySelector(
            epsilon=1.0, delta=1e-6, metric=pdp.Metrics.COUNT,
            is_public_partitions=True)
        import pipelinedp_tpu.dp_computations as dp_computations
        strategy = selector.get_dp_strategy(
            dp_computations.Sensitivities(l0=1, linf=1))
        assert strategy.noise_kind == pdp.NoiseKind.LAPLACE

    def test_privacy_id_count_uses_post_aggregation_thresholding(self):
        selector = analysis.DPStrategySelector(
            epsilon=1.0, delta=1e-6, metric=pdp.Metrics.PRIVACY_ID_COUNT,
            is_public_partitions=False)
        import pipelinedp_tpu.dp_computations as dp_computations
        strategy = selector.get_dp_strategy(
            dp_computations.Sensitivities(l0=10, linf=1))
        assert strategy.post_aggregation_thresholding
        assert strategy.partition_selection_strategy is not None

    def test_select_partitions_case(self):
        selector = analysis.DPStrategySelector(epsilon=1.0, delta=1e-6,
                                               metric=None,
                                               is_public_partitions=False)
        import pipelinedp_tpu.dp_computations as dp_computations
        strategy = selector.get_dp_strategy(
            dp_computations.Sensitivities(l0=5, linf=1))
        assert strategy.noise_kind is None
        assert strategy.partition_selection_strategy is not None


class TestTune:

    def _movie_shaped_rows(self, n_users=400, n_movies=40, seed=0):
        rng = np.random.default_rng(seed)
        rows = []
        for u in range(n_users):
            n_watched = 1 + rng.integers(0, 8)
            movies = rng.choice(n_movies, size=min(n_watched, n_movies),
                                replace=False)
            for m in movies:
                rows.append((u, int(m), float(rng.integers(1, 6))))
        return rows

    def test_tune_count_returns_rmse_ranked_result(self):
        rows = self._movie_shaped_rows()
        histograms = list(computing_histograms.compute_dataset_histograms(
            rows, extractors(), pdp.LocalBackend()))[0]
        options = analysis.TuneOptions(
            epsilon=1.0,
            delta=1e-6,
            aggregate_params=count_params(l0=1, linf=1),
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True,
                max_contributions_per_partition=True),
            number_of_parameter_candidates=16)
        result, per_partition_result = analysis.tune(
            rows, contribution_histograms=histograms, options=options,
            data_extractors=extractors())
        assert isinstance(result, analysis.TuneResult)
        candidates = result.utility_analysis_parameters
        assert candidates.size <= 16
        assert len(result.utility_reports) == candidates.size
        assert 0 <= result.index_best < candidates.size
        # Reports carry RMSE; best really is the argmin.
        rmse = [r.metric_errors[0].absolute_error.rmse
                for r in result.utility_reports]
        assert result.index_best == int(np.argmin(rmse))
        # Strategies were attached per candidate.
        assert len(candidates.noise_kind) == candidates.size
        assert len(candidates.partition_selection_strategy) == candidates.size
        assert per_partition_result

    def test_tune_sum(self):
        rows = self._movie_shaped_rows()
        histograms = list(computing_histograms.compute_dataset_histograms(
            rows, extractors(), pdp.LocalBackend()))[0]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_sum_per_partition=0.0,
                                     max_sum_per_partition=1.0)
        options = analysis.TuneOptions(
            epsilon=1.0,
            delta=1e-6,
            aggregate_params=params,
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True,
                max_sum_per_partition=True),
            number_of_parameter_candidates=9)
        result, _ = analysis.tune(rows, contribution_histograms=histograms,
                                  options=options,
                                  data_extractors=extractors())
        assert result.index_best >= 0
        best = result.utility_analysis_parameters.get_aggregate_params(
            params, result.index_best)
        assert best.max_sum_per_partition > 0

    def test_tune_rejects_two_metrics(self):
        options_kwargs = dict(
            epsilon=1.0, delta=1e-6,
            function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=analysis.ParametersToTune(
                max_partitions_contributed=True))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0, max_value=1)
        with pytest.raises(ValueError, match="one metric"):
            analysis.tune(
                [], contribution_histograms=None,
                options=analysis.TuneOptions(aggregate_params=params,
                                             **options_kwargs),
                data_extractors=extractors())


class TestCandidateGeneration:

    def test_constant_relative_step(self):
        from pipelinedp_tpu.dataset_histograms import histograms as h
        bins = [h.FrequencyBin(1, 2, 10, 5, 1), h.FrequencyBin(
            99, 100, 3, 1, 100)]
        hist = h.Histogram(h.HistogramType.L0_CONTRIBUTIONS, bins)
        candidates = analysis.parameter_tuning.\
            candidates_constant_relative_step(hist, 5)
        assert candidates[0] == 1
        assert candidates[-1] == 100
        assert candidates == sorted(set(candidates))

    def test_2d_grid_size(self):
        from pipelinedp_tpu.analysis.parameter_tuning import candidates_2d_grid
        fn = lambda hist, k: list(range(1, k + 1))
        g1, g2 = candidates_2d_grid(None, None, fn, fn, 16)
        assert len(g1) == len(g2) == 16


class TestDatasetSummary:

    def test_overlap_counts(self):
        rows = [(1, "a", 0.0), (2, "b", 0.0), (3, "c", 0.0)]
        summary = analysis.compute_public_partitions_summary(
            rows, extractors=extractors(),
            public_partitions=["a", "b", "zzz"])
        assert summary.num_dataset_public_partitions == 2
        assert summary.num_dataset_non_public_partitions == 1
        assert summary.num_empty_public_partitions == 1


class TestMultiParameterConfiguration:

    def test_size_validation(self):
        with pytest.raises(ValueError, match="same length"):
            analysis.MultiParameterConfiguration(
                max_partitions_contributed=[1, 2],
                max_contributions_per_partition=[1])

    def test_get_aggregate_params(self):
        config = analysis.MultiParameterConfiguration(
            max_partitions_contributed=[1, 5],
            noise_kind=[pdp.NoiseKind.LAPLACE, pdp.NoiseKind.GAUSSIAN])
        params = config.get_aggregate_params(count_params(), 1)
        assert params.max_partitions_contributed == 5
        assert params.noise_kind == pdp.NoiseKind.GAUSSIAN
