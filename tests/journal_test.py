"""Durable at-most-once journal tests (runtime/journal.py).

The contracts pinned here (RESILIENCE.md):

  * WAL round-trip — a FileReleaseJournal reopened from its file knows
    every committed token and refuses replays (DoubleReleaseError), so
    at-most-once survives process death;
  * write-ahead ordering — the record is fsync'd before commit returns,
    so a crash between commit and publication errs toward zero releases;
  * torn-tail tolerance — a crash mid-append leaves a partial final
    record, which was never acknowledged and is dropped (and truncated)
    on recovery;
  * corruption refusal — a malformed *interior* record means the release
    history cannot be trusted: recovery raises JournalCorruptError
    instead of silently forgetting a committed release;
  * compaction — an atomic rewrite preserving the exact record sequence;
  * the durable spend journal — a re-exec'd accountant replaying a
    committed epsilon spend raises BudgetAccountantError (the
    cross-process half lives in tests/process_kill_test.py).
"""

import json
import os

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler, runtime
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.budget_accounting import BudgetAccountantError
from pipelinedp_tpu.runtime import journal as journal_lib


@pytest.fixture(autouse=True)
def _reset_runtime_counters():
    profiler.reset_events("runtime/")
    yield


def _wal(tmp_path, name="journal.wal"):
    return str(tmp_path / name)


class TestFileJournalRoundTrip:

    def test_clean_recovery_refuses_replay(self, tmp_path):
        path = _wal(tmp_path)
        with runtime.FileReleaseJournal(path) as journal:
            journal.commit(("noise_release", "fp-a", 3))
            journal.commit(("noise_release", "fp-b", 5),
                           kind="selection_release")
        reopened = runtime.FileReleaseJournal(path)
        assert reopened.recovered_records == 2
        assert len(reopened) == 2
        assert reopened.has(("noise_release", "fp-a", 3))
        assert [r.kind for r in reopened.records] == [
            "noise_release", "selection_release"]
        with pytest.raises(runtime.DoubleReleaseError, match="already"):
            reopened.commit(("noise_release", "fp-a", 3))
        # A fresh token still commits after recovery.
        reopened.commit(("noise_release", "fp-c", 7))
        assert len(reopened) == 3
        reopened.close()

    def test_recovery_counters(self, tmp_path):
        path = _wal(tmp_path)
        journal = runtime.FileReleaseJournal(path)
        assert profiler.event_count(runtime.EVENT_JOURNAL_BYTES) == 0
        journal.commit(("t", 1))
        assert profiler.event_count(runtime.EVENT_JOURNAL_BYTES) > 0
        assert profiler.event_count(runtime.EVENT_JOURNAL_RECOVERIES) == 0
        journal.close()
        runtime.FileReleaseJournal(path).close()
        assert profiler.event_count(runtime.EVENT_JOURNAL_RECOVERIES) == 1
        # An empty journal is not a "recovery".
        runtime.FileReleaseJournal(_wal(tmp_path, "empty.wal")).close()
        assert profiler.event_count(runtime.EVENT_JOURNAL_RECOVERIES) == 1

    def test_numpy_scalar_tokens_round_trip(self, tmp_path):
        import numpy as np
        path = _wal(tmp_path)
        with runtime.FileReleaseJournal(path) as journal:
            journal.commit(("spend", np.int64(4), np.float64(0.5)))
        reopened = runtime.FileReleaseJournal(path)
        with pytest.raises(runtime.DoubleReleaseError):
            reopened.commit(("spend", 4, 0.5))
        reopened.close()

    def test_in_memory_journal_unchanged(self):
        journal = runtime.ReleaseJournal()
        journal.commit(("t", 1))
        with pytest.raises(runtime.DoubleReleaseError):
            journal.commit(("t", 1))
        assert journal.has(("t", 1)) and not journal.has(("t", 2))


class TestTornAndCorrupt:

    def _write_records(self, path, n=3):
        with runtime.FileReleaseJournal(path) as journal:
            for i in range(n):
                journal.commit(("t", i))
        with open(path, "rb") as f:
            return f.read()

    def test_torn_tail_partial_line_tolerated(self, tmp_path):
        path = _wal(tmp_path)
        data = self._write_records(path)
        # Crash mid-append: the last record is half-written.
        with open(path, "wb") as f:
            f.write(data[:-7])
        journal = runtime.FileReleaseJournal(path)
        assert journal.recovered_records == 2
        assert not journal.has(("t", 2))
        # The torn bytes were truncated: the token can commit again and
        # a re-open sees a clean 3-record file.
        journal.commit(("t", 2))
        journal.close()
        assert runtime.FileReleaseJournal(path).recovered_records == 3

    def test_torn_tail_digest_mismatch_tolerated(self, tmp_path):
        path = _wal(tmp_path)
        data = self._write_records(path)
        lines = data.splitlines(keepends=True)
        # The final record's bytes were garbled by the crash but a
        # newline survived: still the torn-tail case (only the LAST
        # record may be bad).
        bad = lines[2].replace(b'"t"', b'"x"')
        with open(path, "wb") as f:
            f.writelines(lines[:2] + [bad])
        journal = runtime.FileReleaseJournal(path)
        assert journal.recovered_records == 2
        journal.close()

    def test_interior_corruption_raises(self, tmp_path):
        path = _wal(tmp_path)
        data = self._write_records(path)
        lines = data.splitlines(keepends=True)
        bad = lines[1].replace(b'"t"', b'"x"')
        with open(path, "wb") as f:
            f.writelines([lines[0], bad, lines[2]])
        with pytest.raises(runtime.JournalCorruptError, match="malformed"):
            runtime.FileReleaseJournal(path)

    def test_sequence_gap_is_corruption(self, tmp_path):
        path = _wal(tmp_path)
        data = self._write_records(path, n=4)
        lines = data.splitlines(keepends=True)
        # Dropping an interior record breaks the seq chain even though
        # every remaining line is self-consistent; with further records
        # following, this cannot be a torn tail.
        with open(path, "wb") as f:
            f.writelines([lines[0], lines[2], lines[3]])
        with pytest.raises(runtime.JournalCorruptError):
            runtime.FileReleaseJournal(path)

    def test_every_record_carries_digest(self, tmp_path):
        path = _wal(tmp_path)
        self._write_records(path, n=2)
        with open(path) as f:
            for line in f:
                obj = json.loads(line)
                assert set(obj) == {"seq", "kind", "token", "digest"}
                assert len(obj["digest"]) == 16


class TestCompaction:

    def test_compact_preserves_records_atomically(self, tmp_path):
        path = _wal(tmp_path)
        journal = runtime.FileReleaseJournal(path)
        for i in range(4):
            journal.commit(("t", i))
        size_before = os.path.getsize(path)
        journal.compact()
        assert os.path.getsize(path) == size_before  # nothing to drop
        # Compaction drops truncated garbage for good and keeps the
        # journal appendable.
        journal.commit(("t", 99))
        journal.close()
        reopened = runtime.FileReleaseJournal(path)
        assert [r.token for r in reopened.records] == [
            ("t", 0), ("t", 1), ("t", 2), ("t", 3), ("t", 99)]
        reopened.close()
        assert not [n for n in os.listdir(tmp_path)
                    if n.endswith(".tmp")]


class TestGroupCommit:
    """JsonlWal group commit (ISSUE 17): unsynced appends coalesce
    behind one fsync, and the synced-ticket watermark tells callers
    exactly which records are durable against power loss."""

    def test_one_fsync_covers_many_unsynced_appends(self, tmp_path,
                                                    monkeypatch):
        wal = journal_lib.JsonlWal(_wal(tmp_path))
        fsyncs = []
        real_fsync = os.fsync
        monkeypatch.setattr(journal_lib.os, "fsync",
                            lambda fd: (fsyncs.append(fd),
                                        real_fsync(fd))[1])
        for seq in range(5):
            wal.append({"seq": seq, "n": seq}, sync=False)
        assert fsyncs == []
        ticket = wal.sync_ticket()
        wal.sync_through(ticket)
        assert len(fsyncs) == 1  # one fsync amortized five appends
        assert wal.synced_ticket >= ticket
        # Covered tickets return without another fsync.
        wal.sync_through(ticket)
        assert len(fsyncs) == 1
        wal.close()
        reopened = journal_lib.JsonlWal(_wal(tmp_path))
        assert [p["n"] for p in reopened.recovered] == [0, 1, 2, 3, 4]

    def test_synced_append_advances_watermark(self, tmp_path):
        wal = journal_lib.JsonlWal(_wal(tmp_path))
        wal.append({"seq": 0})
        assert wal.synced_ticket == wal.sync_ticket() == 1
        wal.append({"seq": 1}, sync=False)
        assert wal.synced_ticket == 1
        assert wal.sync_ticket() == 2

    def test_unsynced_appends_survive_reopen(self, tmp_path):
        # Flushed-but-unfsync'd records live in the page cache: a
        # process death (not power loss) keeps them, so recovery after
        # SIGKILL sees the record — the live "commit" crash seam.
        wal = journal_lib.JsonlWal(_wal(tmp_path))
        wal.append({"seq": 0, "k": "a"}, sync=False)
        wal.close()
        reopened = journal_lib.JsonlWal(_wal(tmp_path))
        assert reopened.recovered[0]["k"] == "a"

    def test_concurrent_sync_through_all_covered(self, tmp_path):
        import threading as _threading
        wal = journal_lib.JsonlWal(_wal(tmp_path))
        errors = []
        barrier = _threading.Barrier(8)
        # seq numbering is the caller's job (live.py holds its append
        # lock across append + sync_ticket); mirror that here.
        seq_lock = _threading.Lock()

        def worker(i):
            try:
                barrier.wait()
                with seq_lock:
                    wal.append({"seq": wal.next_seq, "i": i}, sync=False)
                    ticket = wal.sync_ticket()
                wal.sync_through(ticket, window_s=0.005)
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [_threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert wal.synced_ticket == 8
        wal.close()
        reopened = journal_lib.JsonlWal(_wal(tmp_path))
        assert sorted(p["i"] for p in reopened.recovered) == list(range(8))


class TestEngineDurableRelease:
    """The engine's release_journal= knob with a durable journal: the
    same-process half of the cross-process guarantee (the SIGKILL +
    re-exec half lives in tests/process_kill_test.py)."""

    def _aggregate(self, journal, seed=3):
        import numpy as np
        rng = np.random.default_rng(0)
        pid = rng.integers(0, 500, 5_000)
        pk = rng.integers(0, 20, 5_000).astype(np.int32)
        value = rng.uniform(0, 5, 5_000).astype(np.float32)
        accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=seed,
                                 secure_host_noise=False,
                                 release_journal=journal)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=20,
            max_contributions_per_partition=100, min_value=0.0,
            max_value=5.0)
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=list(range(20)))
        accountant.compute_budgets()
        return result.to_columns()

    def test_fresh_process_refuses_replayed_release(self, tmp_path):
        path = _wal(tmp_path)
        with runtime.FileReleaseJournal(path) as journal:
            self._aggregate(journal)
        # "Re-exec": a brand-new journal object over the same file.
        with runtime.FileReleaseJournal(path) as journal2:
            assert journal2.recovered_records == 1
            with pytest.raises(runtime.DoubleReleaseError):
                self._aggregate(journal2)
            # A different seed is a different release and still commits.
            self._aggregate(journal2, seed=4)


class TestDurableSpendJournal:

    def _spend(self, path):
        accountant = pdp.NaiveBudgetAccountant(
            1.0, 1e-6,
            durable_spend_journal=runtime.FileReleaseJournal(path))
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.request_budget(MechanismType.GAUSSIAN)
        accountant.compute_budgets()
        return accountant

    def test_replay_after_reopen_refuses(self, tmp_path):
        path = _wal(tmp_path)
        accountant = self._spend(path)
        assert len(accountant.spend_journal) == 2
        with pytest.raises(BudgetAccountantError, match="replay"):
            self._spend(path)

    def test_distinct_pipelines_share_a_journal(self, tmp_path):
        path = _wal(tmp_path)
        self._spend(path)
        # A different budget split is a different spend identity.
        other = pdp.NaiveBudgetAccountant(
            2.0, 1e-6,
            durable_spend_journal=runtime.FileReleaseJournal(path))
        other.request_budget(MechanismType.LAPLACE)
        other.compute_budgets()
        assert len(other.spend_journal) == 1

    def test_pld_accountant_supported(self, tmp_path):
        # Coarse discretization: this pins the durable-spend-journal
        # semantics (commit + cross-process replay refusal), not PLD
        # tightness -- the golden-value suites cover the numerics.
        path = _wal(tmp_path)
        accountant = pdp.PLDBudgetAccountant(
            1.0, 1e-6, pld_discretization=1e-2,
            durable_spend_journal=runtime.FileReleaseJournal(path))
        accountant.request_budget(MechanismType.GAUSSIAN)
        accountant.compute_budgets()
        replay = pdp.PLDBudgetAccountant(
            1.0, 1e-6, pld_discretization=1e-2,
            durable_spend_journal=runtime.FileReleaseJournal(path))
        replay.request_budget(MechanismType.GAUSSIAN)
        with pytest.raises(BudgetAccountantError, match="replay"):
            replay.compute_budgets()

    def test_in_memory_spend_journal_unaffected(self):
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        assert len(accountant.spend_journal) == 1


class TestCheckpointStoreDigestRetention:
    """FileCheckpointStore satellite: payload digests make a torn
    snapshot distinguishable from a fingerprint mismatch, and retention
    keeps the last K snapshots (atomic prune)."""

    def _checkpoint(self, next_chunk):
        import numpy as np
        rng = np.random.default_rng(next_chunk)
        return runtime.StreamCheckpoint(
            run_id="r", next_chunk=next_chunk, n_chunks=8,
            accs=tuple(rng.random(16).astype(np.float32)
                       for _ in range(5)),
            qhist=None, key_fingerprint="kf", wire_fingerprint="wf",
            key_counter=2)

    def test_retention_keeps_last_k(self, tmp_path):
        store = runtime.FileCheckpointStore(str(tmp_path), keep=2)
        for i in range(5):
            store.save(self._checkpoint(i))
        snapshots = [n for n in os.listdir(tmp_path) if n.endswith(".npz")]
        assert len(snapshots) == 2
        assert store.load("r").next_chunk == 4
        store.delete("r")
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".npz")]

    def test_torn_snapshot_falls_back_to_previous(self, tmp_path):
        store = runtime.FileCheckpointStore(str(tmp_path), keep=3)
        store.save(self._checkpoint(2))
        store.save(self._checkpoint(5))
        newest = max(n for n in os.listdir(tmp_path) if n.endswith(".npz"))
        path = os.path.join(tmp_path, newest)
        with open(path, "rb") as f:
            data = f.read()
        with open(path, "wb") as f:
            f.write(data[: len(data) // 2])  # torn write
        loaded = store.load("r")
        assert loaded is not None and loaded.next_chunk == 2

    def test_bit_flip_detected_by_digest(self, tmp_path):
        store = runtime.FileCheckpointStore(str(tmp_path), keep=3)
        store.save(self._checkpoint(2))
        store.save(self._checkpoint(5))
        newest = max(n for n in os.listdir(tmp_path) if n.endswith(".npz"))
        path = os.path.join(tmp_path, newest)
        with open(path, "r+b") as f:
            f.seek(os.path.getsize(path) // 2)
            byte = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([byte[0] ^ 0xFF]))
        loaded = store.load("r")
        # Either the zip container or the payload digest catches it;
        # the previous snapshot serves the resume.
        assert loaded is not None and loaded.next_chunk == 2

    def test_keep_one_behaves_like_legacy(self, tmp_path):
        store = runtime.FileCheckpointStore(str(tmp_path), keep=1)
        store.save(self._checkpoint(2))
        store.save(self._checkpoint(5))
        snapshots = [n for n in os.listdir(tmp_path) if n.endswith(".npz")]
        assert len(snapshots) == 1
        assert store.load("r").next_chunk == 5

    def test_keep_validated(self, tmp_path):
        with pytest.raises(ValueError, match="keep"):
            runtime.FileCheckpointStore(str(tmp_path), keep=0)

    def test_legacy_unseqed_file_still_loads(self, tmp_path):
        # A pre-retention checkpoint (`<run_id>.npz`, no digest) written
        # by an older release participates as the oldest snapshot.
        import numpy as np
        store = runtime.FileCheckpointStore(str(tmp_path))
        store.save(self._checkpoint(3))
        newest = max(n for n in os.listdir(tmp_path) if n.endswith(".npz"))
        os.rename(os.path.join(tmp_path, newest),
                  os.path.join(tmp_path, "r.npz"))
        loaded = store.load("r")
        assert loaded is not None and loaded.next_chunk == 3
        store.save(self._checkpoint(6))
        assert store.load("r").next_chunk == 6
        store.delete("r")
        assert store.load("r") is None
