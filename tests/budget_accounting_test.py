"""Tests for budget accounting (naive + PLD).

Mirrors the semantics pinned by the reference's
tests/budget_accounting_test.py against budget_accounting.py:40-619.
"""

import math

import pytest

from pipelinedp_tpu import budget_accounting as ba
from pipelinedp_tpu.aggregate_params import MechanismType


class TestMechanismSpec:

    def test_unresolved_access_raises(self):
        spec = ba.MechanismSpec(MechanismType.LAPLACE)
        with pytest.raises(AssertionError):
            _ = spec.eps
        with pytest.raises(AssertionError):
            _ = spec.delta
        with pytest.raises(AssertionError):
            _ = spec.noise_standard_deviation

    def test_use_delta(self):
        assert not ba.MechanismSpec(MechanismType.LAPLACE).use_delta()
        assert ba.MechanismSpec(MechanismType.GAUSSIAN).use_delta()
        assert ba.MechanismSpec(MechanismType.GENERIC).use_delta()


class TestNaiveBudgetAccountant:

    def test_validation(self):
        with pytest.raises(ValueError):
            ba.NaiveBudgetAccountant(total_epsilon=0, total_delta=1e-6)
        with pytest.raises(ValueError):
            ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=-1e-6)
        with pytest.raises(ValueError):
            ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=1e-6,
                                     num_aggregations=2,
                                     aggregation_weights=[1, 1])

    def test_single_mechanism_gets_everything(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1,
                                              total_delta=1e-6)
        spec = accountant.request_budget(MechanismType.GAUSSIAN)
        accountant.compute_budgets()
        assert spec.eps == 1
        assert spec.delta == 1e-6

    def test_laplace_gets_no_delta(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1,
                                              total_delta=1e-6)
        laplace = accountant.request_budget(MechanismType.LAPLACE)
        gaussian = accountant.request_budget(MechanismType.GAUSSIAN)
        accountant.compute_budgets()
        assert laplace.eps == pytest.approx(0.5)
        assert laplace.delta == 0
        assert gaussian.eps == pytest.approx(0.5)
        assert gaussian.delta == pytest.approx(1e-6)

    def test_weights(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        a = accountant.request_budget(MechanismType.LAPLACE, weight=1)
        b = accountant.request_budget(MechanismType.LAPLACE, weight=3)
        accountant.compute_budgets()
        assert a.eps == pytest.approx(0.25)
        assert b.eps == pytest.approx(0.75)

    def test_count(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        a = accountant.request_budget(MechanismType.LAPLACE, count=3)
        b = accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        # a's weight is effectively repeated 3 times in the denominator.
        assert a.eps == pytest.approx(0.25)
        assert b.eps == pytest.approx(0.25)

    def test_gaussian_requires_delta(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        with pytest.raises(ValueError, match="Gaussian"):
            accountant.request_budget(MechanismType.GAUSSIAN)

    def test_request_after_compute_raises(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        with pytest.raises(Exception, match="request_budget"):
            accountant.request_budget(MechanismType.LAPLACE)

    def test_compute_twice_raises(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        with pytest.raises(Exception, match="twice"):
            accountant.compute_budgets()


class TestBudgetAccountantError:
    """Regression: the accounting contract violations raise the typed
    BudgetAccountantError (historically bare Exception), so recovery
    layers can tell an accounting replay from a transient failure."""

    def test_compute_twice_raises_typed_error(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        with pytest.raises(ba.BudgetAccountantError, match="twice"):
            accountant.compute_budgets()

    def test_request_after_compute_raises_typed_error(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        with pytest.raises(ba.BudgetAccountantError, match="request_budget"):
            accountant.request_budget(MechanismType.LAPLACE)

    def test_compute_inside_scope_raises_typed_error(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        scope = accountant.scope(weight=1)
        with scope:
            accountant.request_budget(MechanismType.LAPLACE)
            with pytest.raises(ba.BudgetAccountantError, match="scope"):
                accountant.compute_budgets()

    def test_error_is_an_exception_subclass(self):
        # Callers that historically caught Exception keep working.
        assert issubclass(ba.BudgetAccountantError, Exception)

    def test_replaying_committed_spend_raises(self):
        spec = ba.MechanismSpec(MechanismType.LAPLACE)
        spec.set_eps_delta(1.0, 0.0)
        with pytest.raises(ba.BudgetAccountantError, match="committed"):
            spec.set_eps_delta(1.0, 0.0)
        spec2 = ba.MechanismSpec(MechanismType.GAUSSIAN)
        spec2.set_noise_standard_deviation(2.0)
        with pytest.raises(ba.BudgetAccountantError, match="committed"):
            spec2.set_noise_standard_deviation(2.0)

    def test_spend_journal_records_each_mechanism_once(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=2,
                                              total_delta=1e-6)
        accountant.request_budget(MechanismType.LAPLACE, weight=3)
        accountant.request_budget(MechanismType.GAUSSIAN, weight=1)
        accountant.compute_budgets()
        journal = accountant.spend_journal
        assert [record.index for record in journal] == [0, 1]
        assert journal[0].eps == pytest.approx(1.5)
        assert journal[1].eps == pytest.approx(0.5)
        assert journal[0].delta == 0.0
        assert journal[1].delta == pytest.approx(1e-6)

    def test_pld_spend_journal(self):
        accountant = ba.PLDBudgetAccountant(total_epsilon=1,
                                            total_delta=1e-6)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.request_budget(MechanismType.GENERIC)
        accountant.compute_budgets()
        journal = accountant.spend_journal
        assert len(journal) == 2
        assert all(record.noise_standard_deviation > 0
                   for record in journal)
        # GENERIC also resolves (eps0, delta0).
        assert journal[1].eps is not None and journal[1].eps > 0

    def test_scope_normalizes_weights(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        with accountant.scope(weight=1):
            a = accountant.request_budget(MechanismType.LAPLACE)
            b = accountant.request_budget(MechanismType.LAPLACE)
        with accountant.scope(weight=1):
            c = accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        # Scope 1 splits its half between two mechanisms.
        assert a.eps == pytest.approx(0.25)
        assert b.eps == pytest.approx(0.25)
        assert c.eps == pytest.approx(0.5)

    def test_num_aggregations_restriction(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0,
                                              num_aggregations=2)
        accountant._compute_budget_for_aggregation(1)
        with pytest.raises(ValueError, match="num_aggregations"):
            accountant.compute_budgets()

    def test_aggregation_weights_restriction(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0,
                                              aggregation_weights=[1, 2])
        accountant._compute_budget_for_aggregation(1)
        accountant._compute_budget_for_aggregation(3)
        with pytest.raises(ValueError, match="aggregation_weights"):
            accountant.compute_budgets()

    def test_budget_for_aggregation_split(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1,
                                              total_delta=1e-6,
                                              num_aggregations=2)
        budget = accountant._compute_budget_for_aggregation(1)
        assert budget.epsilon == pytest.approx(0.5)
        assert budget.delta == pytest.approx(5e-7)


class TestPLDBudgetAccountant:

    def test_delta_zero_closed_form(self):
        accountant = ba.PLDBudgetAccountant(total_epsilon=2, total_delta=0)
        spec = accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        # One Laplace mechanism, weight 1: min noise std = sqrt(2)/eps.
        assert spec.noise_standard_deviation == pytest.approx(
            math.sqrt(2) / 2)

    def test_single_laplace_close_to_naive(self):
        accountant = ba.PLDBudgetAccountant(total_epsilon=1,
                                            total_delta=1e-8,
                                            pld_discretization=1e-3)
        spec = accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        # A single Laplace mechanism with eps=1 has std sqrt(2); PLD should
        # find nearly that (tiny delta barely helps).
        assert spec.noise_standard_deviation == pytest.approx(math.sqrt(2),
                                                              rel=0.05)

    def test_composition_beats_naive(self):
        n_mechanisms = 4
        naive = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=1e-6)
        naive_specs = [
            naive.request_budget(MechanismType.GAUSSIAN)
            for _ in range(n_mechanisms)
        ]
        naive.compute_budgets()
        from pipelinedp_tpu import noise_core
        naive_std = noise_core.analytic_gaussian_sigma(
            naive_specs[0].eps, naive_specs[0].delta, 1.0)

        pld = ba.PLDBudgetAccountant(total_epsilon=1, total_delta=1e-6,
                                     pld_discretization=1e-3)
        pld_specs = [
            pld.request_budget(MechanismType.GAUSSIAN)
            for _ in range(n_mechanisms)
        ]
        pld.compute_budgets()
        # PLD composition is tighter than naive composition => less noise.
        assert pld_specs[0].noise_standard_deviation < naive_std

    def test_generic_mechanism(self):
        accountant = ba.PLDBudgetAccountant(total_epsilon=1, total_delta=1e-6,
                                            pld_discretization=1e-3)
        spec = accountant.request_budget(MechanismType.GENERIC)
        accountant.compute_budgets()
        assert spec.eps > 0
        assert spec.delta > 0


class TestPLDLibrary:

    def test_laplace_pld_epsilon_roundtrip(self):
        from pipelinedp_tpu import pld
        dist = pld.from_laplace_mechanism(1.0,
                                          value_discretization_interval=1e-4)
        # Laplace with scale 1, sensitivity 1 is exactly (1, 0)-DP.
        eps = dist.get_epsilon_for_delta(0.0)
        assert eps == pytest.approx(1.0, abs=1e-3)

    def test_gaussian_pld_matches_analytic(self):
        from pipelinedp_tpu import noise_core
        from pipelinedp_tpu import pld
        sigma = noise_core.analytic_gaussian_sigma(1.0, 1e-6, 1.0)
        dist = pld.from_gaussian_mechanism(
            sigma, value_discretization_interval=1e-4)
        eps = dist.get_epsilon_for_delta(1e-6)
        assert eps == pytest.approx(1.0, abs=0.01)

    def test_composition_epsilon_grows(self):
        from pipelinedp_tpu import pld
        one = pld.from_laplace_mechanism(2.0,
                                         value_discretization_interval=1e-4)
        two = one.compose(one)
        eps1 = one.get_epsilon_for_delta(1e-9)
        eps2 = two.get_epsilon_for_delta(1e-9)
        assert eps1 < eps2 <= 2 * eps1 + 1e-6

    def test_self_compose_matches_compose(self):
        from pipelinedp_tpu import pld
        one = pld.from_laplace_mechanism(2.0,
                                         value_discretization_interval=1e-3)
        a = one.compose(one).compose(one)
        b = one.self_compose(3)
        assert a.get_epsilon_for_delta(1e-9) == pytest.approx(
            b.get_epsilon_for_delta(1e-9), rel=1e-6)


def _analytic_gaussian_delta(eps: float, sigma: float) -> float:
    """Exact delta(eps) of the Gaussian mechanism (sensitivity 1).

    Balle & Wang 2018, "Improving the Gaussian Mechanism for Differential
    Privacy", Theorem 8:
        delta(eps) = Phi(1/(2 sigma) - eps sigma)
                     - e^eps * Phi(-1/(2 sigma) - eps sigma).
    This closed form is the mathematical ground truth the reference's
    dp_accounting PLD converges to for the Gaussian mechanism (its
    discretized estimates approach this curve as the interval -> 0), so it
    serves as the golden oracle here — the container does not vendor
    dp_accounting (see pld.py module docstring).
    """
    from scipy import stats
    a = 1.0 / (2.0 * sigma)
    b = eps * sigma
    return float(stats.norm.cdf(a - b) - math.exp(eps)
                 * stats.norm.cdf(-a - b))


def _analytic_gaussian_epsilon(delta: float, sigma: float) -> float:
    """Inverse of _analytic_gaussian_delta by bisection (exact oracle)."""
    lo, hi = 0.0, 1.0
    while _analytic_gaussian_delta(hi, sigma) > delta:
        hi *= 2.0
        assert hi < 1e6
    for _ in range(200):
        mid = 0.5 * (lo + hi)
        if _analytic_gaussian_delta(mid, sigma) > delta:
            lo = mid
        else:
            hi = mid
    return hi


def _laplace_delta_oracle(eps: float, b: float) -> float:
    """Exact delta(eps) of the Laplace mechanism, scale b, sensitivity 1.

    Derived from the closed-form hockey-stick divergence between Lap(0, b)
    and Lap(1, b) (e.g. dp_accounting's LaplacePrivacyLoss; also
    Koskela et al. 2020 eq. (12)):
        delta(eps) = 0 for eps >= 1/b, else
        delta(eps) = 1 - exp((eps - 1/b) / 2) * ... computed here by
    numerically integrating (1 - e^(eps - l)) dP(l) with the exact CDF
    P(L <= l) = exp((l b - 1)/(2 b)) / 2 on l in (eps, 1/b), plus the
    atom of mass 1/2 at l = 1/b.
    """
    from scipy import integrate
    max_loss = 1.0 / b
    if eps >= max_loss:
        return 0.0
    # Continuous part density on (-1/b, 1/b): d/dl [exp((l - 1/b)/2)/2].
    def integrand(loss):
        dens = 0.25 * math.exp((loss - max_loss) / 2.0)
        return (1.0 - math.exp(eps - loss)) * dens
    cont, _ = integrate.quad(integrand, eps, max_loss, limit=200)
    atom = 0.5 * (1.0 - math.exp(eps - max_loss))
    return cont + atom


class TestPLDGoldenValues:
    """Golden-value pins for the self-rolled PLD accountant.

    Every point checks BOTH directions against the exact analytic oracle:
      * soundness — the pessimistic discretization must never claim less
        epsilon than the true curve (an under-estimate would be a privacy
        accounting bug);
      * tightness — it must stay within a small factor of the truth
        (otherwise it silently wastes budget).
    Gaussian k-fold composition is exactly a single Gaussian with
    sigma / sqrt(k), so the oracle covers all composition counts.
    """

    # (sigma, n_compositions, delta) — 24 points spanning high/low noise,
    # deep composition, and two delta regimes.
    GAUSSIAN_POINTS = [
        (0.5, 1, 1e-6), (0.5, 4, 1e-6), (0.5, 16, 1e-6),
        (1.0, 1, 1e-6), (1.0, 4, 1e-6), (1.0, 16, 1e-6), (1.0, 64, 1e-6),
        (2.0, 1, 1e-6), (2.0, 4, 1e-6), (2.0, 16, 1e-6), (2.0, 64, 1e-6),
        (5.0, 1, 1e-6), (5.0, 16, 1e-6), (5.0, 64, 1e-6),
        (0.5, 1, 1e-5), (1.0, 1, 1e-5), (1.0, 16, 1e-5), (2.0, 4, 1e-5),
        (2.0, 64, 1e-5), (5.0, 64, 1e-5),
    ]

    def test_gaussian_composition_table(self):
        from pipelinedp_tpu import pld
        interval = 1e-3
        for sigma, k, delta in self.GAUSSIAN_POINTS:
            dist = pld.from_gaussian_mechanism(
                sigma, value_discretization_interval=interval)
            if k > 1:
                dist = dist.self_compose(k)
            est = dist.get_epsilon_for_delta(delta)
            true_eps = _analytic_gaussian_epsilon(
                delta, sigma / math.sqrt(k))
            # Soundness: pessimistic estimate upper-bounds the truth.
            # (self_compose uses log2(k) convolutions, each of which can
            # only round losses UP; allow float round-off only.)
            assert est >= true_eps - 1e-6, (sigma, k, delta, est, true_eps)
            # Tightness: within 2% + a few grid steps of the truth.
            slack = 0.02 * true_eps + 20 * interval
            assert est <= true_eps + slack, (sigma, k, delta, est, true_eps)

    # (scale b, delta) for single-shot Laplace — exact oracle by
    # integration of the closed-form loss CDF.
    LAPLACE_POINTS = [(0.5, 1e-6), (1.0, 1e-6), (2.0, 1e-6), (4.0, 1e-6),
                      (1.0, 1e-3), (2.0, 1e-3)]

    def test_laplace_single_mechanism_table(self):
        from pipelinedp_tpu import pld
        interval = 1e-4
        for b, delta in self.LAPLACE_POINTS:
            dist = pld.from_laplace_mechanism(
                b, value_discretization_interval=interval)
            est = dist.get_epsilon_for_delta(delta)
            # Invert the oracle by bisection.
            lo, hi = 0.0, 1.0 / b
            if _laplace_delta_oracle(0.0, b) <= delta:
                true_eps = 0.0
            else:
                for _ in range(200):
                    mid = 0.5 * (lo + hi)
                    if _laplace_delta_oracle(mid, b) > delta:
                        lo = mid
                    else:
                        hi = mid
                true_eps = hi
            assert est >= true_eps - 1e-6, (b, delta, est, true_eps)
            assert est <= true_eps + 0.02 * true_eps + 10 * interval, (
                b, delta, est, true_eps)

    def test_laplace_composition_bounds(self):
        # k-fold Laplace: the pessimistic estimate must stay within the
        # [single-mechanism, basic-composition] envelope (plus grid
        # pessimism) for every k.
        from pipelinedp_tpu import pld
        interval = 1e-4
        b = 2.0
        one = pld.from_laplace_mechanism(
            b, value_discretization_interval=interval)
        eps1 = one.get_epsilon_for_delta(1e-9)
        prev = 0.0
        for k in (2, 4, 8, 32):
            est = one.self_compose(k).get_epsilon_for_delta(1e-9)
            assert est > prev  # strictly grows with k
            assert est <= k * eps1 + k * interval  # never beats basic comp
            assert est >= eps1  # never below a single mechanism
            prev = est

    def test_gaussian_upper_bound_property_random_points(self):
        # Property test: across a sweep of (sigma, k, delta) the estimate
        # NEVER under-runs the analytic curve (soundness is the invariant
        # privacy depends on; tightness is only economy).
        from pipelinedp_tpu import pld
        import itertools
        interval = 2e-3
        for sigma, k in itertools.product((0.7, 1.3, 3.1), (1, 3, 10, 30)):
            dist = pld.from_gaussian_mechanism(
                sigma, value_discretization_interval=interval)
            if k > 1:
                dist = dist.self_compose(k)
            for delta in (1e-7, 1e-5, 1e-3):
                est = dist.get_epsilon_for_delta(delta)
                true_eps = _analytic_gaussian_epsilon(
                    delta, sigma / math.sqrt(k))
                assert est >= true_eps - 1e-6, (sigma, k, delta)

    def test_generic_pld_roundtrip(self):
        # from_privacy_parameters pins (eps, delta) -> its own epsilon.
        from pipelinedp_tpu import pld
        for eps, delta in ((0.1, 1e-6), (1.0, 1e-6), (3.0, 1e-4)):
            dist = pld.from_privacy_parameters(
                eps, delta, value_discretization_interval=1e-4)
            est = dist.get_epsilon_for_delta(delta)
            assert est == pytest.approx(eps, abs=2e-3)
