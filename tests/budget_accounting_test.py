"""Tests for budget accounting (naive + PLD).

Mirrors the semantics pinned by the reference's
tests/budget_accounting_test.py against budget_accounting.py:40-619.
"""

import math

import pytest

from pipelinedp_tpu import budget_accounting as ba
from pipelinedp_tpu.aggregate_params import MechanismType


class TestMechanismSpec:

    def test_unresolved_access_raises(self):
        spec = ba.MechanismSpec(MechanismType.LAPLACE)
        with pytest.raises(AssertionError):
            _ = spec.eps
        with pytest.raises(AssertionError):
            _ = spec.delta
        with pytest.raises(AssertionError):
            _ = spec.noise_standard_deviation

    def test_use_delta(self):
        assert not ba.MechanismSpec(MechanismType.LAPLACE).use_delta()
        assert ba.MechanismSpec(MechanismType.GAUSSIAN).use_delta()
        assert ba.MechanismSpec(MechanismType.GENERIC).use_delta()


class TestNaiveBudgetAccountant:

    def test_validation(self):
        with pytest.raises(ValueError):
            ba.NaiveBudgetAccountant(total_epsilon=0, total_delta=1e-6)
        with pytest.raises(ValueError):
            ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=-1e-6)
        with pytest.raises(ValueError):
            ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=1e-6,
                                     num_aggregations=2,
                                     aggregation_weights=[1, 1])

    def test_single_mechanism_gets_everything(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1,
                                              total_delta=1e-6)
        spec = accountant.request_budget(MechanismType.GAUSSIAN)
        accountant.compute_budgets()
        assert spec.eps == 1
        assert spec.delta == 1e-6

    def test_laplace_gets_no_delta(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1,
                                              total_delta=1e-6)
        laplace = accountant.request_budget(MechanismType.LAPLACE)
        gaussian = accountant.request_budget(MechanismType.GAUSSIAN)
        accountant.compute_budgets()
        assert laplace.eps == pytest.approx(0.5)
        assert laplace.delta == 0
        assert gaussian.eps == pytest.approx(0.5)
        assert gaussian.delta == pytest.approx(1e-6)

    def test_weights(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        a = accountant.request_budget(MechanismType.LAPLACE, weight=1)
        b = accountant.request_budget(MechanismType.LAPLACE, weight=3)
        accountant.compute_budgets()
        assert a.eps == pytest.approx(0.25)
        assert b.eps == pytest.approx(0.75)

    def test_count(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        a = accountant.request_budget(MechanismType.LAPLACE, count=3)
        b = accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        # a's weight is effectively repeated 3 times in the denominator.
        assert a.eps == pytest.approx(0.25)
        assert b.eps == pytest.approx(0.25)

    def test_gaussian_requires_delta(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        with pytest.raises(ValueError, match="Gaussian"):
            accountant.request_budget(MechanismType.GAUSSIAN)

    def test_request_after_compute_raises(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        with pytest.raises(Exception, match="request_budget"):
            accountant.request_budget(MechanismType.LAPLACE)

    def test_compute_twice_raises(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        with pytest.raises(Exception, match="twice"):
            accountant.compute_budgets()

    def test_scope_normalizes_weights(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        with accountant.scope(weight=1):
            a = accountant.request_budget(MechanismType.LAPLACE)
            b = accountant.request_budget(MechanismType.LAPLACE)
        with accountant.scope(weight=1):
            c = accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        # Scope 1 splits its half between two mechanisms.
        assert a.eps == pytest.approx(0.25)
        assert b.eps == pytest.approx(0.25)
        assert c.eps == pytest.approx(0.5)

    def test_num_aggregations_restriction(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0,
                                              num_aggregations=2)
        accountant._compute_budget_for_aggregation(1)
        with pytest.raises(ValueError, match="num_aggregations"):
            accountant.compute_budgets()

    def test_aggregation_weights_restriction(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=0,
                                              aggregation_weights=[1, 2])
        accountant._compute_budget_for_aggregation(1)
        accountant._compute_budget_for_aggregation(3)
        with pytest.raises(ValueError, match="aggregation_weights"):
            accountant.compute_budgets()

    def test_budget_for_aggregation_split(self):
        accountant = ba.NaiveBudgetAccountant(total_epsilon=1,
                                              total_delta=1e-6,
                                              num_aggregations=2)
        budget = accountant._compute_budget_for_aggregation(1)
        assert budget.epsilon == pytest.approx(0.5)
        assert budget.delta == pytest.approx(5e-7)


class TestPLDBudgetAccountant:

    def test_delta_zero_closed_form(self):
        accountant = ba.PLDBudgetAccountant(total_epsilon=2, total_delta=0)
        spec = accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        # One Laplace mechanism, weight 1: min noise std = sqrt(2)/eps.
        assert spec.noise_standard_deviation == pytest.approx(
            math.sqrt(2) / 2)

    def test_single_laplace_close_to_naive(self):
        accountant = ba.PLDBudgetAccountant(total_epsilon=1,
                                            total_delta=1e-8,
                                            pld_discretization=1e-3)
        spec = accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        # A single Laplace mechanism with eps=1 has std sqrt(2); PLD should
        # find nearly that (tiny delta barely helps).
        assert spec.noise_standard_deviation == pytest.approx(math.sqrt(2),
                                                              rel=0.05)

    def test_composition_beats_naive(self):
        n_mechanisms = 4
        naive = ba.NaiveBudgetAccountant(total_epsilon=1, total_delta=1e-6)
        naive_specs = [
            naive.request_budget(MechanismType.GAUSSIAN)
            for _ in range(n_mechanisms)
        ]
        naive.compute_budgets()
        from pipelinedp_tpu import noise_core
        naive_std = noise_core.analytic_gaussian_sigma(
            naive_specs[0].eps, naive_specs[0].delta, 1.0)

        pld = ba.PLDBudgetAccountant(total_epsilon=1, total_delta=1e-6,
                                     pld_discretization=1e-3)
        pld_specs = [
            pld.request_budget(MechanismType.GAUSSIAN)
            for _ in range(n_mechanisms)
        ]
        pld.compute_budgets()
        # PLD composition is tighter than naive composition => less noise.
        assert pld_specs[0].noise_standard_deviation < naive_std

    def test_generic_mechanism(self):
        accountant = ba.PLDBudgetAccountant(total_epsilon=1, total_delta=1e-6,
                                            pld_discretization=1e-3)
        spec = accountant.request_budget(MechanismType.GENERIC)
        accountant.compute_budgets()
        assert spec.eps > 0
        assert spec.delta > 0


class TestPLDLibrary:

    def test_laplace_pld_epsilon_roundtrip(self):
        from pipelinedp_tpu import pld
        dist = pld.from_laplace_mechanism(1.0,
                                          value_discretization_interval=1e-4)
        # Laplace with scale 1, sensitivity 1 is exactly (1, 0)-DP.
        eps = dist.get_epsilon_for_delta(0.0)
        assert eps == pytest.approx(1.0, abs=1e-3)

    def test_gaussian_pld_matches_analytic(self):
        from pipelinedp_tpu import noise_core
        from pipelinedp_tpu import pld
        sigma = noise_core.analytic_gaussian_sigma(1.0, 1e-6, 1.0)
        dist = pld.from_gaussian_mechanism(
            sigma, value_discretization_interval=1e-4)
        eps = dist.get_epsilon_for_delta(1e-6)
        assert eps == pytest.approx(1.0, abs=0.01)

    def test_composition_epsilon_grows(self):
        from pipelinedp_tpu import pld
        one = pld.from_laplace_mechanism(2.0,
                                         value_discretization_interval=1e-4)
        two = one.compose(one)
        eps1 = one.get_epsilon_for_delta(1e-9)
        eps2 = two.get_epsilon_for_delta(1e-9)
        assert eps1 < eps2 <= 2 * eps1 + 1e-6

    def test_self_compose_matches_compose(self):
        from pipelinedp_tpu import pld
        one = pld.from_laplace_mechanism(2.0,
                                         value_discretization_interval=1e-3)
        a = one.compose(one).compose(one)
        b = one.self_compose(3)
        assert a.get_epsilon_for_delta(1e-9) == pytest.approx(
            b.get_epsilon_for_delta(1e-9), rel=1e-6)
