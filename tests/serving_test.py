"""Resident-dataset serving tests (pipelinedp_tpu/serving/, SERVING.md).

Contracts:
  * Warm-path parity — a query answered from a DatasetSession is
    BIT-identical (released values, kept partitions) to the same query
    run cold through JaxDPEngine with stream_chunks=session.n_chunks,
    on single-device and mesh8, for device noise and for seeded host
    noise.
  * Batched launch — configs sharing the sorted wire execute as ONE
    vmapped launch per chunk (kernel dispatch counter), matching the
    sequential runs' released values config-for-config.
  * Tenant isolation — independent epsilon ledgers, at-most-once release
    per tenant, exhaustion never blocks another tenant.
  * Integrity — a mutated source dataset is refused; a closed session
    refuses queries; incompatible engines are refused.
  * Concurrency — threaded queries against one session race only on
    caches, never on released bits.
"""

import threading

import jax
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler, serving
from pipelinedp_tpu.ops import finalize, streaming
from pipelinedp_tpu.parallel import sharded
from pipelinedp_tpu.runtime import journal as journal_lib

M = pdp.Metrics

N_ROWS = 40_000
N_USERS = 3_000
N_PARTS = 64  # divides 8: the mesh pads nothing, mesh == single-device
N_CHUNKS = 3


@pytest.fixture(params=["single_device", "mesh8"], scope="module")
def engine_mesh(request):
    if request.param == "single_device":
        return None
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


def make_columns(seed=0, n=N_ROWS, nparts=N_PARTS):
    rng = np.random.default_rng(seed)
    return pdp.ColumnarData(
        pid=rng.integers(0, N_USERS, n).astype(np.int32),
        pk=rng.integers(0, nparts, n).astype(np.int32),
        value=rng.integers(1, 6, n).astype(np.float32))


def count_sum_params(l0=8, linf=4, noise_kind=pdp.NoiseKind.LAPLACE):
    return pdp.AggregateParams(metrics=[M.COUNT, M.SUM],
                               noise_kind=noise_kind,
                               max_partitions_contributed=l0,
                               max_contributions_per_partition=linf,
                               min_value=0.0,
                               max_value=5.0)


def run_cold(data, params, *, seed, mesh=None, secure=False, host_seed=None,
             public=None, n_chunks=N_CHUNKS, epsilon=1.0, delta=1e-6):
    if host_seed is not None:
        pdp.noise_core.seed_fallback_rng(host_seed)
        pdp.partition_selection.seed_rng(host_seed)
    accountant = pdp.NaiveBudgetAccountant(epsilon, delta)
    engine = pdp.JaxDPEngine(accountant, seed=seed, secure_host_noise=secure,
                             mesh=mesh, stream_chunks=n_chunks)
    result = engine.aggregate(data, params, public_partitions=public)
    accountant.compute_budgets()
    return result.to_columns()


def assert_columns_identical(a: dict, b: dict):
    assert list(a) == list(b)
    for name in a:
        np.testing.assert_array_equal(np.asarray(a[name]),
                                      np.asarray(b[name]), err_msg=name)


class TestWarmColdParity:
    """Warm queries are bit-identical to cold runs of the same seed."""

    def test_device_noise_parity(self, engine_mesh):
        data = make_columns()
        params = count_sum_params()
        session = serving.DatasetSession(data, mesh=engine_mesh,
                                         n_chunks=N_CHUNKS)
        for seed in (3, 4):
            warm = session.query(params, epsilon=1.0, delta=1e-6,
                                 seed=seed,
                                 secure_host_noise=False).to_columns()
            cold = run_cold(make_columns(), params, seed=seed,
                            mesh=engine_mesh)
            assert_columns_identical(cold, warm)

    def test_host_noise_parity_seeded(self, engine_mesh):
        data = make_columns()
        params = count_sum_params()
        session = serving.DatasetSession(data, mesh=engine_mesh,
                                         n_chunks=N_CHUNKS)
        pdp.noise_core.seed_fallback_rng(11)
        pdp.partition_selection.seed_rng(11)
        warm = session.query(params, epsilon=1.0, delta=1e-6, seed=5,
                             secure_host_noise=True).to_columns()
        cold = run_cold(make_columns(), params, seed=5, mesh=engine_mesh,
                        secure=True, host_seed=11)
        assert_columns_identical(cold, warm)

    def test_public_partitions_parity(self):
        data = make_columns()
        public = list(range(10, 30))
        params = count_sum_params()
        session = serving.DatasetSession(data, public_partitions=public,
                                         n_chunks=N_CHUNKS)
        warm = session.query(params, epsilon=1.0, delta=1e-6, seed=2,
                             secure_host_noise=False).to_columns()
        cold = run_cold(make_columns(), params, seed=2, public=public)
        assert_columns_identical(cold, warm)

    def test_percentile_parity(self):
        data = make_columns()
        params = pdp.AggregateParams(
            metrics=[M.COUNT, M.PERCENTILE(50), M.PERCENTILE(90)],
            max_partitions_contributed=8,
            max_contributions_per_partition=4,
            min_value=0.0, max_value=5.0)
        session = serving.DatasetSession(data, n_chunks=N_CHUNKS)
        warm = session.query(params, epsilon=1.0, delta=1e-6, seed=7,
                             secure_host_noise=False).to_columns()
        cold = run_cold(make_columns(), params, seed=7)
        assert_columns_identical(cold, warm)

    def test_count_only_no_value_column(self):
        rng = np.random.default_rng(5)
        data = pdp.ColumnarData(
            pid=rng.integers(0, 500, 5000).astype(np.int32),
            pk=rng.integers(0, 20, 5000).astype(np.int32), value=None)
        params = pdp.AggregateParams(metrics=[M.COUNT],
                                     max_partitions_contributed=4,
                                     max_contributions_per_partition=2)
        session = serving.DatasetSession(data, n_chunks=2)
        warm = session.query(params, epsilon=1.0, delta=1e-6, seed=1,
                             secure_host_noise=False).to_columns()
        rng = np.random.default_rng(5)
        cold = run_cold(
            pdp.ColumnarData(
                pid=rng.integers(0, 500, 5000).astype(np.int32),
                pk=rng.integers(0, 20, 5000).astype(np.int32), value=None),
            params, seed=1, n_chunks=2)
        assert_columns_identical(cold, warm)

    def test_empty_dataset(self):
        data = pdp.ColumnarData(pid=np.zeros(0, np.int32),
                                pk=np.zeros(0, np.int32),
                                value=np.zeros(0, np.float32))
        session = serving.DatasetSession(
            data, public_partitions=[0, 1, 2], n_chunks=2)
        cols = session.query(count_sum_params(), epsilon=1.0, delta=1e-6,
                             seed=1).to_columns()
        assert len(cols["partition_id"]) == 3
        assert cols["keep_mask"].all()

    def test_warm_queries_skip_encode_sort_phases(self):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS)
        with profiler.collect_stage_times() as stages:
            session.query(count_sum_params(), epsilon=1.0, delta=1e-6,
                          seed=1).to_columns()
        assert "dp/encode" not in stages
        assert not any(k.startswith("dp/wire_") for k in stages), stages
        assert not any(k.startswith("dp/stream_slab_") for k in stages)


class TestBoundCache:
    """Repeat queries with the same bounding config skip the kernel; the
    cache key includes the kernel-key fingerprint, so hits are exact."""

    def test_hit_is_bitwise_and_skips_replay(self):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS)
        params = count_sum_params()
        r0 = profiler.event_count(streaming.EVENT_SERVING_REPLAYS)
        first = session.query(params, epsilon=1.0, delta=1e-6, seed=9,
                              secure_host_noise=False).to_columns()
        assert profiler.event_count(streaming.EVENT_SERVING_REPLAYS) == r0 + 1
        h0 = profiler.event_count(serving.EVENT_BOUND_HITS)
        second = session.query(params, epsilon=1.0, delta=1e-6, seed=9,
                               secure_host_noise=False).to_columns()
        assert profiler.event_count(serving.EVENT_BOUND_HITS) == h0 + 1
        # No new replay ran for the hit.
        assert profiler.event_count(streaming.EVENT_SERVING_REPLAYS) == r0 + 1
        assert_columns_identical(first, second)

    def test_different_seed_misses(self):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS)
        params = count_sum_params()
        m0 = profiler.event_count(serving.EVENT_BOUND_MISSES)
        session.query(params, epsilon=1.0, delta=1e-6, seed=1).to_columns()
        session.query(params, epsilon=1.0, delta=1e-6, seed=2).to_columns()
        assert profiler.event_count(serving.EVENT_BOUND_MISSES) == m0 + 2

    def test_lru_eviction_under_byte_budget(self):
        data = make_columns(n=8000, nparts=32)
        # Budget sized so the wire fits but at most ~2 cached accumulator
        # sets do (5 arrays x 32 partitions x 4B each = 640B per entry).
        session = serving.DatasetSession(data, n_chunks=2,
                                         resident_bytes=1 << 20)
        room = (1 << 20) - session.stats()["wire_device_bytes"]
        per_entry = 5 * 32 * 4
        fits = room // per_entry
        e0 = profiler.event_count(serving.EVENT_BOUND_EVICTIONS)
        params = count_sum_params()
        for seed in range(int(fits) + 3):
            session.query(params, epsilon=1.0, delta=1e-6,
                          seed=seed).to_columns()
        stats = session.stats()
        assert stats["bound_cache_bytes"] <= room
        assert profiler.event_count(serving.EVENT_BOUND_EVICTIONS) > e0


class TestBatchedQueries:
    """Configs sharing the wire pack into one vmapped launch per chunk."""

    def test_eight_configs_one_launch_per_chunk(self):
        session = serving.DatasetSession(make_columns(), n_chunks=N_CHUNKS)
        configs = [
            serving.QueryConfig(metrics=[M.COUNT, M.SUM], epsilon=1.0,
                                delta=1e-6, max_partitions_contributed=l0,
                                max_contributions_per_partition=linf,
                                min_value=0.0, max_value=float(hi),
                                seed=100 + i)
            for i, (l0, linf, hi) in enumerate([
                (8, 4, 5), (4, 2, 5), (2, 1, 3), (16, 8, 5),
                (8, 2, 4), (1, 1, 5), (8, 4, 2), (3, 3, 5)])
        ]
        d0 = profiler.event_count(streaming.EVENT_SERVING_LAUNCHES)
        outs = session.query_batch(configs, secure_host_noise=False)
        launches = profiler.event_count(
            streaming.EVENT_SERVING_LAUNCHES) - d0
        # ONE launch per wire chunk covers all 8 configs.
        assert launches == session.n_chunks
        data = make_columns()
        for i, cfg in enumerate(configs):
            cold = run_cold(data, cfg.to_params(), seed=cfg.seed)
            assert_columns_identical(cold, outs[i])

    def test_mixed_metric_sets_batch_together(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        configs = [
            serving.QueryConfig(metrics=[M.COUNT], epsilon=1.0, delta=1e-6,
                                max_partitions_contributed=4,
                                max_contributions_per_partition=2, seed=1),
            serving.QueryConfig(metrics=[M.MEAN, M.COUNT, M.SUM],
                                epsilon=2.0, delta=1e-6,
                                max_partitions_contributed=8,
                                max_contributions_per_partition=4,
                                min_value=0.0, max_value=5.0, seed=2),
            serving.QueryConfig(metrics=[M.VARIANCE], epsilon=1.5,
                                delta=1e-6, max_partitions_contributed=2,
                                max_contributions_per_partition=2,
                                min_value=0.0, max_value=5.0, seed=3),
        ]
        d0 = profiler.event_count(streaming.EVENT_SERVING_LAUNCHES)
        outs = session.query_batch(configs, secure_host_noise=False)
        assert (profiler.event_count(streaming.EVENT_SERVING_LAUNCHES)
                - d0) == session.n_chunks
        data = make_columns()
        for i, cfg in enumerate(configs):
            cold = run_cold(data, cfg.to_params(), seed=cfg.seed,
                            n_chunks=2, epsilon=cfg.epsilon,
                            delta=cfg.delta)
            assert_columns_identical(cold, outs[i])

    def test_width_splits_launches(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        configs = [
            serving.QueryConfig(metrics=[M.COUNT], epsilon=1.0, delta=1e-6,
                                max_partitions_contributed=4,
                                max_contributions_per_partition=2,
                                seed=i) for i in range(5)
        ]
        d0 = profiler.event_count(streaming.EVENT_SERVING_LAUNCHES)
        session.query_batch(configs, secure_host_noise=False, max_width=2)
        # ceil(5/2) = 3 launch groups x 2 chunks.
        assert (profiler.event_count(streaming.EVENT_SERVING_LAUNCHES)
                - d0) == 3 * session.n_chunks

    def test_unsupported_configs_raise(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        pct = serving.QueryConfig(metrics=[M.PERCENTILE(50)], epsilon=1.0,
                                  delta=1e-6,
                                  max_partitions_contributed=4,
                                  max_contributions_per_partition=2,
                                  min_value=0.0, max_value=5.0)
        with pytest.raises(NotImplementedError):
            session.query_batch([pct])


class TestQueryPlane:
    """ISSUE 17: query_batch compiles through the planner (cache
    admission, dedupe, fusion) and runs pipelined async epilogues —
    bit-identical config-for-config to sequential session.query."""

    @staticmethod
    def _configs():
        base = dict(metrics=[M.COUNT, M.SUM], epsilon=1.0, delta=1e-6,
                    min_value=0.0, max_value=5.0)
        return [
            serving.QueryConfig(max_partitions_contributed=4,
                                max_contributions_per_partition=2,
                                seed=7, **base),
            serving.QueryConfig(max_partitions_contributed=4,
                                max_contributions_per_partition=2,
                                seed=7, **base),  # exact duplicate of 0
            serving.QueryConfig(metrics=[M.COUNT], epsilon=2.0,
                                delta=1e-6, max_partitions_contributed=8,
                                max_contributions_per_partition=4, seed=8),
            serving.QueryConfig(max_contributions=6, seed=9, **base),
        ]

    def _assert_matches_sequential(self, data, configs, outs, mesh=None):
        ref_sess = serving.DatasetSession(data, mesh=mesh, n_chunks=2)
        for i, cfg in enumerate(configs):
            ref = ref_sess.query(cfg.to_params(), epsilon=cfg.epsilon,
                                 delta=cfg.delta, seed=cfg.seed,
                                 secure_host_noise=False).to_columns()
            assert_columns_identical(ref, outs[i])

    def test_duplicate_configs_trigger_exactly_one_replay(self):
        data = make_columns(n=20_000)
        session = serving.DatasetSession(data, n_chunks=2)
        configs = self._configs()
        d0 = profiler.event_count(serving.EVENT_PLANNER_DEDUPES)
        l0 = profiler.event_count(streaming.EVENT_SERVING_LAUNCHES)
        outs = session.query_batch(configs, secure_host_noise=False)
        # Config 1 duplicates config 0's bound key: one lane, counted.
        assert profiler.event_count(
            serving.EVENT_PLANNER_DEDUPES) - d0 == 1
        # Two fusion groups (the max_contributions lane has different
        # kernel statics), one launch per chunk each — the duplicate
        # adds NO launch.
        assert profiler.event_count(
            streaming.EVENT_SERVING_LAUNCHES) - l0 == 2 * session.n_chunks
        assert_columns_identical(outs[0], outs[1])
        self._assert_matches_sequential(data, configs, outs)

    def test_batch_parity_matrix(self, engine_mesh):
        """Batched-vs-sequential bit parity, single-device + mesh8,
        including the max_contributions (l1) lane."""
        data = make_columns(n=20_000)
        session = serving.DatasetSession(data, mesh=engine_mesh,
                                         n_chunks=2)
        configs = self._configs()
        outs = session.query_batch(configs, secure_host_noise=False)
        self._assert_matches_sequential(data, configs, outs,
                                        mesh=engine_mesh)

    def test_async_epilogues_on_off_bit_identical(self, engine_mesh,
                                                  monkeypatch):
        data = make_columns(n=20_000)
        configs = self._configs()
        monkeypatch.setenv(serving.EPILOGUE_WORKERS_ENV, "2")
        on = serving.DatasetSession(data, mesh=engine_mesh,
                                    n_chunks=2).query_batch(
            configs, secure_host_noise=False)
        monkeypatch.setenv(serving.EPILOGUE_WORKERS_ENV, "0")
        off = serving.DatasetSession(data, mesh=engine_mesh,
                                     n_chunks=2).query_batch(
            configs, secure_host_noise=False)
        for a, b in zip(on, off):
            assert_columns_identical(a, b)

    def test_batch_populates_bound_cache_for_single_queries(self):
        data = make_columns(n=20_000)
        session = serving.DatasetSession(data, n_chunks=2)
        cfg = self._configs()[0]
        outs = session.query_batch([cfg], secure_host_noise=False)
        h0 = profiler.event_count(serving.EVENT_BOUND_HITS)
        r0 = profiler.event_count(streaming.EVENT_SERVING_REPLAYS)
        single = session.query(cfg.to_params(), epsilon=cfg.epsilon,
                               delta=cfg.delta, seed=cfg.seed,
                               secure_host_noise=False).to_columns()
        # The batch lane's accumulators warmed the cache: hit, no replay.
        assert profiler.event_count(serving.EVENT_BOUND_HITS) == h0 + 1
        assert profiler.event_count(
            streaming.EVENT_SERVING_REPLAYS) == r0
        assert_columns_identical(single, outs[0])

    def test_cached_configs_skip_replay_in_batch(self):
        data = make_columns(n=20_000)
        session = serving.DatasetSession(data, n_chunks=2)
        cfg = self._configs()[0]
        session.query(cfg.to_params(), epsilon=cfg.epsilon,
                      delta=cfg.delta, seed=cfg.seed,
                      secure_host_noise=False).to_columns()
        s0 = profiler.event_count(serving.EVENT_PLANNER_CACHE_SKIPS)
        r0 = profiler.event_count(streaming.EVENT_SERVING_REPLAYS)
        outs = session.query_batch([cfg], secure_host_noise=False)
        assert profiler.event_count(
            serving.EVENT_PLANNER_CACHE_SKIPS) - s0 == 1
        assert profiler.event_count(
            streaming.EVENT_SERVING_REPLAYS) == r0
        assert len(outs) == 1

    def test_planner_stats_and_per_config_durations(self):
        data = make_columns(n=20_000)
        session = serving.DatasetSession(data, n_chunks=2)
        configs = self._configs()
        session.query_batch(configs, secure_host_noise=False)
        st = session.stats()["planner"]
        assert st["batches"] == 1
        assert st["configs"] == 4
        assert st["dedupes"] == 1
        assert st["lanes"] == 3
        assert st["fused_groups"] == 2
        assert 0.0 <= st["epilogue_overlap_ratio"] <= 1.0
        recs = session.audit_trail.records()[-len(configs):]
        durations = [r.duration_s for r in recs]
        assert all(d > 0 for d in durations)
        # Per-config, not one batch-wide wall time for every config.
        assert len(set(durations)) > 1

    def test_hammer_mixed_query_and_batch_across_tenants(self):
        data = make_columns(n=20_000)
        session = serving.DatasetSession(data, n_chunks=2)
        session.register_tenant("a", total_epsilon=100.0,
                                total_delta=1e-3)
        session.register_tenant("b", total_epsilon=100.0,
                                total_delta=1e-3)
        params = count_sum_params(l0=4, linf=2)
        errors = []
        results = {}

        def single(tenant, seed):
            try:
                results[("q", tenant, seed)] = session.query(
                    params, epsilon=1.0, delta=1e-6, seed=seed,
                    tenant=tenant, secure_host_noise=False).to_columns()
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        def batch(tenant, seeds):
            try:
                cfgs = [serving.QueryConfig(
                    metrics=[M.COUNT, M.SUM], epsilon=1.0, delta=1e-6,
                    max_partitions_contributed=4,
                    max_contributions_per_partition=2, min_value=0.0,
                    max_value=5.0, seed=s, tenant=tenant)
                    for s in seeds]
                outs = session.query_batch(cfgs,
                                           secure_host_noise=False)
                for s, out in zip(seeds, outs):
                    results[("b", tenant, s)] = out
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        # All seeds distinct: a repeated (tenant, seed, config) release
        # is exactly what the at-most-once journal refuses.
        threads = [
            threading.Thread(target=single, args=("a", 21)),
            threading.Thread(target=single, args=("b", 22)),
            threading.Thread(target=batch, args=("a", (23, 24, 26))),
            threading.Thread(target=batch, args=("b", (25, 27))),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # Every released answer — threaded single or batched, either
        # tenant — is bit-identical to a fresh sequential run.
        ref_sess = serving.DatasetSession(data, n_chunks=2)
        for (_, _, seed), cols in results.items():
            ref = ref_sess.query(params, epsilon=1.0, delta=1e-6,
                                 seed=seed,
                                 secure_host_noise=False).to_columns()
            assert_columns_identical(ref, cols)


class TestTenantIsolation:
    """Two tenants on one resident dataset never share budget or
    release history."""

    def test_independent_ledgers(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        session.register_tenant("a", total_epsilon=2.0, total_delta=1e-5)
        session.register_tenant("b", total_epsilon=3.0, total_delta=1e-5)
        params = count_sum_params()
        session.query(params, epsilon=1.0, delta=1e-6, seed=1,
                      tenant="a").to_columns()
        session.query(params, epsilon=1.5, delta=1e-6, seed=2,
                      tenant="b").to_columns()
        assert session.tenant("a").ledger.spent_epsilon == 1.0
        assert session.tenant("b").ledger.spent_epsilon == 1.5
        assert session.tenant("a").ledger.remaining_epsilon == 1.0

    def test_release_replay_refused_per_tenant(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        session.register_tenant("a", total_epsilon=10.0, total_delta=1e-4)
        session.register_tenant("b", total_epsilon=10.0, total_delta=1e-4)
        params = count_sum_params()
        session.query(params, epsilon=1.0, delta=1e-6, seed=7,
                      tenant="a").to_columns()
        # Same seed again for tenant a: same KeyStream state, same token.
        with pytest.raises(journal_lib.DoubleReleaseError):
            session.query(params, epsilon=1.0, delta=1e-6, seed=7,
                          tenant="a").to_columns()
        # Tenant b's journal is its own: the same seed is fine there.
        session.query(params, epsilon=1.0, delta=1e-6, seed=7,
                      tenant="b").to_columns()

    def test_exhaustion_never_blocks_the_other_tenant(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        session.register_tenant("small", total_epsilon=1.0,
                                total_delta=1e-5)
        session.register_tenant("big", total_epsilon=100.0,
                                total_delta=1e-3)
        params = count_sum_params()
        session.query(params, epsilon=1.0, delta=1e-6, seed=1,
                      tenant="small").to_columns()
        with pytest.raises(serving.BudgetExhaustedError):
            session.query(params, epsilon=0.5, delta=1e-6, seed=2,
                          tenant="small")
        # The failed charge left the ledger untouched...
        assert session.tenant("small").ledger.spent_epsilon == 1.0
        # ...and the other tenant is unaffected.
        session.query(params, epsilon=5.0, delta=1e-6, seed=3,
                      tenant="big").to_columns()
        assert session.tenant("big").ledger.remaining_epsilon == 95.0

    def test_ledger_charge_is_all_or_nothing_under_threads(self):
        ledger = serving.TenantBudgetLedger("t", total_epsilon=10.0)
        errors = []

        def worker():
            for _ in range(10):
                try:
                    ledger.charge(0.5)
                except serving.BudgetExhaustedError:
                    errors.append(1)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # 40 attempted x 0.5 = 20 > 10: exactly 20 commits succeed.
        assert len(ledger.charges) == 20
        assert abs(ledger.spent_epsilon - 10.0) < 1e-9
        assert len(errors) == 20


class TestIntegrity:
    def test_mutated_source_refused(self):
        data = make_columns()
        session = serving.DatasetSession(data, n_chunks=2)
        data.value[100] += 1.0
        with pytest.raises(serving.StaleDatasetError):
            session.query(count_sum_params(), epsilon=1.0, delta=1e-6,
                          seed=1)

    def test_closed_session_refuses(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        session.close()
        with pytest.raises(serving.SessionClosedError):
            session.query(count_sum_params(), epsilon=1.0, delta=1e-6,
                          seed=1)

    def test_mesh_mismatch_refused(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = sharded.make_mesh(8)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant, mesh=mesh)
        with pytest.raises(ValueError, match="mesh"):
            engine.aggregate(session, count_sum_params())

    def test_public_mismatch_refused(self):
        session = serving.DatasetSession(make_columns(),
                                         public_partitions=[1, 2, 3],
                                         n_chunks=2)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant)
        with pytest.raises(ValueError, match="public"):
            engine.aggregate(session, count_sum_params(),
                             public_partitions=[1, 2])

    def test_vector_and_custom_refused(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        engine = pdp.JaxDPEngine(accountant)
        with pytest.raises(NotImplementedError, match="VECTOR_SUM"):
            engine.aggregate(
                session,
                pdp.AggregateParams(metrics=[M.VECTOR_SUM],
                                    max_partitions_contributed=2,
                                    max_contributions_per_partition=2,
                                    vector_size=3, vector_max_norm=1.0,
                                    vector_norm_kind=pdp.NormKind.Linf))

    def test_fingerprint_is_stable_and_data_bound(self):
        a = serving.DatasetSession(make_columns(seed=0), n_chunks=2)
        b = serving.DatasetSession(make_columns(seed=0), n_chunks=2)
        c = serving.DatasetSession(make_columns(seed=1), n_chunks=2)
        assert a.fingerprint == b.fingerprint
        assert a.fingerprint != c.fingerprint


class TestConcurrencyHammer:
    """Threaded queries against one session: no cache races, bitwise-
    stable releases (the CI serving job runs this under
    PIPELINEDP_TPU_REQUIRE_NATIVE=1)."""

    def test_threaded_queries_bitwise_stable(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        params = count_sum_params()
        seeds = list(range(6))
        expected = {
            s: session.query(params, epsilon=1.0, delta=1e-6, seed=s,
                             secure_host_noise=False).to_columns()
            for s in seeds
        }
        results = {}
        errors = []

        def worker(worker_id):
            try:
                for rep in range(3):
                    for s in seeds:
                        cols = session.query(
                            params, epsilon=1.0, delta=1e-6, seed=s,
                            secure_host_noise=False).to_columns()
                        results[(worker_id, rep, s)] = cols
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        for (_, _, s), cols in results.items():
            assert_columns_identical(expected[s], cols)

    def test_threaded_tenants_and_batches(self):
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        for i in range(4):
            session.register_tenant(f"t{i}", total_epsilon=50.0,
                                    total_delta=1e-3)
        params = count_sum_params()
        errors = []

        def worker(i):
            try:
                for rep in range(4):
                    session.query(params, epsilon=1.0, delta=1e-6,
                                  seed=1000 * i + rep,
                                  tenant=f"t{i}").to_columns()
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        for i in range(4):
            assert session.tenant(f"t{i}").ledger.spent_epsilon == 4.0


class TestEpilogueCacheBounds:
    """Satellite: finalize.EpilogueCache is bounded + thread-safe."""

    def test_lru_eviction(self):
        cache = finalize.EpilogueCache(max_entries=2)
        plans = []
        for nparts in (11, 12, 13):
            plan, scalars = self._plan(nparts)
            plans.append(plan)
            cache.get(plan, None, {"x": np.zeros(nparts)})
        assert len(cache) == 2
        assert cache.evictions == 1

    @staticmethod
    def _plan(nparts):
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        from pipelinedp_tpu import combiners as combiners_lib
        params = count_sum_params()
        with accountant.scope(weight=1.0):
            compound = combiners_lib.create_compound_combiner(params,
                                                              accountant)
            spec = accountant.request_budget(pdp.MechanismType.GENERIC)
        accountant.compute_budgets()
        return finalize.build_plan(compound.combiners, params, spec,
                                   is_public=False, num_partitions=nparts)

    def test_hammer_no_races(self):
        cache = finalize.EpilogueCache(max_entries=4)
        plan, _ = self._plan(17)
        errors = []

        def worker(i):
            try:
                for rep in range(50):
                    fn = cache.get(plan, None,
                                   {"x": np.zeros(17 + (rep % 3))})
                    assert fn is not None
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert len(cache) == 1
        assert cache.hits + cache.misses == 8 * 50

    def test_zero_new_traces_after_first_query(self):
        """A 3-query same-shape session performs zero epilogue traces
        after query 1 (the amortization acceptance hook)."""
        session = serving.DatasetSession(make_columns(), n_chunks=2)
        params = count_sum_params()
        traces = []
        for seed in range(3):
            before = finalize.trace_count()
            session.query(params, epsilon=1.0, delta=1e-6, seed=seed,
                          secure_host_noise=False).to_columns()
            traces.append(finalize.trace_count() - before)
        assert traces[1] == 0 and traces[2] == 0, traces


class TestQueryBuilderOnSession:
    def _frame(self):
        rng = np.random.default_rng(3)
        n = 20_000
        return {
            "user": rng.integers(0, 1500, n),
            "day": rng.integers(0, 25, n),
            "spend": rng.integers(1, 6, n).astype(np.float32),
        }

    def test_session_query_matches_frame_query(self):
        df = self._frame()
        session = serving.DatasetSession.from_frame(
            df, "user", "day", "spend", n_chunks=2,
            secure_host_noise=False)
        build = lambda b: (b.groupby(  # noqa: E731
            "day", max_groups_contributed=3,
            max_contributions_per_group=2).count().sum(
                "spend", min_value=0, max_value=5).build_query())
        on_session = build(pdp.QueryBuilder.on(session)).run_query(
            pdp.dataframes.Budget(1.0, 1e-6), seed=4)
        # The cold comparator: same frame through a session-free engine
        # with the session's chunk count.
        data = pdp.ColumnarData(pid=df["user"], pk=df["day"],
                                value=df["spend"])
        params = pdp.AggregateParams(metrics=[M.COUNT, M.SUM],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=2,
                                     min_value=0.0, max_value=5.0)
        cold = run_cold(data, params, seed=4, n_chunks=2)
        keep = cold["keep_mask"]
        np.testing.assert_array_equal(
            np.sort(on_session["day"]),
            np.sort(cold["partition_id"][keep]))
        out_by_day = dict(zip(on_session["day"].tolist(),
                              on_session["count"].tolist()))
        cold_by_day = dict(zip(cold["partition_id"][keep].tolist(),
                               cold["count"][keep].tolist()))
        assert out_by_day == cold_by_day

    def test_wrong_groupby_column_refused(self):
        session = serving.DatasetSession.from_frame(
            self._frame(), "user", "day", "spend", n_chunks=2)
        with pytest.raises(ValueError, match="grouped by"):
            pdp.QueryBuilder.on(session).groupby(
                "user", max_groups_contributed=3,
                max_contributions_per_group=2)

    def test_wrong_value_column_refused(self):
        df = self._frame()
        df["other"] = df["spend"]
        session = serving.DatasetSession.from_frame(
            df, "user", "day", "spend", n_chunks=2)
        builder = pdp.QueryBuilder.on(session).groupby(
            "day", max_groups_contributed=3, max_contributions_per_group=2)
        with pytest.raises(ValueError, match="value column"):
            builder.sum("other", min_value=0, max_value=5).build_query()

    def test_plain_query_caches_conversion(self):
        df = self._frame()
        query = (pdp.QueryBuilder(df, "user").groupby(
            "day", max_groups_contributed=3,
            max_contributions_per_group=2).count().build_query())
        query.run_query(pdp.dataframes.Budget(1.0, 1e-6), seed=1)
        query.run_query(pdp.dataframes.Budget(1.0, 1e-6), seed=2)
        query.run_query(pdp.dataframes.Budget(1.0, 1e-6), seed=3)
        assert query.conversions == 1
