"""Resilient streaming runtime tests (pipelinedp_tpu/runtime/).

The contracts pinned here (RESILIENCE.md):

  * kill-and-resume parity — a run interrupted by an injected fault
    mid-stream and resumed from the last checkpoint releases BIT-IDENTICAL
    output (seeded device noise) to an uninterrupted run, on the
    single-device and the 8-device mesh paths;
  * OOM degradation — an injected RESOURCE_EXHAUSTED at slab N completes
    the run at a reduced slab budget with unchanged released values;
  * at-most-once — replaying a committed mechanism spend or re-releasing
    a finalized epilogue raises; the budget journal shows each spend
    exactly once;
  * checkpoint resumes are refused when the key/data/schedule fingerprints
    do not match (a "resume" that could not be bit-identical);
  * the wirecodec corrupted-input guard (prep-count vs sorted-bucket
    mismatch) fires on both streaming paths.
"""

import os
import time

import jax
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler
from pipelinedp_tpu import runtime
from pipelinedp_tpu.budget_accounting import (BudgetAccountantError,
                                              MechanismSpec)
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.ops import streaming, wirecodec
from pipelinedp_tpu.parallel import sharded
from pipelinedp_tpu.runtime import checkpoint as checkpoint_lib


NO_SLEEP = runtime.RetryPolicy(sleep=lambda s: None)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


@pytest.fixture(autouse=True)
def _reset_runtime_counters():
    profiler.reset_events("runtime/")
    yield


def _data(n=50_000, n_parts=200, seed=0):
    rng = np.random.default_rng(seed)
    pid = rng.integers(1000, 9000, n).astype(np.int64)
    pk = rng.integers(0, n_parts, n).astype(np.int32)
    value = rng.uniform(0, 5, n).astype(np.float32)
    return pid, pk, value


def _aggregate(pid, pk, value, *, n_parts=200, seed=3, stream_chunks=8,
               mesh=None, public=True, metrics=None, **engine_kw):
    """One seeded device-noise aggregate through the public API."""
    accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
    engine = pdp.JaxDPEngine(accountant, seed=seed,
                             stream_chunks=stream_chunks, mesh=mesh,
                             secure_host_noise=False, **engine_kw)
    params = pdp.AggregateParams(
        metrics=metrics or [pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=200,
        max_contributions_per_partition=1000,
        min_value=0.0,
        max_value=5.0)
    result = engine.aggregate(
        pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
        public_partitions=list(range(n_parts)) if public else None)
    accountant.compute_budgets()
    return result.to_columns()


def _assert_same_release(a, b):
    np.testing.assert_array_equal(a["keep_mask"], b["keep_mask"])
    for name in a:
        if name in ("partition_id", "keep_mask"):
            continue
        np.testing.assert_array_equal(a[name], b[name], err_msg=name)


class TestCheckpointStores:

    def _checkpoint(self, run_id="r", next_chunk=3, qhist=None):
        rng = np.random.default_rng(1)
        return checkpoint_lib.StreamCheckpoint(
            run_id=run_id, next_chunk=next_chunk, n_chunks=8,
            accs=tuple(rng.random(16).astype(np.float32) for _ in range(5)),
            qhist=qhist, key_fingerprint="kf", wire_fingerprint="wf",
            key_counter=2)

    @pytest.mark.parametrize("make_store", [
        lambda tmp: runtime.InMemoryCheckpointStore(),
        lambda tmp: runtime.FileCheckpointStore(str(tmp)),
    ], ids=["memory", "file"])
    def test_roundtrip(self, tmp_path, make_store):
        store = make_store(tmp_path)
        cp = self._checkpoint(qhist=np.ones((16, 4), dtype=np.float32))
        store.save(cp)
        loaded = store.load("r")
        assert loaded.next_chunk == 3
        assert loaded.n_chunks == 8
        assert loaded.key_fingerprint == "kf"
        assert loaded.wire_fingerprint == "wf"
        assert loaded.key_counter == 2
        for a, b in zip(cp.accs, loaded.accs):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(cp.qhist, loaded.qhist)
        assert store.load("missing") is None
        store.delete("r")
        assert store.load("r") is None
        store.delete("r")  # idempotent

    def test_file_store_save_replaces(self, tmp_path):
        store = runtime.FileCheckpointStore(str(tmp_path))
        store.save(self._checkpoint(next_chunk=2))
        store.save(self._checkpoint(next_chunk=5))
        assert store.load("r").next_chunk == 5

    def test_memory_store_decouples_arrays(self):
        store = runtime.InMemoryCheckpointStore()
        cp = self._checkpoint()
        store.save(cp)
        cp.accs[0][:] = -1.0  # caller mutates after save
        assert float(store.load("r").accs[0][0]) != -1.0

    def test_validate_refuses_mismatches(self):
        cp = self._checkpoint()
        cp.validate(key_fp="kf", wire_fp="wf", n_chunks=8, key_counter=2)
        with pytest.raises(checkpoint_lib.CheckpointMismatchError,
                           match="PRNG key"):
            cp.validate(key_fp="other", wire_fp="wf", n_chunks=8)
        with pytest.raises(checkpoint_lib.CheckpointMismatchError,
                           match="wire fingerprint"):
            cp.validate(key_fp="kf", wire_fp="other", n_chunks=8)
        with pytest.raises(checkpoint_lib.CheckpointMismatchError,
                           match="chunks"):
            cp.validate(key_fp="kf", wire_fp="wf", n_chunks=4)
        with pytest.raises(checkpoint_lib.CheckpointMismatchError,
                           match="KeyStream"):
            cp.validate(key_fp="kf", wire_fp="wf", n_chunks=8,
                        key_counter=7)


class TestFaultInjector:

    def test_scripted_fault_fires_once(self):
        inj = runtime.FaultInjector([runtime.FaultSpec("transfer",
                                                       at_slab=1)])
        inj.check("transfer", 0)  # below at_slab: no fire
        with pytest.raises(runtime.InjectedTransferError):
            inj.check("transfer", 1)
        inj.check("transfer", 2)  # consumed
        assert inj.fired == [("transfer", 1)]
        assert inj.pending == 0

    def test_kind_point_mapping(self):
        inj = runtime.FaultInjector([
            runtime.FaultSpec("kernel", at_slab=0),
            runtime.FaultSpec("oom", at_slab=0),
        ])
        with pytest.raises(runtime.InjectedOom, match="RESOURCE_EXHAUSTED"):
            inj.check("transfer", 0)
        with pytest.raises(runtime.InjectedKernelError):
            inj.check("kernel", 0)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            runtime.FaultSpec("meteor", at_slab=0)

    def test_chaos_is_deterministic(self):
        a = runtime.FaultInjector.chaos(seed=4, n_slabs=32)
        b = runtime.FaultInjector.chaos(seed=4, n_slabs=32)
        assert [s.__dict__ for s in a._specs] == [s.__dict__
                                                 for s in b._specs]
        c = runtime.FaultInjector.chaos(seed=5, n_slabs=32)
        assert ([s.__dict__ for s in a._specs] !=
                [s.__dict__ for s in c._specs])


class TestRetryPolicy:

    def test_classification(self):
        assert runtime.classify(runtime.InjectedOom(0)) == "oom"
        assert runtime.classify(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory")) == "oom"
        assert runtime.classify(runtime.InjectedTransferError(0)) == \
            "transient"
        assert runtime.classify(runtime.InjectedKernelError(0)) == \
            "transient"
        assert runtime.classify(RuntimeError("UNAVAILABLE: link")) == \
            "transient"
        assert runtime.classify(runtime.HostCrash(0)) == "fatal"
        assert runtime.classify(ValueError("bad input")) == "fatal"
        assert runtime.classify(RuntimeError("wirecodec: prep-time RLE "
                                             "entry counts disagree")) == \
            "fatal"

    def test_backoff_bounded(self):
        policy = runtime.RetryPolicy(backoff_base_s=0.1, backoff_max_s=0.5)
        assert policy.backoff_s(0) == pytest.approx(0.1)
        assert policy.backoff_s(1) == pytest.approx(0.2)
        assert policy.backoff_s(10) == pytest.approx(0.5)

    def test_degrade_floor(self):
        policy = runtime.RetryPolicy()
        assert policy.degrade_slab_buckets(8) == 4
        assert policy.degrade_slab_buckets(1) == 1


class TestKillAndResume:
    """Acceptance: interrupted + resumed == uninterrupted, bitwise."""

    def _run_interrupted_then_resume(self, tmp_path, mesh=None, **agg_kw):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value, mesh=mesh, **agg_kw)
        store = runtime.FileCheckpointStore(str(tmp_path))
        policy = runtime.CheckpointPolicy(store=store, run_id="kill")
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("host_crash", at_slab=1)])
        with pytest.raises(runtime.HostCrash):
            _aggregate(pid, pk, value, mesh=mesh, checkpoint_policy=policy,
                       fault_injector=injector, **agg_kw)
        checkpoint = store.load("kill")
        assert checkpoint is not None and checkpoint.next_chunk > 0
        resumed = _aggregate(pid, pk, value, mesh=mesh,
                             checkpoint_policy=policy, **agg_kw)
        assert profiler.event_count(runtime.EVENT_RESUMES) == 1
        _assert_same_release(clean, resumed)
        # Success cleans up the checkpoint.
        assert store.load("kill") is None

    def test_single_device_public(self, tmp_path):
        self._run_interrupted_then_resume(tmp_path)

    def test_single_device_private_selection(self, tmp_path):
        self._run_interrupted_then_resume(tmp_path, public=False)

    def test_mesh(self, tmp_path, mesh):
        self._run_interrupted_then_resume(tmp_path, mesh=mesh,
                                          stream_chunks=4)

    def test_double_crash_then_resume(self):
        # Two successive process deaths (a fresh injector per simulated
        # process — injector state dies with the process) before a third
        # run resumes to completion.
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="late")
        with pytest.raises(runtime.HostCrash):
            _aggregate(pid, pk, value, checkpoint_policy=policy,
                       fault_injector=runtime.FaultInjector(
                           [runtime.FaultSpec("host_crash", at_slab=1)]))
        first_cursor = store.load("late").next_chunk
        assert first_cursor > 0
        with pytest.raises(runtime.HostCrash):
            _aggregate(pid, pk, value, checkpoint_policy=policy,
                       fault_injector=runtime.FaultInjector(
                           [runtime.FaultSpec("host_crash", at_slab=0)]))
        # The second crash fired before any new slab completed, so the
        # checkpoint is still the first one.
        assert store.load("late").next_chunk == first_cursor
        resumed = _aggregate(pid, pk, value, checkpoint_policy=policy)
        _assert_same_release(clean, resumed)


class TestStreamingResumeHook:
    """The explicit resume_from= hook on the streaming API itself."""

    def _stream(self, pid, pk, value, **kw):
        return streaming.stream_bound_and_aggregate(
            jax.random.PRNGKey(7), pid, pk, value, num_partitions=100,
            linf_cap=1000, l0_cap=100, row_clip_lo=0.0, row_clip_hi=5.0,
            middle=2.5, group_clip_lo=-np.inf, group_clip_hi=np.inf,
            n_chunks=8, **kw)

    def test_resume_from_mid_checkpoint_matches(self):
        pid, pk, value = _data(n=30_000, n_parts=100)
        full = self._stream(pid, pk, value)
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="hook",
                                          delete_on_success=False)
        self._stream(pid, pk, value,
                     resilience=runtime.StreamResilience(
                         checkpoint_policy=policy))
        checkpoint = store.load("hook")
        assert 0 < checkpoint.next_chunk < checkpoint.n_chunks
        resumed = self._stream(pid, pk, value, resume_from=checkpoint)
        for a, b in zip(full, resumed):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_resume_refuses_other_key(self):
        pid, pk, value = _data(n=30_000, n_parts=100)
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="wrongkey",
                                          delete_on_success=False)
        self._stream(pid, pk, value,
                     resilience=runtime.StreamResilience(
                         checkpoint_policy=policy))
        checkpoint = store.load("wrongkey")
        with pytest.raises(checkpoint_lib.CheckpointMismatchError,
                           match="PRNG key"):
            streaming.stream_bound_and_aggregate(
                jax.random.PRNGKey(8), pid, pk, value, num_partitions=100,
                linf_cap=1000, l0_cap=100, row_clip_lo=0.0,
                row_clip_hi=5.0, middle=2.5, group_clip_lo=-np.inf,
                group_clip_hi=np.inf, n_chunks=8,
                resume_from=checkpoint)

    def test_resume_refuses_changed_data(self):
        pid, pk, value = _data(n=30_000, n_parts=100)
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="mutated",
                                          delete_on_success=False)
        self._stream(pid, pk, value,
                     resilience=runtime.StreamResilience(
                         checkpoint_policy=policy))
        checkpoint = store.load("mutated")
        mutated = value.copy()
        mutated[: len(mutated) // 2] += 1.0
        with pytest.raises(checkpoint_lib.CheckpointMismatchError,
                           match="wire fingerprint"):
            self._stream(pid, pk, mutated, resume_from=checkpoint)


class TestOomDegradation:
    """Acceptance: injected RESOURCE_EXHAUSTED completes the run at a
    reduced slab budget with unchanged released values."""

    def test_single_oom_degrades_and_completes(self):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("oom", at_slab=1)])
        degraded = _aggregate(pid, pk, value, fault_injector=injector,
                              retry_policy=NO_SLEEP)
        assert profiler.event_count(runtime.EVENT_DEGRADATIONS) == 1
        assert injector.pending == 0
        _assert_same_release(clean, degraded)

    def test_repeated_oom_degrades_to_floor_then_retries(self):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        # 8 chunks in 2 windows of 4: degradations 4->2->1, then counted
        # retries carry the remaining OOMs.
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("oom", at_slab=0, times=5)])
        degraded = _aggregate(pid, pk, value, fault_injector=injector,
                              retry_policy=NO_SLEEP)
        assert profiler.event_count(runtime.EVENT_DEGRADATIONS) == 2
        assert profiler.event_count(runtime.EVENT_RETRIES) == 3
        _assert_same_release(clean, degraded)

    def test_oom_exhaustion_raises(self):
        pid, pk, value = _data()
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("oom", at_slab=0, times=100)])
        with pytest.raises(runtime.InjectedOom):
            _aggregate(pid, pk, value, fault_injector=injector,
                       retry_policy=runtime.RetryPolicy(
                           max_retries=2, sleep=lambda s: None))


class TestTransientRetry:

    def test_fails_twice_then_succeeds(self):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        sleeps = []
        policy = runtime.RetryPolicy(sleep=sleeps.append)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("transfer", at_slab=1, times=2)])
        retried = _aggregate(pid, pk, value, fault_injector=injector,
                             retry_policy=policy)
        assert profiler.event_count(runtime.EVENT_RETRIES) == 2
        assert sleeps == [policy.backoff_s(0), policy.backoff_s(1)]
        _assert_same_release(clean, retried)

    def test_kernel_fault_retries(self):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("kernel", at_slab=0)])
        retried = _aggregate(pid, pk, value, fault_injector=injector,
                             retry_policy=NO_SLEEP)
        assert profiler.event_count(runtime.EVENT_RETRIES) == 1
        _assert_same_release(clean, retried)

    def test_exhaustion_raises_without_checkpointing(self):
        pid, pk, value = _data()
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("transfer", at_slab=0, times=10)])
        with pytest.raises(runtime.InjectedTransferError):
            _aggregate(pid, pk, value, fault_injector=injector,
                       retry_policy=runtime.RetryPolicy(
                           max_retries=3, sleep=lambda s: None))

    def test_max_retries_zero_fails_fast(self):
        pid, pk, value = _data()
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("transfer", at_slab=0)])
        with pytest.raises(runtime.InjectedTransferError):
            _aggregate(pid, pk, value, fault_injector=injector,
                       retry_policy=runtime.RetryPolicy(max_retries=0))

    def test_mesh_transient_retry(self, mesh):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value, mesh=mesh, stream_chunks=4)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("transfer", at_slab=1, times=2)])
        retried = _aggregate(pid, pk, value, mesh=mesh, stream_chunks=4,
                             fault_injector=injector,
                             retry_policy=NO_SLEEP)
        assert profiler.event_count(runtime.EVENT_RETRIES) == 2
        _assert_same_release(clean, retried)


class TestChaosMatrix:
    """CI's fault-injection job sweeps PIPELINEDP_TPU_CHAOS_SEED; each
    seeded chaos script must be fully absorbed by retries + checkpoints
    with a bit-identical release."""

    def _seeds(self):
        env = os.environ.get("PIPELINEDP_TPU_CHAOS_SEED")
        return [int(env)] if env is not None else [0, 1, 2]

    def test_chaos_run_matches_clean(self, tmp_path):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        for seed in self._seeds():
            injector = runtime.FaultInjector.chaos(seed=seed, n_slabs=16)
            store = runtime.FileCheckpointStore(str(tmp_path / str(seed)))
            chaotic = _aggregate(
                pid, pk, value, fault_injector=injector,
                checkpoint_policy=runtime.CheckpointPolicy(
                    store=store, run_id=f"chaos{seed}"),
                retry_policy=runtime.RetryPolicy(max_retries=20,
                                                 sleep=lambda s: None))
            _assert_same_release(clean, chaotic)

    def test_chaos_with_hangs_under_watchdog(self, tmp_path):
        # The hang-extended chaos script (CI matrix `hang` variant): every
        # scripted stall must be detected by the watchdog within its
        # timeout and absorbed by retries — bit-identical release, never
        # an indefinite hang.
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        fired_hangs_total = 0
        for seed in self._seeds():
            injector = runtime.FaultInjector.chaos(
                seed=seed, n_slabs=16, include_hang=True, hang_s=5.0)
            store = runtime.FileCheckpointStore(str(tmp_path / str(seed)))
            profiler.reset_events("runtime/")
            chaotic = _aggregate(
                pid, pk, value, fault_injector=injector,
                watchdog_timeout_s=0.25,
                checkpoint_policy=runtime.CheckpointPolicy(
                    store=store, run_id=f"chaos-hang{seed}"),
                retry_policy=runtime.RetryPolicy(max_retries=20,
                                                 sleep=lambda s: None))
            _assert_same_release(clean, chaotic)
            # Every hang that fired stalled past the 0.25s budget, so
            # each must show up as exactly one detected timeout.
            n_fired = sum(1 for kind, _ in injector.fired
                          if kind == "hang")
            fired_hangs_total += n_fired
            assert profiler.event_count(
                runtime.EVENT_WATCHDOG_TIMEOUTS) == n_fired
            assert profiler.event_count(runtime.EVENT_HANGS) == n_fired
        # The hang-extended scripts must actually exercise the watchdog
        # for the sweep to mean anything (deterministic per seed).
        assert fired_hangs_total >= 1


class TestDispatchWatchdog:
    """Acceptance: a scripted hang is detected by the watchdog within the
    configured timeout and either retried (transient) or surfaced as a
    typed error after retry exhaustion — never an indefinite hang."""

    def test_hang_detected_and_retried(self):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("hang", at_slab=1, hang_s=30.0)])
        t0 = time.monotonic()
        recovered = _aggregate(pid, pk, value, fault_injector=injector,
                               retry_policy=NO_SLEEP,
                               watchdog_timeout_s=0.25)
        elapsed = time.monotonic() - t0
        # Far below the 30s stall: the watchdog cut it off at ~0.25s.
        assert elapsed < 20.0
        assert injector.pending == 0
        assert profiler.event_count(runtime.EVENT_WATCHDOG_TIMEOUTS) == 1
        assert profiler.event_count(runtime.EVENT_HANGS) == 1
        assert profiler.event_count(runtime.EVENT_RETRIES) == 1
        _assert_same_release(clean, recovered)

    def test_hang_exhaustion_surfaces_typed_error(self):
        # Every attempt hangs; bounded retries then the typed error —
        # the "fatal" arm of the acceptance criterion.
        pid, pk, value = _data()
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("hang", at_slab=0, times=10, hang_s=30.0)])
        t0 = time.monotonic()
        with pytest.raises(runtime.DispatchHangError, match="watchdog"):
            _aggregate(pid, pk, value, fault_injector=injector,
                       retry_policy=runtime.RetryPolicy(
                           max_retries=1, sleep=lambda s: None),
                       watchdog_timeout_s=0.25)
        assert time.monotonic() - t0 < 20.0
        assert profiler.event_count(runtime.EVENT_HANGS) == 2

    def test_hang_without_watchdog_stalls_but_completes(self):
        # Documents the unguarded behavior the watchdog exists for: the
        # stall is simply endured (bounded here only because hang_s is).
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("hang", at_slab=1, hang_s=0.3)])
        stalled = _aggregate(pid, pk, value, fault_injector=injector,
                             retry_policy=NO_SLEEP)
        assert profiler.event_count(runtime.EVENT_WATCHDOG_TIMEOUTS) == 0
        _assert_same_release(clean, stalled)

    def test_hang_classified_transient(self):
        assert runtime.classify(
            runtime.DispatchHangError("transfer", 1.0)) == "transient"

    def test_mesh_hang_detected_and_retried(self, mesh):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value, mesh=mesh, stream_chunks=4)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("hang", at_slab=1, hang_s=30.0)])
        recovered = _aggregate(pid, pk, value, mesh=mesh, stream_chunks=4,
                               fault_injector=injector,
                               retry_policy=NO_SLEEP,
                               watchdog_timeout_s=0.25)
        assert profiler.event_count(runtime.EVENT_WATCHDOG_TIMEOUTS) == 1
        _assert_same_release(clean, recovered)

    def test_watchdog_enabled_clean_run_is_bitwise_identical(self):
        # The watchdog only adds syncs; it must never change released
        # bits on a fault-free run.
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        guarded = _aggregate(pid, pk, value, watchdog_timeout_s=30.0)
        assert profiler.event_count(runtime.EVENT_WATCHDOG_TIMEOUTS) == 0
        _assert_same_release(clean, guarded)

    def test_env_knob_validated(self, monkeypatch):
        from pipelinedp_tpu.runtime import watchdog as watchdog_lib
        monkeypatch.delenv(watchdog_lib.WATCHDOG_ENV, raising=False)
        assert watchdog_lib.env_timeout_s() is None
        monkeypatch.setenv(watchdog_lib.WATCHDOG_ENV, "0")
        assert watchdog_lib.env_timeout_s() is None
        monkeypatch.setenv(watchdog_lib.WATCHDOG_ENV, "7")
        assert watchdog_lib.env_timeout_s() == 7.0
        monkeypatch.setenv(watchdog_lib.WATCHDOG_ENV, "junk")
        with pytest.raises(ValueError):
            watchdog_lib.env_timeout_s()

    def test_watchdog_call_passes_through_results_and_errors(self):
        wd = runtime.DispatchWatchdog(timeout_s=5.0)
        try:
            assert wd.call("op", lambda: 42) == 42
            with pytest.raises(KeyError):
                wd.call("op", lambda: {}["missing"])
            t0 = time.monotonic()
            with pytest.raises(runtime.DispatchHangError, match="op"):
                wd.call("op", lambda: time.sleep(30.0))
            assert time.monotonic() - t0 < 20.0
        finally:
            wd.close()

    def test_watchdog_rejects_bad_timeout(self):
        with pytest.raises(ValueError):
            runtime.DispatchWatchdog(timeout_s=0.0)


class TestPrefetchInterplay:
    """ISSUE 5 satellite: a FaultInjector crash / OOM-degrade while a
    lookahead prefetch is in flight must resume bit-identically to an
    uninterrupted run, on both the single-device and mesh paths. The
    prefetched slab for the window after the fault is discarded and
    recomputed — prepare_slab is pure, so released values cannot depend
    on prefetch state."""

    @pytest.fixture(autouse=True)
    def _deep_prefetch(self, monkeypatch):
        # Depth 2: when the fault fires at window 1, windows 2 and 3 are
        # already prefetching in the background.
        monkeypatch.setenv(streaming.PREFETCH_ENV, "2")
        yield

    def test_crash_with_prefetch_in_flight_resumes_bitwise(self, tmp_path):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        store = runtime.FileCheckpointStore(str(tmp_path))
        policy = runtime.CheckpointPolicy(store=store, run_id="pf-kill")
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("host_crash", at_slab=1)])
        with pytest.raises(runtime.HostCrash):
            _aggregate(pid, pk, value, checkpoint_policy=policy,
                       fault_injector=injector)
        resumed = _aggregate(pid, pk, value, checkpoint_policy=policy)
        _assert_same_release(clean, resumed)

    def test_oom_degrade_discards_stale_prefetches(self):
        # Degradation halves the slab window: prefetches keyed by the old
        # boundaries no longer match and must be recomputed, not spliced.
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value)
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("oom", at_slab=1)])
        degraded = _aggregate(pid, pk, value, fault_injector=injector,
                              retry_policy=NO_SLEEP)
        assert profiler.event_count(runtime.EVENT_DEGRADATIONS) == 1
        _assert_same_release(clean, degraded)

    def test_mesh_crash_with_prefetch_resumes_bitwise(self, tmp_path, mesh):
        pid, pk, value = _data()
        clean = _aggregate(pid, pk, value, mesh=mesh, stream_chunks=4)
        store = runtime.FileCheckpointStore(str(tmp_path))
        policy = runtime.CheckpointPolicy(store=store, run_id="pf-mesh")
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("host_crash", at_slab=1)])
        with pytest.raises(runtime.HostCrash):
            _aggregate(pid, pk, value, mesh=mesh, stream_chunks=4,
                       checkpoint_policy=policy, fault_injector=injector)
        resumed = _aggregate(pid, pk, value, mesh=mesh, stream_chunks=4,
                             checkpoint_policy=policy)
        _assert_same_release(clean, resumed)

    def test_prefetch_disabled_matches_enabled(self, monkeypatch):
        # Depth 0 (no background encode) must release identical bits:
        # prefetch is a scheduling choice, never a semantic one.
        pid, pk, value = _data()
        with_prefetch = _aggregate(pid, pk, value)
        monkeypatch.setenv(streaming.PREFETCH_ENV, "0")
        without = _aggregate(pid, pk, value)
        _assert_same_release(with_prefetch, without)

    def test_prefetch_overlap_recorded(self):
        # The background encode's host seconds surface under the
        # dp/wire_sort_parallel stage (bench reports wire_sort_parallel_s).
        pid, pk, value = _data()
        with profiler.collect_stage_times() as stages:
            _aggregate(pid, pk, value)
        assert any(k == "dp/wire_sort_parallel" for k in stages), stages


class TestAtMostOnceRelease:
    """Acceptance: replaying a committed mechanism or re-releasing a
    finalized epilogue raises; the journal shows each spend once."""

    def test_re_release_same_seed_raises(self):
        pid, pk, value = _data(n=20_000)
        journal = runtime.ReleaseJournal()
        _aggregate(pid, pk, value, release_journal=journal)
        assert len(journal) == 1
        assert journal.records[0].kind == "noise_release"
        with pytest.raises(runtime.DoubleReleaseError):
            _aggregate(pid, pk, value, release_journal=journal)
        assert len(journal) == 1  # the refused release was not recorded

    def test_fresh_seed_is_a_new_release(self):
        pid, pk, value = _data(n=20_000)
        journal = runtime.ReleaseJournal()
        _aggregate(pid, pk, value, release_journal=journal, seed=1)
        _aggregate(pid, pk, value, release_journal=journal, seed=2)
        assert len(journal) == 2

    def test_resumed_run_after_release_raises(self, tmp_path):
        # Completed + released once; a later "resume" of the same run id
        # (stale orchestration) must refuse before drawing noise.
        pid, pk, value = _data(n=20_000)
        journal = runtime.ReleaseJournal()
        policy = runtime.CheckpointPolicy(
            store=runtime.FileCheckpointStore(str(tmp_path)),
            run_id="released")
        _aggregate(pid, pk, value, release_journal=journal,
                   checkpoint_policy=policy)
        with pytest.raises(runtime.DoubleReleaseError):
            _aggregate(pid, pk, value, release_journal=journal,
                       checkpoint_policy=policy)

    def test_legacy_epilogue_also_journaled(self):
        pid, pk, value = _data(n=20_000)
        journal = runtime.ReleaseJournal()
        _aggregate(pid, pk, value, release_journal=journal,
                   fused_epilogue=False)
        with pytest.raises(runtime.DoubleReleaseError):
            _aggregate(pid, pk, value, release_journal=journal,
                       fused_epilogue=False)

    def test_select_partitions_journaled(self):
        # Every release-producing entry point commits, not just
        # aggregate: a same-seed replay of select_partitions refuses.
        pid, pk, _ = _data(n=5_000, n_parts=20)
        journal = runtime.ReleaseJournal()

        def select():
            accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
            engine = pdp.JaxDPEngine(accountant, seed=11,
                                     release_journal=journal)
            result = engine.select_partitions(
                pdp.ColumnarData(pid=pid, pk=pk, value=None),
                pdp.SelectPartitionsParams(max_partitions_contributed=5))
            accountant.compute_budgets()
            return list(result)

        select()
        assert journal.records[0].kind == "selection_release"
        with pytest.raises(runtime.DoubleReleaseError):
            select()

    def test_add_dp_noise_journaled(self):
        journal = runtime.ReleaseJournal()

        def add_noise():
            accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
            engine = pdp.JaxDPEngine(accountant, seed=12,
                                     secure_host_noise=False,
                                     release_journal=journal)
            result = engine.add_dp_noise(
                [("a", 10.0), ("b", 20.0)],
                pdp.AddDPNoiseParams(noise_kind=pdp.NoiseKind.LAPLACE,
                                     l0_sensitivity=1,
                                     linf_sensitivity=1.0))
            accountant.compute_budgets()
            return list(result)

        add_noise()
        with pytest.raises(runtime.DoubleReleaseError):
            add_noise()

    def test_journal_commit_is_atomic_per_token(self):
        journal = runtime.ReleaseJournal()
        journal.commit(("t", 1))
        with pytest.raises(runtime.DoubleReleaseError, match="already"):
            journal.commit(("t", 1))
        journal.commit(("t", 2))
        assert [r.token for r in journal.records] == [("t", 1), ("t", 2)]
        assert journal.has(("t", 1)) and not journal.has(("t", 3))


class TestBudgetSpendJournal:
    """The budget half of at-most-once (budget_accounting.py)."""

    def test_naive_journal_one_record_per_mechanism(self):
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.request_budget(MechanismType.GAUSSIAN)
        assert accountant.spend_journal == ()
        accountant.compute_budgets()
        journal = accountant.spend_journal
        assert [r.index for r in journal] == [0, 1]
        assert journal[0].mechanism_type == MechanismType.LAPLACE
        assert journal[0].eps + journal[1].eps == pytest.approx(1.0)
        assert journal[1].delta == pytest.approx(1e-6)

    def test_pld_journal_one_record_per_mechanism(self):
        # Coarse discretization: pins journal record-keeping, not PLD
        # numerics (golden-value suites cover those).
        accountant = pdp.PLDBudgetAccountant(1.0, 1e-6,
                                             pld_discretization=1e-2)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.request_budget(MechanismType.GAUSSIAN)
        accountant.compute_budgets()
        journal = accountant.spend_journal
        assert len(journal) == 2
        assert all(r.noise_standard_deviation > 0 for r in journal)

    def test_replaying_committed_spend_raises(self):
        spec = MechanismSpec(mechanism_type=MechanismType.LAPLACE)
        spec.set_eps_delta(1.0, 0.0)
        with pytest.raises(BudgetAccountantError, match="committed"):
            spec.set_eps_delta(0.5, 0.0)
        spec2 = MechanismSpec(mechanism_type=MechanismType.GAUSSIAN)
        spec2.set_noise_standard_deviation(2.0)
        with pytest.raises(BudgetAccountantError, match="committed"):
            spec2.set_noise_standard_deviation(3.0)

    def test_compute_budgets_twice_raises_typed(self):
        accountant = pdp.NaiveBudgetAccountant(1.0, 1e-6)
        accountant.request_budget(MechanismType.LAPLACE)
        accountant.compute_budgets()
        with pytest.raises(BudgetAccountantError, match="twice"):
            accountant.compute_budgets()


class TestWirecodecCorruptionGuard:
    """Satellite: input mutated between prep and sort must trip the
    prep-count vs sorted-bucket RuntimeError on BOTH streaming paths.

    The native encoder snapshots rows at prep time, so real mutation of
    the caller's arrays cannot corrupt it; the mutation is simulated at
    the seam — sort_range reporting counts that disagree with prep's."""

    @pytest.fixture()
    def corrupted_sort(self, monkeypatch):
        if wirecodec._load_packer() is None:
            pytest.skip("native codec unavailable")
        original = wirecodec.NativeRleEncoder.sort_range

        def lying_sort(self, b0, b1):
            n_uniq = original(self, b0, b1)
            return n_uniq + 1  # post-sort counts disagree with prep's

        monkeypatch.setattr(wirecodec.NativeRleEncoder, "sort_range",
                            lying_sort)

    def test_single_device_guard_fires(self, corrupted_sort):
        pid, pk, value = _data(n=30_000, n_parts=100)
        with pytest.raises(RuntimeError, match="prep-time RLE entry"):
            streaming.stream_bound_and_aggregate(
                jax.random.PRNGKey(0), pid, pk, value, num_partitions=100,
                linf_cap=1000, l0_cap=100, row_clip_lo=0.0,
                row_clip_hi=5.0, middle=2.5, group_clip_lo=-np.inf,
                group_clip_hi=np.inf, n_chunks=8)

    def test_mesh_guard_fires(self, corrupted_sort, mesh):
        pid, pk, value = _data(n=30_000, n_parts=100)
        with pytest.raises(RuntimeError, match="prep-time RLE entry"):
            sharded.stream_bound_and_aggregate(
                mesh, jax.random.PRNGKey(0), pid, pk, value,
                num_partitions=100, linf_cap=1000, l0_cap=100,
                row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                group_clip_lo=-np.inf, group_clip_hi=np.inf, n_chunks=4)

    def test_guard_is_fatal_not_retried(self, corrupted_sort):
        # A privacy-relevant guard must not be eaten by the retry layer.
        pid, pk, value = _data(n=30_000, n_parts=100)
        injector_free = runtime.StreamResilience(retry_policy=NO_SLEEP)
        with pytest.raises(RuntimeError, match="prep-time RLE entry"):
            streaming.stream_bound_and_aggregate(
                jax.random.PRNGKey(0), pid, pk, value, num_partitions=100,
                linf_cap=1000, l0_cap=100, row_clip_lo=0.0,
                row_clip_hi=5.0, middle=2.5, group_clip_lo=-np.inf,
                group_clip_hi=np.inf, n_chunks=8,
                resilience=injector_free)


class TestRequireNative:
    """Satellite: PIPELINEDP_TPU_REQUIRE_NATIVE=1 turns the silent numpy
    fallback into a hard error."""

    def test_build_failure_raises_when_required(self, monkeypatch):
        from pipelinedp_tpu.native import loader
        monkeypatch.setattr(loader, "_build", lambda stem: False)
        monkeypatch.setattr(loader, "_try_load",
                            lambda so, sym, ver: None)
        monkeypatch.setattr(loader, "_libs", {})
        monkeypatch.setenv(loader.REQUIRE_NATIVE_ENV, "1")
        with pytest.raises(loader.NativeRequiredError):
            loader._load_lib("no_such_lib", "abi")

    def test_cached_failure_raises_when_required(self, monkeypatch):
        from pipelinedp_tpu.native import loader
        monkeypatch.setattr(loader, "_libs", {"no_such_lib": None})
        monkeypatch.setenv(loader.REQUIRE_NATIVE_ENV, "1")
        with pytest.raises(loader.NativeRequiredError):
            loader._load_lib("no_such_lib", "abi")

    def test_silent_fallback_without_env(self, monkeypatch):
        from pipelinedp_tpu.native import loader
        monkeypatch.setattr(loader, "_build", lambda stem: False)
        monkeypatch.setattr(loader, "_try_load",
                            lambda so, sym, ver: None)
        monkeypatch.setattr(loader, "_libs", {})
        monkeypatch.delenv(loader.REQUIRE_NATIVE_ENV, raising=False)
        assert loader._load_lib("no_such_lib", "abi") is None

    def test_ci_job_asserts_native_available(self):
        # Under the CI env (REQUIRE_NATIVE set) the real libraries must
        # load — a toolchain regression fails here, not as a silent
        # numpy fallback.
        from pipelinedp_tpu.native import loader
        if not loader._native_required():
            pytest.skip("PIPELINEDP_TPU_REQUIRE_NATIVE not set")
        assert loader.load_row_packer() is not None
        assert loader.load() is not None


class TestCounters:

    def test_resilience_counters_keys_always_present(self):
        counters = runtime.resilience_counters()
        assert set(counters) == {"retries", "degradations", "resumes",
                                 "checkpoint_bytes", "native_fallbacks",
                                 "watchdog_timeouts", "hangs_detected",
                                 "journal_recoveries", "journal_bytes"}
        assert all(isinstance(v, int) for v in counters.values())

    def test_checkpoint_bytes_counted(self):
        pid, pk, value = _data(n=20_000)
        policy = runtime.CheckpointPolicy(
            store=runtime.InMemoryCheckpointStore(), run_id="bytes")
        _aggregate(pid, pk, value, checkpoint_policy=policy)
        assert profiler.event_count(runtime.EVENT_CHECKPOINT_BYTES) > 0
