"""Round-9 bucketed segment-local sort: bit-parity matrix + knob tests.

The contract pinned here (ISSUE 7 tentpole): ``segment_sort`` is pure
kernel geometry. Released accumulators, kept partitions, and replayed
sampling are BIT-identical whether the packed 3-key bounding sort runs
globally over the whole chunk (legacy, ``segment_sort=False``) or over
fixed-width bucket tiles with the narrow value payload and int32 group
accumulation (``segment_sort=True``/``"auto"``), across:

  {RLE, PID_PLANES} x {group-clip, no-clip} x {single-device, mesh8}
  x {compact merge on/off},

plus resume-from-checkpoint parity with tiling enabled, the
``presorted_fits`` bit-capacity boundary, the int-accumulation exactness
gate, and the VECTOR_SUM packed-sort plumbing.
"""

import jax
import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import profiler
from pipelinedp_tpu import runtime
from pipelinedp_tpu.ops import columnar, streaming, wirecodec
from pipelinedp_tpu.parallel import sharded


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return sharded.make_mesh(8)


@pytest.fixture(autouse=True)
def _reset_sort_counters():
    profiler.reset_events("ops/sort")
    yield


def _rle_data(n=60_000, n_parts=300, seed=0, integer_values=True):
    """Repetitive pids (~20 rows/user) -> PID_RLE wire, small max_run ->
    tiles engage; integer values -> VALUE_PLANES -> narrow sort payload +
    int32 accumulation ride along."""
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n // 20, n).astype(np.int64)
    pk = rng.integers(0, n_parts, n).astype(np.int32)
    if integer_values:
        value = rng.integers(0, 6, n).astype(np.float32)
    else:
        value = rng.uniform(0, 5, n).astype(np.float32)
    return pid, pk, value


def _planes_data(n=60_000, n_parts=300, seed=1):
    """Near-unique pids -> PID_PLANES wire (arrival order, no host sort;
    tiling cannot apply — parity must hold trivially)."""
    rng = np.random.default_rng(seed)
    pid = rng.permutation(n).astype(np.int64)
    pk = rng.integers(0, n_parts, n).astype(np.int32)
    value = rng.integers(0, 6, n).astype(np.float32)
    return pid, pk, value


def _stream(pid, pk, value, *, mesh=None, n_parts=300, has_group_clip=True,
            **kw):
    clips = (dict(row_clip_lo=-np.inf, row_clip_hi=np.inf, middle=0.0,
                  group_clip_lo=-30.0, group_clip_hi=30.0)
             if has_group_clip else
             dict(row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                  group_clip_lo=-np.inf, group_clip_hi=np.inf))
    args = (jax.random.PRNGKey(7), pid, pk, value)
    common = dict(num_partitions=n_parts, linf_cap=6, l0_cap=8,
                  has_group_clip=has_group_clip, n_chunks=8, **clips, **kw)
    if mesh is not None:
        accs = sharded.stream_bound_and_aggregate(mesh, *args, **common)
    else:
        accs = streaming.stream_bound_and_aggregate(*args, **common)
    return jax.device_get(accs)


def _assert_bitwise(a, b):
    for name, x, y in zip(a._fields, a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=name)


class TestTiledSortParityMatrix:
    """segment_sort=True vs False, bitwise, across the full matrix."""

    @pytest.mark.parametrize("has_group_clip", [True, False])
    @pytest.mark.parametrize("compact", [True, False])
    def test_rle_single_device(self, has_group_clip, compact):
        pid, pk, value = _rle_data()
        legacy = _stream(pid, pk, value, has_group_clip=has_group_clip,
                         compact_merge=compact, segment_sort=False)
        profiler.reset_events("ops/sort")
        tiled = _stream(pid, pk, value, has_group_clip=has_group_clip,
                        compact_merge=compact, segment_sort=True)
        # Non-vacuous: the tiled sampler actually ran.
        assert profiler.event_count(columnar.EVENT_SORT_TILES) > 8
        _assert_bitwise(legacy, tiled)

    @pytest.mark.parametrize("has_group_clip", [True, False])
    @pytest.mark.parametrize("compact", [True, False])
    def test_rle_mesh8(self, mesh, has_group_clip, compact):
        pid, pk, value = _rle_data(n=40_000)
        legacy = _stream(pid, pk, value, mesh=mesh,
                         has_group_clip=has_group_clip,
                         compact_merge=compact, segment_sort=False)
        profiler.reset_events("ops/sort")
        tiled = _stream(pid, pk, value, mesh=mesh,
                        has_group_clip=has_group_clip,
                        compact_merge=compact, segment_sort=True)
        assert profiler.event_count(columnar.EVENT_SORT_TILES) > 8
        _assert_bitwise(legacy, tiled)

    @pytest.mark.parametrize("has_group_clip", [True, False])
    def test_planes_single_device(self, has_group_clip):
        pid, pk, value = _planes_data()
        legacy = _stream(pid, pk, value, has_group_clip=has_group_clip,
                         segment_sort=False)
        profiler.reset_events("ops/sort")
        tiled = _stream(pid, pk, value, has_group_clip=has_group_clip,
                        segment_sort=True)
        # PID_PLANES rows arrive unsorted: tiling cannot engage — every
        # executed chunk (n_chunks=8) reports exactly one global sort.
        assert profiler.event_count(columnar.EVENT_SORT_TILES) == 8
        _assert_bitwise(legacy, tiled)

    def test_planes_mesh8(self, mesh):
        pid, pk, value = _planes_data(n=40_000)
        legacy = _stream(pid, pk, value, mesh=mesh, segment_sort=False)
        tiled = _stream(pid, pk, value, mesh=mesh, segment_sort=True)
        _assert_bitwise(legacy, tiled)

    def test_continuous_values_single_device(self):
        # Continuous values defeat the VALUE_PLANES integer grid: the
        # value rides the sort as raw float32 and accumulation stays
        # float — tiling alone must still be bitwise.
        pid, pk, value = _rle_data(integer_values=False)
        legacy = _stream(pid, pk, value, segment_sort=False)
        tiled = _stream(pid, pk, value, segment_sort=True)
        _assert_bitwise(legacy, tiled)

    def test_auto_matches_forced_when_engaged(self):
        # At a shape where the auto heuristic engages (>= 8 tiles per
        # bucket), "auto" and True are the same kernel.
        pid, pk, value = _rle_data(n=300_000, seed=3)
        auto = _stream(pid, pk, value, segment_sort="auto")
        assert profiler.event_count(columnar.EVENT_SORT_TILES) > 0
        forced = _stream(pid, pk, value, segment_sort=True)
        _assert_bitwise(auto, forced)


class TestTiledResumeParity:
    """Resume-from-checkpoint with tiling enabled stays bitwise."""

    def _stream_tiled(self, pid, pk, value, **kw):
        return _stream(pid, pk, value, segment_sort=True, **kw)

    def test_resume_from_mid_checkpoint_matches(self):
        pid, pk, value = _rle_data()
        full = self._stream_tiled(pid, pk, value)
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="tiled",
                                          delete_on_success=False)
        self._stream_tiled(
            pid, pk, value,
            resilience=runtime.StreamResilience(checkpoint_policy=policy))
        checkpoint = store.load("tiled")
        assert 0 < checkpoint.next_chunk < checkpoint.n_chunks
        resumed = self._stream_tiled(pid, pk, value,
                                     resume_from=checkpoint)
        _assert_bitwise(full, resumed)

    def test_crash_resume_through_engine(self):
        pid, pk, value = _rle_data()
        n_parts = 300

        def run(**engine_kw):
            accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
            engine = pdp.JaxDPEngine(accountant, seed=3, stream_chunks=8,
                                     secure_host_noise=False,
                                     segment_sort=True, **engine_kw)
            params = pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                max_partitions_contributed=8,
                max_contributions_per_partition=6,
                min_value=0.0, max_value=5.0)
            result = engine.aggregate(
                pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
                public_partitions=list(range(n_parts)))
            accountant.compute_budgets()
            return result.to_columns()

        clean = run()
        store = runtime.InMemoryCheckpointStore()
        policy = runtime.CheckpointPolicy(store=store, run_id="tiledkill")
        with pytest.raises(runtime.HostCrash):
            run(checkpoint_policy=policy,
                fault_injector=runtime.FaultInjector(
                    [runtime.FaultSpec("host_crash", at_slab=1)]))
        assert store.load("tiledkill").next_chunk > 0
        resumed = run(checkpoint_policy=policy)
        for name in clean:
            np.testing.assert_array_equal(clean[name], resumed[name],
                                          err_msg=name)


class TestSortByteCounters:
    """The CI perf-counter smoke: at the 2^17-partition shape the tiled
    run must report strictly fewer modeled sort operand bytes."""

    def test_tiled_strictly_cheaper_at_128k_partitions(self):
        n_parts = 1 << 17
        rng = np.random.default_rng(5)
        n = 120_000
        pid = rng.integers(0, n // 20, n).astype(np.int64)
        pk = rng.integers(0, n_parts, n).astype(np.int32)
        value = rng.integers(0, 6, n).astype(np.float32)
        legacy = _stream(pid, pk, value, n_parts=n_parts,
                         segment_sort=False)
        legacy_bytes = profiler.event_count(columnar.EVENT_SORT_BYTES)
        legacy_rows = profiler.event_count(columnar.EVENT_SORT_ROWS)
        assert legacy_bytes > 0 and legacy_rows > 0
        profiler.reset_events("ops/sort")
        tiled = _stream(pid, pk, value, n_parts=n_parts,
                        segment_sort=True)
        tiled_bytes = profiler.event_count(columnar.EVENT_SORT_BYTES)
        assert profiler.event_count(columnar.EVENT_SORT_TILES) > 8
        assert tiled_bytes < legacy_bytes
        _assert_bitwise(legacy, tiled)

    def test_sort_cost_model_shapes(self):
        g = columnar.sort_cost(100_000, num_partitions=1 << 17)
        p = columnar.sort_cost(100_000, num_partitions=1 << 17,
                               pid_sorted=True, max_segments=4096)
        t = columnar.sort_cost(100_000, num_partitions=1 << 17,
                               pid_sorted=True, max_segments=4096,
                               tile_rows=1024, tile_slack=64,
                               value_bytes=1)
        assert g["kind"] == "general" and g["tiles"] == 1
        assert p["kind"] == "packed" and p["bytes_per_row"] < \
            g["bytes_per_row"]
        assert t["kind"] == "tiled" and t["tiles"] == -(-100_000 // 1024)
        assert t["operand_bytes"] < p["operand_bytes"] < g["operand_bytes"]


class TestPresortedFitsBoundary:
    """packed_key_layout is the single source of truth for the 3-key bit
    budget; the fit flips exactly where the rand field hits its floor."""

    def test_exact_capacity_edge(self):
        # segbits(2^31) = 32, pkbits(2^20) = 20 -> rand = 96-32-32-20 = 12
        # = _MIN_RAND_BITS: the last fitting layout.
        n = 1 << 20
        assert columnar.presorted_fits(n, 1 << 20, max_segments=2**31)
        segbits, pkbits, randbits, padbits = columnar.packed_key_layout(
            n, 1 << 20, max_segments=2**31)
        assert (segbits, pkbits, randbits, padbits) == (32, 20, 12, 0)
        # One more segment bit starves the rand field below the floor.
        assert not columnar.presorted_fits(n, 1 << 20, max_segments=2**32)
        # One more pk bit does the same at fixed segments.
        assert not columnar.presorted_fits(n, 1 << 21, max_segments=2**31)
        assert columnar.presorted_fits(n, 1 << 19, max_segments=2**31)

    def test_fit_iff_rand_floor_over_sweep(self):
        for seg_pow in (1, 8, 16, 24, 31, 32, 40):
            for pk_pow in (1, 10, 20, 30):
                n = 1 << 16
                fits = columnar.presorted_fits(n, 1 << pk_pow,
                                               max_segments=2**seg_pow)
                _, _, randbits, _ = columnar.packed_key_layout(
                    n, 1 << pk_pow, max_segments=2**seg_pow)
                assert fits == (randbits >= columnar._MIN_RAND_BITS)

    def test_layout_always_spans_96_bits_when_fitting(self):
        segbits, pkbits, randbits, padbits = columnar.packed_key_layout(
            1 << 16, 1000, max_segments=4096)
        assert segbits + 32 + pkbits + randbits + padbits == \
            columnar._KEY_BITS


class TestPlanSegmentTiling:
    def _fmt(self, cap=1 << 15, pid_mode=wirecodec.PID_RLE):
        return wirecodec.WireFormat(
            bytes_pid=3, bits_pk=10, cap=cap, ucap=1 << 12,
            value=wirecodec.ValuePlan(wirecodec.VALUE_PLANES, 0.0, 1.0, 3),
            pid_mode=pid_mode)

    def test_auto_requires_enough_tiles(self):
        fmt = self._fmt(cap=1 << 12)
        # tile 1024 > cap/8: auto declines, True forces.
        assert wirecodec.plan_segment_tiling(fmt, "auto", 16).tile_rows == 0
        forced = wirecodec.plan_segment_tiling(fmt, True, 16)
        assert forced.tile_rows == 1024 and forced.tile_slack == 16

    def test_disabled_cases(self):
        fmt = self._fmt()
        assert wirecodec.plan_segment_tiling(fmt, False, 16).tile_rows == 0
        assert wirecodec.plan_segment_tiling(fmt, "auto", -1).tile_rows == 0
        assert wirecodec.plan_segment_tiling(fmt, "auto", 0).tile_rows == 0
        planes = self._fmt(pid_mode=wirecodec.PID_PLANES)
        assert wirecodec.plan_segment_tiling(planes, True, 16).tile_rows \
            == 0
        # A run so long one tile (+slack) would cover the whole bucket.
        assert wirecodec.plan_segment_tiling(
            self._fmt(cap=1 << 12), True, 1 << 11).tile_rows == 0

    def test_slack_bounds_max_run(self):
        fmt = wirecodec.plan_segment_tiling(self._fmt(), "auto", 100)
        assert fmt.tile_rows >= 4 * 100
        assert fmt.tile_slack >= 100
        assert fmt.tile_rows % 2 == 0 and fmt.tile_slack % 8 == 0


class TestIntAccumulationPlan:
    def test_integer_grid_accepted(self):
        plan = columnar.int_accumulation_plan(0.0, 1.0, 3, 0.0, 5.0, 6)
        assert plan == (0, 5)

    def test_infinite_clips_accepted(self):
        plan = columnar.int_accumulation_plan(0.0, 1.0, 3, -np.inf, np.inf,
                                              6)
        assert plan is not None

    def test_rejections(self):
        # Non-integer scale / lo.
        assert columnar.int_accumulation_plan(0.0, 0.5, 3, 0, 5, 6) is None
        assert columnar.int_accumulation_plan(0.25, 1.0, 3, 0, 5, 6) is None
        # Non-integer finite clip bound.
        assert columnar.int_accumulation_plan(0.0, 1.0, 3, 0.0, 4.5,
                                              6) is None
        # NaN clip bound.
        assert columnar.int_accumulation_plan(0.0, 1.0, 3, 0.0, np.nan,
                                              6) is None
        # Magnitude overflow: linf * max|value| >= 2^24.
        assert columnar.int_accumulation_plan(0.0, 1.0, 20, 0.0, np.inf,
                                              100) is None
        # Reconstruction overflow: |lo| + max_idx*|scale| >= 2^24
        # (4095 * 4096 = 16_773_120 still fits; 8191 * 4096 does not).
        assert columnar.int_accumulation_plan(0.0, 1 << 12, 12, 0.0,
                                              np.inf, 1) is not None
        assert columnar.int_accumulation_plan(0.0, 1 << 12, 13, 0.0,
                                              np.inf, 1) is None
        # Zero / negative caps.
        assert columnar.int_accumulation_plan(0.0, 1.0, 3, 0.0, 5.0,
                                              0) is None

    def test_non_concrete_cap_rejected(self):
        # A traced cap cannot be bounded statically -> no int plan.
        def probe(cap):
            return columnar.int_accumulation_plan(0.0, 1.0, 3, 0.0, 5.0,
                                                  cap) is None

        assert jax.jit(lambda c: jax.numpy.int32(probe(c)))(6) == 1


class TestTiledKernelUnit:
    """Direct columnar-level parity of the tiled sampler (no wire)."""

    def _sorted_rows(self, n=8_192, n_parts=64, seed=2, runs=12):
        rng = np.random.default_rng(seed)
        pid = np.sort(rng.integers(0, n // runs, n)).astype(np.int32)
        pk = rng.integers(0, n_parts, n).astype(np.int32)
        value = rng.integers(0, 6, n).astype(np.float32)
        valid = np.arange(n) < (n - 100)  # padded tail
        # pid-sorted over the valid prefix (padding rows may be anything).
        return pid, pk, value, valid

    def _kernel(self, pid, pk, value, valid, n_parts, **kw):
        return jax.device_get(columnar.bound_and_aggregate(
            jax.random.PRNGKey(11), pid, pk, value, valid,
            num_partitions=n_parts, linf_cap=3, l0_cap=4,
            row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
            group_clip_lo=-np.inf, group_clip_hi=np.inf,
            pid_sorted=True, max_segments=1 << 11, **kw))

    def test_tiled_bitwise_equals_global(self):
        pid, pk, value, valid = self._sorted_rows()
        max_run = int(np.bincount(pid).max())
        base = self._kernel(pid, pk, value, valid, 64)
        tiled = self._kernel(pid, pk, value, valid, 64,
                             tile_rows=1024, tile_slack=max_run)
        _assert_bitwise(base, tiled)

    def test_tiled_narrow_index_int_accumulate(self):
        pid, pk, value, valid = self._sorted_rows()
        max_run = int(np.bincount(pid).max())
        base = self._kernel(pid, pk, value, valid, 64)
        plan = columnar.int_accumulation_plan(0.0, 1.0, 3, 0.0, 5.0, 3)
        assert plan is not None
        narrow = self._kernel(
            pid, pk, value.astype(np.int32), valid, 64,
            tile_rows=1024, tile_slack=max_run, value_is_index=True,
            value_lo=0.0, value_scale=1.0, value_sort_bits=3,
            int_accumulate=True, int_clip_lo=plan[0], int_clip_hi=plan[1])
        _assert_bitwise(base, narrow)

    def test_row_mask_replays_tiled(self):
        pid, pk, value, valid = self._sorted_rows()
        max_run = int(np.bincount(pid).max())
        key = jax.random.PRNGKey(11)
        base = columnar.bound_row_mask(
            key, pid, pk, valid, 3, 4, pid_sorted=True,
            max_segments=1 << 11, num_partitions=64)
        tiled = columnar.bound_row_mask(
            key, pid, pk, valid, 3, 4, pid_sorted=True,
            max_segments=1 << 11, num_partitions=64,
            tile_rows=1024, tile_slack=max_run)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(tiled))

    def test_slack_violation_empties_not_corrupts(self):
        # A pid run longer than tile_slack breaks the binning contract;
        # the kernel's backstop must yield EMPTY accumulators, never a
        # silently re-sampled release.
        n = 4_096
        pid = np.zeros(n, dtype=np.int32)  # one run spanning every tile
        pk = np.zeros(n, dtype=np.int32)
        value = np.ones(n, dtype=np.float32)
        valid = np.ones(n, dtype=bool)
        out = self._kernel(pid, pk, value, valid, 64,
                           tile_rows=1024, tile_slack=8)
        assert float(np.asarray(out.count).sum()) == 0.0


class TestVectorPackedSort:
    """VECTOR_SUM satellite: the packed 3-key sort on pid-sorted rows."""

    def _cols(self, n=20_000, n_parts=50, d=4, seed=6):
        rng = np.random.default_rng(seed)
        pid = np.sort(rng.integers(0, n // 10, n)).astype(np.int32)
        pk = rng.integers(0, n_parts, n).astype(np.int32)
        vec = rng.uniform(-1, 1, (n, d)).astype(np.float32)
        return pid, pk, vec

    def test_presorted_equals_general_when_caps_do_not_bind(self):
        # With caps that never bind, every row is kept under EITHER
        # sampler, so the packed-sort path must produce the exact same
        # sums (the draws differ; the kept set does not).
        pid, pk, vec = self._cols()
        valid = np.ones(len(pid), dtype=bool)
        kw = dict(num_partitions=50, linf_cap=10_000, l0_cap=10_000,
                  max_norm=100.0, norm_ord=0)
        general = columnar.bound_and_aggregate_vector(
            jax.random.PRNGKey(2), pid, pk, vec, valid, **kw)
        packed = columnar.bound_and_aggregate_vector(
            jax.random.PRNGKey(2), pid, pk, vec, valid, pid_sorted=True,
            max_segments=1 << 12, **kw)
        np.testing.assert_array_equal(np.asarray(general[0]),
                                      np.asarray(packed[0]))
        _assert_bitwise(general[1], packed[1])

    def test_packed_caps_enforced(self):
        # Binding caps: the packed sampler must enforce the same bounds
        # (distribution-level, not bitwise, vs the general sampler).
        pid, pk, vec = self._cols()
        valid = np.ones(len(pid), dtype=bool)
        vec = np.abs(vec)
        out, accs = columnar.bound_and_aggregate_vector(
            jax.random.PRNGKey(2), pid, pk, vec, valid,
            num_partitions=50, linf_cap=2, l0_cap=3, max_norm=1.0,
            norm_ord=0, pid_sorted=True, max_segments=1 << 12)
        n_users = len(np.unique(pid))
        # Each user contributes <= l0*linf rows of Linf norm <= 1.
        assert float(np.asarray(out).sum()) <= n_users * 2 * 3 * 4 + 1e-3

    def test_engine_vector_segment_sort_knob(self):
        # segment_sort=False reproduces the legacy unsorted kernel
        # draw-for-draw (deterministic across runs); "auto" host-sorts
        # the rows, so with non-binding caps it keeps the same row set
        # and agrees to float32 association (different segment-sum
        # order, not different sampling).
        rng = np.random.default_rng(8)
        n = 5_000
        data_pid = rng.integers(0, 500, n)
        data_pk = rng.integers(0, 20, n).astype(np.int32)
        vec = rng.uniform(-1, 1, (n, 3)).astype(np.float32)

        def run(segment_sort):
            accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
            engine = pdp.JaxDPEngine(accountant, seed=5,
                                     secure_host_noise=False,
                                     segment_sort=segment_sort)
            params = pdp.AggregateParams(
                metrics=[pdp.Metrics.VECTOR_SUM],
                max_partitions_contributed=1000,
                max_contributions_per_partition=1000,
                vector_size=3, vector_max_norm=100.0,
                vector_norm_kind=pdp.NormKind.Linf)
            result = engine.aggregate(
                pdp.ColumnarData(pid=data_pid, pk=data_pk, value=vec),
                params, public_partitions=list(range(20)))
            accountant.compute_budgets()
            return result.to_columns()

        legacy = run(False)
        np.testing.assert_array_equal(legacy["vector_sum"],
                                      run(False)["vector_sum"])
        auto = run("auto")
        np.testing.assert_allclose(legacy["vector_sum"],
                                   auto["vector_sum"], rtol=1e-4,
                                   atol=1e-4)

    def test_mesh_vector_pid_sorted_exact(self, mesh):
        pid, pk, vec = self._cols(n=16_000)
        valid = np.ones(len(pid), dtype=bool)
        out, _ = sharded.bound_and_aggregate_vector(
            mesh, jax.random.PRNGKey(2), pid, pk, vec, valid,
            num_partitions=50, linf_cap=10_000, l0_cap=10_000,
            max_norm=100.0, norm_ord=0, pid_sorted=True,
            max_segments=1 << 12)
        truth = np.zeros((64, vec.shape[1]), dtype=np.float64)
        np.add.at(truth, pk, vec.astype(np.float64))
        np.testing.assert_allclose(np.asarray(out)[:50], truth[:50],
                                   rtol=1e-4, atol=1e-4)


class TestQuantileTiledReplay:
    """PERCENTILE rides the streamed kernels: the row mask must replay
    the SAME (tiled) sampling as the aggregation kernel, so the released
    quantiles are bitwise invariant to the segment_sort knob."""

    def _run(self, segment_sort):
        rng = np.random.default_rng(9)
        n = 60_000
        pid = rng.integers(0, n // 20, n)
        pk = rng.integers(0, 40, n).astype(np.int32)
        value = rng.integers(0, 101, n).astype(np.float32)
        accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
        engine = pdp.JaxDPEngine(accountant, seed=4, stream_chunks=8,
                                 secure_host_noise=False,
                                 segment_sort=segment_sort)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=8,
            max_contributions_per_partition=6,
            min_value=0.0, max_value=100.0)
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=list(range(40)))
        accountant.compute_budgets()
        return result.to_columns()

    def test_percentiles_bitwise_invariant(self):
        legacy = self._run(False)
        tiled = self._run(True)
        for name in legacy:
            np.testing.assert_array_equal(legacy[name], tiled[name],
                                          err_msg=name)
