"""True process-kill recovery: SIGKILL at slab N, re-exec, resume.

Acceptance (ISSUE 8): a run SIGKILLed mid-stream and re-exec'd in a
fresh process resumes from ``FileCheckpointStore`` + the durable
``FileReleaseJournal`` to a release BIT-IDENTICAL to an uninterrupted
seeded run, and a deliberate replay of the same release token across
processes raises ``DoubleReleaseError``. Unlike the in-process
``host_crash`` fault (tests/resilience_test.py), nothing survives the
kill except what was fsync'd — the harness processes share only the
filesystem.

Each scenario step is a fresh ``python tests/kill_harness.py <mode>``
subprocess (see the harness docstring for the modes).
"""

import json
import os
import signal
import subprocess
import sys

import pytest

from pipelinedp_tpu import runtime
from pipelinedp_tpu.obs import flight as flight_lib

_HARNESS = os.path.join(os.path.dirname(__file__), "kill_harness.py")


def _run_harness(mode: str, workdir: str,
                 mesh: bool = False) -> subprocess.CompletedProcess:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # The harness asserts single-device behavior by default; strip the
    # 8-device virtual mesh this suite's conftest forces on the parent.
    env.pop("XLA_FLAGS", None)
    env.pop("PDP_KH_MESH", None)
    if mesh:
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["PDP_KH_MESH"] = "8"
    return subprocess.run(
        [sys.executable, _HARNESS, mode, workdir],
        capture_output=True, text=True, env=env, timeout=300)


def _marker(proc: subprocess.CompletedProcess, prefix: str) -> str:
    lines = [line for line in proc.stdout.splitlines()
             if line.startswith(prefix)]
    assert lines, (f"no {prefix} marker in harness output;\n"
                   f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    return lines[-1]


def _columns(proc: subprocess.CompletedProcess) -> dict:
    payload = _marker(proc, "HARNESS_RESULT ")[len("HARNESS_RESULT "):]
    return json.loads(payload)["columns"]


@pytest.fixture(scope="module")
def kill_run(tmp_path_factory):
    """Runs the kill -> inspect -> resume -> replay scenario once; the
    tests below assert its facets (subprocesses are expensive)."""
    workdir = str(tmp_path_factory.mktemp("kill"))
    clean = _run_harness("clean", workdir)
    assert clean.returncode == 0, clean.stderr
    killed = _run_harness("killed", workdir)
    # Snapshot the checkpoint state NOW: the successful resume below
    # deletes it (delete_on_success).
    checkpoint_after_kill = runtime.FileCheckpointStore(
        os.path.join(workdir, "ckpt")).load("kill-harness")
    resumed = _run_harness("resume", workdir)
    assert resumed.returncode == 0, resumed.stderr
    replay = _run_harness("replay", workdir)
    assert replay.returncode == 0, replay.stderr
    return {"workdir": workdir, "clean": clean, "killed": killed,
            "resumed": resumed, "replay": replay,
            "checkpoint_after_kill": checkpoint_after_kill}


class TestProcessKillRecovery:

    def test_child_died_by_sigkill_with_checkpoint_on_disk(self, kill_run):
        killed = kill_run["killed"]
        assert killed.returncode == -signal.SIGKILL
        # SIGKILL means no cleanup: the result marker never printed ...
        assert "HARNESS_RESULT" not in killed.stdout
        # ... but the slab-boundary checkpoint was already durable.
        checkpoint = kill_run["checkpoint_after_kill"]
        assert checkpoint is not None
        assert 0 < checkpoint.next_chunk < checkpoint.n_chunks
        # The successful resume consumed and deleted it.
        assert runtime.FileCheckpointStore(
            os.path.join(kill_run["workdir"], "ckpt")).load(
                "kill-harness") is None

    def test_resumed_release_is_bit_identical_to_clean(self, kill_run):
        clean = _columns(kill_run["clean"])
        resumed = _columns(kill_run["resumed"])
        assert clean == resumed  # hex-encoded raw bytes: exact equality

    def test_resume_actually_resumed_not_restarted(self, kill_run):
        # The resumed process recovered the journal file's existence but
        # committed the FIRST release (the killed run died pre-commit):
        # exactly one record, committed by the resume.
        journal = runtime.FileReleaseJournal(
            os.path.join(kill_run["workdir"], "release.wal"))
        try:
            assert len(journal) == 1
            assert journal.records[0].kind == "noise_release"
        finally:
            journal.close()

    def test_cross_process_replay_raises_double_release(self, kill_run):
        _marker(kill_run["replay"], "HARNESS_DOUBLE_RELEASE")
        # The refused replay committed nothing.
        journal = runtime.FileReleaseJournal(
            os.path.join(kill_run["workdir"], "release.wal"))
        try:
            assert len(journal) == 1
        finally:
            journal.close()


class TestCrossProcessSpendReplay:

    def test_spend_replay_refused_after_reexec(self, tmp_path):
        workdir = str(tmp_path)
        first = _run_harness("spend", workdir)
        assert first.returncode == 0, first.stderr
        _marker(first, "HARNESS_SPEND_OK")
        second = _run_harness("spend", workdir)
        assert second.returncode == 0, second.stderr
        _marker(second, "HARNESS_SPEND_REFUSED")


def _ledger(proc: subprocess.CompletedProcess) -> float:
    return float(_marker(proc, "HARNESS_LEDGER ").split()[1])


@pytest.fixture(scope="module",
                params=["single_device",
                        pytest.param("mesh8", marks=pytest.mark.slow)])
def serve_kill_run(tmp_path_factory, request):
    """The serving kill scenario (ISSUE 10): a session saved to the
    SessionStore, SIGKILLed mid-query, reopened, re-issued. One run per
    topology; the tests below assert its facets. The mesh8 leg is
    `slow` (tier-1 runs the single-device leg; CI's process-kill job
    runs both)."""
    mesh = request.param == "mesh8"
    clean_dir = str(tmp_path_factory.mktemp("serve_clean"))
    kill_dir = str(tmp_path_factory.mktemp("serve_kill"))
    clean = _run_harness("serve_clean", clean_dir, mesh=mesh)
    assert clean.returncode == 0, clean.stderr
    prepared = _run_harness("serve_prepare", kill_dir, mesh=mesh)
    assert prepared.returncode == 0, prepared.stderr
    killed = _run_harness("serve_killed", kill_dir, mesh=mesh)
    resumed = _run_harness("serve_resume", kill_dir, mesh=mesh)
    assert resumed.returncode == 0, resumed.stderr
    replay = _run_harness("serve_replay", kill_dir, mesh=mesh)
    assert replay.returncode == 0, replay.stderr
    return {"clean": clean, "killed": killed, "resumed": resumed,
            "replay": replay, "kill_dir": kill_dir, "mesh": mesh}


class TestServingKillRecovery:
    """Kill-and-reopen parity for durable serving sessions: the SIGKILLed
    process leaves only the fsync'd SessionStore payloads and tenant
    WALs; the reopened session must serve bit-identical warm queries
    and refuse cross-restart release replays."""

    def test_child_died_by_sigkill_mid_query(self, serve_kill_run):
        killed = serve_kill_run["killed"]
        assert killed.returncode == -signal.SIGKILL
        assert "HARNESS_RESULT" not in killed.stdout

    def test_reopened_session_serves_bit_identical(self, serve_kill_run):
        clean = _columns(serve_kill_run["clean"])
        resumed = _columns(serve_kill_run["resumed"])
        assert clean == resumed  # hex-encoded raw bytes: exact equality

    def test_killed_charge_survives_conservatively(self, serve_kill_run):
        # The killed query's charge was durably committed before the
        # replay started and its release never committed — after the
        # kill the at-most-once stance keeps it (the dead process cannot
        # prove it released nothing), so the resumed process sees the
        # killed charge plus its own: 2 epsilon spent.
        assert _ledger(serve_kill_run["resumed"]) == pytest.approx(2.0)

    def test_cross_restart_release_replay_refused(self, serve_kill_run):
        _marker(serve_kill_run["replay"], "HARNESS_DOUBLE_RELEASE")

    def test_audit_trail_replays_exactly_across_sigkill(
            self, serve_kill_run):
        """The release audit trail (obs/audit.py) survives SIGKILL
        byte-for-byte: every process recovers exactly the records the
        previous one durably committed — no invented outcomes for the
        killed in-flight query, no lost outcomes for finished ones."""
        def audit(proc, prefix):
            return json.loads(_marker(proc, prefix)[len(prefix):])

        # The killed query never decided an outcome — the trail it saw
        # on open was empty, and it appended nothing before dying.
        assert audit(serve_kill_run["killed"],
                     "HARNESS_AUDIT_RECOVERED ") == []
        # The resume recovered that same empty trail, then recorded its
        # own released query.
        assert audit(serve_kill_run["resumed"],
                     "HARNESS_AUDIT_RECOVERED ") == []
        resumed_post = audit(serve_kill_run["resumed"], "HARNESS_AUDIT ")
        assert [r["outcome"] for r in resumed_post] == ["released"]
        # The replay process recovers the resume's record EXACTLY
        # (same payload bytes through the WAL), then appends the typed
        # refusal.
        replay_pre = audit(serve_kill_run["replay"],
                           "HARNESS_AUDIT_RECOVERED ")
        assert replay_pre == resumed_post
        replay_post = audit(serve_kill_run["replay"], "HARNESS_AUDIT ")
        assert [r["outcome"] for r in replay_post] == [
            "released", "double-release-refused"]
        # Same token both times: the refusal names the release it
        # refused to replay.
        assert replay_post[0]["token"] == replay_post[1]["token"]


def _json_marker(proc: subprocess.CompletedProcess, prefix: str):
    return json.loads(_marker(proc, prefix)[len(prefix):])


@pytest.fixture(scope="module",
                params=["single_device",
                        pytest.param("mesh8", marks=pytest.mark.slow)])
def live_kill_run(tmp_path_factory, request):
    """The live-session kill scenario (ISSUE 15): a streaming session
    SIGKILLed mid-append at both sides of the WAL commit point, then
    mid-release-schedule, reopened each time. One run per topology;
    the tests below assert its facets (see the harness docstring for
    the mode-by-mode script)."""
    mesh = request.param == "mesh8"
    clean_dir = str(tmp_path_factory.mktemp("live_clean"))
    cold_dir = str(tmp_path_factory.mktemp("live_cold"))
    kill_dir = str(tmp_path_factory.mktemp("live_kill"))
    out = {"kill_dir": kill_dir, "mesh": mesh}
    for step, mode, workdir in (
            ("clean", "live_clean", clean_dir),
            ("cold", "live_cold", cold_dir),
            ("prepared", "live_prepare", kill_dir),
            ("killed_append", "live_kill_append", kill_dir),
            ("after_append_kill", "live_epoch", kill_dir),
            ("killed_fold", "live_kill_fold", kill_dir),
            ("after_fold_kill", "live_epoch", kill_dir),
            ("dup", "live_dup", kill_dir),
            ("resumed", "live_resume", kill_dir),
            ("replay", "live_replay", kill_dir),
            ("killed_release", "live_kill_release", kill_dir),
            ("recovered", "live_recover", kill_dir)):
        proc = _run_harness(mode, workdir, mesh=mesh)
        if step.startswith("killed_"):
            assert proc.returncode == -signal.SIGKILL, (
                f"{mode}: expected SIGKILL, got rc={proc.returncode};\n"
                f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
            assert "HARNESS_NOT_KILLED" not in proc.stdout
        else:
            assert proc.returncode == 0, (
                f"{mode} failed;\nstdout:\n{proc.stdout}\n"
                f"stderr:\n{proc.stderr}")
        out[step] = proc
    return out


class TestLiveSessionKillRecovery:
    """Crash-exactly-once streaming append: SIGKILL before the WAL
    commit loses the batch entirely (reopen at N); SIGKILL after it
    loses nothing (reopen at N+1); either way the reopened session is
    bit-identical to the never-killed one."""

    def test_kill_before_wal_commit_reopens_at_n(self, live_kill_run):
        prepared = live_kill_run["prepared"]
        saved_fp = _marker(prepared, "HARNESS_SAVED ").split()[1]
        state = _json_marker(live_kill_run["after_append_kill"],
                             "HARNESS_LIVE_STATE ")
        # The encode-stage kill died before the WAL record: the epoch
        # payload staged on disk is an ignored orphan, and the reopened
        # session is bit-identically the pre-append one.
        assert state["epoch"] == 2
        assert state["fingerprint"] == saved_fp
        assert state["sealed"] == [[0, 1]]

    def test_kill_after_wal_commit_reopens_at_n_plus_1(self,
                                                       live_kill_run):
        state = _json_marker(live_kill_run["after_fold_kill"],
                             "HARNESS_LIVE_STATE ")
        # The fold-stage kill died after the WAL record: the reopened
        # session rebuilt the fold the dead process never ran.
        assert state["epoch"] == 3
        assert state["sealed"] == [[0, 1], [1, 2]]

    def test_resubmitted_batch_is_digest_idempotent(self, live_kill_run):
        dup = _json_marker(live_kill_run["dup"], "HARNESS_LIVE_DUP ")
        assert dup == {"duplicate": True, "epoch_before": 3,
                       "epoch_after": 3}

    def test_windowed_releases_bit_identical_to_cold_batch(
            self, live_kill_run):
        """The acceptance: the windowed release stream over the killed-
        and-reopened session is bit-identical to (a) the never-killed
        live run and (b) cold batch sessions over the same rows."""
        clean = _json_marker(live_kill_run["clean"],
                             "HARNESS_LIVE_WINDOWS ")
        cold = _json_marker(live_kill_run["cold"],
                            "HARNESS_LIVE_WINDOWS ")
        resumed = _json_marker(live_kill_run["resumed"],
                               "HARNESS_LIVE_WINDOWS ")
        assert sorted(resumed) == ["0,1", "1,2", "2,3"]
        assert resumed == clean  # hex-encoded raw bytes
        assert resumed == cold

    def test_full_union_query_bit_identical_to_cold_batch(
            self, live_kill_run):
        clean = _columns(live_kill_run["clean"])
        cold = _columns(live_kill_run["cold"])
        resumed = _columns(live_kill_run["resumed"])
        assert resumed == clean
        assert resumed == cold

    def test_kill_at_group_commit_seam_reopens_at_n_plus_1(
            self, live_kill_run):
        """SIGKILL between the flushed WAL record and the group fsync:
        the record survives process death via the page cache, so the
        reopened session lands at N+1 with the batch committed —
        atomicity at the group-commit seam matches the fold seam."""
        kill_dir = live_kill_run["kill_dir"]
        mesh = live_kill_run["mesh"]
        proc = _run_harness("live_kill_commit", kill_dir, mesh=mesh)
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL;\nstdout:\n{proc.stdout}\n"
            f"stderr:\n{proc.stderr}")
        assert "HARNESS_NOT_KILLED" not in proc.stdout
        before = _json_marker(proc, "HARNESS_EPOCH_BEFORE ")
        after_proc = _run_harness("live_epoch", kill_dir, mesh=mesh)
        assert after_proc.returncode == 0, after_proc.stderr
        after = _json_marker(after_proc, "HARNESS_LIVE_STATE ")
        assert after["epoch"] == before["epoch"] + 1

    def test_cross_restart_schedule_replay_refused(self, live_kill_run):
        replay = live_kill_run["replay"]
        # Catch-up state is exact: nothing due after the reopen ...
        assert _json_marker(replay, "HARNESS_LIVE_DUE ") == []
        # ... and the deliberate replay of a released window is refused
        # by the tenant's durable release journal, charge refunded
        # (3 windows x 0.5 + one 1.0 full query = 2.5, not 3.0).
        _marker(replay, "HARNESS_DOUBLE_RELEASE")
        assert _ledger(replay) == pytest.approx(2.5)

    def test_release_kill_recovers_exactly_once(self, live_kill_run):
        """SIGKILL between a window's release and its outcome record:
        the catch-up re-run is refused by the release journal, recorded
        as 'recovered', and its charge exactly refunded."""
        recovered = live_kill_run["recovered"]
        assert _json_marker(recovered, "HARNESS_LIVE_DUE ") == [
            [1, 2], [2, 3]]
        assert _json_marker(recovered, "HARNESS_LIVE_OUTCOMES ") == [
            [[1, 2], "recovered"], [[2, 3], "released"]]
        # resume (2.5) + killed [0,1) charge (0.5) + killed [1,2)
        # charge (0.5, conservative: the dead process may have
        # released) + recovered [1,2) re-run refunded (net 0) +
        # [2,3) (0.5) = 4.0 exactly.
        assert _ledger(recovered) == pytest.approx(4.0)

    def test_killed_append_process_left_parseable_spool(
            self, live_kill_run):
        spool = _marker(live_kill_run["killed_append"],
                        "HARNESS_FLIGHT ").split(" ", 1)[1]
        assert spool != "None"
        doc = flight_lib.read_spool(spool)
        kinds = [e["kind"] for e in doc["events"]]
        assert "append_start" in kinds


class TestFlightRecorderKillLeg:
    """The PR-13 operational-plane acceptance on the kill harness: a
    SIGKILL'd process leaves a parseable flight-recorder post-mortem
    next to its WALs, the post-mortem correlates to the recovered audit
    trail by trace_id, and /statusz on the reopened fleet reports the
    recovered session."""

    @staticmethod
    def _spool(proc):
        path = _marker(proc, "HARNESS_FLIGHT ").split(" ", 1)[1]
        assert path != "None", "flight spool was never bound"
        return path

    def test_killed_process_spool_parses(self, serve_kill_run):
        # The killed process ran no atexit handler and flushed nothing
        # on exit — the spool must still parse (torn tail tolerated)
        # and hold the dead query's lifecycle up to the kill point.
        spool = self._spool(serve_kill_run["killed"])
        assert os.path.exists(spool)
        doc = flight_lib.read_dump(spool)
        kinds = [e["kind"] for e in doc["events"]]
        assert "query_start" in kinds
        starts = [e for e in doc["events"] if e["kind"] == "query_start"]
        assert all(e["attrs"]["qid"] for e in starts)
        # The kill hit mid-query: no query_finish was ever recorded.
        assert "query_finish" not in kinds

    def test_post_mortem_correlates_to_audit_wal(self, serve_kill_run):
        # The resumed process's released query: its flight-recorder
        # query events and its audit-WAL record share one trace_id.
        spool = self._spool(serve_kill_run["resumed"])
        doc = flight_lib.read_dump(spool)
        start_qids = {e["attrs"]["qid"] for e in doc["events"]
                      if e["kind"] == "query_start"}
        finish = [e for e in doc["events"] if e["kind"] == "query_finish"]
        assert len(finish) == 1
        qid = finish[0]["attrs"]["qid"]
        assert qid in start_qids
        # The replay process recovered the resume's audit record from
        # the WAL — trace_id intact across process death.
        prefix = "HARNESS_AUDIT_RECOVERED "
        recovered = json.loads(
            _marker(serve_kill_run["replay"], prefix)[len(prefix):])
        assert [r["trace_id"] for r in recovered] == [qid]
        assert recovered[0]["outcome"] == "released"

    def test_statusz_reports_recovered_session(self, serve_kill_run):
        proc = _run_harness("serve_ops", serve_kill_run["kill_dir"],
                            mesh=serve_kill_run["mesh"])
        assert proc.returncode == 0, proc.stderr
        statusz = json.loads(
            _marker(proc, "HARNESS_STATUSZ ")[len("HARNESS_STATUSZ "):])
        assert "kh-dataset" in statusz["sessions"]
        sess = statusz["sessions"]["kh-dataset"]
        assert sess["residency"] in ("device", "host")
        assert "acme" in sess["tenants"]
        # The killed charge + the resumed release: 2.0 epsilon burned
        # against the durable ledger, visible over HTTP.
        assert sess["tenants"]["acme"]["spent_epsilon"] == \
            pytest.approx(2.0)
        healthz = json.loads(
            _marker(proc, "HARNESS_HEALTHZ ")[len("HARNESS_HEALTHZ "):])
        assert healthz["status"] == "ok"
        assert healthz["checks"]["wal_writable"] is True
        assert int(_marker(proc, "HARNESS_METRICS_LINES ").split()[1]) > 0
