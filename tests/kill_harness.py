"""Subprocess harness for true process-kill recovery (SIGKILL, not a
simulated HostCrash exception).

tests/process_kill_test.py drives this script through fresh Python
processes, so the recovery path exercised is the real one: the killed
process gets no chance to flush, close, or hand anything over — the only
survivors are the fsync'd artifacts (FileCheckpointStore snapshots and
the FileReleaseJournal WAL), and the re-exec'd process must rebuild
everything else from scratch.

Modes (one per invocation: ``python kill_harness.py <mode> <workdir>``):

  clean   — an uninterrupted seeded run; prints the released columns.
  killed  — same run with a scripted ``sigkill`` fault at slab window 1:
            the process dies mid-stream (SIGKILL — the print never
            happens; the orchestrator asserts returncode -SIGKILL).
  resume  — same run again: auto-resumes from the checkpoint store,
            commits the release to the durable journal, prints the
            released columns (must be bit-identical to ``clean``).
  replay  — same run again after ``resume`` released: the durable
            journal must refuse the replayed release token
            (DoubleReleaseError) before any noise is drawn.
  spend   — accountant-only: commits two mechanism spends to a durable
            spend journal; a second invocation must refuse the replay
            (BudgetAccountantError).

Serving modes (ISSUE 10 — durable sessions; the SessionStore under
``<workdir>/sessions``, a tenant with durable WAL journal + ledger):

  serve_clean   — ingest, save, answer one tenant query from the saved
                  session; prints the released columns (what the
                  pre-kill session serves for this seed).
  serve_prepare — ingest + save only (no query, no release).
  serve_killed  — reopen the session from the store and run the same
                  query with a scripted ``sigkill`` mid-replay: the
                  process dies after the tenant charge was durably
                  committed but before the release token was.
  serve_resume  — reopen again, re-issue the query: released columns
                  must be bit-identical to ``serve_clean``; also prints
                  the tenant's durable ledger spend (the killed query's
                  conservative charge survives).
  serve_replay  — re-issue once more: the tenant's durable release
                  journal must refuse the replayed token
                  (DoubleReleaseError) — cross-restart at-most-once.
  serve_ops     — reopen the fleet through a SessionManager with the
                  observability endpoint up (obs/ops_plane.py) and
                  print the live /statusz and /healthz payloads — the
                  PR-13 acceptance that a reopened fleet reports its
                  recovered session over HTTP.

Every serving-mode process prints ``HARNESS_FLIGHT <spool>`` after the
session is store-bound: the flight recorder (obs/flight.py) spools its
events next to the store's WALs, so even the SIGKILL'd process leaves a
parseable post-mortem with the query's trace id (correlating to the
audit WAL's ``trace_id`` field).

Set ``PDP_KH_MESH=8`` to run the serving modes on an 8-device virtual
mesh (the orchestrator also forces the XLA host-device-count flag).

Marker lines on stdout (prefix ``HARNESS_``) carry the machine-readable
outcome; everything else is free-form noise (JAX logs etc.).
"""

import json
import os
import sys


def _build_inputs():
    import numpy as np

    rng = np.random.default_rng(7)
    n = 20_000
    pid = rng.integers(1_000, 5_000, n).astype(np.int64)
    pk = rng.integers(0, 50, n).astype(np.int32)
    value = rng.uniform(0, 5, n).astype(np.float32)
    return pid, pk, value


def _run_engine(mode: str, workdir: str) -> None:
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import runtime

    pid, pk, value = _build_inputs()
    injector = None
    kwargs = {}
    if mode != "clean":
        store = runtime.FileCheckpointStore(os.path.join(workdir, "ckpt"))
        kwargs["checkpoint_policy"] = runtime.CheckpointPolicy(
            store=store, run_id="kill-harness")
        kwargs["release_journal"] = runtime.FileReleaseJournal(
            os.path.join(workdir, "release.wal"))
    if mode == "killed":
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("sigkill", at_slab=1)])
    accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
    engine = pdp.JaxDPEngine(accountant, seed=3, stream_chunks=8,
                             secure_host_noise=False,
                             fault_injector=injector, **kwargs)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=50,
        max_contributions_per_partition=1_000,
        min_value=0.0,
        max_value=5.0)
    try:
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=list(range(50)))
        accountant.compute_budgets()
        columns = result.to_columns()
    except runtime.DoubleReleaseError:
        print("HARNESS_DOUBLE_RELEASE")
        return
    out = {name: np.asarray(col).tobytes().hex()
           for name, col in sorted(columns.items())}
    print("HARNESS_RESULT " + json.dumps({"mode": mode, "columns": out}))


def _run_spend(workdir: str) -> None:
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import runtime
    from pipelinedp_tpu.aggregate_params import MechanismType
    from pipelinedp_tpu.budget_accounting import BudgetAccountantError

    journal = runtime.FileReleaseJournal(
        os.path.join(workdir, "spend.wal"))
    accountant = pdp.NaiveBudgetAccountant(
        1.0, 1e-6, durable_spend_journal=journal)
    accountant.request_budget(MechanismType.LAPLACE)
    accountant.request_budget(MechanismType.GAUSSIAN)
    try:
        accountant.compute_budgets()
    except BudgetAccountantError:
        print("HARNESS_SPEND_REFUSED")
        return
    print("HARNESS_SPEND_OK")


def _serving_mesh():
    if os.environ.get("PDP_KH_MESH") != "8":
        return None
    from pipelinedp_tpu.parallel import sharded
    return sharded.make_mesh(8)


def _serving_session(workdir: str, mode: str):
    """The (store, session) pair of one serving-mode invocation:
    ingest+save on the first touch of a workdir, reopen from the store
    afterwards — so every post-prepare process exercises the real
    re-hydration path."""
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import serving

    store = serving.SessionStore(os.path.join(workdir, "sessions"))
    mesh = _serving_mesh()
    if store.exists("kh-dataset"):
        session = store.open("kh-dataset", mesh=mesh)
    else:
        pid, pk, value = _build_inputs()
        session = serving.DatasetSession(
            pdp.ColumnarData(pid=pid, pk=pk, value=value),
            public_partitions=list(range(50)), mesh=mesh, n_chunks=8,
            name="kh-dataset")
        session.save(store)
        # Durable-by-default on a store-bound session: WAL release
        # journal + WAL ledger under <workdir>/sessions/kh-dataset/.
        session.register_tenant("acme", total_epsilon=1e9,
                                total_delta=1 - 1e-9)
    return store, session


def _run_serving(mode: str, workdir: str) -> None:
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import runtime, serving

    store, session = _serving_session(workdir, mode)
    # The audit trail recovered from the store's fsync'd WAL — what the
    # previous (possibly SIGKILLed) process durably committed. Printed
    # BEFORE the query so even the killed mode reports it.
    print("HARNESS_AUDIT_RECOVERED " + json.dumps(
        [r.to_payload() for r in session.audit_trail.records()]))
    # The flight-recorder spool this process writes (bound next to the
    # store's WALs by the store binding) — printed BEFORE the query so
    # the killed mode reports where its post-mortem will be.
    from pipelinedp_tpu.obs import flight
    print(f"HARNESS_FLIGHT {flight.recorder().spool_path}")
    sys.stdout.flush()
    if mode == "serve_prepare":
        print("HARNESS_SAVED " + session.fingerprint)
        return
    injector = None
    if mode == "serve_killed":
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("sigkill", at_slab=0)])
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=50,
        max_contributions_per_partition=1_000,
        min_value=0.0,
        max_value=5.0)
    try:
        columns = session.query(params, epsilon=1.0, delta=1e-6, seed=3,
                                tenant="acme", secure_host_noise=False,
                                fault_injector=injector).to_columns()
    except runtime.DoubleReleaseError:
        print("HARNESS_AUDIT " + json.dumps(
            [r.to_payload() for r in session.audit_trail.records()]))
        print("HARNESS_DOUBLE_RELEASE")
        return
    ledger = session.tenant("acme").ledger
    print(f"HARNESS_LEDGER {ledger.spent_epsilon:.6f}")
    print("HARNESS_AUDIT " + json.dumps(
        [r.to_payload() for r in session.audit_trail.records()]))
    out = {name: np.asarray(col).tobytes().hex()
           for name, col in sorted(columns.items())}
    print("HARNESS_RESULT " + json.dumps({"mode": mode, "columns": out}))


def _run_serve_ops(workdir: str) -> None:
    """Reopens the stored fleet under a SessionManager with the obs
    endpoint live and prints what /statusz and /healthz serve."""
    import urllib.request

    from pipelinedp_tpu import serving

    store = serving.SessionStore(os.path.join(workdir, "sessions"))
    manager = serving.SessionManager(store, ops_port=0)
    manager.open("kh-dataset", mesh=_serving_mesh())
    url = manager.ops_server.url
    for marker, endpoint in (("HARNESS_STATUSZ", "/statusz"),
                             ("HARNESS_HEALTHZ", "/healthz")):
        body = urllib.request.urlopen(url + endpoint, timeout=30).read()
        print(f"{marker} {body.decode()}".replace("\n", " "))
    metrics_text = urllib.request.urlopen(
        url + "/metrics", timeout=30).read().decode()
    print("HARNESS_METRICS_LINES "
          f"{sum(1 for li in metrics_text.splitlines() if li)}")
    manager.close()


def main() -> None:
    mode, workdir = sys.argv[1], sys.argv[2]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if mode == "spend":
        _run_spend(workdir)
    elif mode == "serve_ops":
        _run_serve_ops(workdir)
    elif mode.startswith("serve_"):
        _run_serving(mode, workdir)
    else:
        _run_engine(mode, workdir)


if __name__ == "__main__":
    main()
