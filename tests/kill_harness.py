"""Subprocess harness for true process-kill recovery (SIGKILL, not a
simulated HostCrash exception).

tests/process_kill_test.py drives this script through fresh Python
processes, so the recovery path exercised is the real one: the killed
process gets no chance to flush, close, or hand anything over — the only
survivors are the fsync'd artifacts (FileCheckpointStore snapshots and
the FileReleaseJournal WAL), and the re-exec'd process must rebuild
everything else from scratch.

Modes (one per invocation: ``python kill_harness.py <mode> <workdir>``):

  clean   — an uninterrupted seeded run; prints the released columns.
  killed  — same run with a scripted ``sigkill`` fault at slab window 1:
            the process dies mid-stream (SIGKILL — the print never
            happens; the orchestrator asserts returncode -SIGKILL).
  resume  — same run again: auto-resumes from the checkpoint store,
            commits the release to the durable journal, prints the
            released columns (must be bit-identical to ``clean``).
  replay  — same run again after ``resume`` released: the durable
            journal must refuse the replayed release token
            (DoubleReleaseError) before any noise is drawn.
  spend   — accountant-only: commits two mechanism spends to a durable
            spend journal; a second invocation must refuse the replay
            (BudgetAccountantError).

Serving modes (ISSUE 10 — durable sessions; the SessionStore under
``<workdir>/sessions``, a tenant with durable WAL journal + ledger):

  serve_clean   — ingest, save, answer one tenant query from the saved
                  session; prints the released columns (what the
                  pre-kill session serves for this seed).
  serve_prepare — ingest + save only (no query, no release).
  serve_killed  — reopen the session from the store and run the same
                  query with a scripted ``sigkill`` mid-replay: the
                  process dies after the tenant charge was durably
                  committed but before the release token was.
  serve_resume  — reopen again, re-issue the query: released columns
                  must be bit-identical to ``serve_clean``; also prints
                  the tenant's durable ledger spend (the killed query's
                  conservative charge survives).
  serve_replay  — re-issue once more: the tenant's durable release
                  journal must refuse the replayed token
                  (DoubleReleaseError) — cross-restart at-most-once.
  serve_ops     — reopen the fleet through a SessionManager with the
                  observability endpoint up (obs/ops_plane.py) and
                  print the live /statusz and /healthz payloads — the
                  PR-13 acceptance that a reopened fleet reports its
                  recovered session over HTTP.

Every serving-mode process prints ``HARNESS_FLIGHT <spool>`` after the
session is store-bound: the flight recorder (obs/flight.py) spools its
events next to the store's WALs, so even the SIGKILL'd process leaves a
parseable post-mortem with the query's trace id (correlating to the
audit WAL's ``trace_id`` field).

Live-session modes (ISSUE 15 — crash-exactly-once streaming append +
windowed continual releases; ``LiveDatasetSession`` under
``<workdir>/sessions``, tumbling size-1 windows, four 3000-row epochs):

  live_clean        — fresh dir: create, append epochs 0..3, tick a
                      ReleaseSchedule (3 sealed windows) and run one
                      full-union query; prints per-window and full
                      released columns.
  live_cold         — fresh dir: the SAME windows and union answered by
                      cold batch ``DatasetSession``s over the same rows
                      with the same pinned n_chunks and per-window
                      seeds — the bit-identity reference.
  live_prepare      — create + append epochs 0 and 1 only.
  live_kill_append  — reopen and append epoch 2 with the crash seam at
                      the ``encode`` stage (after the epoch payload is
                      staged, BEFORE the WAL commit): SIGKILL — reopen
                      must land at exactly epoch 2 (N).
  live_kill_fold    — append epoch 2 again with the seam at ``fold``
                      (AFTER the WAL commit, before the in-memory
                      fold): SIGKILL — reopen must land at epoch 3
                      (N+1) with the fold rebuilt from the WAL.
  live_epoch        — reopen only; prints epoch / fingerprint / sealed
                      windows (the inspection step between kills).
  live_dup          — reopen and re-submit the epoch-2 batch: must be
                      a digest-idempotent no-op (duplicate=True, epoch
                      unchanged).
  live_resume       — reopen, append epoch 3, tick the schedule: all
                      three sealed windows release; prints per-window
                      and full columns (must be bit-identical to
                      ``live_clean`` — the union crossed two SIGKILLs).
  live_replay       — reopen, reattach the schedule (nothing due), and
                      deliberately replay window [0,1): the tenant's
                      durable release journal must refuse it
                      (DoubleReleaseError) across restarts.
  live_kill_release — a second schedule with the seam at the
                      ``release`` stage: window [0,1) records, [1,2)
                      releases its token then SIGKILL before the
                      outcome record.
  live_recover      — reattach the second schedule: [1,2) is due
                      again, its catch-up re-run is refused by the
                      release journal and recorded as ``recovered``
                      (charge exactly refunded); [2,3) releases.

Fleet-failover modes (ISSUE 19 — leased single-writer sessions, hot
followers, exactly-once releases across host death; same live session
shape as the live modes, two-tick release schedule):

  fleet_clean    — fresh dir: create, append epochs 0..3, tick the
                   schedule (3 sealed windows) and run the full-union
                   query; the uninterrupted reference stream.
  fleet_primary  — fresh dir: create, append 0..1, tick #1 (window
                   [0,1) releases; its columns print), append 2..3,
                   tick #2 with the ``release@1`` seam: window [1,2)'s
                   release token commits durably, then SIGKILL before
                   the outcome record. Window [2,3) is never attempted.
  fleet_follower — same dir, fresh process: a ``FollowerSession``
                   tails the primary's WAL read-only (digest-verified
                   replay; prints replication lag), serves a warm
                   read-only query, observes the lease holder's pid is
                   dead, promotes (lease takeover → fencing token
                   bump), and runs the catch-up tick: [1,2) is refused
                   by the durable release journal (outcome
                   ``recovered``, charge exactly refunded) and [2,3)
                   releases fresh under its pinned window seed. Prints
                   the released windows, the union query, and the
                   ledger — all byte-compared against ``fleet_clean``.
  fleet_stale    — same dir, after the follower closed: opens the
                   session twice (the second open takes over the lease
                   with a higher fencing token), then the superseded
                   writer attempts an append — refused at the WAL with
                   ``StaleWriterError``, the batch dead-lettered.

Set ``PDP_KH_MESH=8`` to run the serving modes on an 8-device virtual
mesh (the orchestrator also forces the XLA host-device-count flag).

Marker lines on stdout (prefix ``HARNESS_``) carry the machine-readable
outcome; everything else is free-form noise (JAX logs etc.).
"""

import json
import os
import sys

# Script-mode execution puts tests/ (not the repo root) on sys.path;
# the harness must import the package no matter how it was launched.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _build_inputs():
    import numpy as np

    rng = np.random.default_rng(7)
    n = 20_000
    pid = rng.integers(1_000, 5_000, n).astype(np.int64)
    pk = rng.integers(0, 50, n).astype(np.int32)
    value = rng.uniform(0, 5, n).astype(np.float32)
    return pid, pk, value


def _run_engine(mode: str, workdir: str) -> None:
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import runtime

    pid, pk, value = _build_inputs()
    injector = None
    kwargs = {}
    if mode != "clean":
        store = runtime.FileCheckpointStore(os.path.join(workdir, "ckpt"))
        kwargs["checkpoint_policy"] = runtime.CheckpointPolicy(
            store=store, run_id="kill-harness")
        kwargs["release_journal"] = runtime.FileReleaseJournal(
            os.path.join(workdir, "release.wal"))
    if mode == "killed":
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("sigkill", at_slab=1)])
    accountant = pdp.NaiveBudgetAccountant(1e9, 1 - 1e-9)
    engine = pdp.JaxDPEngine(accountant, seed=3, stream_chunks=8,
                             secure_host_noise=False,
                             fault_injector=injector, **kwargs)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=50,
        max_contributions_per_partition=1_000,
        min_value=0.0,
        max_value=5.0)
    try:
        result = engine.aggregate(
            pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
            public_partitions=list(range(50)))
        accountant.compute_budgets()
        columns = result.to_columns()
    except runtime.DoubleReleaseError:
        print("HARNESS_DOUBLE_RELEASE")
        return
    out = {name: np.asarray(col).tobytes().hex()
           for name, col in sorted(columns.items())}
    print("HARNESS_RESULT " + json.dumps({"mode": mode, "columns": out}))


def _run_spend(workdir: str) -> None:
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import runtime
    from pipelinedp_tpu.aggregate_params import MechanismType
    from pipelinedp_tpu.budget_accounting import BudgetAccountantError

    journal = runtime.FileReleaseJournal(
        os.path.join(workdir, "spend.wal"))
    accountant = pdp.NaiveBudgetAccountant(
        1.0, 1e-6, durable_spend_journal=journal)
    accountant.request_budget(MechanismType.LAPLACE)
    accountant.request_budget(MechanismType.GAUSSIAN)
    try:
        accountant.compute_budgets()
    except BudgetAccountantError:
        print("HARNESS_SPEND_REFUSED")
        return
    print("HARNESS_SPEND_OK")


def _serving_mesh():
    if os.environ.get("PDP_KH_MESH") != "8":
        return None
    from pipelinedp_tpu.parallel import sharded
    return sharded.make_mesh(8)


def _serving_session(workdir: str, mode: str):
    """The (store, session) pair of one serving-mode invocation:
    ingest+save on the first touch of a workdir, reopen from the store
    afterwards — so every post-prepare process exercises the real
    re-hydration path."""
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import serving

    store = serving.SessionStore(os.path.join(workdir, "sessions"))
    mesh = _serving_mesh()
    if store.exists("kh-dataset"):
        session = store.open("kh-dataset", mesh=mesh)
    else:
        pid, pk, value = _build_inputs()
        session = serving.DatasetSession(
            pdp.ColumnarData(pid=pid, pk=pk, value=value),
            public_partitions=list(range(50)), mesh=mesh, n_chunks=8,
            name="kh-dataset")
        session.save(store)
        # Durable-by-default on a store-bound session: WAL release
        # journal + WAL ledger under <workdir>/sessions/kh-dataset/.
        session.register_tenant("acme", total_epsilon=1e9,
                                total_delta=1 - 1e-9)
    return store, session


def _run_serving(mode: str, workdir: str) -> None:
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import runtime, serving

    store, session = _serving_session(workdir, mode)
    # The audit trail recovered from the store's fsync'd WAL — what the
    # previous (possibly SIGKILLed) process durably committed. Printed
    # BEFORE the query so even the killed mode reports it.
    print("HARNESS_AUDIT_RECOVERED " + json.dumps(
        [r.to_payload() for r in session.audit_trail.records()]))
    # The flight-recorder spool this process writes (bound next to the
    # store's WALs by the store binding) — printed BEFORE the query so
    # the killed mode reports where its post-mortem will be.
    from pipelinedp_tpu.obs import flight
    print(f"HARNESS_FLIGHT {flight.recorder().spool_path}")
    sys.stdout.flush()
    if mode == "serve_prepare":
        print("HARNESS_SAVED " + session.fingerprint)
        return
    injector = None
    if mode == "serve_killed":
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("sigkill", at_slab=0)])
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=50,
        max_contributions_per_partition=1_000,
        min_value=0.0,
        max_value=5.0)
    try:
        columns = session.query(params, epsilon=1.0, delta=1e-6, seed=3,
                                tenant="acme", secure_host_noise=False,
                                fault_injector=injector).to_columns()
    except runtime.DoubleReleaseError:
        print("HARNESS_AUDIT " + json.dumps(
            [r.to_payload() for r in session.audit_trail.records()]))
        print("HARNESS_DOUBLE_RELEASE")
        return
    ledger = session.tenant("acme").ledger
    print(f"HARNESS_LEDGER {ledger.spent_epsilon:.6f}")
    print("HARNESS_AUDIT " + json.dumps(
        [r.to_payload() for r in session.audit_trail.records()]))
    out = {name: np.asarray(col).tobytes().hex()
           for name, col in sorted(columns.items())}
    print("HARNESS_RESULT " + json.dumps({"mode": mode, "columns": out}))


def _run_serve_ops(workdir: str) -> None:
    """Reopens the stored fleet under a SessionManager with the obs
    endpoint live and prints what /statusz and /healthz serve."""
    import urllib.request

    from pipelinedp_tpu import serving

    store = serving.SessionStore(os.path.join(workdir, "sessions"))
    manager = serving.SessionManager(store, ops_port=0)
    manager.open("kh-dataset", mesh=_serving_mesh())
    url = manager.ops_server.url
    for marker, endpoint in (("HARNESS_STATUSZ", "/statusz"),
                             ("HARNESS_HEALTHZ", "/healthz")):
        body = urllib.request.urlopen(url + endpoint, timeout=30).read()
        print(f"{marker} {body.decode()}".replace("\n", " "))
    metrics_text = urllib.request.urlopen(
        url + "/metrics", timeout=30).read().decode()
    print("HARNESS_METRICS_LINES "
          f"{sum(1 for li in metrics_text.splitlines() if li)}")
    manager.close()


# -- live-session modes (ISSUE 15) -------------------------------------------

_LIVE_NAME = "kh-live"
_LIVE_EPOCH_ROWS = 3_000
_LIVE_BASE_SEED = 11
_LIVE_EPS = 0.5
_LIVE_DELTA = 1e-7


def _build_live_epoch(e: int):
    """Epoch ``e``'s micro-batch — deterministic per epoch so every
    process (and the cold reference) regenerates identical rows."""
    import numpy as np

    rng = np.random.default_rng(100 + e)
    n = _LIVE_EPOCH_ROWS
    pid = rng.integers(1_000, 3_000, n).astype(np.int64)
    pk = rng.integers(0, 50, n).astype(np.int32)
    value = rng.uniform(0, 5, n).astype(np.float32)
    return pid, pk, value


def _live_params():
    import pipelinedp_tpu as pdp

    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=50,
        max_contributions_per_partition=1_000,
        min_value=0.0,
        max_value=5.0)


def _hex_columns(columns) -> dict:
    import numpy as np

    return {name: np.asarray(col).tobytes().hex()
            for name, col in sorted(columns.items())}


def _live_session(workdir: str):
    from pipelinedp_tpu import serving

    store = serving.SessionStore(os.path.join(workdir, "sessions"))
    mesh = _serving_mesh()
    if store.exists(_LIVE_NAME):
        session = store.open_live(_LIVE_NAME, mesh=mesh)
    else:
        # secure_host_noise=False on BOTH the live session and the cold
        # reference: the secure path draws OS entropy by design, so
        # bit-identity legs must pin the deterministic generator.
        session = serving.LiveDatasetSession.create(
            store=store, name=_LIVE_NAME,
            public_partitions=list(range(50)), n_chunks=8,
            window=serving.WindowSpec(size=1), mesh=mesh,
            secure_host_noise=False)
        session.register_tenant("acme", total_epsilon=1e9,
                                total_delta=1 - 1e-9)
    return store, session


def _live_schedule(session, schedule_id: str, base_seed: int):
    return session.release_schedule(
        schedule_id, _live_params(), epsilon=_LIVE_EPS,
        delta=_LIVE_DELTA, tenant="acme", base_seed=base_seed,
        secure_host_noise=False)


def _print_live_release(records, session) -> None:
    out = {}
    for r in records:
        a, b = r["window"]
        out[f"{a},{b}"] = _hex_columns(r["result"])
    print("HARNESS_LIVE_WINDOWS " + json.dumps(out))
    columns = session.query(
        _live_params(), epsilon=1.0, delta=1e-6, seed=3, tenant="acme",
        secure_host_noise=False).to_columns()
    print("HARNESS_RESULT " + json.dumps(
        {"mode": "live", "columns": _hex_columns(columns)}))
    ledger = session.tenant("acme").ledger
    print(f"HARNESS_LEDGER {ledger.spent_epsilon:.6f}")


def _run_live_cold(workdir: str) -> None:
    """The bit-identity reference: each window (and the full union)
    answered by a cold batch session over the same rows with the same
    pinned chunk count and the same per-window seed."""
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import serving

    mesh = _serving_mesh()
    params = _live_params()
    epochs = [_build_live_epoch(e) for e in range(4)]
    windows = {}
    for a in range(3):
        pid, pk, value = epochs[a]
        cold = serving.DatasetSession(
            pdp.ColumnarData(pid=pid, pk=pk, value=value),
            public_partitions=list(range(50)), mesh=mesh, n_chunks=8,
            name=f"kh-cold-w{a}")
        cols = cold.query(
            params, epsilon=_LIVE_EPS, delta=_LIVE_DELTA,
            seed=serving.window_seed(_LIVE_BASE_SEED, a, a + 1),
            secure_host_noise=False).to_columns()
        windows[f"{a},{a + 1}"] = _hex_columns(cols)
    print("HARNESS_LIVE_WINDOWS " + json.dumps(windows))
    union = serving.DatasetSession(
        pdp.ColumnarData(pid=np.concatenate([e[0] for e in epochs]),
                         pk=np.concatenate([e[1] for e in epochs]),
                         value=np.concatenate([e[2] for e in epochs])),
        public_partitions=list(range(50)), mesh=mesh, n_chunks=8,
        name="kh-cold-union")
    columns = union.query(params, epsilon=1.0, delta=1e-6, seed=3,
                          secure_host_noise=False).to_columns()
    print("HARNESS_RESULT " + json.dumps(
        {"mode": "live_cold", "columns": _hex_columns(columns)}))


def _run_live(mode: str, workdir: str) -> None:
    from pipelinedp_tpu import serving
    from pipelinedp_tpu.serving import live as live_lib

    if mode == "live_cold":
        _run_live_cold(workdir)
        return
    store, session = _live_session(workdir)
    from pipelinedp_tpu.obs import flight
    print(f"HARNESS_FLIGHT {flight.recorder().spool_path}")
    sys.stdout.flush()

    if mode == "live_clean":
        for e in range(4):
            session.append(*_build_live_epoch(e))
        sched = _live_schedule(session, "sched", _LIVE_BASE_SEED)
        _print_live_release(sched.tick(), session)
    elif mode == "live_prepare":
        for e in range(2):
            session.append(*_build_live_epoch(e))
        print("HARNESS_SAVED " + session.fingerprint)
        print(f"HARNESS_LIVE_EPOCH {session.epoch}")
    elif mode in ("live_kill_append", "live_kill_fold"):
        stage = "encode" if mode == "live_kill_append" else "fold"
        os.environ[live_lib.LIVE_CRASH_ENV] = f"{stage}@2"
        session.append(*_build_live_epoch(2))
        print("HARNESS_NOT_KILLED")  # must never print
    elif mode == "live_kill_commit":
        # The group-commit seam: the WAL record is written + flushed
        # but the group fsync has not run. SIGKILL here must still
        # land the epoch (the page cache survives process death) —
        # only power loss could tear an unfsync'd record.
        print("HARNESS_EPOCH_BEFORE " + json.dumps(
            {"epoch": session.epoch}), flush=True)
        os.environ[live_lib.LIVE_CRASH_ENV] = f"commit@{session.epoch}"
        session.append(*_build_live_epoch(session.epoch))
        print("HARNESS_NOT_KILLED")  # must never print
    elif mode == "live_epoch":
        print("HARNESS_LIVE_STATE " + json.dumps({
            "epoch": session.epoch,
            "fingerprint": session.fingerprint,
            "sealed": [list(w) for w in session.sealed_windows()]}))
    elif mode == "live_dup":
        before = session.epoch
        res = session.append(*_build_live_epoch(2))
        print("HARNESS_LIVE_DUP " + json.dumps({
            "duplicate": res.duplicate, "epoch_before": before,
            "epoch_after": session.epoch}))
    elif mode == "live_resume":
        session.append(*_build_live_epoch(3))
        sched = _live_schedule(session, "sched", _LIVE_BASE_SEED)
        _print_live_release(sched.tick(), session)
    elif mode == "live_replay":
        sched = _live_schedule(session, "sched", _LIVE_BASE_SEED)
        print("HARNESS_LIVE_DUE " + json.dumps(
            [list(w) for w in sched.due_windows()]))
        try:
            sched.replay(0, 1)
        except serving.DoubleReleaseError:
            ledger = session.tenant("acme").ledger
            print(f"HARNESS_LEDGER {ledger.spent_epsilon:.6f}")
            print("HARNESS_DOUBLE_RELEASE")
            return
        print("HARNESS_REPLAY_ALLOWED")  # must never print
    elif mode == "live_kill_release":
        os.environ[live_lib.LIVE_CRASH_ENV] = "release@1"
        # A distinct base seed gives this schedule its own release
        # tokens — "sched" already released these windows once.
        sched = _live_schedule(session, "sched2", _LIVE_BASE_SEED + 1000)
        sched.tick()
        print("HARNESS_NOT_KILLED")  # must never print
    elif mode == "live_recover":
        sched = _live_schedule(session, "sched2", _LIVE_BASE_SEED + 1000)
        print("HARNESS_LIVE_DUE " + json.dumps(
            [list(w) for w in sched.due_windows()]))
        records = sched.tick()
        print("HARNESS_LIVE_OUTCOMES " + json.dumps(
            [[list(r["window"]), r["outcome"]] for r in records]))
        ledger = session.tenant("acme").ledger
        print(f"HARNESS_LEDGER {ledger.spent_epsilon:.6f}")
    else:
        raise SystemExit(f"unknown live mode {mode!r}")


# -- fleet-failover modes (ISSUE 19) -----------------------------------------


def _print_fleet_windows(records) -> None:
    """Released windows only — a ``recovered`` record carries no
    re-drawn result by design (the journal refused the re-run)."""
    out = {}
    for r in records:
        if r["outcome"] != "released":
            continue
        a, b = r["window"]
        out[f"{a},{b}"] = _hex_columns(r["result"])
    print("HARNESS_LIVE_WINDOWS " + json.dumps(out))


def _fleet_union_query(session) -> None:
    columns = session.query(
        _live_params(), epsilon=1.0, delta=1e-6, seed=3, tenant="acme",
        secure_host_noise=False).to_columns()
    print("HARNESS_RESULT " + json.dumps(
        {"mode": "fleet", "columns": _hex_columns(columns)}))
    ledger = session.tenant("acme").ledger
    print(f"HARNESS_LEDGER {ledger.spent_epsilon:.6f}")


def _run_fleet(mode: str, workdir: str) -> None:
    import time as time_lib

    from pipelinedp_tpu import serving
    from pipelinedp_tpu.serving import fleet as fleet_lib
    from pipelinedp_tpu.serving import live as live_lib

    if mode == "fleet_clean":
        store, session = _live_session(workdir)
        for e in range(4):
            session.append(*_build_live_epoch(e))
        sched = _live_schedule(session, "sched", _LIVE_BASE_SEED)
        _print_fleet_windows(sched.tick())
        _fleet_union_query(session)
        print("HARNESS_LEASE " + json.dumps(session.lease.status()))
    elif mode == "fleet_primary":
        # The seam only matches window-start ordinal 1, so tick #1's
        # [0,1) release survives and prints; tick #2 dies mid-[1,2)
        # with the release token durably committed but no outcome
        # record — the exactly-once case the follower must recover.
        os.environ[live_lib.LIVE_CRASH_ENV] = "release@1"
        store, session = _live_session(workdir)
        for e in range(2):
            session.append(*_build_live_epoch(e))
        sched = _live_schedule(session, "sched", _LIVE_BASE_SEED)
        _print_fleet_windows(sched.tick())
        print("HARNESS_LEASE " + json.dumps(session.lease.status()))
        sys.stdout.flush()
        for e in range(2, 4):
            session.append(*_build_live_epoch(e))
        sched.tick()
        print("HARNESS_NOT_KILLED")  # must never print
    elif mode == "fleet_follower":
        store = serving.SessionStore(os.path.join(workdir, "sessions"))
        follower = fleet_lib.FollowerSession(store, _LIVE_NAME,
                                             mesh=_serving_mesh())
        # Tail the primary's WAL until caught up (digest-verified).
        deadline = time_lib.monotonic() + 60.0
        while follower.replication_lag()["records_behind"] > 0:
            follower.poll()
            if time_lib.monotonic() > deadline:
                raise SystemExit("follower never caught up")
            time_lib.sleep(follower.poll_s)
        follower.poll()
        print("HARNESS_FLEET_LAG " + json.dumps(follower.replication_lag()))
        print("HARNESS_FLEET_STATUS " + json.dumps({
            "epoch": follower.session.epoch,
            "role": follower.session.live_status()["role"],
            "applied": follower.session.applied_wal_records,
            "primary_dead": follower.primary_dead(),
            "holder": follower.lease_status()}))
        # A warm read-only query served off the replica — no tenant
        # (tenant ledgers are single-writer state, never replicated).
        ro = follower.session.query(
            _live_params(), epsilon=1.0, delta=1e-6, seed=3,
            secure_host_noise=False).to_columns()
        print("HARNESS_RO_RESULT " + json.dumps(
            {"mode": "fleet_ro", "columns": _hex_columns(ro)}))
        sys.stdout.flush()
        # The holder is dead: promote (lease takeover bumps the
        # fencing token) and run the exactly-once catch-up tick.
        primary = follower.promote()
        print("HARNESS_LEASE " + json.dumps(primary.lease.status()))
        sched = _live_schedule(primary, "sched", _LIVE_BASE_SEED)
        print("HARNESS_LIVE_DUE " + json.dumps(
            [list(w) for w in sched.due_windows()]))
        records = sched.tick()
        print("HARNESS_LIVE_OUTCOMES " + json.dumps(
            [[list(r["window"]), r["outcome"]] for r in records]))
        _print_fleet_windows(records)
        _fleet_union_query(primary)
        primary.close()
    elif mode == "fleet_stale":
        store = serving.SessionStore(os.path.join(workdir, "sessions"))
        stale = store.open_live(_LIVE_NAME, mesh=_serving_mesh())
        old_token = stale.lease.token
        fresh = store.open_live(_LIVE_NAME, mesh=_serving_mesh())
        try:
            stale.append(*_build_live_epoch(9))
        except fleet_lib.StaleWriterError:
            print("HARNESS_FENCED " + json.dumps({
                "old_token": old_token,
                "new_token": fresh.lease.token,
                "fenced_appends": live_lib.live_counters()[
                    "appends_fenced"],
                "deadletters": len(store.deadletter_digests(_LIVE_NAME)),
            }))
            fresh.close()
            return
        print("HARNESS_STALE_ALLOWED")  # must never print
    else:
        raise SystemExit(f"unknown fleet mode {mode!r}")


def main() -> None:
    mode, workdir = sys.argv[1], sys.argv[2]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if mode == "spend":
        _run_spend(workdir)
    elif mode == "serve_ops":
        _run_serve_ops(workdir)
    elif mode.startswith("serve_"):
        _run_serving(mode, workdir)
    elif mode.startswith("live_"):
        _run_live(mode, workdir)
    elif mode.startswith("fleet_"):
        _run_fleet(mode, workdir)
    else:
        _run_engine(mode, workdir)


if __name__ == "__main__":
    main()
