"""Benchmark: DP-aggregated partitions/sec (COUNT+SUM) on the columnar
TPU engine vs the LocalBackend CPU oracle.

Headline config (BASELINE.md): synthetic movie_view_ratings-shaped workload,
100M rows / 1M partitions, COUNT+SUM per partition, Laplace noise, private
partition selection, eps=1 delta=1e-6, max_partitions_contributed=8.

Two measurements:
  * e2e — the full public API path: JaxDPEngine.aggregate on raw host
    columns (ColumnarData), including dictionary encoding, host->device
    transfer, the fused kernel, private partition selection, and the secure
    float64 host noise finalization. This is what a user gets.
  * kernel — the fused device step alone on resident data (the sustained
    throughput once data lives on device, e.g. inside a larger pipeline).

The CPU baseline runs DPEngine+LocalBackend on a smaller sample of the same
shape (rows-per-partition held constant) and its partitions/sec is used
directly — LocalBackend cost is linear in rows == partitions * density, so
partitions/sec at equal density is scale-free.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 100_000_000))
N_PARTITIONS = int(os.environ.get("BENCH_PARTITIONS", 1_000_000))
N_USERS = max(N_ROWS // 10, 1)
L0_CAP = 8
LINF_CAP = 4
EPS, DELTA = 1.0, 1e-6

# 2M rows / 20k partitions: big enough that the partitions/sec extrapolation
# to the 100M-row workload rests on a 50x smaller gap (LocalBackend cost is
# linear in rows; density held equal), small enough to finish in ~30 s.
CPU_ROWS = int(os.environ.get("BENCH_CPU_ROWS", 2_000_000))
CPU_PARTITIONS = max(CPU_ROWS * N_PARTITIONS // N_ROWS, 1)


def _trace_dir() -> str:
    """Where per-row Chrome trace files land (BENCH_TRACE_DIR, default
    a bench-traces dir under the system tmp)."""
    import tempfile
    path = os.environ.get("BENCH_TRACE_DIR")
    if not path:
        path = os.path.join(tempfile.gettempdir(), "pdp_bench_traces")
    os.makedirs(path, exist_ok=True)
    return path


def _traced_run(label: str, fn):
    """One EXTRA (untimed) execution of ``fn`` under a fresh tracer;
    returns the written Chrome-trace path. Separate from the timed runs
    so the published numbers stay tracing-free — the trace documents the
    span structure of the row, not its timing."""
    from pipelinedp_tpu.obs import trace as obs_trace

    tracer = obs_trace.install(obs_trace.Tracer())
    try:
        fn()
        return tracer.write_chrome(
            os.path.join(_trace_dir(), f"{label}.json"))
    finally:
        obs_trace.shutdown()


def _host_columns(seed=0):
    """Zipf-skewed partition popularity (movie-view-shaped): head partitions
    clear the private-selection threshold, the long tail is dropped.

    Values are integer star ratings 1..5 — the reference's north-star
    workload aggregates the Netflix-prize rating column, which is integer
    stars (/root/reference/examples/movie_view_ratings/
    run_without_frameworks.py). The wire codec's continuous-value (raw
    float32) path is exercised separately in tests/wirecodec_test.py."""
    rng = np.random.default_rng(seed)
    pk = (N_PARTITIONS * rng.random(N_ROWS)**4).astype(np.int32)
    return (rng.integers(0, N_USERS, N_ROWS, dtype=np.int32),
            np.minimum(pk, N_PARTITIONS - 1),
            rng.integers(1, 6, N_ROWS).astype(np.float32))


def _params():
    import pipelinedp_tpu as pdp
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=L0_CAP,
        max_contributions_per_partition=LINF_CAP,
        min_value=0.0,
        max_value=5.0)


def bench_e2e(pid, pk, value, n_runs=3, segment_sort="auto"):
    """Full public-API path on raw host columns.

    Returns (partitions_per_sec, phases) where phases is the per-stage
    host wall-second budget of the fastest run (profiler stage times).
    Host encode phases (dp/wire_prep, dp/wire_sort, dp/stream_slab_*) are
    HOST time; device transfer+kernels dispatched inside them run async,
    so the sync stages (dp/partition_selection) absorb whatever the
    device had left — that split is the overlap evidence.
    """
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import profiler

    from pipelinedp_tpu.ops import streaming

    scatter_keys = (streaming.EVENT_PARTITION_SCATTERS,
                    streaming.EVENT_COMPACT_MERGE_SCATTERS,
                    streaming.EVENT_COMPACT_CHUNKS)

    def run(seed):
        before = {k: profiler.event_count(k) for k in scatter_keys}
        with profiler.collect_stage_times() as stages:
            t0 = time.perf_counter()
            data = pdp.ColumnarData(pid=pid, pk=pk, value=value)
            accountant = pdp.NaiveBudgetAccountant(EPS, DELTA)
            engine = pdp.JaxDPEngine(accountant, seed=seed,
                                     segment_sort=segment_sort)
            result = engine.aggregate(data, _params())
            accountant.compute_budgets()
            cols = result.to_columns()
            n_kept = int(np.asarray(cols["keep_mask"]).sum())
            assert n_kept > 0
            elapsed = time.perf_counter() - t0
        stages = dict(stages)
        # Executed scatter-pass counts for THIS aggregate (the structural
        # evidence of the compact merge: row-scale partition passes per
        # chunk -> compact merge passes per aggregate).
        for k in scatter_keys:
            stages["#" + k] = profiler.event_count(k) - before[k]
        return elapsed, stages

    run(100)  # warmup/compile
    # min-of-n: the host->device link bandwidth varies ~2x between runs;
    # the minimum is the honest sustained capability of the path.
    results = [run(i) for i in range(n_runs)]
    best_s, best_stages = min(results, key=lambda r: r[0])
    phases = _coarse_phases(best_stages, best_s)
    try:
        phases["trace_file"] = _traced_run("e2e", lambda: run(200))
    except Exception as e:  # noqa: BLE001 — tracing never fails the row
        phases["trace_error"] = f"{type(e).__name__}: {e}"[:120]
    return N_PARTITIONS / best_s, phases


def _coarse_phases(stages: dict, e2e_s: float) -> dict:
    """Folds raw stage names into the phase budget the bench publishes."""
    slab_host = sum(v for k, v in stages.items()
                    if k.startswith("dp/stream_slab_"))
    sort_piped = stages.get("dp/wire_sort", 0.0)
    sort_upfront = stages.get("dp/wire_sort_upfront", 0.0)
    phases = {
        "e2e_s": round(e2e_s, 3),
        "encode_s": round(stages.get("dp/encode", 0.0), 3),
        "wire_prep_s": round(stages.get("dp/wire_prep", 0.0), 3),
        # Host radix sort inside the slab pipeline (overlapped with the
        # previous slab's transfer + kernels) vs serialized up front.
        "wire_sort_pipelined_s": round(sort_piped, 3),
        "wire_sort_upfront_s": round(sort_upfront, 3),
        # Host seconds the lookahead prefetcher spent encoding upcoming
        # slabs on background threads (sort+emit fully overlapped with
        # the in-flight window's transfer + kernels).
        "wire_sort_parallel_s": round(
            stages.get("dp/wire_sort_parallel", 0.0), 3),
        # Host side of the slab loop: sort (nested) + emit + async puts +
        # kernel dispatch.
        "stream_host_s": round(slab_host, 3),
        # Sync points: whatever device work the pipeline didn't hide.
        "selection_sync_s": round(stages.get("dp/partition_selection",
                                             0.0), 3),
        "noise_s": round(stages.get("dp/noise", 0.0), 3),
        # Fused epilogue (ops/finalize.py): the whole post-aggregation
        # path in one dispatch; finalize_transfer is the single batched
        # device->host sync that replaced the per-metric np.asarray tail.
        "finalize_s": round(stages.get("dp/finalize", 0.0), 3),
        "finalize_transfer_s": round(stages.get("dp/finalize_transfer",
                                                0.0), 3),
    }
    phases["host_encode_overlapped"] = bool(
        sort_upfront == 0.0 and slab_host > 0.0)
    # Executed scatter-pass counters (see bench_e2e.run): legacy pays
    # row-scale partition scatters per chunk; the compact merge pays
    # compact-input merge scatters once per aggregate.
    from pipelinedp_tpu.ops import streaming
    phases["partition_scatter_passes"] = int(
        stages.get("#" + streaming.EVENT_PARTITION_SCATTERS, 0))
    phases["compact_merge_scatter_passes"] = int(
        stages.get("#" + streaming.EVENT_COMPACT_MERGE_SCATTERS, 0))
    phases["compact_chunks"] = int(
        stages.get("#" + streaming.EVENT_COMPACT_CHUNKS, 0))
    return phases


def bench_e2e_steady(pid, pk, value, n_calls=4, secure_host_noise=True):
    """Warm-cache steady state: n_calls repeated `aggregate` calls of the
    same query shape, each through a FRESH engine/accountant (executables
    are cached process-wide). Separates compile amortization from kernel
    gains: the first call pays every trace, steady-state calls must pay
    zero (per-call epilogue trace counts are reported to prove it).
    """
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.ops import finalize

    times, traces = [], []
    for i in range(n_calls):
        traces_before = finalize.trace_count()
        t0 = time.perf_counter()
        data = pdp.ColumnarData(pid=pid, pk=pk, value=value)
        accountant = pdp.NaiveBudgetAccountant(EPS, DELTA)
        engine = pdp.JaxDPEngine(accountant, seed=i,
                                 secure_host_noise=secure_host_noise)
        result = engine.aggregate(data, _params())
        accountant.compute_budgets()
        cols = result.to_columns()
        assert int(np.asarray(cols["keep_mask"]).sum()) > 0
        times.append(time.perf_counter() - t0)
        traces.append(finalize.trace_count() - traces_before)
    cache = finalize.default_cache()
    return {
        "first_call_partitions_per_sec": round(N_PARTITIONS / times[0], 1),
        "steady_state_partitions_per_sec": round(
            N_PARTITIONS / min(times[1:]), 1),
        "per_call_epilogue_traces": traces,
        "epilogue_cache_hits": cache.hits,
        "epilogue_cache_misses": cache.misses,
    }


def bench_kernel(pid, pk, value) -> dict:
    """Fused device step on resident data (sustained throughput).

    Four group-stage configurations of the same bounding kernel A/B the
    round-10 tentpole on resident columns:
      * general — unsorted rows, 4-key/7-operand sort (the historical
        kernel-resident row since round 1, kept for trajectory
        continuity: this is the ~305k/s floor the tentpole targets);
      * packed — rows pre-sorted by pid on host (untimed prep — the
        streamed wire delivers this order for free), packed 3-key global
        sort with the float32 value payload (the wire-ingest kernel of
        rounds 6-8, segment_sort=False);
      * tiled — the same packed keys over bucketed segment-local tiles
        with the narrow value payload and int32 group accumulation
        (rounds 9's default, segment_sort=True);
      * hash — the SORTLESS hash-binned group stage (round 10,
        segment_sort="hash"; the auto default for this COUNT+SUM shape
        under the exactness gate): one scatter into per-segment bins,
        keyed-priority selection, zero sort passes over the wire.
        Bit-identical sampling (and, under the gate, bit-identical
        releases) to packed/tiled.

    Returns {partitions_per_sec (headline = hash, the auto default at
    this shape), *_partitions_per_sec per config, sort: per-config
    columnar.sort_cost rows + reduction ratios + the hash grid's
    occupancy, and modeled_vs_measured_sort_bytes — the statically
    summed model vs the bytes actually credited to the ops/sort_*
    counters during the timed runs (ratio 1.0 = the counter story is
    honest)}; costs are credited to the profiler counters exactly as
    the streaming drivers do per executed chunk.
    """
    import jax
    import jax.numpy as jnp

    from pipelinedp_tpu import profiler
    from pipelinedp_tpu.ops import columnar, noise as noise_ops
    from pipelinedp_tpu.ops import selection as selection_ops
    from pipelinedp_tpu.ops import wirecodec
    from pipelinedp_tpu import partition_selection as ps_lib
    from pipelinedp_tpu import noise_core

    host_strategy = ps_lib.TruncatedGeometricPartitionSelection(
        EPS / 3, DELTA, L0_CAP)
    sp = selection_ops.selection_params_from_strategy(host_strategy)
    # eps split: 1/3 each to selection, count, sum (NaiveBudgetAccountant
    # semantics for COUNT+SUM+selection).
    count_scale = L0_CAP * LINF_CAP / (EPS / 3)
    sum_scale = L0_CAP * LINF_CAP * 5.0 / (EPS / 3)

    def make_step(**kernel_kwargs):
        @jax.jit
        def step(key, pid, pk, value):
            valid = jnp.ones(N_ROWS, dtype=bool)
            accs = columnar.bound_and_aggregate(
                key, pid, pk, value, valid,
                num_partitions=N_PARTITIONS,
                linf_cap=LINF_CAP, l0_cap=L0_CAP,
                row_clip_lo=0.0, row_clip_hi=5.0, middle=2.5,
                group_clip_lo=-jnp.inf, group_clip_hi=jnp.inf,
                need_norm=False, need_norm_sq=False, has_group_clip=False,
                **kernel_kwargs)
            k_sel, k_c, k_s = jax.random.split(jax.random.fold_in(key, 1),
                                               3)
            keep, _ = selection_ops.select_partitions(
                k_sel, accs.pid_count, sp, accs.pid_count > 0)
            dp_count = noise_ops.add_noise(
                k_c, accs.count, False, count_scale,
                noise_core.laplace_granularity(count_scale))
            dp_sum = noise_ops.add_noise(
                k_s, accs.sum, False, sum_scale,
                noise_core.laplace_granularity(sum_scale))
            return dp_count, dp_sum, keep

        return step

    def force(x):
        # device_get of a scalar reduction guarantees the computation ran
        # to completion even on platforms where block_until_ready is lax.
        return float(jax.device_get(jnp.sum(x[0]) + jnp.sum(x[1])))

    def measure(step, columns, cost):
        key = jax.random.PRNGKey(0)
        dev = [jax.device_put(c) for c in columns]
        jax.block_until_ready(dev)
        force(step(jax.random.fold_in(key, 100), *dev))  # warmup/compile
        times = []
        for i in range(3):
            t0 = time.perf_counter()
            force(step(jax.random.fold_in(key, i), *dev))
            times.append(time.perf_counter() - t0)
            profiler.count_event(columnar.EVENT_SORT_ROWS, cost["rows"])
            profiler.count_event(columnar.EVENT_SORT_TILES, cost["tiles"])
            profiler.count_event(columnar.EVENT_SORT_BYTES,
                                 cost["operand_bytes"])
        return N_PARTITIONS / min(times)

    # Host prep for the pid-sorted configs (untimed: the streamed wire
    # delivers pid-sorted buckets as a by-product of its host encode).
    order = np.argsort(pid, kind="stable")
    spid, spk, svalue = pid[order], pk[order], value[order]
    per_pid = np.bincount(spid - spid.min())
    max_run = int(per_pid.max())
    max_segments = wirecodec.round_ucap(int((per_pid > 0).sum()))
    tile_slack = -(-max_run // 8) * 8
    tile_rows = 1 << max(10, (4 * max_run - 1).bit_length())
    # Hash-bin grid (round 10): one bin per pid segment, width = the max
    # single-pid run rounded up — the same prep-time stats the wire's
    # plan_group_binning sizes from.
    hash_bin_rows = max(8, (max_run + 7) & ~7)
    hash_bins = max_segments
    # Narrow value payload: star ratings 1..5 are their own plane index
    # (lo=0, scale=1, 3 bits) — the same affine-grid contract the wire
    # codec's VALUE_PLANES mode ships.
    int_clip = columnar.int_accumulation_plan(0.0, 1.0, 3, 0.0, 5.0,
                                              LINF_CAP)

    sort_kw = dict(num_partitions=N_PARTITIONS, max_segments=max_segments,
                   pid_sorted=True)
    costs = {
        "general": columnar.sort_cost(N_ROWS,
                                      num_partitions=N_PARTITIONS),
        "packed": columnar.sort_cost(N_ROWS, **sort_kw),
        "tiled": columnar.sort_cost(N_ROWS, tile_rows=tile_rows,
                                    tile_slack=tile_slack, value_bytes=1,
                                    **sort_kw),
        "hash": columnar.sort_cost(N_ROWS, hash_bins=hash_bins,
                                   hash_bin_rows=hash_bin_rows,
                                   value_bytes=1, **sort_kw),
    }
    out = {"sort": {name: dict(c) for name, c in costs.items()}}
    out["sort"]["tiled_vs_packed_operand_byte_reduction"] = round(
        1.0 - costs["tiled"]["operand_bytes"]
        / max(costs["packed"]["operand_bytes"], 1), 3)
    out["sort"]["tiled_vs_general_operand_byte_reduction"] = round(
        1.0 - costs["tiled"]["operand_bytes"]
        / max(costs["general"]["operand_bytes"], 1), 3)
    # The sortless group stage: zero sort operand bytes by construction.
    out["sort"]["hash_sort_operand_bytes"] = costs["hash"]["operand_bytes"]
    out["sort"]["hash_bin_occupancy_pct"] = round(
        100.0 * N_ROWS / max(hash_bins * hash_bin_rows, 1), 1)

    bytes_before = profiler.event_count(columnar.EVENT_SORT_BYTES)
    out["general_partitions_per_sec"] = round(
        measure(make_step(), [pid, pk, value], costs["general"]), 1)
    packed_kw = dict(pid_sorted=True, max_segments=max_segments)
    out["packed_partitions_per_sec"] = round(
        measure(make_step(**packed_kw), [spid, spk, svalue],
                costs["packed"]), 1)
    tiled_kw = dict(tile_rows=tile_rows, tile_slack=tile_slack,
                    value_is_index=True, value_lo=0.0, value_scale=1.0,
                    value_sort_bits=3, **packed_kw)
    if int_clip is not None:
        tiled_kw.update(int_accumulate=True, int_clip_lo=int_clip[0],
                        int_clip_hi=int_clip[1])
    out["tiled_partitions_per_sec"] = round(
        measure(make_step(**tiled_kw),
                [spid, spk, svalue.astype(np.int32)], costs["tiled"]), 1)
    hash_kw = dict(hash_bins=hash_bins, hash_bin_rows=hash_bin_rows,
                   value_is_index=True, value_lo=0.0, value_scale=1.0,
                   value_sort_bits=3, **packed_kw)
    # Headline: the hash-binned sortless stage — what segment_sort="auto"
    # compiles for this COUNT+SUM shape under the exactness gate.
    out["hash_partitions_per_sec"] = out["partitions_per_sec"] = round(
        measure(make_step(**hash_kw),
                [spid, spk, svalue.astype(np.int32)], costs["hash"]), 1)
    # Counter-vs-model honesty check: the bytes credited during the
    # timed runs must equal the statically summed model (3 timed
    # executions per config; the hash config contributes zero).
    modeled = 3 * sum(costs[c]["operand_bytes"] for c in costs)
    measured = profiler.event_count(columnar.EVENT_SORT_BYTES) \
        - bytes_before
    out["modeled_vs_measured_sort_bytes"] = {
        "modeled": modeled, "measured_counter": measured,
        "ratio": round(measured / max(modeled, 1), 4),
    }
    return out


# VECTOR_SUM row (ROADMAP item 5): k=64 dense vectors are 64x the value
# bytes per row, so the row count scales down to keep the resident
# footprint near the scalar headline's; partitions scale with it so
# density (rows per partition) matches the headline shape.
VEC_ROWS = int(os.environ.get("BENCH_VECTOR_ROWS", 2_000_000))
VEC_DIM = 64
VEC_PARTITIONS = max(VEC_ROWS * N_PARTITIONS // N_ROWS, 1)

# PERCENTILE row: the streamed quantile path holds a dense
# [partitions, 16^4 leaves] histogram, so the partition count is bounded
# by the device histogram budget (ops/quantiles.MAX_HISTOGRAM_ELEMENTS),
# not by the scatter passes; rows stay above MIN_STREAM_ROWS so the row
# masks ride the streamed (tiled-sort) kernels.
PCT_ROWS = int(os.environ.get("BENCH_PCT_ROWS", 4_000_000))
PCT_PARTITIONS = int(os.environ.get("BENCH_PCT_PARTITIONS", 2_000))


def _engine_row(make_data, params, n_partitions, n_runs=2):
    """Generic engine e2e row -> (partitions/sec, per-phase dict): the
    same warmup + min-of-n + stage-collection protocol as bench_e2e, for
    metrics beyond COUNT+SUM (VECTOR_SUM, PERCENTILE)."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import profiler

    def run(seed):
        with profiler.collect_stage_times() as stages:
            t0 = time.perf_counter()
            accountant = pdp.NaiveBudgetAccountant(EPS, DELTA)
            engine = pdp.JaxDPEngine(accountant, seed=seed)
            result = engine.aggregate(make_data(), params)
            accountant.compute_budgets()
            cols = result.to_columns()
            assert int(np.asarray(cols["keep_mask"]).sum()) > 0
            elapsed = time.perf_counter() - t0
        return elapsed, dict(stages)

    run(100)  # warmup/compile
    results = [run(i) for i in range(n_runs)]
    best_s, best_stages = min(results, key=lambda r: r[0])
    return n_partitions / best_s, _coarse_phases(best_stages, best_s)


def bench_vector_sum(n_runs=2):
    """VECTOR_SUM (k=64) through the full engine path."""
    import pipelinedp_tpu as pdp

    rng = np.random.default_rng(3)
    pk = np.minimum((VEC_PARTITIONS * rng.random(VEC_ROWS)**4).astype(
        np.int32), VEC_PARTITIONS - 1)
    pid = rng.integers(0, max(VEC_ROWS // 10, 1), VEC_ROWS,
                       dtype=np.int32)
    vec = rng.integers(1, 6, (VEC_ROWS, VEC_DIM)).astype(np.float32)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.VECTOR_SUM],
        max_partitions_contributed=L0_CAP,
        max_contributions_per_partition=LINF_CAP,
        vector_size=VEC_DIM,
        vector_max_norm=5.0,
        vector_norm_kind=pdp.NormKind.Linf)
    return _engine_row(
        lambda: pdp.ColumnarData(pid=pid, pk=pk, value=vec), params,
        VEC_PARTITIONS, n_runs=n_runs)


def bench_percentile(n_runs=2):
    """PERCENTILE(50)+PERCENTILE(90) through the streamed quantile path."""
    import pipelinedp_tpu as pdp

    rng = np.random.default_rng(4)
    pk = np.minimum((PCT_PARTITIONS * rng.random(PCT_ROWS)**4).astype(
        np.int32), PCT_PARTITIONS - 1)
    pid = rng.integers(0, max(PCT_ROWS // 10, 1), PCT_ROWS,
                       dtype=np.int32)
    # Integer grid values: the wire ships affine plane indices, so the
    # streamed row-mask kernel exercises the narrow tiled sort.
    value = rng.integers(0, 101, PCT_ROWS).astype(np.float32)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)],
        max_partitions_contributed=L0_CAP,
        max_contributions_per_partition=LINF_CAP,
        min_value=0.0,
        max_value=100.0)
    return _engine_row(
        lambda: pdp.ColumnarData(pid=pid, pk=pk, value=value), params,
        PCT_PARTITIONS, n_runs=n_runs)


def bench_utility_sweep():
    """BASELINE.md #5: 64-configuration multi-parameter utility-analysis
    sweep (COUNT+SUM+PRIVACY_ID_COUNT error grids) on the device vs the
    host numpy oracle. Returns (device_sec, host_sec)."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.analysis import (cross_partition, data_structures,
                                         per_partition)
    from pipelinedp_tpu.analysis.pre_aggregation import PreAggregates

    n_groups = int(os.environ.get("BENCH_SWEEP_GROUPS", 2_000_000))
    n_parts = int(os.environ.get("BENCH_SWEEP_PARTITIONS", 100_000))
    n_cfg = 64
    rng = np.random.default_rng(1)
    counts = rng.integers(1, 10, n_groups).astype(np.float64)
    pre = PreAggregates(
        pk_ids=rng.integers(0, n_parts, n_groups).astype(np.int32),
        counts=counts,
        sums=counts * rng.uniform(0, 5, n_groups),
        n_partitions=rng.integers(1, 50, n_groups).astype(np.int32),
        pk_vocab=None)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                 pdp.Metrics.PRIVACY_ID_COUNT],
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        max_partitions_contributed=8,
        max_contributions_per_partition=4,
        min_sum_per_partition=0.0,
        max_sum_per_partition=5.0)
    multi = data_structures.MultiParameterConfiguration(
        max_partitions_contributed=[1, 2, 3, 4, 6, 8, 12, 16] * 8,
        max_contributions_per_partition=[1, 2, 4, 8] * 16,
        min_sum_per_partition=[0.0] * n_cfg,
        max_sum_per_partition=[float(1 + i % 10) for i in range(n_cfg)])
    options = data_structures.UtilityAnalysisOptions(
        epsilon=4.0, delta=1e-5, aggregate_params=params,
        multi_param_configuration=multi)
    configs = per_partition.resolve_config_budgets(options,
                                                   public_partitions=True)
    metrics = list(params.metrics)

    def run(use_device):
        # Full sweep pipeline: error grids + fused cross-partition report
        # reduction (what parameter_tuning.tune consumes).
        t0 = time.perf_counter()
        arrays = per_partition.compute_per_partition_arrays(
            pre, configs, metrics, public_partitions=True,
            n_partitions=n_parts, use_device=use_device)
        reports = cross_partition.build_reports_with_histogram(
            arrays, metrics, public_partitions=True)
        assert len(reports) == n_cfg
        return time.perf_counter() - t0

    run(True)  # warmup/compile
    device_sec = min(run(True) for _ in range(2))
    host_sec = run(False)
    return device_sec, host_sec


def bench_serving(pid, pk, value):
    """Resident-dataset serving row (ISSUE 9): cold-query vs warm-query
    partitions/sec, queries/sec at batch widths {1, 8, 32, 256} of
    planned configs, resident-cache bytes, and per-query epilogue trace
    counts across a 3-query session.

    Cold = a fresh engine run on raw columns (paying encode + sort +
    transfer), with the session's chunk count so the comparison is
    like-for-like. Warm = the same query answered from the resident
    session: query 1 replays the retained wire (kernel only), queries
    2..3 repeat the same seed/config and ride the bound cache (epilogue
    only). The phase dict of the first warm query is the structural
    evidence that the encode/sort/transfer phase keys are GONE, not just
    small.
    """
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import profiler, serving
    from pipelinedp_tpu.ops import finalize

    params = _params()
    out = {}
    data = pdp.ColumnarData(pid=pid, pk=pk, value=value)
    t0 = time.perf_counter()
    session = serving.DatasetSession(data)
    out["ingest_s"] = round(time.perf_counter() - t0, 3)

    def cold_run(seed):
        with profiler.collect_stage_times() as stages:
            t0 = time.perf_counter()
            acc = pdp.NaiveBudgetAccountant(EPS, DELTA)
            eng = pdp.JaxDPEngine(acc, seed=seed,
                                  stream_chunks=session.n_chunks)
            res = eng.aggregate(
                pdp.ColumnarData(pid=pid, pk=pk, value=value), params)
            acc.compute_budgets()
            assert int(np.asarray(res.to_columns()["keep_mask"]).sum()) > 0
            return time.perf_counter() - t0, dict(stages)

    cold_run(100)  # warmup/compile
    cold_s, cold_stages = min((cold_run(i) for i in range(2)),
                              key=lambda r: r[0])
    out["cold_partitions_per_sec"] = round(N_PARTITIONS / cold_s, 1)
    out["cold_phases"] = _coarse_phases(cold_stages, cold_s)

    # 3-query session, same seed + config: query 1 replays the wire
    # through the kernel, queries 2..3 are bound-cache hits (epilogue +
    # host noise only — the repeat-query serving shape).
    warm_times, traces = [], []
    for q in range(3):
        before = finalize.trace_count()
        with profiler.collect_stage_times() as stages:
            t0 = time.perf_counter()
            cols = session.query(params, epsilon=EPS, delta=DELTA,
                                 seed=0).to_columns()
            assert int(np.asarray(cols["keep_mask"]).sum()) > 0
            warm_times.append(time.perf_counter() - t0)
        traces.append(finalize.trace_count() - before)
        if q == 0:
            out["warm_first_phases"] = _coarse_phases(dict(stages),
                                                      warm_times[0])
            # Amortization evidence: these phase keys must be ABSENT.
            out["warm_encode_sort_phase_keys"] = sorted(
                k for k in stages
                if k.startswith(("dp/encode", "dp/wire_",
                                 "dp/stream_slab_")))
    out["warm_first_query_partitions_per_sec"] = round(
        N_PARTITIONS / warm_times[0], 1)
    out["warm_query_partitions_per_sec"] = round(
        N_PARTITIONS / min(warm_times), 1)
    out["warm_vs_cold"] = round(cold_s / min(warm_times), 2)
    out["per_query_epilogue_traces"] = traces

    # Per-row trace (ISSUE 11): one extra (untimed) warm query exported
    # through session.query(trace_path=) — the published Chrome trace
    # shows the admission -> bound-cache/replay -> finalize span tree of
    # the repeat-query serving shape.
    try:
        from pipelinedp_tpu.obs import trace as obs_trace
        obs_trace.install(obs_trace.Tracer())
        try:
            trace_file = os.path.join(_trace_dir(), "serving_warm.json")
            session.query(params, epsilon=EPS, delta=DELTA, seed=0,
                          trace_path=trace_file).to_columns()
            out["trace_file"] = trace_file
        finally:
            obs_trace.shutdown()
    except Exception as e:  # noqa: BLE001 — tracing never fails the row
        out["trace_error"] = f"{type(e).__name__}: {e}"[:120]
    # This session's released-outcome audit slice (counts only — the
    # row is trajectory data, not the trail itself).
    out["audit_records"] = len(session.audit_trail)

    # Heavy-traffic shape (ISSUE 17): wide batches repeat a small pool
    # of distinct configs, the way production query streams repeat hot
    # queries — the planner dedupes the repeats to one replay lane each
    # and overlaps per-config finalizes with the next group's replay,
    # so queries/sec grows with width instead of shrinking.
    def batch_configs(width, base_seed):
        seeds = [base_seed + i for i in range(min(width, 4))]
        return [
            serving.QueryConfig(
                metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                epsilon=EPS, delta=DELTA,
                max_partitions_contributed=L0_CAP,
                max_contributions_per_partition=LINF_CAP,
                min_value=0.0, max_value=5.0, seed=seeds[i % len(seeds)])
            for i in range(width)
        ]

    out["batched"] = {}
    for width in (1, 8, 32, 256):
        session.query_batch(batch_configs(width, 10_000 * width))  # compile
        t0 = time.perf_counter()
        session.query_batch(batch_configs(width, 10_000 * width + 500))
        dt = time.perf_counter() - t0
        out["batched"][f"width_{width}_queries_per_sec"] = round(
            width / dt, 2)
    # Config-for-config parity evidence: the batched releases equal the
    # sequential releases bit-for-bit, under seeded device noise (the
    # secure host-noise default draws the process RNG and is
    # unreproducible by design). Sequential runs on a fresh session
    # over the same columns — the at-most-once release journal
    # (correctly) refuses re-releasing a seed within one session.
    parity_cfgs = batch_configs(4, 77_000)
    batch_outs = session.query_batch(parity_cfgs, secure_host_noise=False)
    seq_session = serving.DatasetSession(data, n_chunks=session.n_chunks)
    for cfg, got in zip(parity_cfgs, batch_outs):
        want = seq_session.query(params, epsilon=EPS, delta=DELTA,
                                 seed=cfg.seed,
                                 secure_host_noise=False).to_columns()
        for name in want:
            # NaN-aware: released count/sum hold NaN for dropped
            # partitions, and NaN != NaN under plain array_equal.
            a, b = np.asarray(want[name]), np.asarray(got[name])
            np.testing.assert_array_equal(
                a, b, err_msg=(f"batched release diverged: "
                               f"seed={cfg.seed} col={name}"))
    seq_session.close()
    out["batched"]["parity_configs_bitwise_identical"] = len(parity_cfgs)
    stats = session.stats()
    stats.pop("tenants", None)
    out["resident"] = stats
    out["planner"] = stats["planner"]
    out["serving_counters"] = serving.serving_counters()
    out["fleet"] = _bench_serving_fleet(session, params, cold_s)
    session.close()
    return out


def _bench_serving_fleet(session, params, cold_s):
    """Durable-fleet sub-row (ISSUE 10): save/reopen timings, the
    reopen-vs-cold warm-query ratio (the durability cost in the
    trajectory), and the demotion / rehydration / shedding / deadline
    counters — each machinery deliberately engaged once so a zero in
    the trajectory means a regression, not dead code."""
    import tempfile

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import runtime, serving

    out = {}
    with tempfile.TemporaryDirectory() as td:
        store = serving.SessionStore(td)
        t0 = time.perf_counter()
        session.save(store)
        out["save_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        reopened = store.open(session.name)
        out["reopen_s"] = round(time.perf_counter() - t0, 3)
        # Same seed/config as the warm loop: the spilled bound-cache
        # entry re-hydrated, so this is the repeat-query serving shape
        # after a process restart.
        t0 = time.perf_counter()
        cols = reopened.query(params, epsilon=EPS, delta=DELTA,
                              seed=0).to_columns()
        reopen_warm_s = time.perf_counter() - t0
        assert int(np.asarray(cols["keep_mask"]).sum()) > 0
        out["reopen_warm_query_partitions_per_sec"] = round(
            N_PARTITIONS / reopen_warm_s, 1)
        out["reopen_warm_vs_cold"] = round(cold_s / reopen_warm_s, 2)

        # The demotion ladder: a 1-byte fleet budget forces the
        # reopened session down device -> host -> disk when a second
        # session is admitted; querying it re-hydrates on demand.
        manager = serving.SessionManager(store, budget_bytes=1,
                                         max_inflight=1)
        manager.attach(reopened)
        rng = np.random.default_rng(7)
        small = pdp.ColumnarData(
            pid=rng.integers(0, 1000, 50_000).astype(np.int32),
            pk=rng.integers(0, 256, 50_000).astype(np.int32),
            value=rng.uniform(0, 5, 50_000).astype(np.float32))
        manager.create("fleet-b", small, n_chunks=2)
        manager.query(session.name, params, epsilon=EPS, delta=DELTA,
                      seed=1)

        # Overload: the gate is full from this thread, so the query
        # sheds typed (and its cost is the exception, not a queue).
        try:
            with manager.admission():
                manager.query(session.name, params, epsilon=EPS,
                              delta=DELTA, seed=2)
        except serving.SessionOverloadedError:
            pass

        # Deadline: a scripted 5s hang against a 1s deadline trips the
        # typed deadline error within the budget.
        injector = runtime.FaultInjector(
            [runtime.FaultSpec("hang", at_slab=0, hang_s=5.0)])
        try:
            manager.query(session.name, params, epsilon=EPS, delta=DELTA,
                          seed=3, deadline_s=1.0, fault_injector=injector)
        except serving.QueryDeadlineError:
            pass

        out["fleet_counters"] = serving.fleet_counters(manager)
        manager.remove(session.name)
        manager.close()
    return out


# Live-session row (ISSUE 15): streaming-append + continual-release
# shape. Epoch batches are sized so the row finishes in seconds while
# every append still pays the full commit path (micro-encode gate,
# fsync'd WAL record, union re-fold through the pinned chunk schedule).
LIVE_EPOCHS = int(os.environ.get("BENCH_LIVE_EPOCHS", 6))
LIVE_EPOCH_ROWS = int(os.environ.get("BENCH_LIVE_ROWS", 200_000))
LIVE_PARTITIONS = 10_000


def bench_live():
    """Live-session row (ISSUE 15): append rows/sec through the fsync'd
    WAL commit path, scheduled release windows/sec through the tenant
    at-most-once journal, the warm full-union query, and the
    live_counters() delta — so streaming ingest is tracked in the
    trajectory the way batch serving is. Deterministic host noise
    (secure_host_noise=False) keeps the row reproducible."""
    import tempfile

    from pipelinedp_tpu import serving
    from pipelinedp_tpu.serving import live as live_mod

    out = {}
    rng = np.random.default_rng(9)
    epochs = [
        (rng.integers(0, max(LIVE_EPOCH_ROWS // 10, 1), LIVE_EPOCH_ROWS,
                      dtype=np.int32),
         rng.integers(0, LIVE_PARTITIONS, LIVE_EPOCH_ROWS,
                      dtype=np.int32),
         rng.integers(1, 6, LIVE_EPOCH_ROWS).astype(np.float32))
        for _ in range(LIVE_EPOCHS)
    ]
    counters_before = live_mod.live_counters()
    with tempfile.TemporaryDirectory() as td:
        store = serving.SessionStore(td)
        session = serving.LiveDatasetSession.create(
            store=store, name="bench-live",
            public_partitions=list(range(LIVE_PARTITIONS)),
            n_chunks=4, window=serving.WindowSpec(size=2),
            secure_host_noise=False)
        session.register_tenant("bench", 1e6, 1 - 1e-9)
        t0 = time.perf_counter()
        for pid, pk, value in epochs:
            session.append(pid, pk, value)
        append_s = time.perf_counter() - t0
        out["append_rows_per_sec"] = round(
            LIVE_EPOCHS * LIVE_EPOCH_ROWS / append_s, 1)
        out["append_epochs_per_sec"] = round(LIVE_EPOCHS / append_s, 2)
        sched = session.release_schedule(
            "bench-sched", _params(), epsilon=EPS, delta=DELTA,
            tenant="bench", base_seed=17, secure_host_noise=False)
        due = len(sched.due_windows())
        t0 = time.perf_counter()
        records = sched.tick()
        tick_s = time.perf_counter() - t0
        assert len(records) == due and due > 0
        assert all(r["outcome"] == "released" for r in records)
        out["windows_released"] = due
        out["release_windows_per_sec"] = round(due / tick_s, 2)
        # The warm full-union query a live session serves between
        # scheduled releases (the folded union wire is resident).
        t0 = time.perf_counter()
        cols = session.query(_params(), epsilon=EPS, delta=DELTA,
                             seed=5, secure_host_noise=False).to_columns()
        union_s = time.perf_counter() - t0
        assert int(np.asarray(cols["keep_mask"]).sum()) > 0
        out["union_query_partitions_per_sec"] = round(
            LIVE_PARTITIONS / union_s, 1)
        out["status"] = session.live_status()
        sched.close()
        session.close()
    after = live_mod.live_counters()
    out["counters"] = {k: after[k] - counters_before[k] for k in after}
    return out


# Fleet-failover row (ISSUE 19): sized so the row finishes in seconds
# while the follower still replays every epoch digest-verified and the
# promotion pays the real lease-takeover + writable-reopen path.
FLEET_EPOCHS = int(os.environ.get("BENCH_FLEET_EPOCHS", 3))
FLEET_EPOCH_ROWS = int(os.environ.get("BENCH_FLEET_ROWS", 50_000))
FLEET_PARTITIONS = 2_000


def bench_fleet():
    """Fleet-failover row (ISSUE 19): follower replication lag over a
    digest-verified WAL tail, hedged warm-read hit rate through the
    router, and the failover headline — seconds from a dead primary to
    a promoted follower that has taken the lease, reopened writable,
    and committed its first append (``failovers_per_sec`` feeds the
    regress gate as its higher-is-better reciprocal)."""
    import tempfile

    from pipelinedp_tpu import serving
    from pipelinedp_tpu.runtime import watchdog as watchdog_mod
    from pipelinedp_tpu.serving import fleet as fleet_mod

    out = {}
    rng = np.random.default_rng(13)
    epochs = [
        (rng.integers(0, max(FLEET_EPOCH_ROWS // 10, 1),
                      FLEET_EPOCH_ROWS, dtype=np.int32),
         rng.integers(0, FLEET_PARTITIONS, FLEET_EPOCH_ROWS,
                      dtype=np.int32),
         rng.integers(1, 6, FLEET_EPOCH_ROWS).astype(np.float32))
        for _ in range(FLEET_EPOCHS + 1)
    ]
    with tempfile.TemporaryDirectory() as td:
        store = serving.SessionStore(td)
        primary = serving.LiveDatasetSession.create(
            store=store, name="bench-fleet",
            public_partitions=list(range(FLEET_PARTITIONS)),
            n_chunks=4, window=serving.WindowSpec(size=1),
            secure_host_noise=False)
        for pid, pk, value in epochs[:FLEET_EPOCHS]:
            primary.append(pid, pk, value)
        before = fleet_mod.fleet_counters()
        t0 = time.perf_counter()
        follower = fleet_mod.FollowerSession(store, "bench-fleet")
        while follower.replication_lag()["records_behind"] > 0:
            follower.poll()
        out["follower_attach_s"] = round(time.perf_counter() - t0, 4)
        out["replication"] = follower.replication_lag()
        # Hedged warm reads: a burnt deadline routes the tenantless
        # read to the replica instead of betting on the primary.
        router = fleet_mod.FleetRouter()
        router.add_host("primary", primary)
        router.add_follower(follower)
        t0 = time.perf_counter()
        n_reads = 4
        for i in range(n_reads):
            router.query(_params(), shard_key=i,
                         deadline=watchdog_mod.Deadline.after(0.0),
                         epsilon=EPS, delta=DELTA, seed=100 + i,
                         secure_host_noise=False)
        hedge_s = time.perf_counter() - t0
        counters = fleet_mod.fleet_counters()
        hedged = counters["hedged_reads"] - before["hedged_reads"]
        out["hedged_reads"] = hedged
        out["hedged_hit_rate"] = round(
            (counters["hedged_hits"] - before["hedged_hits"])
            / max(hedged, 1), 3)
        out["hedged_reads_per_sec"] = round(n_reads / hedge_s, 2)
        # Failover: the primary goes away; the follower takes the
        # lease (fencing token bump), reopens writable, and proves the
        # new primary with one committed append.
        primary.close()
        t0 = time.perf_counter()
        promoted = follower.promote()
        result = promoted.append(*epochs[FLEET_EPOCHS])
        failover_s = time.perf_counter() - t0
        assert result.committed
        out["failover_time_s"] = round(failover_s, 4)
        out["failovers_per_sec"] = round(1.0 / failover_s, 3)
        out["lease"] = promoted.lease.status()
        final = fleet_mod.fleet_counters()
        out["counters"] = {k: final[k] - before[k] for k in final}
        promoted.close()
    return out


def bench_cpu_baseline() -> float:
    import pipelinedp_tpu as pdp

    rng = np.random.default_rng(0)
    pk = np.minimum((CPU_PARTITIONS * rng.random(CPU_ROWS)**4).astype(int),
                    CPU_PARTITIONS - 1)
    rows = list(
        zip(
            rng.integers(0, max(CPU_ROWS // 10, 1), CPU_ROWS).tolist(),
            pk.tolist(),
            rng.uniform(0, 5, CPU_ROWS).tolist(),
        ))
    params = _params()
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    t0 = time.perf_counter()
    accountant = pdp.NaiveBudgetAccountant(EPS, DELTA)
    engine = pdp.DPEngine(accountant, pdp.LocalBackend())
    result = engine.aggregate(rows, params, extractors)
    accountant.compute_budgets()
    n_out = sum(1 for _ in result)
    elapsed = time.perf_counter() - t0
    assert n_out > 0
    return CPU_PARTITIONS / elapsed


def _metrics_snapshot():
    """The obs metrics-registry JSON snapshot (histograms arrive as
    cumulative bucket counts + sum + count, the Prometheus shape)."""
    from pipelinedp_tpu.obs import metrics as obs_metrics

    return obs_metrics.default_registry().snapshot()


def _resilience_counters():
    """Runtime resilience counters (retries, degradations, resumes,
    checkpoint_bytes, native_fallbacks, watchdog_timeouts,
    hangs_detected, journal_recoveries, journal_bytes —
    pipelinedp_tpu/runtime/). All keys always present; a clean run
    reports zeros, and a run that had to retry/degrade/resume — or had a
    hang cut off by the dispatch watchdog, or recovered a durable
    release journal — shows it here instead of hiding it in the timings,
    so the chaos trajectory is tracked like perf."""
    from pipelinedp_tpu import runtime

    return runtime.resilience_counters()


def main():
    cpu_pps = bench_cpu_baseline()
    steady = {}
    try:
        pid, pk, value = _host_columns()
        # Steady-state rows run FIRST (cold process caches) so the
        # first-call column genuinely includes every compile; the headline
        # e2e below then starts warm, as before (warmup + min-of-3).
        steady["e2e_steady"] = bench_e2e_steady(pid, pk, value)
        steady["e2e_device_noise_steady"] = bench_e2e_steady(
            pid, pk, value, n_calls=3, secure_host_noise=False)
    except Exception as e:  # noqa: BLE001
        steady["e2e_steady_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        e2e_pps, e2e_phases = bench_e2e(pid, pk, value)
        kernel = bench_kernel(pid, pk, value)
        kernel_pps = kernel["partitions_per_sec"]
    except Exception as e:  # noqa: BLE001 — report the failure, don't crash
        print(json.dumps({
            "metric": "DP-aggregated partitions/sec (COUNT+SUM, 1M keys)",
            "value": 0.0,
            "unit": "partitions/sec",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300],
            "resilience": _resilience_counters(),
            **steady,
        }))
        sys.exit(0)
    extra = dict(steady)
    try:
        vec_pps, vec_phases = bench_vector_sum()
        extra["vector_sum_k64_partitions_per_sec"] = round(vec_pps, 1)
        extra["vector_sum_k64_phases"] = vec_phases
    except Exception as e:  # noqa: BLE001
        extra["vector_sum_k64_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        pct_pps, pct_phases = bench_percentile()
        extra["percentile_partitions_per_sec"] = round(pct_pps, 1)
        extra["percentile_phases"] = pct_phases
    except Exception as e:  # noqa: BLE001
        extra["percentile_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        # Round-10 e2e A/B: the same engine path with the group stage
        # forced to the tiled sort vs the sortless hash bins — the e2e
        # twin of the kernel A/B (ROADMAP item 3's measurement ask).
        # The headline e2e row above rides "auto", which resolves to
        # hash for this COUNT+SUM shape under the exactness gate.
        from pipelinedp_tpu import profiler as _prof
        from pipelinedp_tpu.ops import columnar as _columnar
        before = {
            k: _prof.event_count(k)
            for k in (_columnar.EVENT_HASH_PASSES,
                      _columnar.EVENT_HASH_OCCUPANCY,
                      _columnar.EVENT_HASH_DEMOTIONS)
        }
        hash_pps, _ = bench_e2e(pid, pk, value, n_runs=2,
                                segment_sort="hash")
        counters = {
            k.rsplit("/", 1)[1]: _prof.event_count(k) - before[k]
            for k in before
        }
        tiled_pps, _ = bench_e2e(pid, pk, value, n_runs=2,
                                 segment_sort=True)
        extra["e2e_segment_sort_ab"] = {
            "hash_partitions_per_sec": round(hash_pps, 1),
            "tiled_partitions_per_sec": round(tiled_pps, 1),
            "hash_vs_tiled": round(hash_pps / max(tiled_pps, 1e-9), 3),
            "hash_counters": counters,
        }
    except Exception as e:  # noqa: BLE001
        extra["e2e_segment_sort_ab_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        # De-confounding row (round-5 advisor): the same shape with
        # uniform CONTINUOUS values, which defeat the affine-integer plane
        # encoding and ship raw float32 — so codec gains (compressible
        # star ratings, headline row) and workload compressibility are
        # reported separately across rounds.
        rng = np.random.default_rng(7)
        uvalue = rng.uniform(0.0, 5.0, N_ROWS).astype(np.float32)
        uniform_pps, uniform_phases = bench_e2e(pid, pk, uvalue, n_runs=2)
        extra["e2e_uniform_float_partitions_per_sec"] = round(uniform_pps, 1)
        extra["e2e_uniform_float_vs_baseline"] = round(
            uniform_pps / cpu_pps, 2)
        extra["e2e_uniform_float_phases"] = uniform_phases
        del uvalue
    except Exception as e:  # noqa: BLE001
        extra["e2e_uniform_float_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        # Serving row (ISSUE 9): warm queries must drop the
        # encode/sort/transfer phase keys entirely and amortize to >=5x
        # the cold-query throughput; the trajectory JSON tracks it like
        # COUNT+SUM.
        extra["serving"] = bench_serving(pid, pk, value)
    except Exception as e:  # noqa: BLE001
        extra["serving_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        # Live-session row (ISSUE 15): streaming append throughput and
        # scheduled windowed releases, tracked like batch serving.
        extra["live"] = bench_live()
    except Exception as e:  # noqa: BLE001
        extra["live_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        # Fleet-failover row (ISSUE 19): follower replication, hedged
        # warm reads, and the promote-to-first-commit failover time.
        extra["fleet"] = bench_fleet()
    except Exception as e:  # noqa: BLE001
        extra["fleet_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        sweep_dev_sec, sweep_host_sec = bench_utility_sweep()
        extra.update({
            # BASELINE.md #5: 64-config multi-parameter sweep, 2M groups.
            "utility_sweep_64cfg_sec": round(sweep_dev_sec, 3),
            "utility_sweep_host_sec": round(sweep_host_sec, 3),
            "utility_sweep_vs_host": round(sweep_host_sec / sweep_dev_sec,
                                           2),
        })
    except Exception as e:  # noqa: BLE001
        extra["utility_sweep_error"] = f"{type(e).__name__}: {e}"[:200]
    from pipelinedp_tpu.native import loader
    from pipelinedp_tpu.ops import streaming as streaming_mod

    print(json.dumps({
        "metric": "DP-aggregated partitions/sec (COUNT+SUM, 1M keys), "
                  "end-to-end through JaxDPEngine.aggregate",
        # The workload-shape signature the bench regression gate
        # (obs/regress.py) groups comparable rounds by — the resolved
        # BENCH_* knobs, explicit so the gate no longer has to parse
        # them out of the recorded command line.
        "shape": {
            "BENCH_ROWS": str(N_ROWS),
            "BENCH_PARTITIONS": str(N_PARTITIONS),
            "BENCH_CPU_ROWS": str(CPU_ROWS),
            "BENCH_VECTOR_ROWS": str(VEC_ROWS),
            "BENCH_PCT_ROWS": str(PCT_ROWS),
            "BENCH_PCT_PARTITIONS": str(PCT_PARTITIONS),
            "BENCH_LIVE_EPOCHS": str(LIVE_EPOCHS),
            "BENCH_LIVE_ROWS": str(LIVE_EPOCH_ROWS),
            "BENCH_SWEEP_GROUPS": str(
                os.environ.get("BENCH_SWEEP_GROUPS", 2_000_000)),
            "BENCH_SWEEP_PARTITIONS": str(
                os.environ.get("BENCH_SWEEP_PARTITIONS", 100_000)),
        },
        "value": round(e2e_pps, 1),
        "unit": "partitions/sec",
        "vs_baseline": round(e2e_pps / cpu_pps, 2),
        "kernel_partitions_per_sec": round(kernel_pps, 1),
        "kernel_vs_baseline": round(kernel_pps / cpu_pps, 2),
        # Round-10 tentpole A/B on the kernel-resident row: general (the
        # historical ~305k floor), packed (rounds 6-8 wire kernel), tiled
        # (round-9 segment-local sort), hash (round-10 sortless group
        # stage, the new auto default under the exactness gate) — with
        # the modeled ops/sort_* counters per configuration.
        "kernel_sort": kernel,
        "cpu_baseline_partitions_per_sec": round(cpu_pps, 1),
        "e2e_phases": e2e_phases,
        # Encode/pipeline tuning in effect (README "Tuning knobs"):
        # encode_threads 0 = auto (hardware concurrency, capped 16).
        "encode_threads": loader.encode_threads(),
        "host_cores": os.cpu_count(),
        "prefetch_depth": streaming_mod.prefetch_depth(),
        "resilience": _resilience_counters(),
        # The full typed-metrics registry snapshot (ISSUE 11): every
        # counter/gauge/histogram the run populated, plus the legacy
        # event namespace — the same storage `to_prometheus()` scrapes.
        "metrics": _metrics_snapshot(),
        **extra,
    }))


if __name__ == "__main__":
    main()
