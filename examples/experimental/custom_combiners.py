"""Custom combiners: user-defined DP aggregations on both engines.

Role of the reference's examples/experimental/custom_combiners.py: shows a
user-written combiner (here a DP sum-of-squares — a metric the framework
does not ship) running through the standard engine machinery: budget
accounting, contribution bounding, partition selection. The same combiner
runs on the host engine (DPEngine + LocalBackend) and on the columnar
engine (JaxDPEngine, which bounds contributions on the accelerator and
evaluates the combiner logic on host).

Custom combiners are experimental: the combiner owns its DP mechanism, so
a bug in compute_metrics is a privacy bug.

    python custom_combiners.py
"""

import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import pipelinedp_tpu as pdp
from pipelinedp_tpu import dp_computations


class SumOfSquaresCombiner(pdp.CustomCombiner):
    """DP sum of squared values per partition.

    Sensitivity: each privacy unit contributes at most
    max_contributions_per_partition values of magnitude <= max_value to a
    partition, and touches at most max_partitions_contributed partitions —
    so the L1 sensitivity is l0 * linf * max_value**2, and a Laplace
    mechanism calibrated to it makes the released value eps-DP.
    """

    def __init__(self, max_value: float):
        self._max_value = max_value

    def request_budget(self, budget_accountant):
        # Called during graph construction; the spec resolves when the
        # caller runs budget_accountant.compute_budgets().
        self._spec = budget_accountant.request_budget(
            pdp.MechanismType.LAPLACE)

    def create_accumulator(self, values):
        clipped = np.clip(np.asarray(values, dtype=np.float64),
                          -self._max_value, self._max_value)
        return float(np.sum(clipped * clipped))

    def merge_accumulators(self, a, b):
        return a + b

    def compute_metrics(self, acc):
        p = self._aggregate_params
        sensitivities = dp_computations.Sensitivities(
            l0=p.max_partitions_contributed,
            linf=p.max_contributions_per_partition * self._max_value**2)
        mechanism = dp_computations.create_additive_mechanism(
            self._spec, sensitivities)
        return {"sum_squares": mechanism.add_noise(acc)}

    def explain_computation(self):
        return ("Custom combiner: DP sum of squares "
                "(Laplace, L1 sensitivity l0*linf*max_value^2)")


def synthesize_rows(n_users=2_000, n_days=7, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for user in range(n_users):
        for day in rng.choice(n_days, size=rng.integers(1, 4),
                              replace=False):
            rows.append((user, int(day), float(rng.normal(0.0, 2.0))))
    return rows


def main():
    rows = synthesize_rows()
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    params = pdp.AggregateParams(
        metrics=None,
        custom_combiners=[SumOfSquaresCombiner(max_value=4.0)],
        max_partitions_contributed=2,
        max_contributions_per_partition=2)

    for name, make_engine in (
        ("DPEngine + LocalBackend",
         lambda acc: pdp.DPEngine(acc, pdp.LocalBackend())),
        ("JaxDPEngine (columnar)", lambda acc: pdp.JaxDPEngine(acc)),
    ):
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = make_engine(accountant)
        result = engine.aggregate(rows, params, extractors)
        accountant.compute_budgets()
        print(f"-- {name}")
        for day, metrics in sorted(result):
            print(f"  day {day}: sum_squares={metrics[0]['sum_squares']:.1f}")


if __name__ == "__main__":
    main()
