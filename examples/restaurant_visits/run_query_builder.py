"""DP restaurant-visit statistics through the QueryBuilder API.

Role of the reference's examples/restaurant_visits demos, using the
high-level frame API instead of hand-built AggregateParams: visits per
weekday and money spent, with public weekday keys.

    python run_query_builder.py
"""

import numpy as np
import pandas as pd

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import pipelinedp_tpu as pdp
from pipelinedp_tpu import dataframes


def synthesize_visits(n_visitors=5_000, seed=0) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    rows = []
    for visitor in range(n_visitors):
        # Each visitor eats out on a few random weekdays.
        for day in rng.choice(7, size=rng.integers(1, 5), replace=False):
            rows.append((visitor, int(day), float(rng.uniform(5, 40))))
    return pd.DataFrame(rows, columns=["visitor_id", "day", "spent_money"])


def main():
    df = synthesize_visits()

    query = (pdp.QueryBuilder(df, "visitor_id").groupby(
        "day",
        max_groups_contributed=3,
        max_contributions_per_group=1,
        public_keys=list(range(7))).count().sum(
            "spent_money", min_value=0,
            max_value=40).mean("spent_money").build_query())

    result = query.run_query(dataframes.Budget(epsilon=1, delta=1e-6),
                             noise_kind=pdp.NoiseKind.GAUSSIAN)
    print(result.sort_values("day").to_string(index=False))


if __name__ == "__main__":
    main()
