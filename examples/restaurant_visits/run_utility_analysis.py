"""Utility analysis of DP parameters on restaurant-visit data.

Role of the reference's examples/restaurant_visits utility-analysis demo:
evaluate several candidate contribution-bound configurations in a single
vectorized sweep and report the expected errors of each.

    python run_utility_analysis.py
"""

import numpy as np

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import pipelinedp_tpu as pdp
from pipelinedp_tpu import analysis


def synthesize_rows(n_visitors=5_000, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for visitor in range(n_visitors):
        for day in rng.choice(7, size=rng.integers(1, 5), replace=False):
            rows.append((visitor, int(day), float(rng.uniform(5, 40))))
    return rows


def main():
    rows = synthesize_rows()
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])

    # Four candidate configurations analyzed at once (one vectorized pass).
    candidates = analysis.MultiParameterConfiguration(
        max_partitions_contributed=[1, 2, 3, 4],
        max_contributions_per_partition=[1, 1, 2, 2])
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT],
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        max_partitions_contributed=1,
        max_contributions_per_partition=1)
    options = analysis.UtilityAnalysisOptions(
        epsilon=1,
        delta=1e-6,
        aggregate_params=params,
        multi_param_configuration=candidates)

    reports, _ = analysis.perform_utility_analysis(
        rows, options=options, data_extractors=extractors)

    for i, report in enumerate(reports):
        err = report.metric_errors[0].absolute_error
        kept = report.partitions_info.num_non_public_partitions or 0
        print(f"config {i}: l0={candidates.max_partitions_contributed[i]} "
              f"linf={candidates.max_contributions_per_partition[i]} "
              f"count RMSE={err.rmse:.2f} "
              f"kept_partitions~{report.partitions_info.kept_partitions.mean:.1f}"
              f"/{kept}")


if __name__ == "__main__":
    main()
