"""Utility analysis on pre-aggregated data.

Role of the reference's examples/restaurant_visits/run_on_preaggregated_data
.py: when the same dataset is analyzed repeatedly (e.g. parameter sweeps on
different days), pre-aggregating the raw rows once into
(partition_key, (count, sum, n_partitions)) records makes every subsequent
analysis run cheap — the per-row pass happens once.

    python run_on_preaggregated_data.py
"""

import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import pipelinedp_tpu as pdp
from pipelinedp_tpu import analysis


def synthesize_rows(n_visitors=5_000, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for visitor in range(n_visitors):
        for day in rng.choice(7, size=rng.integers(1, 5), replace=False):
            rows.append((visitor, int(day), float(rng.uniform(5, 40))))
    return rows


def main():
    rows = synthesize_rows()
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])

    # Step 1 (once): raw rows -> (partition_key, (count, sum, n_partitions))
    # records, one per (visitor, day) pair. This is the only pass that
    # touches privacy ids; everything below consumes the compact records.
    preaggregated = analysis.preaggregate(rows, data_extractors=extractors)
    print(f"{len(rows)} raw rows -> {len(preaggregated)} pre-aggregated "
          f"records")

    # Step 2 (repeatable): analyze candidate configurations straight from
    # the pre-aggregated records via PreAggregateExtractors.
    pre_extractors = pdp.PreAggregateExtractors(
        partition_extractor=lambda row: row[0],
        preaggregate_extractor=lambda row: row[1])
    candidates = analysis.MultiParameterConfiguration(
        max_partitions_contributed=[1, 2, 4],
        max_contributions_per_partition=[1, 2, 2])
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=1,
        max_contributions_per_partition=1)
    options = analysis.UtilityAnalysisOptions(
        epsilon=1,
        delta=1e-6,
        aggregate_params=params,
        multi_param_configuration=candidates,
        pre_aggregated_data=True)
    reports, _ = analysis.perform_utility_analysis(
        preaggregated, options=options, data_extractors=pre_extractors)

    for i, report in enumerate(reports):
        err = report.metric_errors[0].absolute_error
        print(f"config {i}: l0={candidates.max_partitions_contributed[i]} "
              f"linf={candidates.max_contributions_per_partition[i]} "
              f"count RMSE={err.rmse:.2f}")


if __name__ == "__main__":
    main()
