"""DP movie-view statistics on the host engine (correctness oracle).

Mirror of the reference's run_without_frameworks.py:101-113: the same
aggregation as run_on_tpu.py, executed by DPEngine over the lazy
LocalBackend. Useful for small data and for diffing against the TPU path.

    python run_local.py [--input_file=...] [--output_file=...]
"""

import argparse

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import pipelinedp_tpu as pdp

from common_utils import parse_file, synthesize_views, write_to_file


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--input_file", default=None)
    parser.add_argument("--output_file", default=None)
    parser.add_argument("--multiproc", action="store_true",
                        help="Use the multi-process local backend")
    args = parser.parse_args()

    # Small synthetic default: the host engine is the small-data /
    # correctness path (use run_on_tpu.py for scale).
    movie_views = (parse_file(args.input_file) if args.input_file else
                   synthesize_views(n_rows=20_000, n_movies=200,
                                    n_users=5_000))

    backend = (pdp.MultiProcLocalBackend()
               if args.multiproc else pdp.LocalBackend())
    budget_accountant = pdp.NaiveBudgetAccountant(total_epsilon=1,
                                                  total_delta=1e-6)
    dp_engine = pdp.DPEngine(budget_accountant, backend)

    params = pdp.AggregateParams(
        metrics=[
            pdp.Metrics.COUNT,
            pdp.Metrics.SUM,
            pdp.Metrics.PRIVACY_ID_COUNT,
            pdp.Metrics.PERCENTILE(50),
            pdp.Metrics.PERCENTILE(90),
        ],
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        max_partitions_contributed=2,
        max_contributions_per_partition=1,
        min_value=1,
        max_value=5)

    data_extractors = pdp.DataExtractors(
        partition_extractor=lambda mv: mv.movie_id,
        privacy_id_extractor=lambda mv: mv.user_id,
        value_extractor=lambda mv: mv.rating)

    explain_computation_report = pdp.ExplainComputationReport()
    dp_result = dp_engine.aggregate(
        movie_views,
        params,
        data_extractors,
        out_explain_computation_report=explain_computation_report)
    budget_accountant.compute_budgets()

    print(explain_computation_report.text())

    dp_result = list(dp_result)
    print(f"{len(dp_result)} partitions released")
    for movie, stats in dp_result[:5]:
        print(movie, stats)
    if args.output_file:
        write_to_file(dp_result, args.output_file)


if __name__ == "__main__":
    main()
