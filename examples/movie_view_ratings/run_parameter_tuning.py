"""DP parameter tuning for the movie-view workload.

Role of the reference's examples/movie_view_ratings DP-parameter-tuning
variant: compute dataset contribution histograms, sweep candidate
(max_partitions_contributed, max_contributions_per_partition) bounds in one
vectorized utility analysis, then run the recommended configuration.

    python run_parameter_tuning.py [--input_file=...]
"""

import argparse

import numpy as np

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import pipelinedp_tpu as pdp
from pipelinedp_tpu import analysis
from pipelinedp_tpu.dataset_histograms import computing_histograms

from common_utils import parse_file, synthesize_views


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--input_file", default=None)
    args = parser.parse_args()

    movie_views = (parse_file(args.input_file) if args.input_file else
                   synthesize_views(n_rows=50_000, n_movies=500,
                                    n_users=10_000))

    data_extractors = pdp.DataExtractors(
        partition_extractor=lambda mv: mv.movie_id,
        privacy_id_extractor=lambda mv: mv.user_id,
        value_extractor=lambda mv: mv.rating)

    # 1. Contribution-structure histograms of the dataset (one pass).
    # Lazy pipeline output: one DatasetHistograms element.
    histograms = list(
        computing_histograms.compute_dataset_histograms(
            movie_views, data_extractors, backend=pdp.LocalBackend()))[0]

    # 2. Tune: candidate grid from the histograms, one vectorized sweep.
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT],
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        max_partitions_contributed=1,  # placeholders — tuned below
        max_contributions_per_partition=1)
    tune_options = analysis.TuneOptions(
        epsilon=1,
        delta=1e-6,
        aggregate_params=params,
        function_to_minimize=analysis.MinimizingFunction.ABSOLUTE_ERROR,
        parameters_to_tune=analysis.ParametersToTune(
            max_partitions_contributed=True,
            max_contributions_per_partition=True),
        number_of_parameter_candidates=64)
    tune_result, _ = analysis.tune(movie_views,
                                   contribution_histograms=histograms,
                                   options=tune_options,
                                   data_extractors=data_extractors)

    best = tune_result.index_best
    candidates = tune_result.utility_analysis_parameters
    l0 = candidates.max_partitions_contributed[best]
    linf = candidates.max_contributions_per_partition[best]
    print(f"Tuned bounds: max_partitions_contributed={l0}, "
          f"max_contributions_per_partition={linf}")
    report = tune_result.utility_reports[best]
    rmse = report.metric_errors[0].absolute_error.rmse
    print(f"Expected COUNT RMSE at the tuned bounds: {rmse:.2f}")

    # 3. Run the DP aggregation with the tuned bounds on the TPU engine.
    budget_accountant = pdp.NaiveBudgetAccountant(total_epsilon=1,
                                                  total_delta=1e-6)
    engine = pdp.JaxDPEngine(budget_accountant)
    user_id = np.fromiter((v.user_id for v in movie_views), dtype=np.int64)
    movie_id = np.fromiter((v.movie_id for v in movie_views), dtype=np.int64)
    rating = np.fromiter((v.rating for v in movie_views), dtype=np.int64)
    run_params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT],
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        max_partitions_contributed=int(l0),
        max_contributions_per_partition=int(linf))
    dp_result = engine.aggregate(
        pdp.ColumnarData(pid=user_id, pk=movie_id, value=rating), run_params)
    budget_accountant.compute_budgets()
    rows = list(dp_result)
    print(f"{len(rows)} partitions released with tuned parameters")
    for movie, stats in rows[:5]:
        print(movie, stats)


if __name__ == "__main__":
    main()
