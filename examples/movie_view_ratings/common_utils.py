"""Shared helpers for the movie-view-ratings examples.

Data loading / synthesis only — all privacy logic lives in the example
scripts. Input format is the Netflix-prize text layout the reference
examples consume (movie_view_ratings/common_utils.py: "movie_id:" header
lines followed by "user_id,rating,date" lines); when no input file is given
the examples synthesize a workload of the same shape so they run anywhere.
"""

import dataclasses

import numpy as np


@dataclasses.dataclass
class MovieView:
    user_id: int
    movie_id: int
    rating: int


def parse_file(filename):
    """Parses the Netflix-prize text format into MovieView rows."""
    views = []
    movie_id = None
    with open(filename) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line[-1] == ":":
                movie_id = int(line[:-1])
            else:
                parts = line.split(",")
                views.append(
                    MovieView(user_id=int(parts[0]),
                              movie_id=movie_id,
                              rating=int(parts[1])))
    return views


def synthesize_columns(n_rows=2_000_000, n_movies=10_000, n_users=200_000,
                       seed=0):
    """Synthetic movie-view columns with a Zipf-ish popularity head.

    Returns (user_id, movie_id, rating) int numpy columns — the columnar
    shape the TPU engine ingests directly.
    """
    rng = np.random.default_rng(seed)
    movie_id = np.minimum((n_movies * rng.random(n_rows)**3).astype(np.int64),
                          n_movies - 1)
    user_id = rng.integers(0, n_users, n_rows)
    rating = rng.integers(1, 6, n_rows)
    return user_id, movie_id, rating


def synthesize_views(n_rows=200_000, n_movies=1_000, n_users=20_000, seed=0):
    """Synthetic MovieView rows (the per-row shape the host engine eats)."""
    user_id, movie_id, rating = synthesize_columns(n_rows, n_movies, n_users,
                                                   seed)
    return [
        MovieView(int(u), int(m), int(r))
        for u, m, r in zip(user_id, movie_id, rating)
    ]


def write_to_file(rows, filename):
    with open(filename, "w") as f:
        for row in rows:
            f.write(f"{row}\n")
