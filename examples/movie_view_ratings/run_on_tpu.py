"""DP movie-view statistics on the TPU-native columnar engine.

The flagship demo (role of the reference's
examples/movie_view_ratings/run_without_frameworks.py:101-113, re-targeted
at JaxDPEngine): COUNT, SUM, PRIVACY_ID_COUNT and rating percentiles per
movie, with private partition selection, computed as fused columnar kernels
on the accelerator.

    python run_on_tpu.py                       # synthetic data
    python run_on_tpu.py --input_file=combined_data_1.txt \
        --output_file=out.txt                  # Netflix-prize format
"""

import argparse

import numpy as np

import os
import sys

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

import pipelinedp_tpu as pdp

from common_utils import parse_file, synthesize_columns, write_to_file


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--input_file", default=None,
                        help="Netflix-prize format input; synthetic if unset")
    parser.add_argument("--output_file", default=None)
    parser.add_argument("--pld_accounting", action="store_true",
                        help="PLD accounting instead of naive composition "
                             "(implies public partitions 0..99: PLD does "
                             "not support private partition selection)")
    parser.add_argument("--pre_threshold", type=int, default=None)
    parser.add_argument("--public_partitions", action="store_true",
                        help="Treat movies 0..99 as publicly known keys")
    args = parser.parse_args()

    # Load the data as columns — the TPU engine ingests columnar numpy
    # arrays directly (no per-row objects on the hot path).
    if args.input_file:
        views = parse_file(args.input_file)
        user_id = np.fromiter((v.user_id for v in views), dtype=np.int64)
        movie_id = np.fromiter((v.movie_id for v in views), dtype=np.int64)
        rating = np.fromiter((v.rating for v in views), dtype=np.int64)
    else:
        # 2k movies keeps the demo fast; the percentile metrics scale to
        # millions of movies too (the engine blocks the [movies,
        # tree-leaves] histograms over the device budget automatically).
        user_id, movie_id, rating = synthesize_columns(n_movies=2_000)
    data = pdp.ColumnarData(pid=user_id, pk=movie_id, value=rating)

    if args.pld_accounting:
        budget_accountant = pdp.PLDBudgetAccountant(total_epsilon=1,
                                                    total_delta=1e-6)
    else:
        budget_accountant = pdp.NaiveBudgetAccountant(total_epsilon=1,
                                                      total_delta=1e-6)

    engine = pdp.JaxDPEngine(budget_accountant)

    metrics = [
        pdp.Metrics.COUNT,
        pdp.Metrics.SUM,
        pdp.Metrics.PRIVACY_ID_COUNT,
    ]
    if not args.pld_accounting:
        # PLD accounting does not yet support PERCENTILE computations
        # (parity with the reference example's caveat).
        metrics.extend([
            pdp.Metrics.PERCENTILE(50),
            pdp.Metrics.PERCENTILE(90),
            pdp.Metrics.PERCENTILE(99),
        ])
    params = pdp.AggregateParams(
        metrics=metrics,
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        # One user rates at most 2 movies, once each, ratings in [1, 5].
        max_partitions_contributed=2,
        max_contributions_per_partition=1,
        min_value=1,
        max_value=5)
    if args.pre_threshold:
        params.pre_threshold = args.pre_threshold

    # PLD accounting does not support private partition selection (parity
    # with the reference engine, dp_engine.py:529-531) — the reference
    # example likewise always passes public partitions.
    use_public = args.public_partitions or args.pld_accounting
    if args.pld_accounting and not args.public_partitions:
        print("note: PLD accounting requires public partitions; using "
              "movies 0..99 as publicly known keys")
    public_partitions = list(range(100)) if use_public else None

    explain_computation_report = pdp.ExplainComputationReport()
    # Lazy: the result materializes only after compute_budgets().
    dp_result = engine.aggregate(
        data,
        params,
        public_partitions=public_partitions,
        out_explain_computation_report=explain_computation_report)
    budget_accountant.compute_budgets()

    print(explain_computation_report.text())

    rows = list(dp_result)
    print(f"{len(rows)} partitions released")
    for movie, stats in rows[:5]:
        print(movie, stats)
    if args.output_file:
        write_to_file(rows, args.output_file)


if __name__ == "__main__":
    main()
