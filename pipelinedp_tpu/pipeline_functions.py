"""Composite backend-agnostic helpers built from PipelineBackend primitives.

Parity: pipeline_dp/pipeline_functions.py (key_by :23, size :30,
collect_to_container :41, min_max_elements :102).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Type

from pipelinedp_tpu.backends import base


def key_by(backend: base.PipelineBackend, col, key_extractor: Callable,
           stage_name: str):
    """element -> (key_extractor(element), element)."""
    return backend.map(col, lambda el: (key_extractor(el), el),
                       f"{stage_name}: key by extractor")


def size(backend: base.PipelineBackend, col, stage_name: str):
    """Returns a 1-element collection holding the input's size."""
    keyed = backend.map(col, lambda _: None, f"{stage_name}: to common key")
    counted = backend.count_per_element(keyed, f"{stage_name}: count")
    return backend.values(counted, f"{stage_name}: drop key")


def collect_to_container(backend: base.PipelineBackend, cols: Dict[str, Any],
                         container_class: Type, stage_name: str):
    """Packs several 1-element collections into one container instance.

    ``cols`` maps constructor-argument names to 1-element collections; the
    result is a 1-element collection holding
    ``container_class(**{name: value})``.
    """

    def keyer(key):
        return lambda _: key

    keyed = [
        key_by(backend, col, keyer(key), f"{stage_name}: key inputs")
        for key, col in cols.items()
    ]
    flat = backend.flatten(keyed, f"{stage_name}: flatten inputs")
    as_list = backend.to_list(flat, f"{stage_name}: collect to list")
    as_dict = backend.map(as_list, dict, f"{stage_name}: list to dict")
    return backend.map(as_dict, lambda d: container_class(**d),
                       f"{stage_name}: construct container")


def min_max_elements(backend: base.PipelineBackend, col, stage_name: str):
    """Returns a 1-element collection with (min, max) of the input."""
    keyed = backend.map(col, lambda x: (None, (x, x)),
                        f"{stage_name}: key by dummy key")
    reduced = backend.reduce_per_key(
        keyed, lambda a, b: (min(a[0], b[0]), max(a[1], b[1])),
        f"{stage_name}: reduce min/max")
    return backend.values(reduced, f"{stage_name}: drop keys")
