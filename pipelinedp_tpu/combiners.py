"""Combiners: the per-metric accumulator algebra.

A combiner encapsulates one DP aggregation: ``create_accumulator(values)``
builds per-(privacy_id, partition) state, ``merge_accumulators`` is the
associative reduce, ``compute_metrics`` applies the DP mechanism. The
CompoundCombiner nests several of them with accumulator
``(row_count, (child_accs...))``.

Parity: pipeline_dp/combiners.py (Combiner ABC :32-85, CustomCombiner :88,
CombinerParams :142, MechanismContainerMixin :203-217, AdditiveMechanismMixin
:220, CountCombiner :241, PrivacyIdCountCombiner :283,
PostAggregationThresholdingCombiner :328, SumCombiner :385, MeanCombiner
:440, VarianceCombiner :522, QuantileCombiner :590-669, CompoundCombiner
:698-797, VectorSumCombiner :800, create_compound_combiner :849-922,
create_compound_combiner_with_custom_combiners :925).

Serialization contract: mechanism objects are created lazily and dropped
from pickled state (``MechanismContainerMixin.__getstate__``) so combiners
can ship to workers before budgets resolve — the same MechanismSpec objects
referenced in worker closures are mutated in place by compute_budgets() in
the driver. The columnar JAX engine instead reads specs/sensitivities off
the combiners and lowers them to batched kernels (pipelinedp_tpu/ops).
"""

from __future__ import annotations

import abc
import collections
import copy
from typing import Iterable, List, Optional, Sized, Tuple, Union

import numpy as np

from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import quantile_tree
from pipelinedp_tpu.aggregate_params import (AggregateParams, Metrics,
                                             NoiseKind, noise_to_thresholding)


class Combiner(abc.ABC):
    """Base combiner. Logic lives here; data lives in accumulators."""

    @abc.abstractmethod
    def create_accumulator(self, values):
        """Creates an accumulator from one privacy ID's values."""

    @abc.abstractmethod
    def merge_accumulators(self, accumulator1, accumulator2):
        """Associative merge."""

    @abc.abstractmethod
    def compute_metrics(self, accumulator):
        """Applies the DP mechanism and returns the metric dict."""

    @abc.abstractmethod
    def metrics_names(self) -> List[str]:
        ...

    @abc.abstractmethod
    def explain_computation(self):
        """Returns a string or lazy callable describing the computation."""

    def expects_per_partition_sampling(self) -> bool:
        """If True the framework Linf-samples values before
        create_accumulator; otherwise the combiner bounds sensitivity
        itself."""
        return True


class CustomCombiner(Combiner, abc.ABC):
    """User-provided combiner (experimental).

    Must implement its own DP mechanism in compute_metrics and, if needed,
    contribution bounding in create_accumulator. The budget accountant must
    NOT be stored on self — it lives in the driver only.
    """

    @abc.abstractmethod
    def request_budget(self,
                       budget_accountant: budget_accounting.BudgetAccountant):
        """Called during graph construction; store the returned spec on self."""

    def set_aggregate_params(self, aggregate_params: AggregateParams):
        self._aggregate_params = aggregate_params

    def metrics_names(self) -> List[str]:
        return [self.__class__.__name__]


class CombinerParams:
    """Bundle of (mechanism spec, aggregate params) handed to a combiner."""

    def __init__(self, spec: budget_accounting.MechanismSpec,
                 aggregate_params: AggregateParams):
        self.mechanism_spec = spec
        self.aggregate_params = copy.copy(aggregate_params)

    @property
    def eps(self):
        return self.mechanism_spec.eps

    @property
    def delta(self):
        return self.mechanism_spec.delta

    @property
    def scalar_noise_params(self) -> dp_computations.ScalarNoiseParams:
        p = self.aggregate_params
        return dp_computations.ScalarNoiseParams(
            self.eps, self.delta, p.min_value, p.max_value,
            p.min_sum_per_partition, p.max_sum_per_partition,
            p.max_partitions_contributed, p.max_contributions_per_partition,
            p.noise_kind)

    @property
    def additive_vector_noise_params(
            self) -> dp_computations.AdditiveVectorNoiseParams:
        p = self.aggregate_params
        return dp_computations.AdditiveVectorNoiseParams(
            eps_per_coordinate=self.eps / p.vector_size,
            delta_per_coordinate=self.delta / p.vector_size,
            max_norm=p.vector_max_norm,
            l0_sensitivity=p.max_partitions_contributed,
            linf_sensitivity=p.max_contributions_per_partition,
            norm_kind=p.vector_norm_kind,
            noise_kind=p.noise_kind)


class MechanismContainerMixin(abc.ABC):
    """Lazily creates and caches a DP mechanism; drops it from pickles."""

    @abc.abstractmethod
    def create_mechanism(
        self
    ) -> Union[dp_computations.AdditiveMechanism,
               dp_computations.MeanMechanism,
               dp_computations.ThresholdingMechanism]:
        ...

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_mechanism", None)
        return state

    def get_mechanism(self):
        if not hasattr(self, "_mechanism"):
            self._mechanism = self.create_mechanism()
        return self._mechanism


class AdditiveMechanismMixin(MechanismContainerMixin):
    """MechanismContainer specialization for additive mechanisms."""

    def create_mechanism(self) -> dp_computations.AdditiveMechanism:
        return dp_computations.create_additive_mechanism(
            self.mechanism_spec(), self.sensitivities())

    @abc.abstractmethod
    def sensitivities(self) -> dp_computations.Sensitivities:
        ...

    @abc.abstractmethod
    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        ...


class CountCombiner(Combiner, AdditiveMechanismMixin):
    """DP COUNT. Accumulator: int element count."""
    AccumulatorType = int

    def __init__(self, mechanism_spec: budget_accounting.MechanismSpec,
                 aggregate_params: AggregateParams):
        self._mechanism_spec = mechanism_spec
        self._sensitivities = dp_computations.compute_sensitivities_for_count(
            aggregate_params)
        self._output_noise_stddev = aggregate_params.output_noise_stddev

    def create_accumulator(self, values: Sized) -> int:
        return len(values)

    def merge_accumulators(self, count1: int, count2: int) -> int:
        return count1 + count2

    def compute_metrics(self, count: int) -> dict:
        out = {"count": self.get_mechanism().add_noise(count)}
        if self._output_noise_stddev:
            out["count_noise_stddev"] = self.get_mechanism().std
        return out

    def metrics_names(self) -> List[str]:
        if self._output_noise_stddev:
            return ["count", "count_noise_stddev"]
        return ["count"]

    def explain_computation(self):
        return lambda: (f"Computed DP count with\n"
                        f"     {self.get_mechanism().describe()}")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._mechanism_spec

    def sensitivities(self) -> dp_computations.Sensitivities:
        return self._sensitivities


class PrivacyIdCountCombiner(Combiner, AdditiveMechanismMixin):
    """DP PRIVACY_ID_COUNT. Accumulator: int count of privacy ids."""
    AccumulatorType = int

    def __init__(self, mechanism_spec: budget_accounting.MechanismSpec,
                 aggregate_params: AggregateParams):
        self._mechanism_spec = mechanism_spec
        self._sensitivities = (
            dp_computations.compute_sensitivities_for_privacy_id_count(
                aggregate_params))
        self._output_noise_stddev = aggregate_params.output_noise_stddev

    def create_accumulator(self, values: Sized) -> int:
        return 1 if values else 0

    def merge_accumulators(self, count1: int, count2: int) -> int:
        return count1 + count2

    def compute_metrics(self, count: int) -> dict:
        out = {"privacy_id_count": self.get_mechanism().add_noise(count)}
        if self._output_noise_stddev:
            out["privacy_id_count_noise_stddev"] = self.get_mechanism().std
        return out

    def metrics_names(self) -> List[str]:
        if self._output_noise_stddev:
            return ["privacy_id_count", "privacy_id_count_noise_stddev"]
        return ["privacy_id_count"]

    def explain_computation(self):
        return lambda: (f"Computed DP privacy_id_count with\n"
                        f"     {self.get_mechanism().describe()}")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._mechanism_spec

    def sensitivities(self) -> dp_computations.Sensitivities:
        return self._sensitivities

    def expects_per_partition_sampling(self) -> bool:
        return False


class PostAggregationThresholdingCombiner(Combiner, MechanismContainerMixin):
    """DP privacy-id count + thresholding partition selection in one step.

    Requests its own (thresholding) budget at construction time.
    """
    AccumulatorType = int

    def __init__(self, budget_accountant: budget_accounting.BudgetAccountant,
                 aggregate_params: AggregateParams):
        mechanism_type = noise_to_thresholding(aggregate_params.noise_kind)
        self._mechanism_spec = budget_accountant.request_budget(
            mechanism_type, weight=aggregate_params.budget_weight)
        self._sensitivities = (
            dp_computations.compute_sensitivities_for_privacy_id_count(
                aggregate_params))
        self._pre_threshold = aggregate_params.pre_threshold
        self._output_noise_stddev = aggregate_params.output_noise_stddev

    def create_accumulator(self, values: Sized) -> int:
        return 1 if values else 0

    def merge_accumulators(self, count1: int, count2: int) -> int:
        return count1 + count2

    def compute_metrics(self, count: int) -> dict:
        out = {
            "privacy_id_count":
                self.get_mechanism().noised_value_if_should_keep(count)
        }
        if self._output_noise_stddev:
            out["privacy_id_count_noise_stddev"] = (
                self.get_mechanism().strategy.noise_stddev)
        return out

    def metrics_names(self) -> List[str]:
        if self._output_noise_stddev:
            return ["privacy_id_count", "privacy_id_count_noise_stddev"]
        return ["privacy_id_count"]

    def explain_computation(self):
        return lambda: (f"Computed DP privacy_id_count with thresholding:\n"
                        f"     {self.get_mechanism().describe()}")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._mechanism_spec

    def sensitivities(self) -> dp_computations.Sensitivities:
        return self._sensitivities

    def expects_per_partition_sampling(self) -> bool:
        return False

    def create_mechanism(self) -> dp_computations.ThresholdingMechanism:
        return dp_computations.create_thresholding_mechanism(
            self.mechanism_spec(), self.sensitivities(), self._pre_threshold)


class SumCombiner(Combiner, AdditiveMechanismMixin):
    """DP SUM with either per-contribution or per-partition clipping."""
    AccumulatorType = float

    def __init__(self, mechanism_spec: budget_accounting.MechanismSpec,
                 aggregate_params: AggregateParams):
        self._mechanism_spec = mechanism_spec
        self._sensitivities = dp_computations.compute_sensitivities_for_sum(
            aggregate_params)
        self._output_noise_stddev = aggregate_params.output_noise_stddev
        self._bounding_per_partition = (
            aggregate_params.bounds_per_partition_are_set)
        if self._bounding_per_partition:
            self._min_bound = aggregate_params.min_sum_per_partition
            self._max_bound = aggregate_params.max_sum_per_partition
        else:
            self._min_bound = aggregate_params.min_value
            self._max_bound = aggregate_params.max_value

    def create_accumulator(self, values: Iterable[float]) -> float:
        if self._bounding_per_partition:
            # Sum first, then clip the per-partition sum.
            return float(np.clip(sum(values), self._min_bound,
                                 self._max_bound))
        # Clip each value, then sum.
        return float(
            np.clip(np.asarray(list(values), dtype=np.float64),
                    self._min_bound, self._max_bound).sum())

    def merge_accumulators(self, sum1: float, sum2: float) -> float:
        return sum1 + sum2

    def compute_metrics(self, sum_: float) -> dict:
        out = {"sum": self.get_mechanism().add_noise(sum_)}
        if self._output_noise_stddev:
            out["sum_noise_stddev"] = self.get_mechanism().std
        return out

    def metrics_names(self) -> List[str]:
        if self._output_noise_stddev:
            return ["sum", "sum_noise_stddev"]
        return ["sum"]

    def expects_per_partition_sampling(self) -> bool:
        return not self._bounding_per_partition

    def explain_computation(self):
        return lambda: (f"Computed DP sum with\n"
                        f"     {self.get_mechanism().describe()}")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._mechanism_spec

    def sensitivities(self) -> dp_computations.Sensitivities:
        return self._sensitivities


class MeanCombiner(Combiner, MechanismContainerMixin):
    """DP MEAN (optionally also count and sum).

    Accumulator: (count, normalized_sum) with values normalized to the middle
    of [min_value, max_value].
    """
    AccumulatorType = Tuple[int, float]

    def __init__(self, count_spec: budget_accounting.MechanismSpec,
                 sum_spec: budget_accounting.MechanismSpec,
                 params: AggregateParams, metrics_to_compute: Iterable[str]):
        metrics_to_compute = list(metrics_to_compute)
        if len(metrics_to_compute) != len(set(metrics_to_compute)):
            raise ValueError(f"{metrics_to_compute} cannot contain duplicates")
        for metric in metrics_to_compute:
            if metric not in ("count", "sum", "mean"):
                raise ValueError(
                    f"{metric} should be one of ['count', 'sum', 'mean']")
        if "mean" not in metrics_to_compute:
            raise ValueError(
                f"one of the {metrics_to_compute} should be 'mean'")
        self._count_spec = count_spec
        self._sum_spec = sum_spec
        self._metrics_to_compute = metrics_to_compute
        self._min_value = params.min_value
        self._max_value = params.max_value
        self._count_sensitivities = (
            dp_computations.compute_sensitivities_for_count(params))
        self._sum_sensitivities = (
            dp_computations.compute_sensitivities_for_normalized_sum(params))

    def create_accumulator(self,
                           values: Iterable[float]) -> Tuple[int, float]:
        values = np.asarray(list(values), dtype=np.float64)
        middle = dp_computations.compute_middle(self._min_value,
                                                self._max_value)
        normalized = np.clip(values, self._min_value, self._max_value) - middle
        return len(values), float(normalized.sum())

    def merge_accumulators(self, accum1, accum2):
        return accum1[0] + accum2[0], accum1[1] + accum2[1]

    def compute_metrics(self, accum: Tuple[int, float]) -> dict:
        count, normalized_sum = accum
        noisy_count, noisy_sum, noisy_mean = self.get_mechanism().compute_mean(
            count, normalized_sum)
        result = {"mean": noisy_mean}
        if "count" in self._metrics_to_compute:
            result["count"] = noisy_count
        if "sum" in self._metrics_to_compute:
            result["sum"] = noisy_sum
        return result

    def metrics_names(self) -> List[str]:
        return self._metrics_to_compute

    def explain_computation(self):
        return lambda: ("DP mean computation:\n" +
                        self.get_mechanism().describe())

    def create_mechanism(self) -> dp_computations.MeanMechanism:
        middle = dp_computations.compute_middle(self._min_value,
                                                self._max_value)
        return dp_computations.create_mean_mechanism(
            middle, self._count_spec, self._count_sensitivities,
            self._sum_spec, self._sum_sensitivities)

    def mechanism_spec(self):
        return (self._count_spec, self._sum_spec)


class VarianceCombiner(Combiner):
    """DP VARIANCE (optionally also mean, sum, count).

    Accumulator: (count, normalized_sum, normalized_sum_of_squares).
    """
    AccumulatorType = Tuple[int, float, float]

    def __init__(self, params: CombinerParams,
                 metrics_to_compute: Iterable[str]):
        self._params = params
        metrics_to_compute = list(metrics_to_compute)
        if len(metrics_to_compute) != len(set(metrics_to_compute)):
            raise ValueError(f"{metrics_to_compute} cannot contain duplicates")
        for metric in metrics_to_compute:
            if metric not in ("count", "sum", "mean", "variance"):
                raise ValueError(f"{metric} should be one of "
                                 f"['count', 'sum', 'mean', 'variance']")
        if "variance" not in metrics_to_compute:
            raise ValueError(
                f"one of the {metrics_to_compute} should be 'variance'")
        self._metrics_to_compute = metrics_to_compute

    def create_accumulator(self, values) -> Tuple[int, float, float]:
        p = self._params.aggregate_params
        values = np.asarray(list(values), dtype=np.float64)
        middle = dp_computations.compute_middle(p.min_value, p.max_value)
        normalized = np.clip(values, p.min_value, p.max_value) - middle
        return len(values), float(normalized.sum()), float(
            (normalized**2).sum())

    def merge_accumulators(self, accum1, accum2):
        return (accum1[0] + accum2[0], accum1[1] + accum2[1],
                accum1[2] + accum2[2])

    def compute_metrics(self, accum) -> dict:
        count, norm_sum, norm_sq = accum
        noisy_count, noisy_sum, noisy_mean, noisy_var = (
            dp_computations.compute_dp_var(count, norm_sum, norm_sq,
                                           self._params.scalar_noise_params))
        result = {"variance": noisy_var}
        if "count" in self._metrics_to_compute:
            result["count"] = noisy_count
        if "sum" in self._metrics_to_compute:
            result["sum"] = noisy_sum
        if "mean" in self._metrics_to_compute:
            result["mean"] = noisy_mean
        return result

    def metrics_names(self) -> List[str]:
        return self._metrics_to_compute

    def explain_computation(self):
        return lambda: (f"Computed variance with (eps={self._params.eps} "
                        f"delta={self._params.delta})")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._params.mechanism_spec


class QuantileCombiner(Combiner):
    """DP percentiles via mergeable quantile-tree sketches.

    Accumulator: serialized tree summary bytes (fixed-size dense array).
    """
    AccumulatorType = bytes

    def __init__(self, params: CombinerParams,
                 percentiles_to_compute: List[float]):
        self._params = params
        self._percentiles = percentiles_to_compute
        self._quantiles_to_compute = [p / 100 for p in percentiles_to_compute]

    def create_accumulator(self, values) -> bytes:
        tree = self._create_empty_quantile_tree()
        tree.add_entries(list(values))
        return tree.serialize().to_bytes()

    def merge_accumulators(self, acc1: bytes, acc2: bytes) -> bytes:
        tree = self._create_empty_quantile_tree()
        tree.merge(quantile_tree.bytes_to_summary(acc1))
        tree.merge(quantile_tree.bytes_to_summary(acc2))
        return tree.serialize().to_bytes()

    def compute_metrics(self, accumulator: bytes) -> dict:
        tree = self._create_empty_quantile_tree()
        tree.merge(quantile_tree.bytes_to_summary(accumulator))
        p = self._params.aggregate_params
        quantiles = tree.compute_quantiles(self._params.eps,
                                           self._params.delta,
                                           p.max_partitions_contributed,
                                           p.max_contributions_per_partition,
                                           self._quantiles_to_compute,
                                           self._noise_type())
        return dict(zip(self.metrics_names(), quantiles))

    def metrics_names(self) -> List[str]:

        def format_name(p: float) -> str:
            int_p = int(round(p))
            text = str(int_p) if int_p == p else str(p).replace(".", "_")
            return f"percentile_{text}"

        return [format_name(p) for p in self._percentiles]

    def explain_computation(self):
        return lambda: (f"Computed percentiles {self._percentiles} with "
                        f"(eps={self._params.eps} delta={self._params.delta})")

    def _create_empty_quantile_tree(self) -> quantile_tree.QuantileTree:
        p = self._params.aggregate_params
        return quantile_tree.QuantileTree(
            p.min_value, p.max_value, quantile_tree.DEFAULT_TREE_HEIGHT,
            quantile_tree.DEFAULT_BRANCHING_FACTOR)

    def _noise_type(self) -> str:
        noise_kind = self._params.aggregate_params.noise_kind
        if noise_kind == NoiseKind.LAPLACE:
            return "laplace"
        if noise_kind == NoiseKind.GAUSSIAN:
            return "gaussian"
        raise ValueError(f"{noise_kind} is not supported by quantile tree.")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._params.mechanism_spec


# -- namedtuple output type (picklable across processes) ---------------------

_named_tuple_cache = {}


def _get_or_create_named_tuple(type_name: str, field_names: tuple):
    """namedtuple type with a __reduce__ making instances picklable even
    though the type is created dynamically."""
    cache_key = (type_name, field_names)
    named_tuple = _named_tuple_cache.get(cache_key)
    if named_tuple is None:
        named_tuple = collections.namedtuple(type_name, field_names)
        named_tuple.__reduce__ = lambda self: (_create_named_tuple_instance,
                                               (type_name, field_names,
                                                tuple(self)))
        _named_tuple_cache[cache_key] = named_tuple
    return named_tuple


def _create_named_tuple_instance(type_name: str, field_names: tuple, values):
    return _get_or_create_named_tuple(type_name, field_names)(*values)


class CompoundCombiner(Combiner):
    """Nests several combiners; accumulator = (row_count, (child_accs...)).

    row_count counts input rows (after grouping by privacy id it is the
    privacy-id count, which private partition selection consumes).
    """

    AccumulatorType = Tuple[int, Tuple]

    def __init__(self, combiners: Iterable[Combiner],
                 return_named_tuple: bool):
        self._combiners = list(combiners)
        self._return_named_tuple = return_named_tuple
        self._metrics_to_compute = []
        if not return_named_tuple:
            return
        for combiner in self._combiners:
            self._metrics_to_compute.extend(combiner.metrics_names())
        if len(self._metrics_to_compute) != len(set(self._metrics_to_compute)):
            raise ValueError(
                f"two combiners in {combiners} cannot compute the same metrics"
            )
        self._metrics_to_compute = tuple(self._metrics_to_compute)
        self._MetricsTuple = _get_or_create_named_tuple(
            "MetricsTuple", self._metrics_to_compute)

    @property
    def combiners(self) -> List[Combiner]:
        return self._combiners

    def create_accumulator(self, values) -> "CompoundCombiner.AccumulatorType":
        return (1,
                tuple(
                    combiner.create_accumulator(values)
                    for combiner in self._combiners))

    def merge_accumulators(self, acc1, acc2):
        row_count1, children1 = acc1
        row_count2, children2 = acc2
        merged = tuple(
            combiner.merge_accumulators(a1, a2)
            for combiner, a1, a2 in zip(self._combiners, children1, children2))
        return (row_count1 + row_count2, merged)

    def compute_metrics(self, compound_accumulator):
        _, children = compound_accumulator
        if not self._return_named_tuple:
            return tuple(
                combiner.compute_metrics(acc)
                for combiner, acc in zip(self._combiners, children))
        combined = {}
        for combiner, acc in zip(self._combiners, children):
            metrics = combiner.compute_metrics(acc)
            for name in metrics:
                if name in combined:
                    raise Exception(
                        f"{name} computed by {combiner} was already computed "
                        f"by another combiner")
            combined.update(metrics)
        return _create_named_tuple_instance("MetricsTuple",
                                            tuple(combined.keys()),
                                            tuple(combined.values()))

    def metrics_names(self) -> List[str]:
        return self._metrics_to_compute

    def explain_computation(self):
        return [combiner.explain_computation() for combiner in self._combiners]

    def expects_per_partition_sampling(self) -> bool:
        return any(c.expects_per_partition_sampling()
                   for c in self._combiners)


class VectorSumCombiner(Combiner):
    """DP VECTOR_SUM. Accumulator: np.ndarray of shape (vector_size,)."""
    AccumulatorType = np.ndarray

    def __init__(self, params: CombinerParams):
        self._params = params

    def create_accumulator(self, values) -> np.ndarray:
        expected_shape = (self._params.aggregate_params.vector_size,)
        array_sum = None
        for value in values:
            value = np.asarray(value)
            if value.shape != expected_shape:
                raise TypeError(
                    f"Shape mismatch: {value.shape} != {expected_shape}")
            array_sum = value if array_sum is None else array_sum + value
        return array_sum

    def merge_accumulators(self, sum1: np.ndarray,
                           sum2: np.ndarray) -> np.ndarray:
        return sum1 + sum2

    def compute_metrics(self, array_sum: np.ndarray) -> dict:
        out = {
            "vector_sum":
                dp_computations.add_noise_vector(
                    array_sum, self._params.additive_vector_noise_params)
        }
        if self._params.aggregate_params.output_noise_stddev:
            out["vector_sum_noise_stddev"] = (
                dp_computations.vector_noise_stddev(
                    self._params.additive_vector_noise_params))
        return out

    def metrics_names(self) -> List[str]:
        if self._params.aggregate_params.output_noise_stddev:
            return ["vector_sum", "vector_sum_noise_stddev"]
        return ["vector_sum"]

    def explain_computation(self):
        return lambda: (f"Computed vector sum with (eps={self._params.eps} "
                        f"delta={self._params.delta})")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._params.mechanism_spec


def create_compound_combiner(
        aggregate_params: AggregateParams,
        budget_accountant: budget_accounting.BudgetAccountant
) -> CompoundCombiner:
    """Builds the CompoundCombiner for the requested metrics, requesting one
    budget per underlying mechanism (VARIANCE subsumes MEAN subsumes
    COUNT/SUM so their budgets are not double-requested)."""
    combiners = []
    metrics = aggregate_params.metrics
    mechanism_type = aggregate_params.noise_kind.convert_to_mechanism_type()
    weight = aggregate_params.budget_weight

    if Metrics.VARIANCE in metrics:
        spec = budget_accountant.request_budget(mechanism_type, weight=weight)
        extra = [
            name for metric, name in ((Metrics.MEAN, "mean"),
                                      (Metrics.COUNT, "count"),
                                      (Metrics.SUM, "sum")) if metric in metrics
        ]
        combiners.append(
            VarianceCombiner(CombinerParams(spec, aggregate_params),
                             ["variance"] + extra))
    elif Metrics.MEAN in metrics:
        count_spec = budget_accountant.request_budget(mechanism_type,
                                                      weight=weight)
        sum_spec = budget_accountant.request_budget(mechanism_type,
                                                    weight=weight)
        extra = [
            name for metric, name in ((Metrics.COUNT, "count"),
                                      (Metrics.SUM, "sum")) if metric in metrics
        ]
        combiners.append(
            MeanCombiner(count_spec, sum_spec, aggregate_params,
                         ["mean"] + extra))
    else:
        if Metrics.COUNT in metrics:
            spec = budget_accountant.request_budget(mechanism_type,
                                                    weight=weight)
            combiners.append(CountCombiner(spec, aggregate_params))
        if Metrics.SUM in metrics:
            spec = budget_accountant.request_budget(mechanism_type,
                                                    weight=weight)
            combiners.append(SumCombiner(spec, aggregate_params))

    if Metrics.PRIVACY_ID_COUNT in metrics:
        if aggregate_params.post_aggregation_thresholding:
            combiners.append(
                PostAggregationThresholdingCombiner(budget_accountant,
                                                    aggregate_params))
        else:
            spec = budget_accountant.request_budget(mechanism_type,
                                                    weight=weight)
            combiners.append(PrivacyIdCountCombiner(spec, aggregate_params))

    if Metrics.VECTOR_SUM in metrics:
        spec = budget_accountant.request_budget(mechanism_type, weight=weight)
        combiners.append(
            VectorSumCombiner(CombinerParams(spec, aggregate_params)))

    percentiles = [m.parameter for m in metrics if m.is_percentile]
    if percentiles:
        spec = budget_accountant.request_budget(mechanism_type, weight=weight)
        combiners.append(
            QuantileCombiner(CombinerParams(spec, aggregate_params),
                             percentiles))

    return CompoundCombiner(combiners, return_named_tuple=True)


def create_compound_combiner_with_custom_combiners(
        aggregate_params: AggregateParams,
        budget_accountant: budget_accounting.BudgetAccountant,
        custom_combiners: Iterable[CustomCombiner]) -> CompoundCombiner:
    for combiner in custom_combiners:
        params_copy = copy.copy(aggregate_params)
        params_copy.custom_combiners = None
        combiner.set_aggregate_params(params_copy)
        combiner.request_budget(budget_accountant)
    return CompoundCombiner(custom_combiners, return_named_tuple=False)
