"""Dataset-histogram types: log-binned frequency histograms of contribution
structure, used by parameter tuning and utility analysis.

Parity: pipeline_dp/dataset_histograms/histograms.py (FrequencyBin :21,
HistogramType :60-77, Histogram + quantiles :79-162, compute_ratio_dropped
:165-204, DatasetHistograms :207-216).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Sequence, Tuple, Union

Number = Union[int, float]


@dataclasses.dataclass
class FrequencyBin:
    """One histogram bin over [lower, upper).

    The upper bound is exclusive except for the last bin of float-valued
    histograms, where it is inclusive. ``count`` is the number of elements in
    the bin, ``sum`` their total, ``max`` the largest element seen.
    """
    lower: Number
    upper: Number
    count: int
    sum: Number
    max: Number

    def __add__(self, other: "FrequencyBin") -> "FrequencyBin":
        assert self.lower == other.lower and self.upper == other.upper, (
            f"Cannot add bins with different bounds: "
            f"[{self.lower}, {self.upper}) vs [{other.lower}, {other.upper})")
        return FrequencyBin(self.lower, self.upper, self.count + other.count,
                            self.sum + other.sum, max(self.max, other.max))

    def __eq__(self, other) -> bool:
        return (self.lower == other.lower and self.count == other.count and
                self.sum == other.sum and self.max == other.max)


class HistogramType(enum.Enum):
    # count = #privacy units contributing to [lower, upper) partitions,
    # sum = total (privacy_unit, partition) pairs for those units.
    L0_CONTRIBUTIONS = "l0_contributions"
    L1_CONTRIBUTIONS = "l1_contributions"
    # count = #(privacy_unit, partition) pairs with [lower, upper)
    # contributions, sum = total contributions of those pairs.
    LINF_CONTRIBUTIONS = "linf_contributions"
    LINF_SUM_CONTRIBUTIONS = "linf_sum_contributions"
    COUNT_PER_PARTITION = "count_per_partition"
    COUNT_PRIVACY_ID_PER_PARTITION = "privacy_id_per_partition_count"
    SUM_PER_PARTITION = "sum_per_partition"


@dataclasses.dataclass
class Histogram:
    """A frequency histogram: integer (log-binned) or float (equal bins)."""
    name: HistogramType
    bins: List[FrequencyBin]
    lower: Optional[Number] = dataclasses.field(init=False)
    upper: Optional[Number] = dataclasses.field(init=False)

    def __post_init__(self):
        if not self.bins:
            self.lower = self.upper = None
        else:
            self.lower = 1 if self.is_integer else self.bins[0].lower
            self.upper = None if self.is_integer else self.bins[-1].upper

    @property
    def is_integer(self) -> bool:
        return self.name not in (HistogramType.LINF_SUM_CONTRIBUTIONS,
                                 HistogramType.SUM_PER_PARTITION)

    def total_count(self) -> int:
        return sum(b.count for b in self.bins)

    def total_sum(self) -> Number:
        return sum(b.sum for b in self.bins)

    def max_value(self) -> Number:
        return self.bins[-1].max

    def quantiles(self, q: List[float]) -> List[Number]:
        """Approximate quantiles, chosen among bin lower bounds.

        For each target q returns the lower of the first bin such that the
        fraction of data strictly left of that bin is <= q. ``q`` must be
        sorted ascending.
        """
        assert sorted(q) == list(q), "Quantiles to compute must be sorted."
        total = self.total_count()
        if total == 0:
            raise ValueError("Cannot compute quantiles of an empty histogram")
        result = []
        count_smaller = total
        i_q = len(q) - 1
        for bin_ in reversed(self.bins):
            count_smaller -= bin_.count
            ratio_smaller = count_smaller / total
            while i_q >= 0 and q[i_q] >= ratio_smaller:
                result.append(bin_.lower)
                i_q -= 1
        while i_q >= 0:
            result.append(self.bins[0].lower)
            i_q -= 1
        return result[::-1]


def compute_ratio_dropped(
        contribution_histogram: Histogram) -> Sequence[Tuple[int, float]]:
    """For each candidate bounding threshold (bin lowers + max value),
    the fraction of data that contribution bounding at that threshold drops.

    An element of size s bounded at threshold t drops (s - t) units; summing
    over the histogram (using bin counts/sums as sufficient statistics)
    yields the exact drop ratio at every bin lower. Returns ascending
    (threshold, ratio) pairs, beginning with (0, 1).
    """
    if not contribution_histogram.bins:
        return []
    bins = contribution_histogram.bins
    total_sum = contribution_histogram.total_sum()
    ratios = []
    previous_value = bins[-1].lower
    if contribution_histogram.max_value() != previous_value:
        ratios.append((contribution_histogram.max_value(), 0.0))
    dropped = 0.0
    elements_larger = 0
    for bin_ in reversed(bins):
        current = bin_.lower
        dropped += (elements_larger * (previous_value - current) +
                    (bin_.sum - bin_.count * current))
        ratios.append((current, dropped / total_sum))
        previous_value = current
        elements_larger += bin_.count
    ratios.append((0, 1))
    return ratios[::-1]


@dataclasses.dataclass
class DatasetHistograms:
    """The seven dataset histograms driving tuning and analysis."""
    l0_contributions_histogram: Optional[Histogram]
    l1_contributions_histogram: Optional[Histogram]
    linf_contributions_histogram: Optional[Histogram]
    linf_sum_contributions_histogram: Optional[Histogram]
    count_per_partition_histogram: Optional[Histogram]
    count_privacy_id_per_partition: Optional[Histogram]
    sum_per_partition_histogram: Optional[Histogram]
